"""Elastic-fleet benchmark: a replayable 10x traffic swing, autoscaled
vs static, with chaos fired during the scale events.

Three legs over the SAME seeded open-loop trace (serving/workload.py —
bit-deterministic in the scenario seed, so every leg sees identical
arrivals):

1. **static-peak** — a fixed fleet provisioned for the peak
   (`--max-replicas` engines up the whole run): the goodput ceiling and
   the chip-hours ceiling.
2. **autoscaled** — the fleet starts at `--min-replicas` and the
   `Autoscaler` grows/shrinks it from the SLO error budget (windowed
   p99 vs `--slo-ms`, utilisation watermarks, brownout) with
   hysteresis + cooldown. Scale-up warmup hides behind the
   single-trace restart path; the leg asserts every member compiled
   exactly once (`{"decode": 1, "cow": 1}`).
3. **chaos** — the autoscaled leg re-run under a `ChaosSchedule` that
   fires *during* the scale events: delay on the first
   ``serving.scale_up`` and ``serving.scale_down``, a raise on the
   first ``serving.drain`` eviction attempt (retried at the next
   watchdog poll), and a replica crash mid-swing via
   ``serving.replica_step`` — then certifies ``fired == planned`` and
   exactly-once delivery (every arrival's future resolved exactly
   once: zero lost, zero duplicated).

Each leg reports goodput, SLO-violation-minutes (1-second buckets of
submit time whose bucket p99 exceeds `--slo-ms`), and chip-hours
(`ReplicaSet.replica_seconds()` — a replica costs its chip whether
busy or idle; measured from replay start to last completion, so the
post-trace drain wait is not charged). One JSON line per leg plus a
final ``BENCH_FLEET`` object. ``--smoke`` shrinks the model/trace and
asserts the acceptance bar: both clean legs at goodput 1.0, autoscaled
strictly cheaper in chip-hours than static-peak, chaos goodput 1.0
with the full schedule delivered.

``--rollout`` swaps the legs for the zero-downtime rollout story
(serving/rollout.py) over the same seeded surge against a fixed
2-replica fleet: **rollout-upgrade** (a clean canary → wave → commit
from a real checkpoint dir lands at goodput 1.0 with one fleet-wide
version and compile-once rebuilds), **rollout-rollback** (weights
corrupted after their golden digests freeze are caught bitwise by the
canary gate and auto-rolled-back to a fleet bitwise-identical to
pre-rollout, with chaos delaying the registry load —
``serving.rollout_load`` — and failing the first rollback attempt —
``serving.rollback``), and **rollout-chaos** (a replica killed
mid-rollout — ``serving.canary`` dwell + a ``serving.replica_heartbeat``
stall past the liveness timeout — replays its in-flight requests
pinned to the weight version they were decoding on, and the rollout
still commits).

``--tenants`` swaps the legs for the multi-tenant isolation story
(serving/tenancy.py) over the same seeded flash crowd against a fixed
2-replica weighted-fair fleet with live batched LoRA adapter banks:
**tenants-isolation** (a weight-1 bronze tenant floods while a
weight-4 gold tenant trickles; DRR admission must keep the victim's
p99 within ITS SLO while the flood queues in its own share, each
tenant decoding its own adapter batched in the same step) and
**tenants-chaos** (the same leg with ``serving.admit_tenant`` drops on
the noisy tenant — per-tenant shed accounting must be EXACT: fired ==
the noisy tenant's shed counter, the victim sheds zero — plus a
mid-leg adapter rollout whose wave faults at ``serving.adapter_swap``
and must roll back all-or-nothing with the old bank serving bitwise).

CPU smoke (the tier-1 case):

    JAX_PLATFORMS=cpu python bench_fleet.py --smoke
    JAX_PLATFORMS=cpu python bench_fleet.py --rollout --smoke
    JAX_PLATFORMS=cpu python bench_fleet.py --tenants --smoke
"""

from __future__ import annotations

import argparse
import json
import threading
import time

import numpy as np


def percentile(xs, p):
    ys = sorted(xs)
    if not ys:
        return 0.0
    i = min(int(round((p / 100.0) * (len(ys) - 1))), len(ys) - 1)
    return ys[i]


class _MemberSampler:
    """Background membership/chip sampler: peak size + (t, members)
    timeline for the report."""

    def __init__(self, replica_set, period_s=0.05):
        self.rs = replica_set
        self.period_s = period_s
        self.samples = []
        self._stop = threading.Event()
        self._t0 = None
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.wait(self.period_s):
            self.samples.append(
                (round(time.monotonic() - self._t0, 3),
                 self.rs.member_replicas(), self.rs.live_replicas()))

    def start(self):
        self._t0 = time.monotonic()
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(2.0)
        return self.samples


def make_router(serving, model, args, name, autoscaled):
    kw = dict(
        engine_kw=dict(max_slots=args.max_slots,
                       max_seq_len=args.max_seq_len,
                       block_size=args.block_size),
        queue_cap=args.queue_cap, hedge=False, retry_budget=3,
        liveness_timeout_s=30.0, backoff_base_s=0.05,
        # never shed: the certification is exactly-once over EVERY
        # arrival, and a transient zero-capacity window (mid-kill)
        # must queue, not brownout-shed
        brownout_priority=0, name=name)
    if autoscaled:
        kw["autoscale"] = dict(
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas, slo_p99_ms=args.slo_ms,
            cooldown_s=args.cooldown_s, window=args.slo_window)
        n = args.min_replicas
    else:
        n = args.max_replicas
    return serving.Router(model, n, **kw).start()


def run_leg(router, scenario, args, label, during=None):
    """Replay the scenario open-loop against one fleet; returns the
    result row. Exactly-once is certified per arrival: its future must
    resolve exactly one time (zero lost, zero duplicated). `during` is
    an optional thunk started alongside the replay (the rollout legs
    drive a live upgrade through it) and joined before the row is cut;
    its outcome lands in ``row["during"]``."""
    from paddle_tpu.serving import workload

    trace = scenario.trace()
    rs = router.replica_set
    lock = threading.Lock()
    reqs = {}                 # future id -> bookkeeping
    t0 = time.monotonic()

    def submit(a):
        fut = router.submit(a.prompt, max_new_tokens=a.max_new,
                            priority=a.priority, timeout=120.0)
        info = {"t_submit": time.monotonic() - t0, "done": 0,
                "lat_s": None, "ok": False}
        with lock:
            reqs[fut.id] = info

        def cb(f, info=info):
            with lock:
                info["done"] += 1
                info["lat_s"] = time.monotonic() - t0 - info["t_submit"]
                info["ok"] = f._error is None
        fut.add_done_callback(cb)
        return fut

    sampler = _MemberSampler(rs).start()
    chip0 = rs.replica_seconds()
    during_out, dthread = {}, None
    if during is not None:
        def _during():
            try:
                during_out["result"] = during()
            except Exception as e:  # noqa: BLE001 — reported in the row
                during_out["error"] = f"{type(e).__name__}: {e}"
        dthread = threading.Thread(target=_during, daemon=True)
        dthread.start()
    records = workload.replay(submit, trace,
                              time_scale=args.time_scale)
    shed = sum(1 for r in records if r["error"] is not None)
    for r in records:
        if r["future"] is not None:
            try:
                r["future"].result(120.0)
            except Exception:  # noqa: BLE001 — typed failures count
                pass
    if dthread is not None:
        dthread.join(240.0)
        if dthread.is_alive():
            during_out["error"] = "during-thunk still running"
    chip_s = rs.replica_seconds() - chip0
    wall = time.monotonic() - t0
    samples = sampler.stop()
    # an autoscale build may still be in flight (the trace ended while
    # a replica was tracing): let it land so scale_ups/compile counts
    # describe the whole leg
    asc = getattr(router, "autoscaler", None)
    if asc is not None:
        for _ in range(600):
            t = asc._scale_thread
            if t is None or not t.is_alive():
                break
            t.join(0.1)
    compiles = router.compile_counts()

    with lock:
        rows = list(reqs.values())
    ok = sum(1 for r in rows if r["ok"])
    failed = len(rows) - ok + shed
    lost = sum(1 for r in rows if r["done"] == 0)
    dup = sum(1 for r in rows if r["done"] > 1)
    # SLO-violation time: 1-second submit buckets whose p99 e2e
    # latency exceeds the SLO
    buckets: dict = {}
    for r in rows:
        if r["lat_s"] is not None:
            buckets.setdefault(int(r["t_submit"]), []).append(r["lat_s"])
    violation_s = sum(
        1.0 for lats in buckets.values()
        if percentile(lats, 99) * 1e3 > args.slo_ms)
    lats = [r["lat_s"] for r in rows if r["ok"] and r["lat_s"] is not None]
    total = len(rows) + shed
    row = {
        "leg": label,
        "arrivals": len(trace),
        "requests_ok": ok,
        "requests_failed": failed,
        "lost": lost,
        "duplicated": dup,
        "goodput": round(ok / total, 4) if total else 0.0,
        "wall_s": round(wall, 4),
        "chip_s": round(chip_s, 3),
        "chip_hours": round(chip_s / 3600.0, 6),
        "slo_violation_s": violation_s,
        "slo_violation_min": round(violation_s / 60.0, 4),
        "p50_ms": round(percentile(lats, 50) * 1e3, 3),
        "p99_ms": round(percentile(lats, 99) * 1e3, 3),
        "peak_members": max((m for _, m, _ in samples), default=0),
        "min_members": min((m for _, m, _ in samples), default=0),
        "compiles_once": all(c == {"decode": 1, "cow": 1}
                             for c in compiles.values()),
        "scale_ups": router.metrics.get("replicas_added"),
        "scale_downs": router.metrics.get("replicas_removed"),
        "replays": router.metrics.get("replays"),
        "restarts": router.metrics.get("replica_restarts"),
    }
    if during is not None:
        row["during"] = during_out
    if args.timeline:
        row["members_timeline"] = samples
    return row


def wait_scaled_down(router, args, timeout=20.0):
    """Post-trace: wait for the autoscaler to drain back to the floor
    (drives the serving.scale_down/serving.drain chaos sites)."""
    deadline = time.monotonic() + timeout
    rs = router.replica_set
    while time.monotonic() < deadline:
        if rs.member_replicas() <= args.min_replicas \
                and not any(r.state == "draining" for r in rs.replicas):
            return True
        time.sleep(0.02)
    return False


# ---------------------------------------------------------------------------
# --rollout: zero-downtime model rollout under the traffic swing
# ---------------------------------------------------------------------------


def _perturbed_values(model, seed=13):
    """v1 weights: every v0 leaf nudged by a deterministic gaussian —
    same shapes/dtypes (no retrace), different greedy decodes."""
    import jax.numpy as jnp
    from paddle_tpu.engine import state_values

    rng = np.random.RandomState(seed)
    out = {}
    for k, v in state_values(model).items():
        a = np.asarray(v)
        out[k] = jnp.asarray(a + rng.normal(0.0, 0.02, a.shape)
                             .astype(a.dtype))
    return out


def rollout_legs(args, serving, faults, model, scenario):
    """Three legs, each a rolling upgrade driven DURING the same seeded
    surge (fixed 2-replica fleet, no autoscaler):

    - **rollout-upgrade** — clean canary → wave → commit from a real
      checkpoint dir; must land at goodput 1.0, zero lost/dup, one
      fleet-wide version, compile-once after every rebuild.
    - **rollout-rollback** — the new version's weights are corrupted
      AFTER its golden digests freeze; the canary's bitwise gate
      catches it and auto-rollback restores a single-version fleet
      bitwise-identical to pre-rollout. Chaos delays the registry load
      and fails the first rollback attempt (retried).
    - **rollout-chaos** — a replica is killed mid-rollout; its
      in-flight requests replay pinned to the weight version they were
      decoding on, and the rollout still converges and commits.
    """
    import os
    import tempfile

    from paddle_tpu.distributed import checkpoint as ckpt
    from paddle_tpu.serving.rollout import (
        RolloutController, WeightRegistry)

    tmpdir = tempfile.mkdtemp(prefix="bench-rollout-")
    ckpt.CheckpointManager(tmpdir, max_to_keep=10).save(
        1, _perturbed_values(model))
    ckpt_dir = os.path.join(tmpdir, "ckpt-1")
    probe = np.random.RandomState(5).randint(
        0, args.vocab, (6,)).astype(np.int32)

    def fleet(name, n=2, liveness_timeout_s=30.0):
        return serving.Router(
            model, n,
            engine_kw=dict(max_slots=args.max_slots,
                           max_seq_len=args.max_seq_len,
                           block_size=args.block_size),
            queue_cap=args.queue_cap, hedge=False, retry_budget=3,
            liveness_timeout_s=liveness_timeout_s, backoff_base_s=0.05,
            brownout_priority=0, name=name).start()

    def controller(router, reg):
        # generous SLO for the burn gate: these legs certify the
        # bitwise/convergence story under surge; the SLO-gate teeth
        # are unit-tested where latency is controllable
        return RolloutController(router, reg, canary_secs=0.1,
                                 wave_size=1, poll_s=0.005,
                                 replica_timeout_s=120.0,
                                 slo_p99_ms=60000.0)

    def versions_after(router):
        return sorted({r.engine.weight_version
                       for r in router.replica_set.replicas
                       if r.state == "healthy"})

    # -- leg A: clean rollout mid-surge -------------------------------------
    router = fleet("frollA")
    reg = WeightRegistry(model)
    wv1 = reg.load_dir(ckpt_dir)
    ro = controller(router, reg)
    ro.ensure_golden(wv1)
    legA = run_leg(router, scenario, args, "rollout-upgrade",
                   during=lambda: ro.roll_to(wv1.version))
    legA["rollout_state"] = ro.state
    legA["rollout_error"] = ro.error
    legA["versions"] = versions_after(router)
    router.shutdown(drain=True)
    print(json.dumps(legA))

    # -- leg B: corrupt canary -> bitwise auto-rollback under chaos ---------
    router = fleet("frollB")
    reg = WeightRegistry(model)
    ro = controller(router, reg)
    specs_b = [
        "serving.rollout_load@1:delay:0.01",   # slow the registry load
        "serving.rollback@1:raise",            # first rollback attempt
    ]                                          # fails; it is retried
    with faults.ChaosSchedule(*specs_b) as sched:
        wv_bad = reg.load_dir(ckpt_dir)
        ro.ensure_golden(wv_bad)               # digests freeze here...
        emb = "gpt.embeddings.word_embeddings.weight"
        import jax.numpy as jnp
        # ...then the weights rot: roll the tied embedding's vocab rows
        # (uniform shifts cancel in the tied head; a roll never does)
        wv_bad.values[emb] = jnp.roll(wv_bad.values[emb], 1, axis=0)
        pre = np.asarray(router.generate(probe, max_new_tokens=6,
                                         timeout=60.0))
        legB = run_leg(router, scenario, args, "rollout-rollback",
                       during=lambda: ro.roll_to(wv_bad.version))
        post = np.asarray(router.generate(probe, max_new_tokens=6,
                                          timeout=60.0))
        fired_b = sched.verify()
    legB["chaos_fired"] = fired_b
    legB["rollout_state"] = ro.state
    legB["rollout_error"] = ro.error
    legB["versions"] = versions_after(router)
    legB["bitwise_restored"] = bool(pre.shape == post.shape
                                    and (pre == post).all())
    legB["rollback_retries"] = router.metrics.get("rollback_retries")
    router.shutdown(drain=True)
    print(json.dumps(legB))

    # -- leg C: kill a replica mid-rollout (version-pinned replay) ----------
    # 3 replicas so the pinned version stays reachable whatever the
    # kill's timing: r1's in-flight replay onto a sibling still serving
    # the SAME weight version (bitwise), while r1 itself backoff-
    # restarts pinned to whatever the rollout had assigned it
    router = fleet("frollC", n=3, liveness_timeout_s=0.5)
    reg = WeightRegistry(model)
    wv1c = reg.load_dir(ckpt_dir)
    ro = controller(router, reg)
    ro.ensure_golden(wv1c)
    specs_c = [
        "serving.canary@1:delay:0.02",         # dwell in the canary
        "serving.replica_heartbeat[frollC.r1]@100:delay:1.0",
    ]            # heartbeat stall past the liveness timeout = a kill
    with faults.ChaosSchedule(*specs_c) as sched:
        legC = run_leg(router, scenario, args, "rollout-chaos",
                       during=lambda: ro.roll_to(wv1c.version))
        fired_c = sched.verify()
    legC["chaos_fired"] = fired_c
    legC["rollout_state"] = ro.state
    legC["rollout_error"] = ro.error
    legC["versions"] = versions_after(router)
    legC["replays_pinned"] = router.metrics.get("replays_pinned")
    legC["deaths"] = router.metrics.get("replica_deaths")
    router.shutdown(drain=True)
    print(json.dumps(legC))

    result = {
        "bench": "BENCH_FLEET_ROLLOUT",
        "scenario": scenario.to_dict(),
        "config": {"replicas": 2, "max_slots": args.max_slots,
                   "queue_cap": args.queue_cap,
                   "time_scale": args.time_scale,
                   "model": {"vocab": args.vocab, "hidden": args.hidden,
                             "layers": args.layers, "heads": args.heads},
                   "chaos_specs": {"rollback": specs_b,
                                   "chaos": specs_c}},
        "upgrade": legA, "rollback": legB, "chaos": legC,
    }
    print(json.dumps(result))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)

    if args.smoke:
        for leg in (legA, legB, legC):
            assert leg["lost"] == 0, f"{leg['leg']}: lost futures"
            assert leg["duplicated"] == 0, \
                f"{leg['leg']}: duplicated outcomes"
            assert leg["goodput"] == 1.0, leg
            assert leg["compiles_once"], \
                f"{leg['leg']}: a rebuild retraced"
            assert "error" not in leg["during"], leg["during"]
        assert legA["rollout_state"] == "committed", legA
        assert legA["during"].get("result") is True, legA["during"]
        assert legA["versions"] == [1], legA
        assert legB["rollout_state"] == "rolled_back", legB
        assert legB["versions"] == [0], legB
        assert legB["bitwise_restored"], \
            "post-rollback decode is not bitwise pre-rollout"
        assert legB["rollback_retries"] >= 1, legB
        assert legB["chaos_fired"] == {"serving.rollout_load": 1,
                                       "serving.rollback": 1}, legB
        assert legC["rollout_state"] == "committed", legC
        assert legC["versions"] == [1], legC
        assert legC["chaos_fired"] == {
            "serving.canary": 1, "serving.replica_heartbeat": 1}, legC
        assert legC["deaths"] >= 1, "the stall never killed a replica"
        assert legC["replays"] >= 1, "the kill never forced a replay"
        assert legC["replays_pinned"] == legC["replays"], legC
        print("SMOKE OK")
    return 0


# ---------------------------------------------------------------------------
# --tenants: weighted-fair isolation + batched-adapter serving
# ---------------------------------------------------------------------------


def tenant_legs(args, serving, faults, model, scenario):
    """Two legs over the same seeded flash crowd, redrawn 4:1 across a
    noisy bronze tenant ("crowd") and a gold victim ("steady"), against
    a fixed 2-replica weighted-fair fleet whose engines decode a live
    batched LoRA bank (one adapter per tenant, gathered per slot inside
    the single decode trace):

    - **tenants-isolation** — the crowd floods its own DRR share while
      steady (weight 4) keeps flowing: steady's p99 must stay within
      ITS SLO, nothing sheds, and the adapter install retraced nothing.
    - **tenants-chaos** — the same replay with ``serving.admit_tenant``
      drops injected on the crowd (per-tenant shed accounting must be
      EXACT: client-observed sheds == the tenant's shed counter == the
      planned drops; steady sheds zero) while a mid-leg adapter rollout
      faults at its wave swap (``serving.adapter_swap``) and must roll
      back all-or-nothing with the OLD bank serving bitwise.
    """
    from paddle_tpu.serving import workload
    from paddle_tpu.serving.tenancy import (
        AdapterRollout, ArtifactCatalog, TenantDirectory, TenantSpec)

    # same swing curve, arrivals now drawn 4:1 crowd:steady; the victim
    # rides the top priority class, but the isolation teeth are in the
    # DRR share — priority never reorders a tenant's own FIFO
    sdict = scenario.to_dict()
    sdict["name"] += "-tenants"
    sdict["tenants"] = {"crowd": {"weight": 4.0},
                        "steady": {"weight": 1.0, "priority": 2}}
    scenario = workload.Scenario.from_dict(sdict)

    n_adapters, rank = 3, 4
    adapter_of = {"steady": 1, "crowd": 2}

    def banks(seed, scale):
        """Stacked [N, r, H] / [N, V, r] f32 banks; row 0 stays all-zero
        (adapter id 0 = the base model, bitwise)."""
        rng = np.random.RandomState(seed)
        la = np.zeros((n_adapters, rank, args.hidden), np.float32)
        lb = np.zeros((n_adapters, args.vocab, rank), np.float32)
        for i in range(1, n_adapters):
            la[i] = rng.normal(0.0, scale, (rank, args.hidden))
            lb[i] = rng.normal(0.0, scale, (args.vocab, rank))
        return la, lb

    def fleet(name):
        # fresh TenantDirectory per fleet: buckets/deficits are live
        # state. brownout_tier=0 = never tier-shed — this bench
        # certifies exactly-once over EVERY arrival (the tier-shed
        # teeth are unit-tested in test_tenancy.py); no budgets either,
        # so every shed in the chaos leg is one of OUR injected drops
        tenancy = TenantDirectory([
            TenantSpec("steady", weight=4.0, priority=2,
                       slo_class="gold", slo_p99_ms=args.tenant_slo_ms),
            TenantSpec("crowd", weight=1.0, slo_class="bronze"),
        ], brownout_tier=0)
        return serving.Router(
            model, 2,
            engine_kw=dict(max_slots=args.max_slots,
                           max_seq_len=args.max_seq_len,
                           block_size=args.block_size,
                           max_adapters=n_adapters, lora_rank=rank),
            tenancy=tenancy,
            queue_cap=args.queue_cap, hedge=False, retry_budget=3,
            liveness_timeout_s=30.0, backoff_base_s=0.05,
            brownout_priority=0, name=name).start()

    la1, lb1 = banks(seed=29, scale=0.5)
    probe = np.random.RandomState(5).randint(
        0, args.vocab, (6,)).astype(np.int32)

    def run_tenant_leg(router, label, during=None):
        trace = scenario.trace()
        lock = threading.Lock()
        reqs = {}
        t0 = time.monotonic()

        def submit(a):
            fut = router.submit(a.prompt, max_new_tokens=a.max_new,
                                priority=a.priority, tenant=a.tenant,
                                adapter_id=adapter_of.get(a.tenant, 0),
                                timeout=120.0)
            info = {"tenant": a.tenant,
                    "t_submit": time.monotonic() - t0, "done": 0,
                    "lat_s": None, "ok": False, "err": None}
            with lock:
                reqs[fut.id] = info

            def cb(f, info=info):
                with lock:
                    info["done"] += 1
                    info["lat_s"] = time.monotonic() - t0 \
                        - info["t_submit"]
                    info["ok"] = f._error is None
                    info["err"] = None if f._error is None \
                        else type(f._error).__name__
            fut.add_done_callback(cb)
            return fut

        during_out, dthread = {}, None
        if during is not None:
            def _during():
                try:
                    during_out["result"] = during()
                except Exception as e:  # noqa: BLE001 — in the row
                    during_out["error"] = f"{type(e).__name__}: {e}"
            dthread = threading.Thread(target=_during, daemon=True)
            dthread.start()
        records = workload.replay(submit, trace,
                                  time_scale=args.time_scale)
        for r in records:
            if r["future"] is not None:
                try:
                    r["future"].result(120.0)
                except Exception:  # noqa: BLE001 — typed failures count
                    pass
        if dthread is not None:
            dthread.join(240.0)
            if dthread.is_alive():
                during_out["error"] = "during-thunk still running"
        wall = time.monotonic() - t0
        compiles = router.compile_counts()

        with lock:
            rows = list(reqs.values())
        # a synchronous shed never produced a future: replay recorded
        # the raise; fold it in as a resolved (done once) outcome
        for r in records:
            if r["error"] is not None:
                rows.append({"tenant": r["arrival"].tenant,
                             "t_submit": r["t_submit"], "done": 1,
                             "lat_s": None, "ok": False,
                             "err": type(r["error"]).__name__})
        per_tenant = {}
        for t in sorted({r["tenant"] for r in rows}):
            sub = [r for r in rows if r["tenant"] == t]
            ok = [r for r in sub if r["ok"]]
            shed = [r for r in sub if r["err"] == "TenantBudgetError"]
            lats = [r["lat_s"] for r in ok if r["lat_s"] is not None]
            per_tenant[t] = {
                "submitted": len(sub),
                "ok": len(ok),
                "shed": len(shed),
                "failed_other": len(sub) - len(ok) - len(shed),
                "p50_ms": round(percentile(lats, 50) * 1e3, 3),
                "p99_ms": round(percentile(lats, 99) * 1e3, 3),
                "shed_counter": router.metrics.tenant_get(t, "shed"),
            }
        total = len(rows)
        ok_n = sum(pt["ok"] for pt in per_tenant.values())
        shed_n = sum(pt["shed"] for pt in per_tenant.values())
        row = {
            "leg": label,
            "arrivals": len(trace),
            "requests_ok": ok_n,
            "shed": shed_n,
            "lost": sum(1 for r in rows if r["done"] == 0),
            "duplicated": sum(1 for r in rows if r["done"] > 1),
            "goodput": round(ok_n / total, 4) if total else 0.0,
            # the injected sheds are deterministic 429s, not losses:
            # everything ADMITTED must land exactly once
            "goodput_served": round(ok_n / (total - shed_n), 4)
                if total > shed_n else 0.0,
            "wall_s": round(wall, 4),
            "tenants": per_tenant,
            "compiles_once": all(c == {"decode": 1, "cow": 1}
                                 for c in compiles.values()),
            "adapter_versions": sorted({
                r.engine.adapter_version
                for r in router.replica_set.replicas
                if r.state == "healthy"}),
        }
        if during is not None:
            row["during"] = during_out
        return row

    # -- leg A: isolation — the crowd floods, the victim's p99 holds --------
    router = fleet("ftenA")
    catalog = ArtifactCatalog()
    ro = AdapterRollout(router, catalog, name="tenant-adapters")
    ro.roll_to(la1, lb1, probe=probe)      # live install, zero retraces
    legA = run_tenant_leg(router, "tenants-isolation")
    legA["adapter_state"] = ro.state
    router.shutdown(drain=True)
    print(json.dumps(legA))

    # -- leg B: chaos — injected tenant sheds + a faulted adapter wave ------
    router = fleet("ftenB")
    catalog = ArtifactCatalog()
    ro = AdapterRollout(router, catalog, name="tenant-adapters")
    ro.roll_to(la1, lb1, probe=probe)      # v1 installs BEFORE the
    pre = np.asarray(router.generate(      # schedule: swap occurrences
        probe, max_new_tokens=6, tenant="steady", adapter_id=1,
        timeout=60.0))                     # below count from zero
    la2, lb2 = banks(seed=31, scale=0.25)
    drops = (5, 9, 14)                     # 5th/9th/14th crowd admission
    specs = ["serving.admit_tenant[crowd]@%d:drop" % k for k in drops]
    specs.append("serving.adapter_swap@2:raise")   # the WAVE swap (the
    ro2 = AdapterRollout(router, catalog,          # canary is occ 1) ->
                         name="tenant-adapters")   # auto-rollback
    with faults.ChaosSchedule(*specs) as sched:
        legB = run_tenant_leg(
            router, "tenants-chaos",
            during=lambda: ro2.roll_to(la2, lb2, timeout=60.0))
        fired = sched.verify()
    post = np.asarray(router.generate(
        probe, max_new_tokens=6, tenant="steady", adapter_id=1,
        timeout=60.0))
    legB["chaos_fired"] = fired
    legB["adapter_state"] = ro2.state
    legB["adapter_error"] = ro2.error
    legB["bank_bitwise_after_rollback"] = bool(
        pre.shape == post.shape and (pre == post).all())
    legB["catalog_serving"] = catalog.serving_version(
        "adapter", "tenant-adapters")
    router.shutdown(drain=True)
    print(json.dumps(legB))

    result = {
        "bench": "BENCH_FLEET_TENANTS",
        "scenario": scenario.to_dict(),
        "config": {"replicas": 2, "max_slots": args.max_slots,
                   "queue_cap": args.queue_cap,
                   "time_scale": args.time_scale,
                   "tenant_slo_ms": args.tenant_slo_ms,
                   "adapters": {"n": n_adapters, "rank": rank,
                                "by_tenant": adapter_of},
                   "model": {"vocab": args.vocab, "hidden": args.hidden,
                             "layers": args.layers, "heads": args.heads},
                   "chaos_specs": specs},
        "isolation": legA, "chaos": legB,
    }
    print(json.dumps(result))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)

    if args.smoke:
        for leg in (legA, legB):
            assert leg["lost"] == 0, f"{leg['leg']}: lost futures"
            assert leg["duplicated"] == 0, \
                f"{leg['leg']}: duplicated outcomes"
            assert leg["compiles_once"], \
                f"{leg['leg']}: an adapter swap retraced"
            assert leg["adapter_versions"] == [1], leg
            assert leg["goodput_served"] == 1.0, leg
            st = leg["tenants"]["steady"]
            assert st["shed"] == 0 and st["shed_counter"] == 0, st
            assert st["failed_other"] == 0, st
            assert st["p99_ms"] <= args.tenant_slo_ms, \
                (leg["leg"], st["p99_ms"], args.tenant_slo_ms)
        assert legA["shed"] == 0 and legA["goodput"] == 1.0, legA
        assert legA["adapter_state"] == "committed", legA
        cr = legB["tenants"]["crowd"]
        assert cr["shed"] == len(drops), cr          # client-observed
        assert cr["shed_counter"] == len(drops), cr  # metrics-side
        assert legB["chaos_fired"] == {
            "serving.admit_tenant": len(drops),
            "serving.adapter_swap": 1}, legB
        assert legB["adapter_state"] == "rolled_back", legB
        assert "FaultError" in (legB["adapter_error"] or ""), legB
        assert "error" in legB["during"], legB["during"]
        assert legB["bank_bitwise_after_rollback"], \
            "post-rollback adapter decode is not bitwise pre-rollout"
        assert legB["catalog_serving"] == 1, legB    # v2 retired
        print("SMOKE OK")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--trace", default=None,
                    help="scenario JSON (path or inline); default = "
                    "the canonical 10x swing built from --low/high-rps")
    ap.add_argument("--low-rps", type=float, default=6.0)
    ap.add_argument("--high-rps", type=float, default=60.0,
                    help="peak offered load (default 10x the base)")
    ap.add_argument("--low-s", type=float, default=3.0)
    ap.add_argument("--high-s", type=float, default=4.0)
    ap.add_argument("--arrival", default="poisson",
                    choices=("poisson", "burst", "heavy_tail"))
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--time-scale", type=float, default=1.0)
    ap.add_argument("--min-replicas", type=int, default=1)
    ap.add_argument("--max-replicas", type=int, default=4)
    ap.add_argument("--slo-ms", type=float, default=250.0,
                    help="e2e p99 SLO (autoscaler signal + violation "
                    "accounting)")
    ap.add_argument("--cooldown-s", type=float, default=0.5)
    ap.add_argument("--slo-window", type=int, default=64,
                    help="autoscaler p99 window (most recent samples)")
    ap.add_argument("--max-slots", type=int, default=2)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--queue-cap", type=int, default=256)
    ap.add_argument("--prompt-len", default="4,10")
    ap.add_argument("--max-new", default="12,16")
    ap.add_argument("--vocab", type=int, default=97)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--max-seq-len", type=int, default=64)
    ap.add_argument("--timeline", action="store_true",
                    help="include the (t, members, live) timeline per leg")
    ap.add_argument("--json", default=None,
                    help="write the final BENCH_FLEET object here")
    ap.add_argument("--no-chaos", action="store_true",
                    help="skip the chaos leg")
    ap.add_argument("--rollout", action="store_true",
                    help="run the zero-downtime rollout legs instead "
                    "of the autoscale legs: a rolling weight upgrade, "
                    "a bitwise auto-rollback, and a kill-mid-rollout "
                    "driven during the same surge")
    ap.add_argument("--tenants", action="store_true",
                    help="run the multi-tenant legs instead of the "
                    "autoscale legs: a weighted-fair flash crowd with "
                    "per-tenant adapters, then the same replay under "
                    "injected tenant sheds + a faulted adapter wave")
    ap.add_argument("--tenant-slo-ms", type=float, default=2000.0,
                    help="the victim (gold) tenant's e2e p99 SLO for "
                    "the --tenants legs")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny model + short trace + assert the "
                    "acceptance bar (tier-1 CPU case)")
    args = ap.parse_args(argv)

    if args.smoke:
        # heavy-enough decodes that the 10x+ swing actually saturates
        # one single-slot replica (queueing -> p99 over SLO -> scale-up)
        args.hidden, args.layers, args.heads = 64, 2, 4
        args.vocab, args.max_seq_len = 31, 64
        args.low_rps, args.high_rps = 2.5, 60.0
        args.low_s, args.high_s = 1.5, 2.5
        args.max_new = "12,16"
        args.max_slots, args.max_replicas = 1, 3
        args.slo_ms, args.cooldown_s = 150.0, 0.4
        if args.rollout or args.tenants:
            # two slots per replica: the fleet dips to one serving
            # replica while the other drains/rebuilds (rollout), or
            # absorbs the crowd's backlog in its own DRR share while
            # the victim keeps flowing (tenants); the surge must queue
            # (never shed) through that window
            args.max_slots = 2

    import paddle_tpu as paddle
    from paddle_tpu import serving
    from paddle_tpu.framework import faults
    from paddle_tpu.nlp.transformers import GPTConfig, GPTForPretraining
    from paddle_tpu.serving import workload

    paddle.seed(7)
    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers, num_heads=args.heads,
                    max_seq_len=args.max_seq_len, dropout=0.0,
                    attn_dropout=0.0, use_parallel=False)
    model = GPTForPretraining(cfg)
    model.eval()

    plen = tuple(int(x) for x in args.prompt_len.split(","))
    mnew = tuple(int(x) for x in args.max_new.split(","))
    if args.trace:
        scenario = workload.Scenario.from_json(args.trace)
    else:
        scenario = workload.Scenario.swing(
            low_rps=args.low_rps, high_rps=args.high_rps,
            low_s=args.low_s, high_s=args.high_s, arrival=args.arrival,
            seed=args.seed, vocab=args.vocab, prompt_len=plen,
            max_new=mnew)

    if args.rollout:
        return rollout_legs(args, serving, faults, model, scenario)
    if args.tenants:
        return tenant_legs(args, serving, faults, model, scenario)

    # -- leg 1: static fleet provisioned for the peak -----------------------
    router = make_router(serving, model, args, "fstatic",
                         autoscaled=False)
    static = run_leg(router, scenario, args, "static-peak")
    router.shutdown(drain=True)
    print(json.dumps(static))

    # -- leg 2: autoscaled --------------------------------------------------
    router = make_router(serving, model, args, "fauto", autoscaled=True)
    auto = run_leg(router, scenario, args, "autoscaled")
    auto["scaled_down_after"] = wait_scaled_down(router, args)
    router.shutdown(drain=True)
    print(json.dumps(auto))

    # -- leg 3: the autoscaled fleet under chaos fired DURING scale events --
    chaos_row = chaos_fired = None
    chaos_specs = [
        "serving.scale_up@1:delay:0.05",       # slow first grow
        "serving.scale_down@1:delay:0.02",     # slow first shrink
        "serving.drain@1:raise",               # first eviction attempt
                                               # fails; watchdog retries
        "serving.replica_step[fchaos.r0]@150:raise",  # crash a replica
                                               # mid-swing (failover)
    ]
    if not args.no_chaos:
        router = make_router(serving, model, args, "fchaos",
                             autoscaled=True)
        with faults.ChaosSchedule(*chaos_specs) as sched:
            chaos_row = run_leg(router, scenario, args, "chaos")
            chaos_row["scaled_down_after"] = wait_scaled_down(
                router, args)
            chaos_fired = sched.verify()   # fired == planned, per site
        chaos_row["chaos_fired"] = chaos_fired
        router.shutdown(drain=True)
        print(json.dumps(chaos_row))

    result = {
        "bench": "BENCH_FLEET",
        "scenario": scenario.to_dict(),
        "config": {
            "min_replicas": args.min_replicas,
            "max_replicas": args.max_replicas,
            "slo_ms": args.slo_ms, "cooldown_s": args.cooldown_s,
            "max_slots": args.max_slots, "queue_cap": args.queue_cap,
            "time_scale": args.time_scale,
            "model": {"vocab": args.vocab, "hidden": args.hidden,
                      "layers": args.layers, "heads": args.heads},
            "chaos_specs": None if args.no_chaos else chaos_specs,
        },
        "static": static,
        "autoscaled": auto,
        "chaos": chaos_row,
        "chip_hours_saved": round(
            static["chip_hours"] - auto["chip_hours"], 6),
        "chip_fraction_vs_static": round(
            auto["chip_s"] / static["chip_s"], 4) if static["chip_s"]
            else None,
        "chaos_goodput": None if chaos_row is None
            else chaos_row["goodput"],
    }
    print(json.dumps(result))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)

    if args.smoke:
        for leg in filter(None, (static, auto, chaos_row)):
            assert leg["lost"] == 0, f"{leg['leg']}: lost futures"
            assert leg["duplicated"] == 0, \
                f"{leg['leg']}: duplicated outcomes"
        assert static["goodput"] == 1.0, static
        assert auto["goodput"] == 1.0, auto
        assert auto["compiles_once"], "a scale-up retraced"
        assert auto["scale_ups"] >= 1, "autoscaler never grew the fleet"
        assert auto["scaled_down_after"], \
            "autoscaler never drained back to the floor"
        assert auto["chip_s"] < static["chip_s"], \
            (auto["chip_s"], static["chip_s"])
        if chaos_row is not None:
            assert chaos_row["goodput"] == 1.0, chaos_row
            for site in ("serving.scale_up", "serving.scale_down",
                         "serving.drain", "serving.replica_step"):
                assert chaos_fired.get(site, 0) >= 1, (site, chaos_fired)
        print("SMOKE OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
