"""Gang-supervised training chaos certification bench (ISSUE 14).

Runs a real N-process CPU gang (separate python processes under a
GangSupervisor, gradients averaged cross-rank over the p2p mailbox,
checkpoints globally committed through GangCheckpointManager's commit
barrier) through four legs:

  clean   uninterrupted run -> the reference loss trajectory
  kill    SIGKILL one rank MID-COLLECTIVE (its peer is blocked inside
          the all-reduce); the survivor unblocks via its
          FLAGS_dist_timeout_s deadline with a typed retriable error,
          the supervisor tears the gang down and restarts it from the
          newest globally committed step
  hang    one rank goes silent (alive, no heartbeat/step progress); the
          supervisor's watermark stall detector restarts the gang
  chaos   scripted fault sweep inside every rank (delayed collectives /
          barriers / p2p, a dropped heartbeat) over a clean completion;
          each rank certifies fired == planned from its own counters

Every recovering leg must reproduce the clean run's per-step loss
trajectory BITWISE (last execution of each step wins), and every leg
must complete every planned step (goodput 1.0). Prints one BENCH_GANG
JSON line; ``--smoke`` shrinks the step counts and asserts the gates.

Worker mode (internal): ``python bench_gang.py --worker <out_dir>`` is
what the supervisor spawns per rank.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import time

WORLD = 2
LR = 0.05
CKPT_EVERY = 3

#: per-rank chaos sweep for the chaos leg (PADDLE_TPU_FAULTS in every
#: worker) and the per-rank fired plan it must deliver exactly
CHAOS_SPECS = ("dist.allreduce@3:delay:0.05;"
               "dist.barrier@2:delay:0.02;"
               "dist.p2p_send@4:delay:0.02;"
               "dist.p2p_recv@6:delay:0.02;"
               "gang.heartbeat@2:drop")
CHAOS_PLAN = {"faults.dist.allreduce": 1, "faults.dist.barrier": 1,
              "faults.dist.p2p_send": 1, "faults.dist.p2p_recv": 1,
              "faults.gang.heartbeat": 1}


# ---------------------------------------------------------------------------
# worker (one rank)
# ---------------------------------------------------------------------------


def _batch(rank, step):
    import numpy as np

    rng = np.random.RandomState(1000 + 97 * step + rank)
    return rng.randn(8, 4), rng.randn(8)


def worker(out_dir):
    import numpy as np

    from paddle_tpu.distributed import preempt
    from paddle_tpu.distributed.checkpoint import GangCheckpointManager
    from paddle_tpu.distributed.gang import (
        CollectiveTimeoutError, GangWorker, PeerGoneError, allreduce_host)
    from paddle_tpu.framework import monitor

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    world = int(os.environ["PADDLE_TRAINERS_NUM"])
    attempt = int(os.environ.get("PADDLE_GANG_ATTEMPT", "1"))
    steps = int(os.environ.get("GANG_BENCH_STEPS", "12"))
    kill_rank = int(os.environ.get("GANG_BENCH_KILL_RANK", "-1"))
    kill_step = int(os.environ.get("GANG_BENCH_KILL_STEP", "-1"))
    hang_rank = int(os.environ.get("GANG_BENCH_HANG_RANK", "-1"))
    hang_step = int(os.environ.get("GANG_BENCH_HANG_STEP", "-1"))

    preempt.install()  # SIGTERM defers: blocked collectives hit their
    # deadline and exit typed instead of dying silent mid-teardown
    gw = GangWorker()
    mgr = GangCheckpointManager(os.path.join(out_dir, "ckpt"), rank,
                                world)
    w = np.linspace(0.1, 0.4, 4)
    start = 0
    if mgr.latest_committed_step() is not None:
        got_step, st = mgr.restore({"w": w})
        w, start = np.asarray(st["w"]), got_step + 1
    lossf = open(os.path.join(out_dir, f"losses.r{rank}.log"), "a")
    try:
        for step in range(start, steps):
            gw.beat(step=step)
            if rank == hang_rank and step == hang_step and attempt == 1:
                while True:  # alive but silent: the stall-detector leg
                    time.sleep(0.5)
            if rank == kill_rank and step == kill_step and attempt == 1:
                time.sleep(0.3)  # let the peer block inside the
                os.kill(os.getpid(), signal.SIGKILL)  # collective first
            x, y = _batch(rank, step)
            err = x @ w - y
            g = (2.0 / len(y)) * (x.T @ err)
            g = allreduce_host(g, "mean", rank=rank, world=world)
            w = w - LR * g
            loss = allreduce_host(np.asarray(np.mean(err * err)),
                                  "mean", rank=rank, world=world)
            if rank == 0:
                lossf.write(f"{step} {float(loss).hex()}\n")
                lossf.flush()
            if (step + 1) % CKPT_EVERY == 0:
                mgr.save(step, {"w": w})
    except (CollectiveTimeoutError, PeerGoneError) as e:
        # the acceptance-criterion moment: a peer died mid-collective
        # and this rank UNBLOCKED via its deadline with a typed error
        with open(os.path.join(out_dir, f"typed.r{rank}.log"), "a") as f:
            f.write(f"{type(e).__name__}\n")
        sys.exit(13)
    with open(os.path.join(out_dir, f"faults.r{rank}.a{attempt}.json"),
              "w") as f:
        json.dump(monitor.stats("faults."), f)
    return 0


# ---------------------------------------------------------------------------
# legs (supervisor side)
# ---------------------------------------------------------------------------


def _losses(out_dir):
    """step -> loss hex, LAST execution of each step wins (re-executed
    steps after a restore must overwrite identically for bitwise)."""
    out = {}
    path = os.path.join(out_dir, "losses.r0.log")
    if os.path.exists(path):
        for line in open(path):
            step, hexval = line.split()
            out[int(step)] = hexval
    return out


def run_leg(name, steps, *, kill=None, hang=None, chaos=False):
    from paddle_tpu.distributed.gang import GangSupervisor
    from paddle_tpu.framework import monitor

    out = tempfile.mkdtemp(prefix=f"paddle-gang-{name}-")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "GANG_BENCH_STEPS": str(steps),
        # hang leg: worker deadlines far ABOVE the supervisor's stall
        # threshold so the restart is attributed by the watermark
        # detector, not a collective timeout racing it
        "FLAGS_dist_timeout_s": "30.0" if hang else "1.0",
    })
    if kill:
        env["GANG_BENCH_KILL_RANK"] = str(kill[0])
        env["GANG_BENCH_KILL_STEP"] = str(kill[1])
    if hang:
        env["GANG_BENCH_HANG_RANK"] = str(hang[0])
        env["GANG_BENCH_HANG_STEP"] = str(hang[1])
    if chaos:
        env["PADDLE_TPU_FAULTS"] = CHAOS_SPECS
    sup = GangSupervisor(
        [sys.executable, "-u", os.path.abspath(__file__), "--worker",
         out],
        WORLD, gang_dir=os.path.join(out, "gang"),
        max_restarts=2, hang_secs=2.0 if hang else 0.0,
        grace_s=6.0, poll_interval=0.05, backoff_base_s=0.05,
        backoff_max_s=0.1, base_env=env,
        log_dir=os.path.join(out, "logs"))
    lost0 = monitor.stat_get("gang.restart_lost_ms")
    t0 = time.perf_counter()
    code = sup.run()
    wall_s = time.perf_counter() - t0
    if code != 0:
        for slot in range(WORLD):
            p = os.path.join(out, "logs", f"workerlog.{slot}")
            if os.path.exists(p):
                sys.stderr.write(open(p).read()[-2000:])
        raise SystemExit(f"gang leg {name!r} failed with code {code}")
    return {
        "out": out,
        "losses": _losses(out),
        "wall_s": wall_s,
        "restarts": sup.restarts,
        "restart_lost_s":
            (monitor.stat_get("gang.restart_lost_ms") - lost0) / 1e3,
    }


def _typed_errors(out_dir):
    names = []
    for slot in range(WORLD):
        p = os.path.join(out_dir, f"typed.r{slot}.log")
        if os.path.exists(p):
            names += open(p).read().split()
    return names


def _chaos_fired(out_dir):
    """Per-rank fired counters from the workers' exit dumps."""
    fired = {}
    for slot in range(WORLD):
        p = os.path.join(out_dir, f"faults.r{slot}.a1.json")
        with open(p) as f:
            fired[slot] = {k: v for k, v in json.load(f).items()
                           if k in CHAOS_PLAN}
    return fired


def main():
    smoke = "--smoke" in sys.argv
    if "--worker" in sys.argv:
        sys.exit(worker(sys.argv[sys.argv.index("--worker") + 1]))

    from paddle_tpu.framework import faults

    steps = 8 if smoke else 12
    kill_at, hang_at = (4, 3) if smoke else (7, 4)

    clean = run_leg("clean", steps)
    assert len(clean["losses"]) == steps, clean["losses"]

    # the SIGKILL-mid-collective leg also certifies the supervisor-side
    # gang.restart site fired exactly as planned
    with faults.ChaosSchedule("gang.restart@1:delay:0.01") as ch:
        kill = run_leg("kill", steps, kill=(1, kill_at))
        restart_fired = ch.verify()
    hang = run_leg("hang", steps, hang=(1, hang_at))
    chaos = run_leg("chaos", steps, chaos=True)

    bitwise_kill = kill["losses"] == clean["losses"]
    bitwise_hang = hang["losses"] == clean["losses"]
    bitwise_chaos = chaos["losses"] == clean["losses"]
    typed = _typed_errors(kill["out"])
    chaos_fired = _chaos_fired(chaos["out"])
    fired_equals_planned = all(
        rankfired.get(k, 0) == want
        for rankfired in chaos_fired.values()
        for k, want in CHAOS_PLAN.items()) and \
        restart_fired.get("gang.restart") == 1
    # goodput: every planned step completed on every leg despite chaos
    goodput = min(len(leg["losses"]) for leg in
                  (clean, kill, hang, chaos)) / steps

    out = {
        "metric": "gang_chaos_certification",
        "value": goodput,
        "unit": "goodput_steps_completed",
        "bitwise_equal_kill": bitwise_kill,
        "bitwise_equal_hang": bitwise_hang,
        "bitwise_equal_chaos": bitwise_chaos,
        "typed_errors_kill": typed,
        "restarts": {"kill": kill["restarts"], "hang": hang["restarts"]},
        "recovery_s": {"kill": round(kill["restart_lost_s"], 3),
                       "hang": round(hang["restart_lost_s"], 3)},
        "fired_equals_planned": fired_equals_planned,
        "chaos_fired_per_rank": {str(k): v
                                 for k, v in chaos_fired.items()},
        "clean_wall_s": round(clean["wall_s"], 3),
        "world": WORLD, "steps": steps,
    }
    print("BENCH_GANG " + json.dumps(out))

    failures = []
    if not (bitwise_kill and bitwise_hang and bitwise_chaos):
        failures.append("loss trajectory diverged from the clean run")
    if goodput != 1.0:
        failures.append(f"steps lost: goodput {goodput}")
    if not fired_equals_planned:
        failures.append(f"chaos under-delivered: {chaos_fired}")
    if kill["restarts"] != 1 or hang["restarts"] != 1:
        failures.append(f"unexpected restart counts {out['restarts']}")
    if not any(n in ("PeerGoneError", "CollectiveTimeoutError")
               for n in typed):
        failures.append("survivor never raised a typed deadline error")
    if failures:
        raise SystemExit("; ".join(failures))


if __name__ == "__main__":
    main()
