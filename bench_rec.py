"""Recommender-serving bench: zipfian CTR ranking over the durable PS
(ISSUE 11 / ROADMAP item 4).

Two phases, ONE ``BENCH_REC`` JSON line:

* **Load** — a `rec.RankingService` (wide&deep, PS-cached embeddings,
  SSD sparse tables holding many times the cache-resident rows) serves
  zipfian-keyed ranking waves while an `rec.OnlineTrainer` streams
  click batches through the Communicator's geo mode underneath.
  Reports QPS, p50/p99, cache hit rate, and the staleness histogram of
  served reads (every bucket must sit within `FLAGS_ps_geo_staleness`).

* **Chaos** — the same serve-while-training workload over a WAL +
  replica stack, with scripted mid-push faults, and the PS primary's
  transport killed mid-stream WHILE ranking futures are in flight.
  Certification: ``chaos_goodput == 1.0`` (every submitted ranking
  request completes exactly once — futures are first-wins), the
  ChaosSchedule delivered exactly its plan, and the post-failover pull
  digests of both embedding tables are BITWISE equal to an
  uninterrupted clean run with identical durability config (exactly-
  once pushes across retries and failover).

Small-footprint smoke: ``python bench_rec.py --smoke`` shrinks every
knob (used by the tier-1 subprocess test).
"""

from __future__ import annotations

import hashlib
import json
import sys
import tempfile
import time

import numpy as np

DIM = 16
SLOTS = 8
ZIPF_A = 1.2

# load phase
N_IDS = 20_000          # logical id space (SSD table rows)
CACHE_ROWS = 1_024      # device-cache capacity: ~20x fewer than the table
WAVES = 30
WAVE = 64
MAX_BATCH = 16

# chaos phase
CHAOS_IDS = 600
CHAOS_CACHE = 256
CHAOS_FEEDS = 12
CHAOS_WAVE = 16
CHAOS_BATCH = 16
CHAOS_SLOTS = 4


def _zipf_ids(rng, n, size):
    return ((rng.zipf(ZIPF_A, size) - 1) % n).astype(np.int64)


def _mk_runtime(eps, mode, *, backups=None, geo_step=4, **client_kw):
    from paddle_tpu.distributed import ps
    from paddle_tpu.distributed.ps.service import Communicator

    rm = ps.PSRoleMaker(server_endpoints=eps, role="TRAINER",
                        trainer_id=0, n_trainers=1)
    rt = ps.PSRuntime(rm, mode=mode)
    rt._client = ps.PSClient(eps, backups=backups, **client_kw)
    rt._communicator = Communicator(rt._client, mode=mode,
                                    geo_step=geo_step).start()
    return rt


def _close_runtime(rt):
    try:
        rt._communicator.stop()
    except Exception:  # noqa: BLE001 — a dead primary can fail the drain
        pass
    rt._client.close()


def _build_rec_stack(serve_rt, train_rt, *, n_ids, cache_rows, slots,
                     dnn_dims=(32, 16), max_batch=MAX_BATCH,
                     queue_cap=512, max_wait_s=0.001):
    """Serving service + online trainer over shared SSD-backed tables.

    Separate PS clients/runtimes on purpose: serving pulls must ride
    their own failover without perturbing the trainer's deterministic
    push order (the bitwise-digest certification depends on it)."""
    from paddle_tpu import rec
    from paddle_tpu.distributed import ps
    from paddle_tpu.serving.metrics import ServingMetrics

    def caches(rt):
        deep = ps.TPUEmbeddingCache("rec_deep", DIM, capacity=cache_rows,
                                    init_range=0.01, runtime=rt,
                                    storage="ssd", mem_rows=cache_rows)
        wide = ps.TPUEmbeddingCache("rec_wide", 1, capacity=cache_rows,
                                    init_range=0.01, runtime=rt,
                                    storage="ssd", mem_rows=cache_rows)
        return deep, wide

    s_deep, s_wide = caches(serve_rt)
    model = rec.WideDeepCTR(n_ids, n_ids, embed_dim=DIM,
                            dnn_dims=dnn_dims, deep_embedding=s_deep,
                            wide_embedding=s_wide)
    svc = rec.RankingService(model, max_batch=max_batch,
                             max_wait_s=max_wait_s, queue_cap=queue_cap,
                             metrics=ServingMetrics())
    zero = np.zeros(slots, np.int64)
    svc.warmup(zero, zero)
    svc.start()

    t_deep, t_wide = caches(train_rt)
    tmodel = rec.WideDeepCTR(n_ids, n_ids, embed_dim=DIM,
                             dnn_dims=dnn_dims, deep_embedding=t_deep,
                             wide_embedding=t_wide)
    trainer = rec.OnlineTrainer(tmodel, runtime=train_rt,
                                invalidate=[s_deep, s_wide])
    return svc, trainer, s_deep, s_wide


def run_load(waves=WAVES, wave=WAVE, n_ids=N_IDS, cache_rows=CACHE_ROWS,
             batch_size=32):
    """Zipfian serving + online learning against one plain PS."""
    from paddle_tpu import rec
    from paddle_tpu.distributed import ps

    srv = ps.PSServer("127.0.0.1:0").start()
    eps = [srv.endpoint]
    serve_rt = _mk_runtime(eps, "sync")
    train_rt = _mk_runtime(eps, "geo", geo_step=2)
    svc, trainer, s_deep, s_wide = _build_rec_stack(
        serve_rt, train_rt, n_ids=n_ids, cache_rows=cache_rows,
        slots=SLOTS)

    rng = np.random.RandomState(11)
    feed = rec.synthetic_ctr_reader(waves, batch_size=batch_size,
                                    dnn_dim=n_ids, lr_dim=n_ids,
                                    slots=SLOTS, seed=12)
    n_requests = 0
    t0 = time.perf_counter()
    for clicks in feed:
        dq = _zipf_ids(rng, n_ids, (wave, SLOTS))
        lq = _zipf_ids(rng, n_ids, (wave, SLOTS))
        futs = [svc.submit(dq[i], lq[i]) for i in range(wave)]
        trainer.feed(*clicks)     # embeddings move under the in-flight wave
        for f in futs:
            f.result(60)
        n_requests += wave
    trainer.flush()
    elapsed = time.perf_counter() - t0

    lat = svc.metrics.snapshot().get("latency_s", {}).get("e2e", {})
    snap = svc.snapshot()
    hist = snap["caches"]["deep"]["staleness_hist"]
    out = {
        "qps": round(n_requests / elapsed, 1),
        "p50_ms": round(lat.get("p50", 0.0) * 1e3, 3),
        "p99_ms": round(lat.get("p99", 0.0) * 1e3, 3),
        "cache_hit_rate": round(s_deep.hit_rate, 4),
        "cache_rows": cache_rows,
        "table_rows": n_ids,
        "ssd_over_cache_x": round(n_ids / cache_rows, 1),
        "requests": n_requests,
        "score_compiles": snap["score_compiles"],
        "staleness_hist": {str(k): v for k, v in sorted(hist.items())},
        "max_served_staleness": s_deep.max_served_staleness,
        "invalidations": s_deep.invalidations + s_wide.invalidations,
        "refreshes": s_deep.refreshes + s_wide.refreshes,
    }
    svc.close()
    _close_runtime(serve_rt)
    _close_runtime(train_rt)
    srv.stop()
    return out


def _chaos_workload(svc, trainer, feeds, *, n_ids, slots, wave,
                    batch_size, kill_at=None, primary=None):
    """Deterministic serve-while-training stream; returns goodput.

    Requests are submitted BEFORE the feed each round, so when the
    primary dies at round `kill_at` there are ranking futures in flight
    riding the failover alongside the trainer's pushes."""
    from paddle_tpu import rec

    rng = np.random.RandomState(21)
    submitted = completed = 0
    stream = rec.synthetic_ctr_reader(feeds, batch_size=batch_size,
                                      dnn_dim=n_ids, lr_dim=n_ids,
                                      slots=slots, seed=22)
    recovery_s = None
    for k, clicks in enumerate(stream):
        dq = _zipf_ids(rng, n_ids, (wave, slots))
        lq = _zipf_ids(rng, n_ids, (wave, slots))
        futs = [svc.submit(dq[i], lq[i]) for i in range(wave)]
        submitted += wave
        if kill_at is not None and k == kill_at:
            # transport vanishes mid-stream: the in-flight ranking wave
            # AND this round's pushes must ride the failover
            t_kill = time.perf_counter()
            primary.kill_transport()
        trainer.feed(*clicks)
        if kill_at is not None and k == kill_at:
            recovery_s = time.perf_counter() - t_kill
        for f in futs:
            f.result(120)
            completed += 1
    trainer.flush()
    return submitted, completed, recovery_s


def _pull_digest(client, n_ids):
    probe = np.arange(n_ids, dtype=np.int64)
    h = hashlib.sha256()
    for table in ("rec_deep", "rec_wide"):
        h.update(client.pull_sparse(table, probe).tobytes())
    return h.hexdigest()


def run_chaos(feeds=CHAOS_FEEDS, n_ids=CHAOS_IDS,
              cache_rows=CHAOS_CACHE):
    """Mid-push primary kill WHILE serving, certified against a clean
    run: exactly-once pushes, zero lost/dup requests, bitwise digests."""
    import paddle_tpu
    from paddle_tpu.distributed import ps
    from paddle_tpu.framework import faults, monitor

    def stack(wal_dir):
        # identical dense towers in the clean and chaos stacks: the
        # sparse deltas certified below are d(loss)/d(rows) THROUGH the
        # dense net, so its init must match bitwise across both runs
        paddle_tpu.seed(777)
        backup = ps.PSServer("127.0.0.1:0").start()
        primary = ps.PSServer("127.0.0.1:0", wal_dir=wal_dir,
                              backup=backup.endpoint).start()
        eps = [primary.endpoint]
        kw = dict(backups=[backup.endpoint], retry_backoff_s=0.01,
                  op_deadline_s=60.0)
        serve_rt = _mk_runtime(eps, "sync", **kw)
        train_rt = _mk_runtime(eps, "geo", geo_step=2, **kw)
        svc, trainer, s_deep, s_wide = _build_rec_stack(
            serve_rt, train_rt, n_ids=n_ids, cache_rows=cache_rows,
            slots=CHAOS_SLOTS, dnn_dims=(16,), max_batch=8,
            queue_cap=256)
        return backup, primary, serve_rt, train_rt, svc, trainer

    wl = dict(n_ids=n_ids, slots=CHAOS_SLOTS, wave=CHAOS_WAVE,
              batch_size=CHAOS_BATCH)

    with tempfile.TemporaryDirectory() as d_ref, \
            tempfile.TemporaryDirectory() as d:
        # clean reference: identical durability config (WAL + replica),
        # identical streams, no faults, no kill
        backup, primary, serve_rt, train_rt, svc, trainer = stack(d_ref)
        t0 = time.perf_counter()
        n, c, _ = _chaos_workload(svc, trainer, feeds, **wl)
        clean_s = time.perf_counter() - t0
        assert n == c, f"clean run lost requests: {c}/{n}"
        want = _pull_digest(serve_rt._client, n_ids)
        svc.close()
        _close_runtime(serve_rt)
        _close_runtime(train_rt)
        primary.stop()
        backup.stop()

        dedup0 = monitor.stat_get("ps.dedup_hits")
        fo0 = monitor.stat_get("ps.failovers")
        specs = ["ps.push@6:raise", "ps.push@10:raise",
                 "rec.score@2:delay:0.001", "rec.embed_pull@3:delay:0.001",
                 "rec.online_push@1:delay:0.001"]
        t0 = time.perf_counter()
        with faults.ChaosSchedule(*specs) as chaos:
            backup, primary, serve_rt, train_rt, svc, trainer = stack(d)
            n, c, recovery_s = _chaos_workload(
                svc, trainer, feeds, kill_at=feeds // 2, primary=primary,
                **wl)
            fired = chaos.verify()   # fired == planned or AssertionError
        chaos_s = time.perf_counter() - t0
        got = _pull_digest(serve_rt._client, n_ids)
        svc.close()
        _close_runtime(serve_rt)
        _close_runtime(train_rt)
        try:
            primary.stop()
        except Exception:  # noqa: BLE001 — transport already dead
            pass
        backup.stop()

        out = {
            "chaos_goodput": round(c / n, 4),
            "chaos_submitted": n,
            "chaos_completed": c,
            "digest_bitwise_equal": got == want,
            "pull_digest": got[:16],
            "recovery_s": round(recovery_s, 4),
            "clean_s": round(clean_s, 3),
            "chaos_s": round(chaos_s, 3),
            "dedup_hits": monitor.stat_get("ps.dedup_hits") - dedup0,
            "failovers": monitor.stat_get("ps.failovers") - fo0,
            "chaos_fired": fired,
        }
        if not out["digest_bitwise_equal"]:
            print("BENCH_REC " + json.dumps({"error": "digest", **out}))
            raise SystemExit("chaos run diverged from the clean run")
        if out["chaos_goodput"] != 1.0:
            print("BENCH_REC " + json.dumps({"error": "goodput", **out}))
            raise SystemExit("ranking requests lost under chaos")
        return out


def main():
    smoke = "--smoke" in sys.argv
    if smoke:
        load = run_load(waves=4, wave=8, n_ids=400, cache_rows=128,
                        batch_size=8)
        chaos = run_chaos(feeds=6, n_ids=200, cache_rows=96)
    else:
        load = run_load()
        chaos = run_chaos()
    out = {"metric": "rec_serving", "unit": "qps",
           "value": load["qps"], **load, **chaos}
    print("BENCH_REC " + json.dumps(out))


if __name__ == "__main__":
    main()
