"""Perf attribution for the ERNIE train step (not the driver bench).

Times variants with the same differenced scan-N method as bench.py to
locate where step time goes: full step (default dispatch — the Pallas
flash kernel at seq >= 128), dropout off, and forced pallas/jnp paths
for kernel-vs-XLA comparisons.

The `attrib` variant skips the timing sweep and instead captures an
xplane trace of the running step, printing the device-time bucket split
(observe.attribute) plus the collective-overlap pairing
(observe.overlap_report).  A capture whose device plane holds no
classifiable op rows is a broken capture, not a zero measurement — the
variant exits nonzero with a message instead of printing a JSON line
full of silent zeros.
"""

import json
import os
import sys
import time

import numpy as np


def _timed_scan_ms(eng, ids, labels, *, n1, reps):
    """Differenced-scan ms/step shared by every variant: scan n1 and
    3*n1 steps inside one jit each (true step-to-step data dependency),
    difference paired timings so dispatch/tunnel overhead cancels, min
    over `reps` pairs."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from paddle_tpu import amp
    from paddle_tpu.framework import random as _random

    raw = eng._step_fn._raw_step_fn
    xj, yj = jnp.asarray(ids), jnp.asarray(labels)
    lr = jnp.asarray(1e-4, jnp.float32)
    key = _random.default_generator.next_key()
    st = eng.state

    def make(n):
        @jax.jit
        def run(params, buffers, opt_state):
            def body(carry, i):
                p, b, o = carry
                with amp.auto_cast(enable=True, dtype="bfloat16"):
                    loss, p2, b2, o2 = raw(
                        p, b, o, {"inputs": (xj,), "labels": (yj,)},
                        lr, jax.random.fold_in(key, i))
                return (p2, b2, o2), loss
            (p, b, o), losses = lax.scan(
                body, (params, buffers, opt_state), jnp.arange(n))
            return losses[-1]
        return run

    r1, r2 = make(n1), make(3 * n1)
    for r in (r1, r2):
        float(np.asarray(r(st.params, st.buffers, st.opt_state)))
    diffs = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(np.asarray(r1(st.params, st.buffers, st.opt_state)))
        t1 = time.perf_counter()
        float(np.asarray(r2(st.params, st.buffers, st.opt_state)))
        t2 = time.perf_counter()
        diffs.append((t2 - t1) - (t1 - t0))
    return min(diffs) / (2 * n1) * 1e3


def main():
    import jax
    jax.config.update("jax_default_prng_impl", "rbg")
    import jax.numpy as jnp
    from jax import lax

    import paddle_tpu as paddle
    from paddle_tpu import amp
    from paddle_tpu.engine import Engine
    from paddle_tpu.framework import random as _random
    from paddle_tpu.nlp.transformers import (
        ErnieConfig, ErnieForPretraining, ErniePretrainingCriterion,
    )

    batch = int(os.environ.get("BENCH_BATCH", "32"))
    seq = 512
    iters = 16

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 18000, (batch, seq)).astype(np.int32)
    labels = ids.copy()
    labels[rng.rand(batch, seq) > 0.15] = -100

    def build(dropout, force_attn=None, mesh=None):
        if force_attn:
            os.environ["PADDLE_TPU_FLASH_FORCE"] = force_attn
        else:
            os.environ.pop("PADDLE_TPU_FLASH_FORCE", None)
        paddle.seed(0)
        cfg = ErnieConfig(vocab_size=18000, hidden_size=768, num_layers=12,
                          num_heads=12, ffn_hidden_size=3072,
                          max_seq_len=seq, dropout=dropout,
                          attn_dropout=dropout, use_parallel=False)
        model = ErnieForPretraining(cfg)
        criterion = ErniePretrainingCriterion(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     weight_decay=0.01)

        def loss_fn(outputs, mlm_labels):
            logits, nsp = outputs
            return criterion(logits, nsp, mlm_labels)

        kwargs = {}
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            kwargs = dict(mesh=mesh,
                          batch_spec=NamedSharding(mesh, P("dp")))
        eng = Engine(model, opt, loss_fn, **kwargs)
        with amp.auto_cast(enable=True, dtype="bfloat16"):
            eng.train_batch(ids, labels)  # build + warm
        return eng

    def timed_step(eng):
        return _timed_scan_ms(eng, ids, labels, n1=iters, reps=1)

    variant = sys.argv[1] if len(sys.argv) > 1 else "full"
    if variant == "longctx":
        return longctx()
    if variant == "attrib":
        return attrib()
    if variant == "full":
        eng = build(dropout=0.1)
    elif variant == "nodrop":
        eng = build(dropout=0.0)
    elif variant == "pallas_attn":
        eng = build(dropout=0.1, force_attn="pallas")
    elif variant == "pallas_nodrop":
        eng = build(dropout=0.0, force_attn="pallas")
    elif variant == "mesh1":
        # GSPMD-partitioned step over a 1-device mesh: must match the
        # un-meshed step time now that the Pallas kernel survives
        # partitioning via custom_partitioning (VERDICT r4 item 1)
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))
        eng = build(dropout=0.1, mesh=mesh)
    else:
        raise SystemExit(f"unknown variant {variant}")
    ms = timed_step(eng)
    print(json.dumps({"variant": variant, "step_ms": round(ms, 2)}))


def attrib():
    """Device-time attribution + overlap pairing of the live train step.

    Exits 2 (with a stderr message) when the xplane capture comes back
    with an empty device plane — zero classified rows means the
    profiler produced nothing to attribute, and a silent all-zero JSON
    line would read as "no collective time" rather than "no data".
    """
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import amp
    from paddle_tpu.engine import Engine
    from paddle_tpu.nlp.transformers import (
        ErnieConfig, ErnieForPretraining, ErniePretrainingCriterion,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        batch, seq = int(os.environ.get("BENCH_BATCH", "32")), 512
        cfg = ErnieConfig(vocab_size=18000, hidden_size=768, num_layers=12,
                          num_heads=12, ffn_hidden_size=3072,
                          max_seq_len=seq, dropout=0.1, attn_dropout=0.1,
                          use_parallel=False)
    else:
        batch, seq = 4, 64
        cfg = ErnieConfig(vocab_size=512, hidden_size=64, num_layers=2,
                          num_heads=4, ffn_hidden_size=128,
                          max_seq_len=seq, dropout=0.0,
                          use_parallel=False)

    paddle.seed(0)
    model = ErnieForPretraining(cfg)
    criterion = ErniePretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 weight_decay=0.01)

    def loss_fn(outputs, mlm_labels):
        logits, nsp = outputs
        return criterion(logits, nsp, mlm_labels)

    eng = Engine(model, opt, loss_fn)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    labels = ids.copy()
    labels[rng.rand(batch, seq) > 0.15] = -100
    with amp.auto_cast(enable=True, dtype="bfloat16"):
        eng.train_batch(ids, labels)
        eng.train_batch(ids, labels)  # warm: attribute a steady step

    try:
        report = eng.attribute_step(steps=3)
        overlap = eng.overlap_report(steps=3)
    except FileNotFoundError as e:
        print(f"bench_attrib attrib: xplane capture missing ({e}); the "
              "profiler wrote no device trace — nothing to attribute",
              file=sys.stderr)
        return 2
    if report["total_us"] <= 0.0 or overlap["total_us"] <= 0.0:
        print("bench_attrib attrib: xplane capture yielded an EMPTY "
              "device plane (zero classified op rows); the profiler "
              "backend produced no device events — refusing to print "
              "an all-zero attribution", file=sys.stderr)
        return 2
    print(json.dumps({
        "variant": "attrib",
        "batch": batch, "seq": seq,
        "buckets_us": {k: round(v, 1)
                       for k, v in report["buckets"].items()},
        "fractions": {k: round(v, 4)
                      for k, v in report["fractions"].items()},
        "exposed_collective_frac":
            round(overlap["exposed_collective_frac"], 4),
        "collective_share": round(overlap["collective_share"], 4),
        "hidden_collective_us": round(overlap["hidden_collective_us"], 1),
        "total_us": round(report["total_us"], 1),
    }))
    return 0


def longctx():
    """Long-context evidence: GPT-base causal train step at seq 8192 on
    ONE chip — possible because the flash backward's VMEM is bounded by
    block sizes (the XLA attention path OOMs at seq 4096)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    import paddle_tpu as paddle
    from paddle_tpu import amp
    from paddle_tpu.engine import Engine
    from paddle_tpu.framework import random as _random
    from paddle_tpu.nlp.transformers import (
        GPTConfig, GPTForPretraining, GPTPretrainingCriterion,
    )

    batch, seq = int(os.environ.get("BENCH_LC_BATCH", "1")), 8192
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                    num_heads=12, max_seq_len=seq, dropout=0.1,
                    attn_dropout=0.1, use_parallel=False)
    model = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 weight_decay=0.01)
    eng = Engine(model, opt,
                 lambda logits, labels: crit(logits, labels))
    rng = np.random.RandomState(0)
    toks = rng.randint(0, cfg.vocab_size,
                       (batch, seq + 1)).astype(np.int32)
    x, y = toks[:, :-1], toks[:, 1:]
    with amp.auto_cast(enable=True, dtype="bfloat16"):
        eng.train_batch(x, y)
    ms = _timed_scan_ms(eng, x, y, n1=4, reps=3)
    tokens_per_sec = batch * seq / (ms / 1e3)
    print(json.dumps({"variant": "longctx", "seq": seq, "batch": batch,
                      "step_ms": round(ms, 2),
                      "tokens_per_sec": round(tokens_per_sec, 1)}))


if __name__ == "__main__":
    sys.exit(main())
