"""Closed-loop serving benchmark: thread-based clients hammer the
continuous-batching engine across an offered-load sweep.

Each load level runs `--clients N` closed-loop clients (every client
waits for its previous request before issuing the next — the classic
closed-loop model, so offered load scales with N) for `--steps` requests
each, then reports throughput, batch occupancy, paged-KV block
occupancy, prefix-cache hit rate, and latency percentiles from the
serving metrics registry. One JSON line per level plus a final
``BENCH_SERVING`` object (written to --json when given), in the same
family as bench_ops.py's BENCH_* records.

The paged-concurrency headline: the block pool is sized to the *bytes*
of a dense `--dense-equiv-slots` pool (default 8 slots x max_seq), but
because a request only holds ceil((prompt+max_new)/block_size) blocks,
the same HBM sustains `--max-slots` (default 32) concurrent requests —
`concurrency_vs_dense` in each row is measured in-flight requests over
the dense-equivalent slot count (the ISSUE acceptance asks >= 4x at
unchanged footprint). `--shared-prefix K` prepends a common K-token
system prompt to every request so the prefix cache gets real traffic.

CPU dry-run (the tier-1 smoke case):

    JAX_PLATFORMS=cpu python bench_serving.py --steps 2 --clients 1,2 \
        --max-new 3 --hidden 16 --layers 1 --heads 2 --vocab 31

``--chaos`` switches to the resilience benchmark: a clean fleet run
(`--replicas` supervised engines behind the Router) followed by the
same offered load under a scripted fault schedule (transient step
failures on every replica + one mid-run replica kill), emitting a
``BENCH_SERVING_CHAOS`` object — goodput fraction (requests resolved
successfully over submitted), restart/retry/replay counters, and the
degraded-vs-clean p99 delta — so future rounds can ratchet
degraded-mode performance.

``--trace scenario.json`` replays a seeded open-loop trace from the
workload simulator (serving/workload.py) instead of closed-loop
clients, emitting ``BENCH_SERVING_TRACE`` — the same scenario language
bench_fleet.py sweeps, so the LLM bench and the elasticity bench grade
against identical offered load.

Fast-decode legs: ``--spec [K]`` turns on speculative decoding (K draft
tokens per round, self-draft by default — the ISSUE-16 acceptance
config) and ``--int8`` freezes the weights to int8 through the dequant
epilogue path; every row reports ``tokens_per_s_per_chip`` and
``acceptance_rate``. ``--smoke`` runs the certification instead of the
sweep: a plain-greedy baseline leg vs a speculative leg (vs an optional
``--int8`` leg) over the same pinned prompts, asserting >= 2x decode
tokens/s at acceptance >= 0.7, a bitwise-equal greedy output digest,
compile counters frozen at one trace per kind for the server's life,
and zero errors — then emits one ``BENCH_SERVING_SMOKE`` object.

Mesh-sharded legs (ISSUE 17): ``--mesh dp1.mp2`` shards every engine's
weights and paged KV pool over a (dp, mp) device mesh (GSPMD,
serving/sharding.py) in any mode. ``--disagg`` runs the disaggregation
benchmark instead of the sweep: a colocated fleet (every replica serves
prefill AND decode through one chunk-wide compiled step) vs a
disaggregated fleet (one prefill-role replica, decode-role replicas
compiled at a narrow chunk, finished KV blocks streamed over the
deadline-guarded mailbox) at EQUAL chips, over the same pinned prompts
plus the same closed-loop load. Each leg reports ``decode_p99_ms`` /
``prefill_p50_ms`` (from the per-step phase-latency series) and KV
migration throughput; the run asserts bitwise greedy parity and a live
migration path, and with ``--smoke`` additionally gates on the
disaggregated decode p99 beating colocated — the unified step's cost
scales with its compiled prefill width, so colocated decode pays the
wide-chunk program every step while a decode-role replica never does.
Emits ``BENCH_SERVING_DISAGG``. CPU certification dry-run:

    JAX_PLATFORMS=cpu python bench_serving.py --disagg --smoke \
        --mesh dp1.mp2 --clients 4 --steps 2 --prefill-chunk 64 \
        --block-size 8 --hidden 32 --layers 2

Durable sessions (ISSUE 18): ``--sessions`` certifies the global KV
fabric instead of the sweep — multi-turn sessions whose radix caches
are drained through the crc-framed SSD spill tier between turns, then
resumed (same replica, and cross-replica after a kill) with bitwise
greedy parity against an uninterrupted reference; a chaos leg raising
once at each of serving.spill / serving.kv_restore / serving.affinity
(goodput 1.0, fired == planned, compile counters frozen); and a
multi-turn workload replay grading fleet-wide prefix hit rate with
affinity routing on vs the best single replica with it off. Emits
``BENCH_SESSIONS``. CPU certification dry-run:

    JAX_PLATFORMS=cpu python bench_serving.py --sessions --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time

import numpy as np


def run_level(server, n_clients, steps, prompt_len, max_new, vocab,
              shared_prefix=0):
    """One offered-load level; returns its result row."""
    errors = []
    done = [0]
    lock = threading.Lock()
    barrier = threading.Barrier(n_clients)
    system = np.arange(2, 2 + shared_prefix, dtype=np.int32) % vocab

    def client(cid):
        rng = np.random.RandomState(1000 + cid)
        barrier.wait()
        for _ in range(steps):
            tail = rng.randint(0, vocab, (prompt_len,)).astype(np.int32)
            prompt = np.concatenate([system, tail]) if shared_prefix \
                else tail
            try:
                out = server.generate(prompt, max_new_tokens=max_new,
                                      timeout=120.0)
                assert out.shape == (prompt.size + max_new,)
                with lock:
                    done[0] += 1
            except Exception as e:  # noqa: BLE001 — report, keep load up
                errors.append(repr(e)[:200])

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(n_clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    import jax

    eng = server.engine
    snap = server.snapshot()
    lat = snap["latency_s"].get("e2e", {})
    blk = snap.get("kv_blocks", {})
    pfx = snap.get("prefix_cache", {})
    cp = snap.get("chunked_prefill", {})
    spec = snap.get("speculative", {})
    ndev = max(jax.device_count(), 1)
    row = {
        "clients": n_clients,
        "requests": done[0],
        "errors": len(errors),
        "wall_s": round(wall, 4),
        "qps": round(done[0] / wall, 3),
        "tokens_per_s": round(done[0] * max_new / wall, 2),
        "tokens_per_s_per_chip": round(done[0] * max_new / wall / ndev,
                                       2),
        "acceptance_rate": round(spec.get("acceptance_rate", 0.0), 4),
        "occupancy_avg": round(snap["batch_occupancy"]["avg"], 4),
        "occupancy_max": round(snap["batch_occupancy"]["max"], 4),
        # peak simultaneous in-flight requests this level actually hit
        "max_inflight": round(
            snap["batch_occupancy"]["max"] * eng.max_slots),
        "kv_blocks_total": blk.get("total", eng._alloc.usable),
        "kv_block_occ_avg": round(blk.get("occupancy", 0.0), 4),
        "kv_block_occ_max": round(blk.get("occupancy_max", 0.0), 4),
        "prefix_hit_rate": round(pfx.get("hit_rate", 0.0), 4),
        "prefill_tokens_per_step": round(cp.get("tokens_per_step", 0.0),
                                         3),
        "p50_ms": round(lat.get("p50", 0.0) * 1e3, 3),
        "p95_ms": round(lat.get("p95", 0.0) * 1e3, 3),
        "p99_ms": round(lat.get("p99", 0.0) * 1e3, 3),
    }
    if errors:
        row["first_error"] = errors[0]
    return row


def run_fleet_level(server, n_clients, steps, prompt_len, max_new, vocab,
                    kill_replica=None, kill_after_s=None):
    """One closed-loop level against a fleet server; optionally kills
    one replica mid-run. Returns (row, ok, failed)."""
    ok, failed, errors = [0], [0], []
    lock = threading.Lock()
    barrier = threading.Barrier(n_clients)

    def client(cid):
        rng = np.random.RandomState(2000 + cid)
        barrier.wait()
        for _ in range(steps):
            prompt = rng.randint(0, vocab, (prompt_len,)).astype(np.int32)
            try:
                out = server.generate(prompt, max_new_tokens=max_new,
                                      timeout=120.0)
                assert out.shape[0] >= prompt.size
                with lock:
                    ok[0] += 1
            except Exception as e:  # noqa: BLE001 — typed errors count
                with lock:
                    failed[0] += 1
                    errors.append(repr(e)[:200])

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(n_clients)]
    killer = None
    if kill_replica is not None:
        killer = threading.Timer(
            kill_after_s or 0.5,
            lambda: server.router.kill(kill_replica, "bench chaos kill"))
        killer.daemon = True
    t0 = time.monotonic()
    for t in threads:
        t.start()
    if killer is not None:
        killer.start()
    for t in threads:
        t.join()
    if killer is not None:
        killer.cancel()
    wall = time.monotonic() - t0
    snap = server.snapshot()
    lat = snap["latency_s"].get("e2e", {})
    total = ok[0] + failed[0]
    row = {
        "clients": n_clients,
        "requests_ok": ok[0],
        "requests_failed": failed[0],
        "goodput": round(ok[0] / total, 4) if total else 0.0,
        "wall_s": round(wall, 4),
        "qps": round(ok[0] / wall, 3),
        "p50_ms": round(lat.get("p50", 0.0) * 1e3, 3),
        "p99_ms": round(lat.get("p99", 0.0) * 1e3, 3),
    }
    if errors:
        row["first_error"] = errors[0]
    return row


def run_trace(args, model, serving):
    """--trace: open-loop replay of a workload-simulator scenario
    (serving/workload.py) — the shared scenario language with
    bench_fleet.py. Arrivals are issued on the trace's schedule
    regardless of completions, so overload shows up as queueing and
    shed, not hidden client back-pressure."""
    from paddle_tpu.serving import workload

    scenario = workload.Scenario.from_json(args.trace)
    trace = scenario.trace()
    blocks_per_seq = -(-args.max_seq_len // args.block_size)
    num_blocks = args.kv_blocks or \
        args.dense_equiv_slots * blocks_per_seq + 1
    fleet = dict(hedge=False, liveness_timeout_s=30.0,
                 name="btrace") if args.replicas > 1 else None
    server = serving.Server(
        model, replicas=args.replicas, max_slots=args.max_slots,
        max_seq_len=args.max_seq_len, block_size=args.block_size,
        num_blocks=num_blocks, prefill_chunk=args.prefill_chunk,
        queue_cap=max(64, 4 * args.max_slots), mesh=args.mesh or None,
        fleet=fleet).start()

    def submit(a):
        return server.submit(a.prompt, max_new_tokens=a.max_new,
                             priority=a.priority, timeout=120.0)

    t0 = time.monotonic()
    records = workload.replay(submit, trace,
                              time_scale=args.time_scale)
    ok = failed = 0
    for rec in records:
        if rec["error"] is not None:
            failed += 1
            continue
        try:
            rec["future"].result(120.0)
            ok += 1
        except Exception:  # noqa: BLE001 — typed failures count
            failed += 1
    wall = time.monotonic() - t0
    snap = server.snapshot()
    lat = snap["latency_s"].get("e2e", {})
    pfx = snap.get("prefix_cache", {})
    server.shutdown(drain=True)
    total = ok + failed
    result = {
        "bench": "BENCH_SERVING_TRACE",
        "scenario": scenario.to_dict(),
        "time_scale": args.time_scale,
        "arrivals": len(trace),
        "requests_ok": ok,
        "requests_failed": failed,
        "goodput": round(ok / total, 4) if total else 0.0,
        "wall_s": round(wall, 4),
        "qps": round(ok / wall, 3),
        "prefix_hit_rate": round(pfx.get("hit_rate", 0.0), 4),
        "p50_ms": round(lat.get("p50", 0.0) * 1e3, 3),
        "p99_ms": round(lat.get("p99", 0.0) * 1e3, 3),
    }
    print(json.dumps(result))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
    return 0


def run_chaos(args, model, serving):
    """--chaos: clean fleet baseline, then the same load under a
    scripted fault schedule + one mid-run replica kill."""
    from paddle_tpu.framework import faults

    n_clients = [int(c) for c in args.clients.split(",") if c][0]
    blocks_per_seq = -(-args.max_seq_len // args.block_size)
    num_blocks = args.kv_blocks or \
        args.dense_equiv_slots * blocks_per_seq + 1

    def make_server(name):
        return serving.Server(
            model, replicas=args.replicas, max_slots=args.max_slots,
            max_seq_len=args.max_seq_len, block_size=args.block_size,
            num_blocks=num_blocks, prefill_chunk=args.prefill_chunk,
            queue_cap=max(64, 2 * n_clients), mesh=args.mesh or None,
            fleet=dict(hedge=False, retry_budget=3,
                       liveness_timeout_s=30.0, backoff_base_s=0.05,
                       name=name)).start()

    srv = make_server("bclean")
    clean = run_fleet_level(srv, n_clients, args.steps, args.prompt_len,
                            args.max_new, args.vocab)
    srv.shutdown(drain=True)
    print(json.dumps({"level": "clean", **clean}))

    srv = make_server("bchaos")
    # transient step failures on every replica + one replica killed
    # mid-run: exercises retry, failover replay, and restart at once
    specs = [f"serving.replica_step[bchaos.r{i}]@{4 + 3 * i}:raise"
             for i in range(args.replicas)]
    with faults.inject(*specs):
        chaos = run_fleet_level(
            srv, n_clients, args.steps, args.prompt_len, args.max_new,
            args.vocab, kill_replica="bchaos.r0",
            kill_after_s=min(0.3, clean["wall_s"] * 0.3))
    m = srv.metrics
    # let the supervised restart land before reading the counter
    deadline = time.monotonic() + 30
    while m.get("replica_restarts") < m.get("replica_deaths") and \
            time.monotonic() < deadline:
        time.sleep(0.05)
    counters = {k: m.get(k) for k in (
        "replica_deaths", "replica_restarts", "retries", "replays",
        "hedges", "stale_attempts", "retry_budget_exhausted",
        "fleet_submitted", "fleet_completed", "fleet_failed")}
    srv.shutdown(drain=True)
    print(json.dumps({"level": "chaos", **chaos}))

    result = {
        "bench": "BENCH_SERVING_CHAOS",
        "config": {
            "replicas": args.replicas, "clients": n_clients,
            "steps": args.steps, "prompt_len": args.prompt_len,
            "max_new": args.max_new, "max_slots": args.max_slots,
            "model": {"vocab": args.vocab, "hidden": args.hidden,
                      "layers": args.layers, "heads": args.heads},
        },
        "clean": clean,
        "chaos": chaos,
        "goodput": chaos["goodput"],
        "restarts": counters["replica_restarts"],
        "retries": counters["retries"],
        "replays": counters["replays"],
        "counters": counters,
        "p99_delta_ms": round(chaos["p99_ms"] - clean["p99_ms"], 3),
    }
    print(json.dumps(result))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
    return 0


def run_disagg(args, model, serving):
    """--disagg: colocated vs disaggregated prefill/decode at equal
    chips (same replica count, same mesh, same block pool). The
    colocated fleet compiles every replica at the wide --prefill-chunk;
    the disaggregated fleet gives one replica the prefill role (wide
    chunk) and compiles the decode-role replicas at a narrow chunk
    (--block-size), with finished KV blocks migrating prefill->decode
    through the deadline-guarded mailbox. Correctness gates (always):
    bitwise greedy parity between the legs, zero failed requests, and a
    live migration path; perf gate (--smoke): disaggregated decode p99
    strictly under colocated."""
    import hashlib

    n_clients = [int(c) for c in args.clients.split(",") if c][0]
    blocks_per_seq = -(-args.max_seq_len // args.block_size)
    num_blocks = args.kv_blocks or \
        args.dense_equiv_slots * blocks_per_seq + 1
    wide = args.prefill_chunk
    narrow = min(args.block_size, wide)
    rng = np.random.RandomState(17)
    pinned = [rng.randint(0, args.vocab,
                          (args.prompt_len,)).astype(np.int32)
              for _ in range(4)]

    def leg(name, fleet_kw):
        server = serving.Server(
            model, replicas=args.replicas, max_slots=args.max_slots,
            max_seq_len=args.max_seq_len, block_size=args.block_size,
            num_blocks=num_blocks, prefill_chunk=wide,
            prefix_cache=True, queue_cap=max(64, 2 * n_clients),
            mesh=args.mesh or None,
            fleet=dict(hedge=False, liveness_timeout_s=30.0,
                       name=name, **fleet_kw)).start()
        # pinned parity probe first (also warms every compiled trace so
        # the timed load below measures steps, not compiles)
        outs = [np.asarray(server.generate(p, max_new_tokens=args.max_new,
                                           timeout=120.0), np.int64)
                for p in pinned]
        digest = hashlib.sha256(
            b"".join(np.ascontiguousarray(o).tobytes()
                     for o in outs)).hexdigest()
        m = server.metrics
        moved0 = m.get("kv_migrate_bytes")
        row = run_fleet_level(server, n_clients, args.steps,
                              args.prompt_len, args.max_new, args.vocab)
        dec = m.latency_percentiles("decode", (99,))[99]
        pre = m.latency_percentiles("prefill", (50,))[50]
        moved = m.get("kv_migrate_bytes") - moved0
        row.update({
            "digest": digest,
            "decode_p99_ms": round((dec or 0.0) * 1e3, 3),
            "prefill_p50_ms": round((pre or 0.0) * 1e3, 3),
            "kv_migrations": m.get("kv_migrations"),
            "kv_migrate_blocks": m.get("kv_migrate_blocks"),
            "kv_migrate_bytes": m.get("kv_migrate_bytes"),
            "kv_migrate_faults": m.get("kv_migrate_faults"),
            "kv_migrate_mb_per_s": round(
                moved / max(row["wall_s"], 1e-9) / 2**20, 3),
        })
        server.shutdown(drain=True)
        return row

    colo = leg("dcolo", {})
    print(json.dumps({"leg": "colocated", **colo}))
    roles = ["prefill"] + ["decode"] * max(args.replicas - 1, 1)
    dis = leg("ddis", dict(
        roles=roles[:max(args.replicas, 2)],
        role_kw={"decode": {"prefill_chunk": narrow}}, disagg=True))
    print(json.dumps({"leg": "disagg", **dis}))

    failures = []
    if colo["requests_failed"] or dis["requests_failed"]:
        failures.append(f"failed requests: colo="
                        f"{colo['requests_failed']} "
                        f"disagg={dis['requests_failed']}")
    if dis["digest"] != colo["digest"]:
        failures.append("greedy parity digest mismatch")
    if not dis["kv_migrations"]:
        failures.append("disagg leg migrated no KV blocks")
    if args.smoke and dis["decode_p99_ms"] >= colo["decode_p99_ms"]:
        failures.append(
            f"disagg decode p99 {dis['decode_p99_ms']}ms >= "
            f"colocated {colo['decode_p99_ms']}ms")
    result = {
        "bench": "BENCH_SERVING_DISAGG",
        "config": {
            "replicas": args.replicas, "mesh": args.mesh or None,
            "clients": n_clients, "steps": args.steps,
            "prompt_len": args.prompt_len, "max_new": args.max_new,
            "prefill_chunk_wide": wide, "prefill_chunk_narrow": narrow,
            "block_size": args.block_size, "kv_blocks": num_blocks,
            "model": {"vocab": args.vocab, "hidden": args.hidden,
                      "layers": args.layers, "heads": args.heads},
        },
        "colocated": colo,
        "disagg": dis,
        "decode_p99_speedup": round(
            colo["decode_p99_ms"] / max(dis["decode_p99_ms"], 1e-9), 3),
        "greedy_parity": dis["digest"] == colo["digest"],
        "smoke": bool(args.smoke),
        "ok": not failures,
    }
    if failures:
        result["failures"] = failures
    print(json.dumps(result))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
    return 0 if result["ok"] else 1


def run_smoke(args, serving):
    """--smoke: the ISSUE-16 fast-decode certification. Same pinned
    greedy prompts through a plain baseline leg and a speculative
    (self-draft) leg — plus an ``--int8`` leg when asked — asserting
    the >=2x tokens/s speedup at >=0.7 acceptance, bitwise output
    parity (sha256 digest over all emitted ids), one compiled trace
    per kind for each server's whole life, and zero errors."""
    import hashlib

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.nlp.transformers import GPTConfig, GPTForPretraining

    k = args.spec or 3
    max_new, n_req, prompt_len = 24, 6, 8
    # hidden 256 x 6 layers over a 64-wide unified step: enough
    # per-dispatch compute that the speedup reflects column work (the
    # TPU regime), not host dispatch overhead, while a full leg stays
    # ~1s on a tier-1 CPU run. The wide step is the point: base decode
    # pays all 64 columns for 1 token/slot, speculation fills k+1 of
    # them per round for the same step cost.
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=6,
                    num_heads=4, max_seq_len=64, dropout=0.0,
                    attn_dropout=0.0, use_parallel=False)
    model = GPTForPretraining(cfg)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size,
                           (prompt_len,)).astype(np.int32)
               for _ in range(n_req)]
    ndev = max(jax.device_count(), 1)

    def leg(spec_len, quantize):
        server = serving.Server(
            model, max_slots=4, max_seq_len=64, block_size=16,
            num_blocks=17, prefill_chunk=64, spec_len=spec_len,
            quantize=quantize).start()
        # compile outside the timed window (same trace serves the run)
        server.generate(prompts[0], max_new_tokens=4, timeout=120.0)
        # best-of-2: one repetition can eat a scheduler hiccup on a
        # loaded CI box; greedy decode makes both reps bitwise equal
        wall, outs = None, None
        for _ in range(2):
            t0 = time.monotonic()
            futs = [server.submit(p, max_new_tokens=max_new,
                                  timeout=120.0)
                    for p in prompts]
            outs = [np.asarray(f.result(120.0), np.int64)
                    for f in futs]
            rep = time.monotonic() - t0
            wall = rep if wall is None else min(wall, rep)
        snap = server.snapshot()
        counts = {str(c): v
                  for c, v in server.engine.compile_counts.items()}
        server.shutdown(drain=True)
        spec = snap.get("speculative", {})
        return {
            "tokens_per_s": round(n_req * max_new / wall, 2),
            "tokens_per_s_per_chip": round(
                n_req * max_new / wall / ndev, 2),
            "wall_s": round(wall, 4),
            "acceptance_rate": round(
                spec.get("acceptance_rate", 0.0), 4),
            "errors": snap["counters"].get("failed", 0),
            "compiles": counts,
            "digest": hashlib.sha256(
                b"".join(np.ascontiguousarray(o, np.int64).tobytes()
                         for o in outs)).hexdigest(),
        }

    base = leg(0, False)
    print(json.dumps({"leg": "base", **base}))
    spec = leg(k, False)
    print(json.dumps({"leg": "spec", **spec}))
    speedup = spec["tokens_per_s"] / max(base["tokens_per_s"], 1e-9)
    failures = []
    if base["errors"] or spec["errors"]:
        failures.append(f"errors: base={base['errors']} "
                        f"spec={spec['errors']}")
    if spec["digest"] != base["digest"]:
        failures.append("greedy parity digest mismatch")
    if speedup < 2.0:
        failures.append(f"speedup {speedup:.2f} < 2.0")
    if spec["acceptance_rate"] < 0.7:
        failures.append(
            f"acceptance {spec['acceptance_rate']} < 0.7")
    if base["compiles"] != {"decode": 1, "cow": 1}:
        failures.append(f"base compiles {base['compiles']}")
    if spec["compiles"] != {"decode": 1, "draft": 1, "cow": 1}:
        failures.append(f"spec compiles {spec['compiles']}")
    result = {
        "bench": "BENCH_SERVING_SMOKE",
        "spec_len": k,
        "requests": n_req,
        "max_new": max_new,
        "model": {"vocab": cfg.vocab_size, "hidden": cfg.hidden_size,
                  "layers": cfg.num_layers, "heads": cfg.num_heads},
        "base": base,
        "spec": spec,
        "speedup": round(speedup, 3),
        "greedy_parity": spec["digest"] == base["digest"],
        "ok": not failures,
    }
    if args.int8:
        q = leg(k, True)
        print(json.dumps({"leg": "int8", **q}))
        result["int8"] = q
        if q["errors"]:
            failures.append(f"int8 errors: {q['errors']}")
        if q["compiles"] != {"decode": 1, "draft": 1, "cow": 1}:
            failures.append(f"int8 compiles {q['compiles']}")
        result["ok"] = not failures
    if failures:
        result["failures"] = failures
    print(json.dumps(result))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
    return 0 if result["ok"] else 1


def run_w8a8(args, serving):
    """--w8a8: the ISSUE-19 low-precision decode certification. Same
    pinned greedy prompts through three servers of one model — f32
    reference, weights-only int8 (the PR16 dequant epilogue), and w8a8
    (int8 weights x int8 activations through the fused
    ``w8a8_matmul`` epilogue with a frozen per-tensor activation
    scale) — asserting:

    - greedy-token agreement of the w8a8 leg vs the f32 reference at
      >= the tolerance (autoregressive drift compounds after a first
      divergence, so agreement is measured per emitted token);
    - the compile contract is UNTOUCHED: ``{decode: 1, cow: 1}`` for
      every leg's whole life (the activation scale is a runtime
      argument of the one compiled trace, never a retrace);
    - the activation scale actually froze (calibration ended inside
      the run) and zero request errors;

    and reporting tokens/s/chip per leg."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.nlp.transformers import GPTConfig, GPTForPretraining

    max_new, n_req, prompt_len = 24, 6, 8
    tol = float(os.environ.get("BENCH_W8A8_TOL", "0.8"))
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=6,
                    num_heads=4, max_seq_len=64, dropout=0.0,
                    attn_dropout=0.0, use_parallel=False)
    model = GPTForPretraining(cfg)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size,
                           (prompt_len,)).astype(np.int32)
               for _ in range(n_req)]
    ndev = max(jax.device_count(), 1)

    def leg(name, quantize, w8a8):
        server = serving.Server(
            model, max_slots=4, max_seq_len=64, block_size=16,
            num_blocks=17, prefill_chunk=64, quantize=quantize,
            w8a8=w8a8).start()
        server.generate(prompts[0], max_new_tokens=4, timeout=120.0)
        t0 = time.monotonic()
        futs = [server.submit(p, max_new_tokens=max_new, timeout=120.0)
                for p in prompts]
        outs = [np.asarray(f.result(120.0), np.int64) for f in futs]
        wall = time.monotonic() - t0
        snap = server.snapshot()
        eng = server.engine
        counts = {str(c): v for c, v in eng.compile_counts.items()}
        row = {
            "leg": name,
            "tokens_per_s": round(n_req * max_new / wall, 2),
            "tokens_per_s_per_chip": round(
                n_req * max_new / wall / ndev, 2),
            "errors": snap["counters"].get("failed", 0),
            "compiles": counts,
        }
        if w8a8:
            row["act_scale"] = round(float(eng._act_scale), 5)
            row["act_scale_frozen"] = bool(eng._act_frozen)
        server.shutdown(drain=True)
        return row, outs

    f32, ref = leg("f32", False, False)
    print(json.dumps(f32))
    int8, _ = leg("int8", True, False)
    print(json.dumps(int8))
    w8a8, outs = leg("w8a8", True, True)

    total = sum(len(o) for o in ref)
    match = sum(int(np.sum(np.asarray(a[:min(len(a), len(b))]) ==
                           np.asarray(b[:min(len(a), len(b))])))
                for a, b in zip(outs, ref))
    agree = match / max(total, 1)
    w8a8["token_agreement"] = round(agree, 4)
    print(json.dumps(w8a8))

    failures = []
    for row in (f32, int8, w8a8):
        if row["errors"]:
            failures.append(f"{row['leg']} errors: {row['errors']}")
        if row["compiles"] != {"decode": 1, "cow": 1}:
            failures.append(
                f"{row['leg']} compiles {row['compiles']}")
    if agree < tol:
        failures.append(f"token agreement {agree:.3f} < {tol}")
    if not w8a8.get("act_scale_frozen"):
        failures.append("activation scale never froze")
    result = {
        "bench": "BENCH_SERVING_W8A8",
        "requests": n_req,
        "max_new": max_new,
        "tolerance": tol,
        "model": {"vocab": cfg.vocab_size, "hidden": cfg.hidden_size,
                  "layers": cfg.num_layers, "heads": cfg.num_heads},
        "f32": f32,
        "int8": int8,
        "w8a8": w8a8,
        "token_agreement": round(agree, 4),
        "ok": not failures,
    }
    if failures:
        result["failures"] = failures
    print(json.dumps(result))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
    return 0 if result["ok"] else 1


def run_sessions(args, serving):
    """--sessions: the ISSUE-18 durable multi-turn certification.

    Four legs over one pinned tiny model, all greedy and graded
    bitwise against an uninterrupted single-engine reference:

    - resume: a 2-replica affinity fleet serves turn 1, every radix
      cache is drained through the SSD spill tier (the between-turn
      pressure model), and turn 2 resumes from restored blocks —
      p50 turn-2 latency is compared against a cold fleet that must
      re-prefill the whole transcript;
    - cross: the replica that served turn 1 is killed between turns;
      turn 2 fails over and restores the session from the shared
      spill file on the surviving replica;
    - chaos: the cross leg again, under a scripted fault schedule
      raising once at each of serving.spill / serving.kv_restore /
      serving.affinity — goodput must stay 1.0, the schedule must
      certify fired == planned, and every replica (including the
      supervised restart) must hold compile counters at one decode +
      one cow trace;
    - hitrate: a seeded multi-turn workload trace replayed through an
      affinity-on fleet vs an affinity-off fleet — the affinity
      fleet's fleet-wide prefix hit rate must strictly beat the best
      single replica of the scattered fleet.

    Emits one ``BENCH_SESSIONS`` object; ``--smoke`` additionally
    gates the resumed-vs-cold latency win and the hit-rate ordering.
    """
    import os
    import shutil
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu.framework import faults
    from paddle_tpu.nlp.transformers import GPTConfig, GPTForPretraining
    from paddle_tpu.serving import kvstore, workload

    max_new, p1_len, tail_len, bs = 4, 104, 16, 8
    paddle.seed(7)
    cfg = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=160, dropout=0.0,
                    attn_dropout=0.0, use_parallel=False)
    model = GPTForPretraining(cfg)
    spill_root = tempfile.mkdtemp(prefix="bench_sessions_kv_")
    failures = []

    def fleet_server(name, spill, affinity=True, max_seq=160,
                     num_blocks=161):
        kvstore.reset_spill_stores()
        # prefill_chunk == block_size so cold prefill is multi-step:
        # the resumed leg's win is exactly the chunks it skips
        return serving.Server(
            model, replicas=2, max_slots=4, max_seq_len=max_seq,
            block_size=bs, num_blocks=num_blocks, prefill_chunk=bs,
            spill_dir=spill,
            fleet=dict(hedge=False, liveness_timeout_s=30.0,
                       backoff_base_s=0.05, name=name,
                       prefix_affinity=affinity)).start()

    ref = serving.Server(model, max_slots=4, max_seq_len=160,
                         block_size=bs, num_blocks=161, prefill_chunk=bs,
                         prefix_cache=False).start()

    def ref_out(prompt):
        return np.asarray(ref.generate(prompt, max_new_tokens=max_new,
                                       timeout=120.0), np.int32)

    def gen(srv, prompt):
        t0 = time.monotonic()
        out = np.asarray(srv.generate(prompt, max_new_tokens=max_new,
                                      timeout=120.0), np.int32)
        return out, time.monotonic() - t0

    def drain_caches(srv):
        """Between-turn pressure model: every replica's radix cache is
        evicted through the spill tier, so turn 2 can only be cheap if
        the SSD restore path works."""
        for r in srv.router.replica_set.replicas:
            if r.engine is not None:
                r.engine.spill_cache()

    def parity(tag, got, want):
        if not np.array_equal(got, want):
            failures.append(f"{tag}: bitwise parity mismatch")

    rng = np.random.RandomState(5)
    n_sessions = 4
    prompts1 = [rng.randint(0, cfg.vocab_size, (p1_len,)).astype(np.int32)
                for _ in range(n_sessions)]
    tails = [rng.randint(0, cfg.vocab_size, (tail_len,)).astype(np.int32)
             for _ in range(n_sessions)]

    # -- leg 1: same-fleet resume (spill -> restore) vs cold re-prefill
    srv = fleet_server("bsess", os.path.join(spill_root, "resume"))
    outs1 = [gen(srv, p)[0] for p in prompts1]
    drain_caches(srv)
    prompts2 = [np.concatenate([o, t]) for o, t in zip(outs1, tails)]
    resumed = [gen(srv, p) for p in prompts2]
    restored_blocks = srv.metrics.get("kv_restored_blocks")
    spilled_blocks = srv.metrics.get("kv_spilled_blocks")
    aff_snap = srv.router.snapshot().get("affinity", {})
    srv.shutdown(drain=True)
    for i, (out2, _) in enumerate(resumed):
        parity(f"resume s{i}", out2, ref_out(prompts2[i]))
    if restored_blocks <= 0:
        failures.append("resume leg restored no KV blocks from spill")
    if spilled_blocks <= 0:
        failures.append("resume leg spilled no KV blocks")

    srv = fleet_server("bcold", None)
    cold = [gen(srv, p) for p in prompts2]
    srv.shutdown(drain=True)
    for i, (out2, _) in enumerate(cold):
        parity(f"cold s{i}", out2, ref_out(prompts2[i]))
    resumed_p50 = serving.percentile([t for _, t in resumed], 50)
    cold_p50 = serving.percentile([t for _, t in cold], 50)
    leg_resume = {
        "leg": "resume",
        "resumed_p50_ttft_ms": round(resumed_p50 * 1e3, 3),
        "cold_p50_ttft_ms": round(cold_p50 * 1e3, 3),
        "spilled_blocks": spilled_blocks,
        "restored_blocks": restored_blocks,
        "affinity": {k: aff_snap.get(k) for k in
                     ("lookups", "hits", "hit_rate")},
    }
    print(json.dumps(leg_resume))
    if args.smoke and resumed_p50 >= cold_p50:
        failures.append(
            f"resumed p50 {resumed_p50 * 1e3:.1f}ms not below cold "
            f"re-prefill {cold_p50 * 1e3:.1f}ms")

    # -- leg 2: replica death between turns; resume on the survivor
    def killed_session_turn(name, chaos=None):
        srv = fleet_server(name, os.path.join(spill_root, name))
        sched = faults.ChaosSchedule(*chaos) if chaos else None
        if sched:
            sched.__enter__()
        ok = bad = 0
        try:
            outs = []
            for p in prompts1[:3]:
                try:
                    outs.append(gen(srv, p)[0])
                    ok += 1
                except Exception:  # noqa: BLE001 — graded as goodput
                    outs.append(None)
                    bad += 1
            drain_caches(srv)
            reps = srv.router.replica_set.replicas
            home = next((r for r in reps
                         if r.engine is not None
                         and r.engine.prefix_lookups > 0), reps[0])
            srv.router.kill(home.name, "bench session kill")
            outs2 = []
            for o, t in zip(outs, tails):
                if o is None:
                    outs2.append(None)
                    continue
                try:
                    outs2.append(gen(srv, np.concatenate([o, t]))[0])
                    ok += 1
                except Exception:  # noqa: BLE001 — graded as goodput
                    outs2.append(None)
                    bad += 1
        finally:
            if sched:
                sched.__exit__(None, None, None)
        # let the supervised restart land before the compile audit
        m = srv.metrics
        deadline = time.monotonic() + 30
        while m.get("replica_restarts") < m.get("replica_deaths") \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        compiles = {name_: {str(k): v for k, v in counts.items()}
                    for name_, counts in srv.router.compile_counts().items()}
        restored = m.get("kv_restored_blocks")
        fired = sched.fired() if sched else {}
        planned = sched.planned() if sched else {}
        if sched:
            try:
                sched.verify()
            except AssertionError as e:
                failures.append(str(e))
        srv.shutdown(drain=True)
        for i, o2 in enumerate(outs2):
            if o2 is not None:
                parity(f"{name} s{i}", o2,
                       ref_out(np.concatenate([outs[i], tails[i]])))
        return {
            "leg": name, "ok": ok, "failed": bad,
            "goodput": round(ok / max(ok + bad, 1), 4),
            "killed": home.name, "restored_blocks": restored,
            "compiles": compiles, "fired": fired, "planned": planned,
        }

    leg_cross = killed_session_turn("bkill")
    print(json.dumps(leg_cross))
    if leg_cross["goodput"] != 1.0:
        failures.append(f"cross leg goodput {leg_cross['goodput']} < 1.0")
    if leg_cross["restored_blocks"] <= 0:
        failures.append("cross leg restored no KV blocks after the kill")

    # -- leg 3: the same kill under faults at every new site
    leg_chaos = killed_session_turn("bchaosess", chaos=(
        "serving.spill@1:raise",
        "serving.kv_restore@1:raise",
        "serving.affinity@1:raise",
    ))
    print(json.dumps(leg_chaos))
    if leg_chaos["goodput"] != 1.0:
        failures.append(f"chaos leg goodput {leg_chaos['goodput']} < 1.0")
    bad_compiles = {n: c for n, c in leg_chaos["compiles"].items()
                    if c != {"decode": 1, "cow": 1}}
    if bad_compiles:
        failures.append(f"chaos leg compiles {bad_compiles}")

    # -- leg 4: fleet-wide affinity hit rate vs best scattered replica
    # session-private content dominates the shared one-block user
    # prefix, so the hit-rate split measures AFFINITY, not luck: a
    # turn landing off its home replica can only hit the user prefix
    sc = workload.Scenario(
        name="sessions", seed=3, vocab=cfg.vocab_size, n_users=32,
        user_prefix_len=8, prompt_len=(16, 24), max_new=(2, 4),
        multi_turn=True, session_turns=(3, 4), think_time=(0.0, 0.01),
        phases=[{"duration_s": 1.5, "rate_rps": 8.0}])
    if workload.Scenario.from_json(sc.to_json()).to_json() != sc.to_json():
        failures.append("multi-turn scenario JSON roundtrip drifted")
    trace = sc.trace()

    def hit_leg(name, affinity):
        srv = fleet_server(name, None, affinity=affinity)
        by_turn = {}
        for a in trace:
            by_turn.setdefault(a.turn, []).append(a)
        ok = bad = 0
        # waves: all sessions' turn-k arrivals in flight together, so
        # load-based routing actually scatters when affinity is off
        for turn in sorted(by_turn):
            futs = [srv.submit(a.prompt, max_new_tokens=a.max_new,
                               timeout=120.0) for a in by_turn[turn]]
            for f in futs:
                try:
                    f.result(120.0)
                    ok += 1
                except Exception:  # noqa: BLE001 — graded as goodput
                    bad += 1
        snap = srv.metrics.snapshot()
        per = {r.name: round(r.engine.prefix_hit_rate(), 4)
               for r in srv.router.replica_set.replicas
               if r.engine is not None}
        srv.shutdown(drain=True)
        fleet_rate = snap.get("prefix_cache", {}).get("hit_rate", 0.0)
        return {"leg": name, "affinity": affinity, "ok": ok,
                "failed": bad, "fleet_hit_rate": round(fleet_rate, 4),
                "per_replica_hit_rate": per}

    hit_on = hit_leg("baffon", True)
    print(json.dumps(hit_on))
    hit_off = hit_leg("baffoff", False)
    print(json.dumps(hit_off))
    best_single = max(hit_off["per_replica_hit_rate"].values() or [0.0])
    if hit_on["failed"] or hit_off["failed"]:
        failures.append("hit-rate legs dropped requests")
    if args.smoke and hit_on["fleet_hit_rate"] <= best_single:
        failures.append(
            f"fleet-wide hit rate {hit_on['fleet_hit_rate']} not above "
            f"best scattered replica {best_single}")

    ref.shutdown(drain=True)
    kvstore.reset_spill_stores()
    shutil.rmtree(spill_root, ignore_errors=True)

    result = {
        "bench": "BENCH_SESSIONS",
        "sessions": n_sessions,
        "turn_tokens": {"turn1": p1_len, "tail": tail_len,
                        "max_new": max_new},
        "model": {"vocab": cfg.vocab_size, "hidden": cfg.hidden_size,
                  "layers": cfg.num_layers, "heads": cfg.num_heads},
        "resume": leg_resume,
        "cross": leg_cross,
        "chaos": leg_chaos,
        "hitrate": {"on": hit_on, "off": hit_off,
                    "best_single_replica": best_single},
        "ok": not failures,
    }
    if failures:
        result["failures"] = failures
    print(json.dumps(result))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
    return 0 if result["ok"] else 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--clients", default="1,8,32",
                    help="comma-separated closed-loop client counts")
    ap.add_argument("--steps", type=int, default=8,
                    help="requests per client per level")
    ap.add_argument("--prompt-len", type=int, default=12)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--max-slots", type=int, default=32,
                    help="slot-pool size (concurrency cap; actual "
                    "admission is limited by free KV blocks)")
    ap.add_argument("--block-size", type=int, default=16,
                    help="tokens per physical KV block")
    ap.add_argument("--kv-blocks", type=int, default=0,
                    help="physical KV blocks incl. the reserved null "
                    "block; 0 = size the pool to the BYTES of a dense "
                    "--dense-equiv-slots pool")
    ap.add_argument("--dense-equiv-slots", type=int, default=8,
                    help="dense-pool slot count whose HBM budget the "
                    "paged pool is matched to (the 4x baseline)")
    ap.add_argument("--prefill-chunk", type=int, default=16)
    ap.add_argument("--shared-prefix", type=int, default=0,
                    help="common system-prompt tokens prepended to "
                    "every request (exercises prefix sharing)")
    ap.add_argument("--vocab", type=int, default=97)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--max-seq-len", type=int, default=128)
    ap.add_argument("--json", default=None,
                    help="write the final BENCH_SERVING object here")
    ap.add_argument("--chaos", action="store_true",
                    help="resilience mode: clean fleet baseline + the "
                    "same load under a scripted fault schedule; emits "
                    "BENCH_SERVING_CHAOS instead of BENCH_SERVING")
    ap.add_argument("--replicas", type=int, default=2,
                    help="fleet size for --chaos / --trace")
    ap.add_argument("--trace", default=None,
                    help="workload-scenario JSON (path or inline) to "
                    "replay open-loop instead of closed-loop clients; "
                    "emits BENCH_SERVING_TRACE (see serving/workload.py "
                    "and bench_fleet.py for the shared language)")
    ap.add_argument("--time-scale", type=float, default=1.0,
                    help="--trace: multiply every arrival time (0.5 = "
                    "replay twice as fast)")
    ap.add_argument("--spec", type=int, nargs="?", const=3, default=0,
                    help="speculative decoding with K draft tokens per "
                    "round (bare --spec = 3); self-draft unless a real "
                    "draft model is wired in code")
    ap.add_argument("--int8", action="store_true",
                    help="freeze weights to int8 (dequant epilogue "
                    "decode path)")
    ap.add_argument("--w8a8", action="store_true",
                    help="low-precision decode certification: f32 vs "
                    "weights-only int8 vs w8a8 legs, greedy-token "
                    "tolerance + compile-once assertions; emits "
                    "BENCH_SERVING_W8A8")
    ap.add_argument("--smoke", action="store_true",
                    help="fast-decode certification: baseline vs "
                    "speculative legs, >=2x + parity + compile-once "
                    "assertions; emits BENCH_SERVING_SMOKE (with "
                    "--disagg: adds the decode-p99-win gate to the "
                    "disaggregation benchmark)")
    ap.add_argument("--mesh", default="",
                    help="serving mesh spec 'dpD.mpM' (e.g. dp1.mp2): "
                    "shard every engine's weights + paged KV pool over "
                    "a (dp, mp) device mesh via GSPMD "
                    "(serving/sharding.py; default FLAGS_serving_mesh)")
    ap.add_argument("--disagg", action="store_true",
                    help="disaggregation benchmark: colocated fleet vs "
                    "prefill/decode-role fleet at equal chips, decode "
                    "p99 / prefill p50 / KV-migration throughput per "
                    "leg; emits BENCH_SERVING_DISAGG")
    ap.add_argument("--sessions", action="store_true",
                    help="durable multi-turn session benchmark: SSD KV "
                    "spill/restore vs cold re-prefill, cross-replica "
                    "resume after a kill, chaos at the kv-fabric fault "
                    "sites, and affinity-on vs -off prefix hit rates; "
                    "emits BENCH_SESSIONS (--smoke gates the latency "
                    "and hit-rate wins)")
    args = ap.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu import serving
    from paddle_tpu.nlp.transformers import GPTConfig, GPTForPretraining

    if args.sessions:
        return run_sessions(args, serving)
    if args.w8a8:
        return run_w8a8(args, serving)
    if args.smoke and not args.disagg:
        return run_smoke(args, serving)

    paddle.seed(7)
    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers, num_heads=args.heads,
                    max_seq_len=args.max_seq_len, dropout=0.0,
                    attn_dropout=0.0, use_parallel=False)
    model = GPTForPretraining(cfg)

    if args.disagg:
        return run_disagg(args, model, serving)
    if args.chaos:
        return run_chaos(args, model, serving)
    if args.trace:
        return run_trace(args, model, serving)

    # match the dense pool's bytes exactly: a dense [slots, nh, max_seq,
    # hd] pool holds slots*max_seq token rows = that many block rows of
    # the paged pool (plus the one reserved null block)
    blocks_per_seq = -(-args.max_seq_len // args.block_size)
    num_blocks = args.kv_blocks or \
        args.dense_equiv_slots * blocks_per_seq + 1

    levels = []
    for n_clients in [int(c) for c in args.clients.split(",") if c]:
        # fresh server per level so occupancy/latency are per-level
        server = serving.Server(
            model, max_slots=args.max_slots,
            max_seq_len=args.max_seq_len, block_size=args.block_size,
            num_blocks=num_blocks, prefill_chunk=args.prefill_chunk,
            queue_cap=max(64, 2 * n_clients),
            spec_len=args.spec, quantize=args.int8,
            mesh=args.mesh or None).start()
        row = run_level(server, n_clients, args.steps, args.prompt_len,
                        args.max_new, args.vocab,
                        shared_prefix=args.shared_prefix)
        row["compiles"] = {str(k): v
                           for k, v in server.engine.compile_counts.items()}
        row["concurrency_vs_dense"] = round(
            row["max_inflight"] / args.dense_equiv_slots, 3)
        kv_bytes = server.engine.kv_pool_bytes
        server.shutdown(drain=True)
        print(json.dumps(row))
        levels.append(row)

    result = {
        "bench": "BENCH_SERVING",
        "config": {
            "steps": args.steps, "prompt_len": args.prompt_len,
            "max_new": args.max_new, "max_slots": args.max_slots,
            "block_size": args.block_size, "kv_blocks": num_blocks,
            "dense_equiv_slots": args.dense_equiv_slots,
            "prefill_chunk": args.prefill_chunk,
            "shared_prefix": args.shared_prefix,
            "spec_len": args.spec, "int8": args.int8,
            "mesh": args.mesh or None,
            "kv_pool_bytes": kv_bytes,
            "model": {"vocab": args.vocab, "hidden": args.hidden,
                      "layers": args.layers, "heads": args.heads},
        },
        "levels": levels,
        "peak_tokens_per_s": max(r["tokens_per_s"] for r in levels),
        "peak_qps": max(r["qps"] for r in levels),
        "peak_inflight": max(r["max_inflight"] for r in levels),
        "peak_concurrency_vs_dense": max(
            r["concurrency_vs_dense"] for r in levels),
    }
    print(json.dumps(result))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
