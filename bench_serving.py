"""Closed-loop serving benchmark: thread-based clients hammer the
continuous-batching engine across an offered-load sweep.

Each load level runs `--clients N` closed-loop clients (every client
waits for its previous request before issuing the next — the classic
closed-loop model, so offered load scales with N) for `--steps` requests
each, then reports throughput, batch occupancy, and latency percentiles
from the serving metrics registry. One JSON line per level plus a final
``BENCH_SERVING`` object (written to --json when given), in the same
family as bench_ops.py's BENCH_* records.

CPU dry-run (the tier-1 smoke case):

    JAX_PLATFORMS=cpu python bench_serving.py --steps 2 --clients 1,2 \
        --max-new 3 --hidden 16 --layers 1 --heads 2 --vocab 31
"""

from __future__ import annotations

import argparse
import json
import threading
import time

import numpy as np


def run_level(server, n_clients, steps, prompt_len, max_new, vocab):
    """One offered-load level; returns its result row."""
    errors = []
    done = [0]
    lock = threading.Lock()
    barrier = threading.Barrier(n_clients)

    def client(cid):
        rng = np.random.RandomState(1000 + cid)
        barrier.wait()
        for _ in range(steps):
            prompt = rng.randint(0, vocab, (prompt_len,)).astype(np.int32)
            try:
                out = server.generate(prompt, max_new_tokens=max_new,
                                      timeout=120.0)
                assert out.shape == (prompt_len + max_new,)
                with lock:
                    done[0] += 1
            except Exception as e:  # noqa: BLE001 — report, keep load up
                errors.append(repr(e)[:200])

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(n_clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    snap = server.snapshot()
    lat = snap["latency_s"].get("e2e", {})
    row = {
        "clients": n_clients,
        "requests": done[0],
        "errors": len(errors),
        "wall_s": round(wall, 4),
        "qps": round(done[0] / wall, 3),
        "tokens_per_s": round(done[0] * max_new / wall, 2),
        "occupancy_avg": round(snap["batch_occupancy"]["avg"], 4),
        "occupancy_max": round(snap["batch_occupancy"]["max"], 4),
        "p50_ms": round(lat.get("p50", 0.0) * 1e3, 3),
        "p95_ms": round(lat.get("p95", 0.0) * 1e3, 3),
        "p99_ms": round(lat.get("p99", 0.0) * 1e3, 3),
    }
    if errors:
        row["first_error"] = errors[0]
    return row


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--clients", default="1,4,8",
                    help="comma-separated closed-loop client counts")
    ap.add_argument("--steps", type=int, default=8,
                    help="requests per client per level")
    ap.add_argument("--prompt-len", type=int, default=12)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--max-slots", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=97)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--max-seq-len", type=int, default=128)
    ap.add_argument("--json", default=None,
                    help="write the final BENCH_SERVING object here")
    args = ap.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu import serving
    from paddle_tpu.nlp.transformers import GPTConfig, GPTForPretraining

    paddle.seed(7)
    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers, num_heads=args.heads,
                    max_seq_len=args.max_seq_len, dropout=0.0,
                    attn_dropout=0.0, use_parallel=False)
    model = GPTForPretraining(cfg)

    levels = []
    for n_clients in [int(c) for c in args.clients.split(",") if c]:
        # fresh server per level so occupancy/latency are per-level
        server = serving.Server(model, max_slots=args.max_slots,
                                prefill_buckets=(16, 32, 64)).start()
        row = run_level(server, n_clients, args.steps, args.prompt_len,
                        args.max_new, args.vocab)
        row["compiles"] = {str(k): v
                           for k, v in server.engine.compile_counts.items()}
        server.shutdown(drain=True)
        print(json.dumps(row))
        levels.append(row)

    result = {
        "bench": "BENCH_SERVING",
        "config": {
            "steps": args.steps, "prompt_len": args.prompt_len,
            "max_new": args.max_new, "max_slots": args.max_slots,
            "model": {"vocab": args.vocab, "hidden": args.hidden,
                      "layers": args.layers, "heads": args.heads},
        },
        "levels": levels,
        "peak_tokens_per_s": max(r["tokens_per_s"] for r in levels),
        "peak_qps": max(r["qps"] for r in levels),
    }
    print(json.dumps(result))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
