"""Micro-bench: native C++ SSD spill table vs the Python reference,
plus the durable-PS chaos certification bench (``--chaos``).

Default mode (VERDICT r4 item 8 done-criterion): the native spill hot
path (hash -> on-disk record, read-merge, LRU) must beat the Python
implementation by a large factor under eviction churn.  Prints ONE
JSON line.

Workload: Zipf-ish id stream over a table 10x the LRU capacity (every
batch faults spilled rows back and evicts hot ones — the spill path IS
the hot path), pull + push_sgd per batch.

``--chaos`` (ISSUE 10 satellite 5): the same push workload over the RPC
service with a WAL + replica, injected mid-push faults and a primary
kill mid-stream. Emits one ``BENCH_PS_CHAOS`` JSON line: failover
recovery time, goodput clean vs chaos, WAL records replayed by a fresh
recovery, dedup hits, and the ChaosSchedule fired==planned verdict —
with the final state certified bitwise-equal to the clean run (zero
lost, zero double-applied).
"""

from __future__ import annotations

import json
import sys
import tempfile
import time

import numpy as np

from paddle_tpu.distributed.ps.tables import SSDSparseTable

DIM = 64
MEM_ROWS = 2_000
N_IDS = 20_000
BATCH = 512
STEPS = 200


def _run(native: bool) -> float:
    rng = np.random.RandomState(0)
    with tempfile.TemporaryDirectory() as d:
        t = SSDSparseTable("bench", dim=DIM, optimizer="sgd", lr=0.01,
                           mem_rows=MEM_ROWS, spill_dir=d,
                           use_native=native)
        if native and t._ssd_handle is None:
            raise RuntimeError("native toolchain unavailable")
        # pre-populate so the steady state is spill-dominated
        warm = np.arange(N_IDS, dtype=np.int64)
        for lo in range(0, N_IDS, 4096):
            t.pull(warm[lo:lo + 4096])
        batches = [rng.randint(0, N_IDS, BATCH).astype(np.int64)
                   for _ in range(STEPS)]
        grads = rng.randn(BATCH, DIM).astype(np.float32)
        t0 = time.perf_counter()
        for ids in batches:
            t.pull(ids)
            t.push_grad(ids, grads)
        dt = time.perf_counter() - t0
        t.close()
    return dt


def _chaos_workload(client, n_pushes, dim):
    rng = np.random.RandomState(1)
    grads = [rng.randn(dim).astype(np.float32) for _ in range(n_pushes)]
    t0 = time.perf_counter()
    for g in grads:
        client.push_dense_grad("w", g)
    return time.perf_counter() - t0


def run_chaos(n_pushes=200, dim=256):
    from paddle_tpu.distributed import ps
    from paddle_tpu.framework import faults, monitor

    with tempfile.TemporaryDirectory() as d, \
            tempfile.TemporaryDirectory() as d_ref:
        # clean reference: identical durability config (WAL + sync
        # replica), same stream, no faults, no kill — so the goodput
        # ratio isolates the chaos cost, not the durability cost
        ref_backup = ps.PSServer("127.0.0.1:0").start()
        ref_srv = ps.PSServer("127.0.0.1:0", wal_dir=d_ref,
                              backup=ref_backup.endpoint).start()
        ref = ps.PSClient([ref_srv.endpoint])
        ref.create_dense_table("w", [dim], optimizer="adagrad", lr=0.1)
        clean_s = _chaos_workload(ref, n_pushes, dim)
        want = ref.pull_dense("w")

        backup = ps.PSServer("127.0.0.1:0").start()
        primary = ps.PSServer("127.0.0.1:0", wal_dir=d,
                              backup=backup.endpoint).start()
        client = ps.PSClient([primary.endpoint],
                             backups=[backup.endpoint],
                             retry_backoff_s=0.01, op_deadline_s=30.0)
        dedup0 = monitor.stat_get("ps.dedup_hits")
        fo0 = monitor.stat_get("ps.failovers")
        half = n_pushes // 2
        rng = np.random.RandomState(1)
        grads = [rng.randn(dim).astype(np.float32)
                 for _ in range(n_pushes)]
        specs = ["ps.push@10:raise", "ps.push@40:raise",
                 "ps.pull@1:delay:0.001"]
        t0 = time.perf_counter()
        with faults.ChaosSchedule(*specs) as chaos:
            client.create_dense_table("w", [dim], optimizer="adagrad",
                                      lr=0.1)
            for g in grads[:half]:
                client.push_dense_grad("w", g)
            client.pull_dense("w")
            # primary dies mid-stream; the next push rides the failover
            primary.kill_transport()
            t_kill = time.perf_counter()
            client.push_dense_grad("w", grads[half])
            recovery_s = time.perf_counter() - t_kill
            for g in grads[half + 1:]:
                client.push_dense_grad("w", g)
            fired = chaos.verify()   # fired == planned or AssertionError
        chaos_s = time.perf_counter() - t0

        got = client.pull_dense("w")
        bitwise_equal = got.tobytes() == want.tobytes()

        # a fresh recovery over the primary's WAL replays every record
        # it had applied before death (creates + the first-half pushes)
        rec = ps.PSServer("127.0.0.1:0", wal_dir=d).start()
        wal_replayed = rec.recovered_records
        rec.stop()

        out = {
            "metric": "ps_chaos_certification",
            "value": round(clean_s / chaos_s, 3) if chaos_s else 0.0,
            "unit": "goodput_chaos_over_clean",
            "bitwise_equal": bitwise_equal,
            "recovery_s": round(recovery_s, 4),
            "clean_rows_per_s": round(n_pushes / clean_s, 1),
            "chaos_rows_per_s": round(n_pushes / chaos_s, 1),
            "wal_replayed_records": wal_replayed,
            "dedup_hits": monitor.stat_get("ps.dedup_hits") - dedup0,
            "failovers": monitor.stat_get("ps.failovers") - fo0,
            "chaos_fired": fired,
            "n_pushes": n_pushes, "dim": dim,
        }
        print("BENCH_PS_CHAOS " + json.dumps(out))
        client.stop_servers()
        backup.stop()
        primary.stop()
        ref.stop_servers()
        ref_srv.stop()
        ref_backup.stop()
        if not bitwise_equal:
            raise SystemExit("chaos run diverged from the clean run")


def main():
    if "--chaos" in sys.argv:
        run_chaos()
        return
    py = _run(False)
    nat = _run(True)
    rows_per_sec_nat = STEPS * BATCH * 2 / nat
    print(json.dumps({
        "metric": "ps_ssd_spill_speedup",
        "value": round(py / nat, 2),
        "unit": "x_vs_python",
        "python_s": round(py, 3),
        "native_s": round(nat, 3),
        "native_rows_per_sec": round(rows_per_sec_nat, 0),
        "dim": DIM, "mem_rows": MEM_ROWS, "n_ids": N_IDS,
        "batch": BATCH, "steps": STEPS,
    }))


if __name__ == "__main__":
    main()
