"""Micro-bench: native C++ SSD spill table vs the Python reference.

VERDICT r4 item 8 done-criterion: the native spill hot path (hash ->
on-disk record, read-merge, LRU) must beat the Python implementation by
a large factor under eviction churn.  Prints ONE JSON line.

Workload: Zipf-ish id stream over a table 10x the LRU capacity (every
batch faults spilled rows back and evicts hot ones — the spill path IS
the hot path), pull + push_sgd per batch.
"""

from __future__ import annotations

import json
import tempfile
import time

import numpy as np

from paddle_tpu.distributed.ps.tables import SSDSparseTable

DIM = 64
MEM_ROWS = 2_000
N_IDS = 20_000
BATCH = 512
STEPS = 200


def _run(native: bool) -> float:
    rng = np.random.RandomState(0)
    with tempfile.TemporaryDirectory() as d:
        t = SSDSparseTable("bench", dim=DIM, optimizer="sgd", lr=0.01,
                           mem_rows=MEM_ROWS, spill_dir=d,
                           use_native=native)
        if native and t._ssd_handle is None:
            raise RuntimeError("native toolchain unavailable")
        # pre-populate so the steady state is spill-dominated
        warm = np.arange(N_IDS, dtype=np.int64)
        for lo in range(0, N_IDS, 4096):
            t.pull(warm[lo:lo + 4096])
        batches = [rng.randint(0, N_IDS, BATCH).astype(np.int64)
                   for _ in range(STEPS)]
        grads = rng.randn(BATCH, DIM).astype(np.float32)
        t0 = time.perf_counter()
        for ids in batches:
            t.pull(ids)
            t.push_grad(ids, grads)
        dt = time.perf_counter() - t0
        t.close()
    return dt


def main():
    py = _run(False)
    nat = _run(True)
    rows_per_sec_nat = STEPS * BATCH * 2 / nat
    print(json.dumps({
        "metric": "ps_ssd_spill_speedup",
        "value": round(py / nat, 2),
        "unit": "x_vs_python",
        "python_s": round(py, 3),
        "native_s": round(nat, 3),
        "native_rows_per_sec": round(rows_per_sec_nat, 0),
        "dim": DIM, "mem_rows": MEM_ROWS, "n_ids": N_IDS,
        "batch": BATCH, "steps": STEPS,
    }))


if __name__ == "__main__":
    main()
