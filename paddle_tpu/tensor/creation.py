"""Tensor creation API (ref: python/paddle/tensor/creation.py)."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core import config
from ..core.dispatch import apply
from ..core.dtype import to_jax_dtype
from ..core.tensor import Tensor


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)


def _default(dtype):
    return dtype if dtype is not None else config.get_default_dtype()


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(tuple(int(s) for s in shape),
                            to_jax_dtype(_default(dtype))))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(tuple(int(s) for s in shape),
                           to_jax_dtype(_default(dtype))))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = "bool"
        elif isinstance(fill_value, int):
            dtype = "int64"
        else:
            dtype = config.get_default_dtype()
    return Tensor(jnp.full(tuple(int(s) for s in shape), fill_value,
                           to_jax_dtype(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype, name)


def zeros_like(x, dtype=None, name=None):
    return apply("full_like", x, fill_value=0, dtype=dtype)


def ones_like(x, dtype=None, name=None):
    return apply("full_like", x, fill_value=1, dtype=dtype)


def full_like(x, fill_value, dtype=None, name=None):
    return apply("full_like", x, fill_value=fill_value, dtype=dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype, name)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v

    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = "int64" if all(
            isinstance(v, (int, np.integer)) for v in (start, end, step)) \
            else config.get_default_dtype()
    return Tensor(jnp.arange(start, end, step, dtype=to_jax_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v

    dtype = _default(dtype)
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                               dtype=to_jax_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    dtype = _default(dtype)
    return Tensor(jnp.logspace(start, stop, int(num), base=base,
                               dtype=to_jax_dtype(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    dtype = _default(dtype)
    return Tensor(jnp.eye(int(num_rows),
                          int(num_columns) if num_columns else None,
                          dtype=to_jax_dtype(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    return apply("diag", x, offset=offset, padding_value=padding_value)


def diagflat(x, offset=0, name=None):
    flat = x.numpy().reshape(-1) if isinstance(x, Tensor) else np.ravel(x)
    return Tensor(jnp.diagflat(jnp.asarray(flat), k=offset))


def assign(x, output=None):
    out = apply("assign", x)
    if output is not None:
        output.set_value(out)
        return output
    return out


def clone(x, name=None):
    return x.clone()


def tril(x, diagonal=0, name=None):
    return apply("tril", x, diagonal=diagonal)


def triu(x, diagonal=0, name=None):
    return apply("triu", x, diagonal=diagonal)


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return list(apply("meshgrid", *args))


def complex(real, imag, name=None):
    from ..core.dispatch import apply as _apply

    return Tensor(jnp.asarray(real.numpy() + 1j * imag.numpy()))


def clone_detached(x):
    return x.detach()
