"""Statistics API (ref: python/paddle/tensor/stat.py)."""

from __future__ import annotations

from ..core.dispatch import apply


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply("var", x, axis=axis, unbiased=unbiased, keepdim=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply("std", x, axis=axis, unbiased=unbiased, keepdim=keepdim)


def median(x, axis=None, keepdim=False, name=None):
    return apply("median", x, axis=axis, keepdim=keepdim)


def quantile(x, q, axis=None, keepdim=False, name=None):
    return apply("quantile", x, q=q, axis=axis, keepdim=keepdim)


def numel(x, name=None):
    from .creation import to_tensor

    return to_tensor(x.size, dtype="int64")


def histogram(input, bins=100, min=0, max=0, name=None):
    return apply("histogram", input, bins=bins, min=min, max=max)


def bincount(x, weights=None, minlength=0, name=None):
    if weights is not None:
        return apply("bincount", x, weights, minlength=minlength)
    return apply("bincount", x, weights=None, minlength=minlength)
