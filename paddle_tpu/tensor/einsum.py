"""Einsum API (ref: python/paddle/tensor/einsum.py)."""

from __future__ import annotations

from ..core.dispatch import apply


def einsum(equation, *operands):
    return apply("einsum", *operands, equation=equation)
