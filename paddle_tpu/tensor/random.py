"""Random tensor API (ref: python/paddle/tensor/random.py). Keys thread
through paddle_tpu.framework.random (works both eagerly and under jit
capture via rng_scope)."""

from __future__ import annotations

from ..core import config
from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..framework import random as _random


def _key():
    return Tensor(_random.next_key())


def _shape_list(shape):
    return [int(s.item()) if isinstance(s, Tensor) else int(s)
            for s in shape]


def randn(shape, dtype=None, name=None):
    dtype = dtype or config.get_default_dtype()
    return apply("gaussian_random", _key(), shape=_shape_list(shape),
                 mean=0.0, std=1.0, dtype=dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        base = mean if isinstance(mean, Tensor) else std
        noise = apply("normal_like", base, _key(), mean=0.0, std=1.0)
        return mean + noise * std
    shape = _shape_list(shape if shape is not None else [1])
    return apply("gaussian_random", _key(), shape=shape, mean=float(mean),
                 std=float(std), dtype=config.get_default_dtype())


def rand(shape, dtype=None, name=None):
    dtype = dtype or config.get_default_dtype()
    return apply("uniform_random", _key(), shape=_shape_list(shape),
                 min=0.0, max=1.0, dtype=dtype)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    dtype = dtype or config.get_default_dtype()
    return apply("uniform_random", _key(), shape=_shape_list(shape),
                 min=float(min), max=float(max), dtype=dtype)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return apply("randint", _key(), low=int(low), high=int(high),
                 shape=_shape_list(shape), dtype=dtype)


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    return apply("randint", _key(), low=int(low), high=int(high),
                 shape=x.shape, dtype=dtype or x.dtype.name)


def randperm(n, dtype="int64", name=None):
    return apply("randperm", _key(), n=int(n), dtype=dtype)


def bernoulli(x, name=None):
    return apply("bernoulli", x, _key())


def multinomial(x, num_samples=1, replacement=False, name=None):
    return apply("multinomial", x, _key(), num_samples=int(num_samples),
                 replacement=replacement)


def poisson(x, name=None):
    return apply("poisson", x, _key())


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype, name)


def exponential_(x, lam=1.0, name=None):
    out = apply("exponential", x, _key(), lam=lam)
    x._value = out._value
    return x
