"""Linalg API (ref: python/paddle/tensor/linalg.py + paddle.linalg)."""

from __future__ import annotations

from ..core.dispatch import apply


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    if p == "fro":
        return apply("frobenius_norm", x, axis=axis, keepdim=keepdim)
    return apply("p_norm", x, porder=float(p), axis=axis, keepdim=keepdim)


def dist(x, y, p=2, name=None):
    return apply("p_norm", x - y, porder=float(p), axis=None, keepdim=False)


def cond(x, p=None, name=None):
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    return Tensor(jnp.linalg.cond(x._value, p=p))


def inv(x, name=None):
    return apply("inverse", x)


def cholesky(x, upper=False, name=None):
    return apply("cholesky", x, upper=upper)


def matrix_power(x, n, name=None):
    return apply("matrix_power", x, n=n)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply("matrix_rank", x, tol=tol, hermitian=hermitian)


def svd(x, full_matrices=False, name=None):
    return apply("svd", x, full_matrices=full_matrices)


def qr(x, mode="reduced", name=None):
    return apply("qr", x, mode=mode)


def eigh(x, UPLO="L", name=None):
    return apply("eigh", x, UPLO=UPLO)


def eigvalsh(x, UPLO="L", name=None):
    return apply("eigvalsh", x, UPLO=UPLO)


def det(x, name=None):
    return apply("det", x)


def slogdet(x, name=None):
    return apply("slogdet", x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply("pinv", x, rcond=rcond, hermitian=hermitian)


def solve(x, y, name=None):
    return apply("solve", x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    return apply("triangular_solve", x, y, upper=upper, transpose=transpose,
                 unitriangular=unitriangular)


def lstsq(x, y, rcond=None, name=None):
    return apply("lstsq", x, y, rcond=rcond)


def multi_dot(x, name=None):
    out = x[0]
    for m in x[1:]:
        out = apply("matmul_v2", out, m)
    return out
