"""Logic / comparison API (ref: python/paddle/tensor/logic.py)."""

from __future__ import annotations

from ..core.dispatch import apply


def equal(x, y, name=None):
    return apply("equal", x, y)


def not_equal(x, y, name=None):
    return apply("not_equal", x, y)


def less_than(x, y, name=None):
    return apply("less_than", x, y)


def less_equal(x, y, name=None):
    return apply("less_equal", x, y)


def greater_than(x, y, name=None):
    return apply("greater_than", x, y)


def greater_equal(x, y, name=None):
    return apply("greater_equal", x, y)


def logical_and(x, y, out=None, name=None):
    return apply("logical_and", x, y)


def logical_or(x, y, out=None, name=None):
    return apply("logical_or", x, y)


def logical_xor(x, y, out=None, name=None):
    return apply("logical_xor", x, y)


def logical_not(x, out=None, name=None):
    return apply("logical_not", x)


def is_empty(x, name=None):
    from .creation import to_tensor

    return to_tensor(x.size == 0)


def is_tensor(x):
    from ..core.tensor import Tensor

    return isinstance(x, Tensor)
