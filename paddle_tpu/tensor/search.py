"""Search/sort API (ref: python/paddle/tensor/search.py)."""

from __future__ import annotations

from ..core.dispatch import apply


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    return apply("arg_max", x, axis=axis, keepdim=keepdim, dtype=dtype)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return apply("arg_min", x, axis=axis, keepdim=keepdim, dtype=dtype)


def argsort(x, axis=-1, descending=False, name=None):
    return apply("argsort", x, axis=axis, descending=descending)


def sort(x, axis=-1, descending=False, name=None):
    vals, _ = apply("sort_op", x, axis=axis, descending=descending)
    return vals


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    from ..core.tensor import Tensor

    if isinstance(k, Tensor):
        k = int(k.item())
    vals, idx = apply("top_k_v2", x, k=k, axis=axis, largest=largest,
                      sorted=sorted)
    return vals, idx


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    return apply("kthvalue", x, k=k, axis=axis, keepdim=keepdim)


def mode(x, axis=-1, keepdim=False, name=None):
    return apply("mode_op", x, axis=axis, keepdim=keepdim)


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    return apply("searchsorted", sorted_sequence, values,
                 out_int32=out_int32, right=right)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return apply("bucketize", x, sorted_sequence, out_int32=out_int32,
                 right=right)


def index_put(x, indices, value, accumulate=False, name=None):
    return apply("index_put", x, indices, value)
