"""paddle_tpu.tensor — functional tensor API + Tensor method attachment.

Ref parity: python/paddle/tensor/__init__.py, which monkey-patches the
generated method list onto the Tensor class.
"""

from . import creation, einsum, linalg, logic, manipulation, math, random, \
    search, stat  # noqa: F401
from ..core.tensor import Tensor

# Functions that become Tensor methods, paddle-style (x is self).
_METHOD_SOURCES = [math, manipulation, logic, search, stat, linalg]

_SKIP = {"pow", "scale"}  # defined manually below / operator-backed


def _attach_methods():
    for mod in _METHOD_SOURCES:
        for name in dir(mod):
            if name.startswith("_"):
                continue
            fn = getattr(mod, name)
            if not callable(fn):
                continue
            if hasattr(Tensor, name) and name not in ("where",):
                continue
            setattr(Tensor, name, fn)
    # manual cases
    Tensor.pow = lambda self, y, name=None: math.pow(self, y)
    Tensor.scale = lambda self, scale=1.0, bias=0.0, bias_after_scale=True, \
        act=None, name=None: math.scale(self, scale, bias, bias_after_scale,
                                        act)
    Tensor.norm = linalg.norm
    Tensor.matmul = math.matmul
    Tensor.mm = math.mm


_attach_methods()
