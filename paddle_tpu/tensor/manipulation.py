"""Manipulation API (ref: python/paddle/tensor/manipulation.py)."""

from __future__ import annotations

from ..core.dispatch import apply
from ..core.tensor import Tensor


def _shape_list(shape):
    out = []
    for s in shape:
        out.append(int(s.item()) if isinstance(s, Tensor) else int(s))
    return out


def reshape(x, shape, name=None):
    return apply("reshape", x, shape=_shape_list(shape))


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._value = out._value
    return x


def transpose(x, perm, name=None):
    return apply("transpose", x, perm=list(perm))


def t(x, name=None):
    if x.ndim < 2:
        return x
    return apply("transpose", x, perm=[1, 0])


def moveaxis(x, source, destination, name=None):
    return apply("moveaxis", x, source=source, destination=destination)


def swapaxes(x, axis0, axis1, name=None):
    return apply("swapaxes", x, axis0=axis0, axis1=axis1)


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = axis.item()
    return apply("concat", *x, axis=axis)


def stack(x, axis=0, name=None):
    return apply("stack", *x, axis=axis)


def unstack(x, axis=0, num=None):
    return list(apply("unstack", x, axis=axis, num=num))


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = axis.item()
    return list(apply("split", x, num_or_sections=num_or_sections, axis=axis))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def squeeze(x, axis=None, name=None):
    return apply("squeeze", x, axis=axis)


def unsqueeze(x, axis, name=None):
    return apply("unsqueeze", x, axis=axis)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return apply("flatten", x, start_axis=start_axis, stop_axis=stop_axis)


def expand(x, shape, name=None):
    return apply("expand_v2", x, shape=_shape_list(shape))


def expand_as(x, y, name=None):
    return apply("expand_v2", x, shape=y.shape)


def broadcast_to(x, shape, name=None):
    return apply("broadcast_to", x, shape=_shape_list(shape))


def broadcast_tensors(inputs, name=None):
    import numpy as np

    shapes = [t.shape for t in inputs]
    out_shape = np.broadcast_shapes(*[tuple(s) for s in shapes])
    return [broadcast_to(t, list(out_shape)) for t in inputs]


def tile(x, repeat_times, name=None):
    return apply("tile", x, repeat_times=_shape_list(repeat_times))


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = axis.item()
    return apply("gather", x, index, axis=axis)


def gather_nd(x, index, name=None):
    return apply("gather_nd", x, index)


def scatter(x, index, updates, overwrite=True, name=None):
    return apply("scatter", x, index, updates, overwrite=overwrite)


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite)
    x._value = out._value
    return x


def scatter_nd_add(x, index, updates, name=None):
    return apply("scatter_nd_add", x, index, updates)


def scatter_nd(index, updates, shape, name=None):
    import jax.numpy as jnp

    zeros = Tensor(jnp.zeros(_shape_list(shape),
                             updates._value.dtype))
    return apply("scatter_nd_add", zeros, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply("index_select", x, index, axis=axis)


def index_sample(x, index):
    return apply("index_sample", x, index)


def take_along_axis(arr, indices, axis, name=None):
    return apply("take_along_axis", arr, indices, axis=axis)


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    return apply("put_along_axis", arr, indices, values, axis=axis,
                 reduce=reduce)


def roll(x, shifts, axis=None, name=None):
    return apply("roll", x, shifts=shifts, axis=axis)


def flip(x, axis, name=None):
    return apply("flip", x, axis=axis)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply("rot90", x, k=k, axes=axes)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    return apply("where", condition, x, y)


def nonzero(x, as_tuple=False):
    out = apply("nonzero", x)
    if not as_tuple:
        return out
    return tuple(out[:, i] for i in range(out.shape[1]))


def masked_select(x, mask, name=None):
    return apply("masked_select", x, mask)


def masked_fill(x, mask, value, name=None):
    if isinstance(value, Tensor):
        value = value.item()
    return apply("masked_fill", x, mask, value=value)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    res = apply("unique", x, return_index=return_index,
                return_inverse=return_inverse, return_counts=return_counts,
                axis=axis)
    return res


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    out = flatten(x, start_axis, stop_axis)
    x._value = out._value
    return x


def slice(input, axes, starts, ends):
    return apply("slice_op", input, axes=list(axes),
                 starts=_shape_list(starts), ends=_shape_list(ends))


def strided_slice(x, axes, starts, ends, strides, name=None):
    return apply("strided_slice", x, axes=list(axes),
                 starts=_shape_list(starts), ends=_shape_list(ends),
                 strides=_shape_list(strides))


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        repeats = repeats._value
    return apply("repeat_interleave", x, repeats=repeats, axis=axis)


def as_complex(x, name=None):
    return apply("as_complex", x)


def as_real(x, name=None):
    return apply("as_real", x)


def cast(x, dtype):
    return x.astype(dtype)


def crop(x, shape=None, offsets=None, name=None):
    shape = _shape_list(shape)
    offsets = _shape_list(offsets) if offsets is not None else [0] * x.ndim
    axes = list(range(x.ndim))
    ends = [o + s for o, s in zip(offsets, shape)]
    return apply("slice_op", x, axes=axes, starts=offsets, ends=ends)


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    return apply("diag_embed", input, offset=offset, dim1=dim1, dim2=dim2)


def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(a) if isinstance(a, (list, tuple)) else a
                     for a in axes)
    return apply("tensordot", x, y, axes=axes)
