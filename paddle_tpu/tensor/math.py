"""Math API (ref: python/paddle/tensor/math.py). Thin wrappers over the op
registry; autograd/AMP handled in core.dispatch."""

from __future__ import annotations

import sys

from ..core.dispatch import apply

_this = sys.modules[__name__]

# simple unary: api name -> op name
_UNARY = {
    "exp": "exp", "expm1": "expm1", "log": "log", "log2": "log2",
    "log10": "log10", "log1p": "log1p", "sqrt": "sqrt", "square": "square",
    "rsqrt": "rsqrt", "abs": "abs", "ceil": "ceil", "floor": "floor",
    "round": "round", "trunc": "trunc", "frac": "frac",
    "reciprocal": "reciprocal", "neg": "neg", "sign": "sign",
    "sin": "sin", "cos": "cos", "tan": "tan", "asin": "asin",
    "acos": "acos", "atan": "atan", "sinh": "sinh", "cosh": "cosh",
    "tanh": "tanh", "asinh": "asinh", "acosh": "acosh", "atanh": "atanh",
    "erf": "erf", "erfinv": "erfinv", "digamma": "digamma",
    "lgamma": "lgamma", "i0": "i0", "angle": "angle", "conj": "conj",
    "real": "real", "imag": "imag",
}

for _api, _op in _UNARY.items():
    def _make(op):
        def f(x, name=None):
            return apply(op, x)
        return f
    _f = _make(_op)
    _f.__name__ = _api
    setattr(_this, _api, _f)

_BINARY = {
    "add": "elementwise_add", "subtract": "elementwise_sub",
    "multiply": "elementwise_mul", "divide": "elementwise_div",
    "floor_divide": "elementwise_floordiv", "mod": "elementwise_mod",
    "remainder": "remainder", "floor_mod": "elementwise_mod",
    "maximum": "elementwise_max", "minimum": "elementwise_min",
    "fmax": "fmax", "fmin": "fmin", "atan2": "atan2",
    "nextafter": "nextafter", "logaddexp": "logaddexp",
    "heaviside": "elementwise_heaviside",
}

for _api, _op in _BINARY.items():
    def _make2(op):
        def f(x, y, name=None):
            return apply(op, x, y)
        return f
    _f = _make2(_op)
    _f.__name__ = _api
    setattr(_this, _api, _f)


def pow(x, y, name=None):
    if isinstance(y, (int, float)):
        return apply("pow", x, factor=float(y))
    return apply("elementwise_pow", x, y)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = apply("scale", x, scale=float(scale), bias=float(bias),
                bias_after_scale=bias_after_scale)
    if act is not None:
        out = apply(act, out)
    return out


def clip(x, min=None, max=None, name=None):
    def _v(v):
        from ..core.tensor import Tensor
        return v.item() if isinstance(v, Tensor) else v
    return apply("clip", x, min=_v(min), max=_v(max))


def lerp(x, y, weight, name=None):
    return apply("lerp", x, y, weight)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply("stanh", x, scale_a=scale_a, scale_b=scale_b)


# -- matmul family ---------------------------------------------------------


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return apply("matmul_v2", x, y, trans_x=transpose_x, trans_y=transpose_y)


def mm(x, y, name=None):
    return apply("matmul_v2", x, y)


def bmm(x, y, name=None):
    return apply("bmm", x, y)


def addmm(input, x, y, alpha=1.0, beta=1.0, name=None):
    return apply("addmm", input, x, y, alpha=alpha, beta=beta)


def dot(x, y, name=None):
    return apply("dot", x, y)


def outer(x, y, name=None):
    return apply("outer", x, y)


def cross(x, y, axis=None, name=None):
    return apply("cross", x, y, axis=axis)


def kron(x, y, name=None):
    return apply("kron", x, y)


def inner(x, y, name=None):
    return apply("matmul_v2", x, y, trans_x=False, trans_y=True)


def multiply_(x, y):
    out = apply("elementwise_mul", x, y)
    x._value = out._value
    return x


# -- reductions ------------------------------------------------------------


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    out = apply("reduce_sum", x, axis=axis, keepdim=keepdim)
    if dtype is not None:
        out = out.astype(dtype)
    return out


def mean(x, axis=None, keepdim=False, name=None):
    return apply("reduce_mean", x, axis=axis, keepdim=keepdim)


def max(x, axis=None, keepdim=False, name=None):
    return apply("reduce_max", x, axis=axis, keepdim=keepdim)


def min(x, axis=None, keepdim=False, name=None):
    return apply("reduce_min", x, axis=axis, keepdim=keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    out = apply("reduce_prod", x, axis=axis, keepdim=keepdim)
    if dtype is not None:
        out = out.astype(dtype)
    return out


def amax(x, axis=None, keepdim=False, name=None):
    return apply("amax", x, axis=axis, keepdim=keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return apply("amin", x, axis=axis, keepdim=keepdim)


def any(x, axis=None, keepdim=False, name=None):
    return apply("reduce_any", x, axis=axis, keepdim=keepdim)


def all(x, axis=None, keepdim=False, name=None):
    return apply("reduce_all", x, axis=axis, keepdim=keepdim)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply("logsumexp", x, axis=axis, keepdim=keepdim)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    out = apply("nansum", x, axis=axis, keepdim=keepdim)
    return out.astype(dtype) if dtype is not None else out


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply("nanmean", x, axis=axis, keepdim=keepdim)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply("count_nonzero", x, axis=axis, keepdim=keepdim)


# -- cumulative ------------------------------------------------------------


def cumsum(x, axis=None, dtype=None, name=None):
    out = apply("cumsum", x, axis=axis)
    return out.astype(dtype) if dtype is not None else out


def cumprod(x, dim=None, dtype=None, name=None):
    out = apply("cumprod", x, dim=dim)
    return out.astype(dtype) if dtype is not None else out


def logcumsumexp(x, axis=None, name=None):
    return apply("logcumsumexp", x, axis=axis)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("trace_op", x, offset=offset, axis1=axis1, axis2=axis2)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("diagonal", x, offset=offset, axis1=axis1, axis2=axis2)


def increment(x, value=1.0, name=None):
    out = apply("scale", x, scale=1.0, bias=float(value))
    x._value = out._value
    return x


def isnan(x, name=None):
    return apply("isnan", x)


def isinf(x, name=None):
    return apply("isinf", x)


def isfinite(x, name=None):
    return apply("isfinite", x)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply("isclose", x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply("reduce_all", apply("isclose", x, y, rtol=rtol, atol=atol,
                                     equal_nan=equal_nan))


def equal_all(x, y, name=None):
    return apply("reduce_all", apply("equal", x, y))
