"""paddle_tpu.jit — compiled capture of eager code.

Ref parity: python/paddle/fluid/dygraph/jit.py (@to_static / declarative,
jit.save/load, TracedLayer). TPU-native: instead of AST-rewriting Python
into a ProgramDesc (dygraph_to_static/), the eager code *is* traceable —
`to_static` runs the same forward under `jax.jit` with parameters passed
functionally, producing one cached XLA computation per input signature.
`jit.save` serialises the lowered StableHLO via jax.export plus the
state_dict; `jit.load` restores an executable TranslatedLayer.
"""

from __future__ import annotations

import os
import pickle

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..engine import functional_call, state_values
from ..nn.layer.layers import Layer


class InputSpec:
    """ref: python/paddle/static/input.py InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"

    def to_shape_dtype(self):
        from ..core.dtype import to_jax_dtype

        shape = [1 if s is None or s < 0 else s for s in self.shape]
        return jax.ShapeDtypeStruct(tuple(shape), to_jax_dtype(self.dtype))


class StaticFunction:
    """A callable that runs its wrapped eager function as a compiled XLA
    program (ref: dygraph_to_static/program_translator.py StaticFunction)."""

    def __init__(self, function, input_spec=None, layer=None):
        self._function = function
        self._input_spec = input_spec
        self._layer = layer
        self._jitted = None
        self._writeback = None
        self._read_entry = None

    def _get_layer(self):
        if self._layer is not None:
            return self._layer
        self_obj = getattr(self._function, "__self__", None)
        if isinstance(self_obj, Layer):
            return self_obj
        return None

    def _build(self):
        layer = self._get_layer()

        fn = self._function
        from .dy2static import ProgramTranslator, maybe_rewrite

        if ProgramTranslator.enable_to_static:
            # AST pass: tensor-dependent if/while/for lower to lax
            # control flow instead of failing at trace time
            fn = maybe_rewrite(fn)

        # global/nonlocal cell passing: jit the INNER function (whose
        # returns pack the cell finals as data), read the LIVE entry
        # values per call (threaded as jit inputs, never baked into the
        # cached program), and apply the write-back to the concrete
        # outputs outside the trace — a traced store into a Python cell
        # would leak tracers
        self._writeback = getattr(fn, "__d2s_writeback__", None)
        self._read_entry = getattr(fn, "__d2s_read_entry__", None)
        cell_names = getattr(fn, "__d2s_cell_names__", ())
        self._cell_names = cell_names
        self._cell_stash = {}
        from collections import OrderedDict

        self._sig_lru = OrderedDict()
        self._cache_warned = False
        if self._writeback is not None:
            fn = fn.__d2s_inner__
        n_cells = len(cell_names)
        stash = self._cell_stash
        from .dy2static import UNDEF as _UNDEF

        def _is_arrayish(u):
            return isinstance(u, (bool, int, float, jax.Array)) or (
                hasattr(u, "dtype") and hasattr(u, "shape"))

        def _split_cells(arrs):
            if not n_cells:
                return arrs, {}
            user, extra = arrs[:-n_cells], arrs[-n_cells:]
            kw = {nm: (Tensor(v) if isinstance(v, jax.Array) else v)
                  for nm, v in zip(cell_names, extra)}
            return user, kw

        def _digest(u):
            """Structural, hashable digest of one static entry value.
            Must be identical for the same value at trace time and call
            time (id() is not: the trace-time object dies and new equal
            objects get fresh — or recycled — ids), and must match how
            jax keys its own cache: hashable values by value, traced
            pytrees by treedef + leaf shape/dtype."""
            if _is_arrayish(u):
                shp = tuple(getattr(u, "shape", ()) or ())
                # canonicalized result_type so a python-float leaf at
                # call time digests identically to the weak-f32 tracer
                # it becomes under the trace
                try:
                    dt = str(jax.dtypes.canonicalize_dtype(
                        jnp.result_type(u)))
                except (TypeError, ValueError):
                    dt = type(u).__name__
                return ("a", shp, dt)
            try:
                hash(u)
                return ("h", u)
            except TypeError:
                leaves, treedef = jax.tree_util.tree_flatten(u)
                return ("t", type(u).__name__, str(treedef),
                        tuple(_digest(l) for l in leaves))

        def _cell_sig(extra_vals):
            """Hashable signature of the NON-array cell inputs — keys
            the stash so per-static-value retraces never serve another
            value's stashed write-back."""
            sig = []
            for j, v in enumerate(extra_vals):
                u = v._value if isinstance(v, Tensor) else v
                if not _is_arrayish(u):
                    sig.append((j, _digest(u)))
            return tuple(sig)

        def _sanitize(vals, kind, sig):
            """Cell write-back values leaving the jitted program: arrays
            pass through; non-array trace-time constants (str/objects)
            are stashed under the static-input signature and replaced by
            the UNDEF pytree node (valid jit output structure, no
            leaves) — the caller substitutes the stash back."""
            out = []
            for j, v in enumerate(vals):
                u = v._value if isinstance(v, Tensor) else v
                if _is_arrayish(u):
                    out.append(u)
                else:
                    if u is not _UNDEF:
                        leaves = jax.tree_util.tree_leaves(
                            u, is_leaf=lambda t: isinstance(t, Tensor))
                        if any(isinstance(
                                l._value if isinstance(l, Tensor) else l,
                                jax.core.Tracer) for l in leaves):
                            raise TypeError(
                                "dy2static: a cell/global written inside "
                                "a to_static function holds traced "
                                "tensors inside a plain Python container "
                                f"({type(u).__name__}) — the values "
                                "would leak out of the compiled program "
                                "as tracers. Write back the tensors "
                                "directly (or a list/dict jax can "
                                "flatten is fine as a RETURN value); "
                                "keep trace-time constants "
                                "(str/int/objects) pure Python.")
                        stash[(sig, kind, j)] = u
                    out.append(_UNDEF)
            return tuple(out)

        def _pack_out(out, kw):
            if self._writeback is None:
                return jax.tree.map(
                    lambda t: t._value if isinstance(t, Tensor) else t,
                    out, is_leaf=lambda t: isinstance(t, Tensor))
            o, cv, gv = out
            o = jax.tree.map(
                lambda t: t._value if isinstance(t, Tensor) else t, o,
                is_leaf=lambda t: isinstance(t, Tensor))
            sig = _cell_sig(tuple(kw.values()))
            nn = len(cv)
            both = _sanitize(tuple(cv) + tuple(gv), "cg", sig)
            return o, both[:nn], both[nn:]

        self._cell_sig = _cell_sig

        if layer is not None:
            # call the original forward, not layer() — when to_static
            # replaced layer.forward, going through Layer.__call__ would
            # recurse into this StaticFunction
            orig_forward = fn
            from ..engine import _swap_state, _unwrap

            def run(values, *arrs):
                from ..core.config import no_tape

                user, kw = _split_cells(arrs)
                wrapped = [Tensor(a) if isinstance(a, jax.Array) else a
                           for a in user]
                with no_tape(), _swap_state(layer, values):
                    out = orig_forward(*wrapped, **kw)
                if self._writeback is not None:
                    return _pack_out(out, kw)
                return _unwrap(out)

            self._run = run
            self._with_values = True
        else:
            def run(*arrs):
                user, kw = _split_cells(arrs)
                wrapped = [Tensor(a) if isinstance(a, jax.Array) else a
                           for a in user]
                out = fn(*wrapped, **kw)
                return _pack_out(out, kw)

            self._run = run
            self._with_values = False
        self._jitted = {}

    def _note_sig(self, sig):
        """LRU bookkeeping for the per-static-value caches. Each distinct
        static cell/global value keys a stash entry AND a trace in the
        jax.jit cache; code that cycles through unbounded distinct values
        (f-strings, fresh objects per call) would grow both forever.
        Beyond PADDLE_TPU_D2S_STATIC_CACHE distinct signatures (default
        32) the oldest signature's stash entries are dropped and the jit
        caches cleared (a later call with an evicted value retraces —
        correct, just slower), with a one-time warning."""
        lru = self._sig_lru
        if sig in lru:
            lru.move_to_end(sig)
            return
        lru[sig] = None
        limit = int(os.environ.get("PADDLE_TPU_D2S_STATIC_CACHE", "32"))
        if len(lru) <= max(limit, 1):
            return
        old, _ = lru.popitem(last=False)
        for k in [k for k in self._cell_stash if k[0] == old]:
            del self._cell_stash[k]
        for j in self._jitted.values():
            clear = getattr(j, "clear_cache", None)
            if clear is not None:
                clear()
        if not self._cache_warned:
            self._cache_warned = True
            import warnings

            warnings.warn(
                "to_static: more than "
                f"{limit} distinct static (non-array) cell/global values "
                "seen by one compiled function — each forces its own "
                "retrace. Evicting least-recently-used entries; raise "
                "PADDLE_TPU_D2S_STATIC_CACHE or make the value a traced "
                "array if this is hot-path.")

    def __call__(self, *args, **kwargs):
        if self._jitted is None:
            self._build()
        import numpy as _np

        if kwargs:
            # the compiled runner is positional-only: bind keywords into
            # their positional slots (silently dropping them would run
            # the function with default values)
            import inspect

            bound = inspect.signature(self._function).bind(*args,
                                                           **kwargs)
            if bound.kwargs:
                raise NotImplementedError(
                    "to_static: keyword-only arguments are not "
                    f"supported: {sorted(bound.kwargs)}")
            args = bound.args
        # tensors/arrays/floats are traced; Python bools/ints (the
        # values that drive Python control flow and shapes) stay static
        # so plain-Python `if`/`range` on them keeps exact semantics
        offset = 1 if self._with_values else 0
        arrs = []
        static_idx = []
        for i, a in enumerate(args):
            if isinstance(a, Tensor):
                arrs.append(a._value)
            elif isinstance(a, (_np.ndarray, jax.Array)):
                arrs.append(jnp.asarray(a))
            elif isinstance(a, (bool, int, str)) or a is None:
                arrs.append(a)
                static_idx.append(i + offset)
            elif isinstance(a, (list, tuple)):
                try:
                    arrs.append(jnp.asarray(a))
                except (TypeError, ValueError):
                    arrs.append(tuple(a) if isinstance(a, list) else a)
                    static_idx.append(i + offset)
            else:
                arrs.append(jnp.asarray(a))
        entry_vals = None
        if self._read_entry is not None:
            # live cell/global entry values, threaded so the cached
            # program recomputes from the CURRENT state every call:
            # numerics trace; hashable non-arrays (str/enums/objects)
            # become STATIC args (value-keyed recompile — exact
            # semantics per distinct value); list/dict pytrees trace
            # their leaves
            entry_vals = self._read_entry()
            for v in entry_vals:
                u = v._value if isinstance(v, Tensor) else v
                if isinstance(u, jax.Array):
                    arrs.append(u)
                elif isinstance(u, (bool, int, float, _np.ndarray)):
                    arrs.append(jnp.asarray(u))
                elif isinstance(u, (list, dict)):
                    arrs.append(u)          # pytree leaves trace
                else:
                    arrs.append(u)
                    static_idx.append(offset + len(arrs) - 1)
        key = tuple(static_idx)
        if key not in self._jitted:
            self._jitted[key] = jax.jit(self._run, static_argnums=key)
        layer = self._get_layer()
        if layer is not None:
            out = self._jitted[key](state_values(layer), *arrs)
        else:
            out = self._jitted[key](*arrs)
        if self._writeback is not None:
            out, cvals, gvals = out
            from .dy2static import UNDEF as _UNDEF

            n_cells = len(self._cell_names)
            sig = self._cell_sig(tuple(entry_vals)) \
                if entry_vals is not None else ()
            self._note_sig(sig)
            nn = len(cvals)

            def resolve(kind_j, v):
                if v is _UNDEF:
                    return self._cell_stash.get((sig, "cg", kind_j),
                                                _UNDEF)
                return v

            cvals = tuple(resolve(j, v) for j, v in enumerate(cvals))
            gvals = tuple(resolve(nn + j, v)
                          for j, v in enumerate(gvals))
            self._writeback(cvals, gvals)
        return jax.tree.map(Tensor, out)

    @property
    def code(self):
        import inspect

        return inspect.getsource(self._function)

    def concrete_program(self, *args):
        return self._jitted


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None):
    """Decorator / wrapper. Accepts a function, bound method, or Layer."""

    def decorate(fn):
        if isinstance(fn, Layer):
            layer = fn
            sf = StaticFunction(layer.forward, input_spec, layer=layer)
            layer.forward = sf
            return layer
        return StaticFunction(fn, input_spec)

    if function is not None:
        return decorate(function)
    return decorate


declarative = to_static


def not_to_static(fn):
    return fn


# ---------------------------------------------------------------------------
# save / load
# ---------------------------------------------------------------------------


def save(layer, path, input_spec=None, **configs):
    """Serialise layer -> {path}.pdiparams (state dict) + {path}.pdmodel
    (jax.export StableHLO bytes, when exportable).

    ref: fluid/dygraph/jit.py:515 jit.save -> save_inference_model.
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    is_layer = isinstance(layer, Layer)
    state = {}
    if is_layer:
        for k, v in layer.state_dict().items():
            state[k] = np.asarray(v._value)
    from ..framework.op_version import version_map

    state["__op_versions__"] = version_map()
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state, f, protocol=4)

    exported_bytes = None
    if input_spec is not None and is_layer:
        try:
            import jax.export  # noqa: F401 — not exposed by bare `import jax`
            specs = [s.to_shape_dtype() if isinstance(s, InputSpec) else
                     jax.ShapeDtypeStruct(tuple(s.shape),
                                          s._value.dtype)
                     for s in input_spec]
            values = state_values(layer)

            def run(values, *arrs):
                return functional_call(layer, values, *arrs)

            exp = jax.export.export(jax.jit(run))(
                jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    values), *specs)
            exported_bytes = exp.serialize()
        except Exception as e:  # noqa: BLE001 — export is best-effort
            import warnings

            warnings.warn(f"jit.save: StableHLO export failed ({e}); "
                          "saving params only")
    if exported_bytes is not None:
        with open(path + ".pdmodel", "wb") as f:
            f.write(exported_bytes)


class TranslatedLayer(Layer):
    """Executable deserialised program (ref: fluid/dygraph/io.py
    TranslatedLayer)."""

    def __init__(self, exported, state):
        super().__init__()
        self._exported = exported
        self._state = state

    def forward(self, *args):
        from collections import OrderedDict

        arrs = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
                for a in args]
        # the export traced an OrderedDict of values — the call-time
        # pytree must match its type and key order exactly
        values = OrderedDict(
            (k, jnp.asarray(v)) for k, v in self._state.items())
        out = self._exported.call(values, *arrs)
        return jax.tree.map(Tensor, out)


def load(path, **configs):
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    saved_versions = state.pop("__op_versions__", None)
    if saved_versions is not None:
        from ..framework.op_version import check_compatibility

        check_compatibility(saved_versions)
    model_path = path + ".pdmodel"
    if os.path.exists(model_path):
        import jax.export  # noqa: F401 — not exposed by bare `import jax`

        with open(model_path, "rb") as f:
            exported = jax.export.deserialize(f.read())
        return TranslatedLayer(exported, state)
    return state


class TracedLayer:
    """ref fluid/dygraph/jit.py:1136 TracedLayer: trace a dygraph layer
    once with example inputs, then run/serialise the captured program.

        out, traced = TracedLayer.trace(layer, [x])
        y = traced([x2])
        traced.save_inference_model("path")
    """

    def __init__(self, layer, example_inputs):
        self._layer = layer
        self._example = [a._value if isinstance(a, Tensor)
                         else jnp.asarray(a) for a in example_inputs]
        self._static = StaticFunction(layer.forward, layer=layer)

    @staticmethod
    def trace(layer, inputs):
        inputs = list(inputs) if isinstance(inputs, (list, tuple)) \
            else [inputs]
        traced = TracedLayer(layer, inputs)
        out = traced(inputs)
        return out, traced

    def __call__(self, inputs):
        inputs = list(inputs) if isinstance(inputs, (list, tuple)) \
            else [inputs]
        return self._static(*inputs)

    def save_inference_model(self, path, feed=None, fetch=None, **kw):
        if feed is not None or fetch is not None:
            raise NotImplementedError(
                "TracedLayer.save_inference_model saves the full traced "
                "forward; feed/fetch pruning is not supported — slice "
                "inputs/outputs in the layer instead")
        specs = [InputSpec(list(a.shape), a.dtype.name)
                 for a in self._example]
        save(self._layer, path, input_spec=specs)
