"""AST dygraph-to-static: rewrite Python control flow over tensors.

Ref parity: python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py:759 (ProgramTranslator) + the transformer files
(ifelse_transformer, loop_transformer, logical_transformer,
convert_operators). The reference rewrites `if`/`while`/`for`/`and`/`or`
into convert_* calls that build ProgramDesc cond/while blocks. TPU-native
redesign: the same source rewrite, but the convert helpers dispatch at
RUN time — a predicate that is a concrete value keeps exact Python
semantics (including side effects and early exit), and only an abstract
traced value lowers to `lax.cond` / `lax.while_loop`, which is what XLA
compiles. There is no ProgramDesc: the rewritten function is ordinary
Python that jax.jit traces.

Mechanics (mirrors the reference's UndefinedVar machinery):
- every name STORED in a branch/loop-body becomes an explicit in/out of
  a lifted local function; pure reads resolve through the closure;
- names possibly unbound at the call site are captured with `_d2s_ld`,
  which yields the UNDEF sentinel (a childless pytree node, so jax
  treats it as structure, not data);
- early returns ANYWHERE outside loops are normalised to
  all-paths-tail-return by duplicating continuations into
  non-returning paths (`_flatten_returns`; the reference's
  return_transformer reaches the same form with a guard flag — flags
  would join a returned value with an undefined one, which lax.cond's
  matched-pytree branches cannot express);
- return/break/continue INSIDE While/For(range) bodies lower through a
  flag pre-pass (`_LoopEscapeLowerer`): escapes become boolean guards
  threaded through the loop carry, the loop test gains `not brk`, and
  a post-loop `if ret: return rv` re-enters the early-return
  normalisation; the return-value slot starts as an AutoZero sentinel
  the runtime promotes to structure-matched zeros (never observable —
  every read is guarded by the flag);
- `global`/`nonlocal` lower via cell passing (`_lower_cell_vars`, ref
  variable_trans_func.py): declared names are entry-loaded into plain
  locals (threading through lax control flow like any stored name) and
  every exit packs the finals into the return (`_d2s_cpack`); the
  caller-side wrapper stores them back OUTSIDE any jit trace
  (to_static jits `__d2s_inner__` and applies `__d2s_writeback__` to
  concrete outputs).  Documented divergence: stores become visible at
  function exit, not per assignment;
- escapes inside `try` BODIES / except handlers / else lower through
  the same flag pre-pass (setting a flag never raises and never jumps,
  so handler reachability and `finally` timing match Python exactly);
- For over non-range iterables with escapes desugars to a counter over
  an indexable view (`_d2s_seq`/`_d2s_getitem`): sequences index
  live (Python's own list iterator is index-based, so mid-loop
  mutation behaves identically), generic iterables materialise once,
  and when a traced escape lowers the loop, python sequences densify
  to arrays (tensor iterables index dynamically as-is);
- REMAINING trace fallbacks, each with a written argument: escapes
  inside `finally` (Python's finally-escape OVERRIDES an in-flight
  try-body escape — an ordering the forward-only flag rewrite cannot
  express, see _escape_inside_finally) and functions whose source is
  unavailable (exec/REPL);
- an in-loop `return x` in a function that can also fall off the end
  (implicit None) cannot trace — the structures differ; the cond join
  raises a TypeError explaining the fix (concrete inputs still run
  with exact Python semantics).
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
import warnings

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor

__all__ = ["rewrite", "maybe_rewrite", "ProgramTranslator",
           "convert_ifelse", "convert_while_loop"]


# ---------------------------------------------------------------------------
# runtime convert helpers
# ---------------------------------------------------------------------------


class _Undef:
    """Placeholder for a possibly-unbound local (ref UndefinedVar).
    Any use raises like the UnboundLocalError the original code would
    have produced (instead of the sentinel flowing into results)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<undefined local>"

    def _raise(self, *a, **k):
        raise UnboundLocalError(
            "dy2static: local variable referenced before assignment "
            "(it is only bound in an untaken branch)")

    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _raise
    __truediv__ = __rtruediv__ = __matmul__ = __rmatmul__ = _raise
    __neg__ = __abs__ = __bool__ = __float__ = __int__ = _raise
    __lt__ = __le__ = __gt__ = __ge__ = __call__ = _raise
    __getitem__ = __setitem__ = __len__ = __iter__ = _raise


UNDEF = _Undef()
jax.tree_util.register_pytree_node(
    _Undef, lambda u: ((), None), lambda aux, ch: UNDEF)


class _AutoZero:
    """Initializer for COMPILER-GENERATED slots (the loop-escape return
    value `__d2s_rvN`).  Unlike UNDEF, a traced branch join is allowed
    to promote it to zeros matching the other side's structure — safe
    only because generated code guards every read of the slot behind
    the escape flag, so the zeros are never observable."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<autozero>"


AUTOZERO = _AutoZero()
jax.tree_util.register_pytree_node(
    _AutoZero, lambda u: ((), None), lambda aux, ch: AUTOZERO)


def _contains_auto(t):
    leaf = lambda v: isinstance(v, _AutoZero)  # noqa: E731
    return any(leaf(x) for x in jax.tree_util.tree_leaves(t, is_leaf=leaf))


def _zeros_like_sds(t):
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), t)


def _poison_like_sds(t):
    """Loud initializer for USER variables first assigned inside a
    traced loop: if the loop runs zero iterations at runtime, a
    post-loop read sees NaN (floats) / int-min (ints) instead of the
    UnboundLocalError eager Python would raise — trace-time lowering
    cannot raise data-dependently, so make the value propagate visibly
    rather than silently as zeros."""
    def fill(s):
        if jnp.issubdtype(s.dtype, jnp.floating):
            return jnp.full(s.shape, jnp.nan, s.dtype)
        if jnp.issubdtype(s.dtype, jnp.integer):
            return jnp.full(s.shape, jnp.iinfo(s.dtype).min, s.dtype)
        return jnp.zeros(s.shape, s.dtype)

    return jax.tree.map(fill, t)


def _promote_autozero(run, self_shapes, other_shapes):
    """Wrap a traced branch/body so output slots that are AutoZero on
    this side but concrete on the other come out as zeros of the other
    side's structure, letting lax.cond join a returned value with its
    not-yet-assigned slot."""
    if not (isinstance(self_shapes, tuple) and isinstance(other_shapes,
                                                          tuple)
            and len(self_shapes) == len(other_shapes)):
        return run
    fixes = {
        i: other_shapes[i]
        for i in range(len(self_shapes))
        if isinstance(self_shapes[i], _AutoZero)
        and not _contains_auto(other_shapes[i])
    }
    if not fixes:
        return run

    def fixed(operand):
        outs = list(run(operand))
        for i, sds in fixes.items():
            outs[i] = _zeros_like_sds(sds)
        return tuple(outs)

    return fixed


def _d2s_ld(thunk):
    """Capture a local that may be unbound at this point."""
    try:
        return thunk()
    except NameError:
        return UNDEF


def _unwrap(v):
    return v._value if isinstance(v, Tensor) else v


def _tree_unwrap(t):
    return jax.tree.map(_unwrap, t,
                        is_leaf=lambda x: isinstance(x, Tensor))


def _tree_wrap(t):
    return jax.tree.map(
        lambda x: Tensor(x) if isinstance(x, jax.Array) else x, t)


_TRACE_ERRORS = (jax.errors.TracerBoolConversionError,
                 jax.errors.ConcretizationTypeError)


def convert_ifelse(pred, true_fn, false_fn, ins):
    """ref convert_operators.convert_ifelse: Python `if` for concrete
    predicates, lax.cond for traced ones."""
    p = _unwrap(pred)
    try:
        pb = bool(p)
    except _TRACE_ERRORS:
        init = _tree_unwrap(tuple(ins))

        def branch(fn):
            def run(operand):
                outs = fn(*_tree_wrap(operand))
                return _tree_unwrap(outs)
            return run

        tb, fb = branch(true_fn), branch(false_fn)
        if _contains_auto(init):
            ts = jax.eval_shape(tb, init)
            fs = jax.eval_shape(fb, init)
            tb = _promote_autozero(tb, ts, fs)
            fb = _promote_autozero(fb, fs, ts)
        try:
            out = lax.cond(jnp.reshape(p, ()), tb, fb, init)
        except TypeError as e:
            if "structure" in str(e) or "pytree" in str(e):
                raise TypeError(
                    "dy2static: the two paths of a tensor-dependent "
                    "branch produce different value structures (e.g. a "
                    "lowered in-loop `return x` joining a fall-off-the-"
                    "end implicit `return None`). Give every exit path "
                    "of the function the same structure. Original "
                    "error: " + str(e)) from e
            raise
        return _tree_wrap(out)
    return true_fn(*ins) if pb else false_fn(*ins)


def convert_while_loop(cond_fn, body_fn, ins):
    """ref convert_operators.convert_while_loop.  Concrete predicates
    run as a Python loop; the first traced predicate — including one
    that only BECOMES traced mid-loop, e.g. `while True` whose escape
    flag turns traced when a tensor-pred `break` fires — lowers the
    remaining iterations to lax.while_loop (loop peeling)."""
    vals = tuple(ins)
    while True:
        try:
            cb = bool(_unwrap(cond_fn(*vals)))
        except _TRACE_ERRORS:
            return _lax_while(cond_fn, body_fn, vals)
        if not cb:
            return vals
        vals = tuple(body_fn(*vals))


def _lax_while(cond_fn, body_fn, ins):
    init = _tree_unwrap(tuple(ins))

    def cond_w(carry):
        return jnp.reshape(_unwrap(cond_fn(*_tree_wrap(carry))), ())

    def body_w(carry):
        return _tree_unwrap(body_fn(*_tree_wrap(carry)))

    if any(isinstance(a, (_AutoZero, _Undef)) for a in init):
        # Materialize placeholder carry slots at the structure the body
        # produces for them: AutoZero (compiler-generated loop-escape
        # return values; zero-filled — every read is flag-guarded) and
        # UNDEF (user names first assigned inside the loop body —
        # poison-filled, so a post-loop read after a zero-trip loop is
        # loudly NaN, and a read-before-write inside the body still
        # raises during eval_shape).  Fixed-point iteration: one slot's
        # promotion can concretize another's structure (chained escapes
        # through nested loops).
        def is_ph(v):
            return isinstance(v, (_AutoZero, _Undef))

        for _ in range(8):
            out_s = jax.eval_shape(body_w, init)
            init2, changed = [], False
            for a, b in zip(init, tuple(out_s)):
                if is_ph(a) and not any(
                        is_ph(x) for x in jax.tree_util.tree_leaves(
                            b, is_leaf=is_ph)):
                    init2.append(_zeros_like_sds(b)
                                 if isinstance(a, _AutoZero)
                                 else _poison_like_sds(b))
                    changed = True
                elif isinstance(a, _Undef) and isinstance(b, _AutoZero):
                    # inner lowered loop whose return never fired in
                    # this trace: converge the slot to AutoZero
                    init2.append(AUTOZERO)
                    changed = True
                else:
                    init2.append(a)
            init = tuple(init2)
            if not changed:
                break
    return _tree_wrap(lax.while_loop(cond_w, body_w, init))


def convert_logical_and(a, b_thunk):
    av = _unwrap(a)
    try:
        ab = bool(av)
    except _TRACE_ERRORS:
        return Tensor(jnp.logical_and(av, _unwrap(b_thunk())))
    return b_thunk() if ab else a


def convert_logical_or(a, b_thunk):
    av = _unwrap(a)
    try:
        ab = bool(av)
    except _TRACE_ERRORS:
        return Tensor(jnp.logical_or(av, _unwrap(b_thunk())))
    return a if ab else b_thunk()


def convert_logical_not(a):
    av = _unwrap(a)
    try:
        ab = bool(av)
    except _TRACE_ERRORS:
        return Tensor(jnp.logical_not(av))
    return not ab


def _d2s_seq(it):
    """Indexable view of a for-loop iterable: sequences/arrays/Tensors
    pass through (index-based iteration, mutation-visible like Python's
    list iterator); other iterables materialise once."""
    if isinstance(it, (list, tuple, Tensor)) or hasattr(it, "shape"):
        return it
    return list(it)


def _d2s_seq_len(s):
    if isinstance(s, (list, tuple)):
        return len(s)
    return int(s.shape[0])


def _d2s_getitem(seq, i):
    """Loop-element fetch: plain indexing while the counter is
    concrete; when the loop has lowered to lax.while (traced escape
    predicate) a python sequence densifies to an array so the traced
    counter can index it — non-uniform sequences cannot, with a clear
    error."""
    iv = _unwrap(i)
    if isinstance(seq, (list, tuple)) and isinstance(iv, jax.core.Tracer):
        try:
            arr = jnp.asarray(
                [_unwrap(v) for v in seq])
        except (TypeError, ValueError) as e:
            raise TypeError(
                "dy2static: a for-loop with a tensor-dependent escape "
                "over a non-uniform python sequence cannot lower to "
                "compiled control flow; iterate a tensor or make the "
                "escape predicate concrete") from e
        return Tensor(arr[iv])
    return seq[i]


def _d2s_cget(cell):
    try:
        return cell.cell_contents
    except ValueError:
        return UNDEF


def _d2s_gget(gdict, name):
    try:
        return gdict[name]
    except KeyError:
        import builtins

        return getattr(builtins, name, UNDEF)


def _d2s_cpack(val, cvals, gvals):
    """Returns thread cell/global write-backs as data: the to_static
    wrapper applies them OUTSIDE the jitted program (a traced store
    into a Python cell would leak tracers), the eager wrapper applies
    them immediately."""
    return val, tuple(cvals), tuple(gvals)


def _write_cells(cells, cvals, gdict, gnames, gvals):
    for c, v in zip(cells, cvals):
        if v is not UNDEF:
            c.cell_contents = v
    for n, v in zip(gnames, gvals):
        if v is not UNDEF:
            gdict[n] = v


_HELPERS = {
    "_d2s_if": convert_ifelse,
    "_d2s_while": convert_while_loop,
    "_d2s_and": convert_logical_and,
    "_d2s_or": convert_logical_or,
    "_d2s_not": convert_logical_not,
    "_d2s_ld": _d2s_ld,
    "_d2s_auto": AUTOZERO,
    "_d2s_seq": _d2s_seq,
    "_d2s_seq_len": _d2s_seq_len,
    "_d2s_getitem": _d2s_getitem,
    "_d2s_cget": _d2s_cget,
    "_d2s_gget": _d2s_gget,
    "_d2s_cpack": _d2s_cpack,
}


# ---------------------------------------------------------------------------
# scope analysis (skips nested scopes: defs, lambdas, comprehensions)
# ---------------------------------------------------------------------------

_NESTED_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                  ast.ListComp, ast.SetComp, ast.DictComp,
                  ast.GeneratorExp, ast.ClassDef)


def _walk_scope(node_or_list):
    """Yield nodes of the current function scope only (never descends
    into nested defs/lambdas/comprehensions, including when one is a
    top-level element of the input list)."""
    stack = list(node_or_list) if isinstance(node_or_list, list) \
        else [node_or_list]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, _NESTED_SCOPES):
            continue
        for child in ast.iter_child_nodes(n):
            stack.append(child)


def _stored_names(stmts):
    out = []
    for n in _walk_scope(stmts):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            if n.id not in out:
                out.append(n.id)
    return out


def _scan_scope(stmts, visit, *, in_loop=False, in_try=False):
    """Shared walker for the escape analyses: depth-first over the
    current function scope (never entering nested defs/lambdas/
    comprehensions), tracking whether each node sits inside a loop /
    try *of this scope*.  `visit(node, in_loop, in_try)` returning True
    short-circuits the walk."""
    for n in stmts:
        if isinstance(n, _NESTED_SCOPES):
            continue
        if visit(n, in_loop, in_try):
            return True
        if _scan_scope(list(ast.iter_child_nodes(n)), visit,
                       in_loop=in_loop or isinstance(
                           n, (ast.For, ast.While)),
                       in_try=in_try or isinstance(n, ast.Try)):
            return True
    return False


def _has_escape(stmts, *, loop_level=False):
    """True if the statements contain return (any depth in this scope)
    or break/continue belonging to an enclosing loop."""
    return _scan_scope(
        stmts,
        lambda n, in_loop, _t: isinstance(n, ast.Return) or (
            isinstance(n, (ast.Break, ast.Continue)) and not in_loop),
        in_loop=loop_level)


# ---------------------------------------------------------------------------
# the transformer
# ---------------------------------------------------------------------------


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _returns_in(stmts):
    return [n for n in _walk_scope(stmts) if isinstance(n, ast.Return)]


def _tail_return_only(stmts):
    """True if the only Return in `stmts` is its final statement."""
    rets = _returns_in(stmts)
    return len(rets) == 1 and stmts and stmts[-1] is rets[0]


def _has_break_continue(stmts):
    return _scan_scope(
        stmts,
        lambda n, in_loop, _t: isinstance(
            n, (ast.Break, ast.Continue)) and not in_loop)


def _returns_inside_loops(stmts):
    """True if any Return sits inside a For/While of this scope."""
    return _scan_scope(
        stmts,
        lambda n, in_loop, _t: isinstance(n, ast.Return) and in_loop)


def _definitely_returns(stmts):
    """True if every path through `stmts` ends in a Return."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, ast.Return):
        return True
    if isinstance(last, ast.If):
        return (_definitely_returns(last.body)
                and last.orelse and _definitely_returns(last.orelse))
    return False


def _flatten_returns(stmts, cont):
    """Rewrite so every Return ends its enclosing branch, by duplicating
    the continuation into non-returning paths (the general early-return
    normalisation; ref return_transformer.py, which reaches the same
    all-paths-return form with a guard-flag rewrite instead —
    duplication is chosen here because it never joins a returned value
    with an undefined one, which `lax.cond`'s matched-pytree branches
    cannot express).

    `cont` is the (already flattened) continuation that follows `stmts`;
    it is deep-copied at each insertion point so AST nodes stay unshared.
    Dead code after an unconditional Return is dropped."""
    import copy

    out = []
    for i, s in enumerate(stmts):
        if isinstance(s, ast.Return):
            out.append(s)
            return out
        if isinstance(s, ast.If) and _returns_in([s]):
            rest = _flatten_returns(stmts[i + 1:], cont)
            s.body = _flatten_returns(s.body, copy.deepcopy(rest))
            s.orelse = _flatten_returns(s.orelse or [],
                                        copy.deepcopy(rest))
            out.append(s)
            return out
        out.append(s)
    out.extend(copy.deepcopy(cont))
    return out


def _absorb_tail_returns(stmts):
    """Normalise `if c: ...; return A` + trailing code into
    `if c: ...; return A  else: <trailing code>` (ref
    return_transformer.py's early-return handling, restricted to
    tail-position returns). Applied recursively outside loops."""
    out = []
    i = 0
    while i < len(stmts):
        s = stmts[i]
        if isinstance(s, ast.If):
            s.body = _absorb_tail_returns(s.body)
            s.orelse = _absorb_tail_returns(s.orelse)
            rest = stmts[i + 1:]
            if (_tail_return_only(s.body)
                    and not _has_break_continue(s.body)
                    and not s.orelse and rest
                    and not _has_break_continue(rest)):
                s.orelse = _absorb_tail_returns(rest)
                out.append(s)
                return out
        out.append(s)
        i += 1
    return out


def _assign(name, value):
    return ast.Assign(targets=[_name(name, ast.Store())], value=value)




def _loop_escapes(body):
    """(has_return, has_break, has_continue) at THIS loop's level:
    returns at any scope depth; break/continue not inside a nested
    loop (those belong to the nested loop)."""
    has_ret = has_brk = has_cnt = False

    def visit(n, nested, _t):
        nonlocal has_ret, has_brk, has_cnt
        if isinstance(n, ast.Return):
            has_ret = True
        if not nested and isinstance(n, ast.Break):
            has_brk = True
        if not nested and isinstance(n, ast.Continue):
            has_cnt = True
        return False  # full walk, no short-circuit

    _scan_scope(body, visit)
    return has_ret, has_brk, has_cnt


def _escape_inside_finally(body, *, in_loop=False, in_finally=False):
    """True if an escape this loop must handle sits inside a `finally`
    block.  Escapes in try BODIES / except handlers / else lower fine
    with the flag rewrite — setting a flag never raises, so handler
    reachability is unchanged, and because the flag form never JUMPS,
    the finally still runs at exactly the point Python would run it
    before the escape.  A `finally`-resident escape is different: in
    Python it OVERRIDES any in-flight return/break from the try body,
    an ordering the forward-only flag rewrite cannot express — written
    impossibility argument, kept as a documented trace fallback."""
    for n in body:
        if isinstance(n, _NESTED_SCOPES):
            continue
        if in_finally and (isinstance(n, ast.Return) or (
                not in_loop and isinstance(n, (ast.Break, ast.Continue)))):
            return True
        if isinstance(n, ast.Try):
            blocks = [(n.body, in_finally), (n.orelse, in_finally),
                      (n.finalbody, True)]
            blocks += [(h.body, in_finally) for h in n.handlers]
            for blk, fin in blocks:
                if _escape_inside_finally(blk, in_loop=in_loop,
                                          in_finally=fin):
                    return True
        else:
            if _escape_inside_finally(
                    list(ast.iter_child_nodes(n)),
                    in_loop=in_loop or isinstance(n, (ast.For, ast.While)),
                    in_finally=in_finally):
                return True
    return False


def _range_for_parts(node, ivar):
    """Decompose `for <name> in range(...)` into (init, test, bind,
    bump) over loop counter `ivar`, or None if the iterable is not a
    supported range call.  `init` is a statement list: Python evaluates
    range() bounds exactly once, so non-constant bounds are snapshotted
    into a hidden temp there rather than re-evaluated by the test."""
    if (not isinstance(node.target, ast.Name)
            or not isinstance(node.iter, ast.Call)
            or not isinstance(node.iter.func, ast.Name)
            or node.iter.func.id != "range"
            or node.iter.keywords):
        return None
    rargs = node.iter.args
    if len(rargs) == 1:
        start, stop, step = ast.Constant(0), rargs[0], ast.Constant(1)
    elif len(rargs) == 2:
        start, stop, step = rargs[0], rargs[1], ast.Constant(1)
    elif (len(rargs) == 3 and isinstance(rargs[2], ast.Constant)
            and isinstance(rargs[2].value, int) and rargs[2].value > 0):
        start, stop, step = rargs
    else:
        return None  # negative/dynamic step: keep Python semantics
    init = [_assign(ivar, start)]
    if not isinstance(stop, ast.Constant):
        svar = ivar + "_stop"
        init.append(_assign(svar, stop))
        stop = _name(svar)
    test = ast.Compare(left=_name(ivar), ops=[ast.Lt()],
                       comparators=[stop])
    bind = ast.Assign(targets=[ast.Name(id=node.target.id,
                                        ctx=ast.Store())],
                      value=_name(ivar))
    bump = ast.AugAssign(target=_name(ivar, ast.Store()),
                         op=ast.Add(), value=step)
    return init, test, bind, bump


def _seq_for_parts(node, ivar, seqvar):
    """Decompose `for <target> in <iterable>` (non-range) into counter
    form over an indexable sequence: lists/tuples/arrays/Tensors index
    directly (and, like Python's index-based list iterator, observe
    mutations mid-loop — the length is re-read per iteration); other
    iterables are materialised once.  Tensor sequences stay Tensors, so
    a traced escape predicate lowers the loop to lax.while with
    dynamic row indexing (ref loop_transformer.py's for-iterable
    desugar)."""
    if isinstance(node.iter, (ast.Starred,)):
        return None
    init = [ast.Assign(targets=[_name(seqvar, ast.Store())],
                       value=ast.Call(func=_name("_d2s_seq"),
                                      args=[node.iter], keywords=[])),
            _assign(ivar, ast.Constant(0))]
    test = ast.Compare(
        left=_name(ivar), ops=[ast.Lt()],
        comparators=[ast.Call(func=_name("_d2s_seq_len"),
                              args=[_name(seqvar)], keywords=[])])
    bind = ast.Assign(
        targets=[node.target],
        value=ast.Call(func=_name("_d2s_getitem"),
                       args=[_name(seqvar), _name(ivar)], keywords=[]))
    bump = ast.AugAssign(target=_name(ivar, ast.Store()),
                         op=ast.Add(), value=ast.Constant(1))
    return init, test, bind, bump


class _LoopEscapeLowerer(ast.NodeTransformer):
    """Pre-pass: lower return/break/continue INSIDE While/For(range)
    bodies into escape flags threaded through the loop (ref
    break_continue_transformer.py + return_transformer.py — the
    reference reaches the same form with boolean guard variables; here
    the flags ride the lax.while_loop carry, and the return-value slot
    is an AutoZero the runtime promotes to a structure-matched zeros
    init).  Runs bottom-up so nested-loop returns chain outward.
    Loops whose escapes sit in try blocks, or For loops over non-range
    iterables, are left unchanged (existing Python/trace behavior)."""

    def __init__(self):
        self.counter = 0

    def _next(self):
        self.counter += 1
        return self.counter

    # nested scopes keep their own control flow
    def visit_FunctionDef(self, node):
        return node

    def visit_AsyncFunctionDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_ClassDef(self, node):
        return node

    def _liftable(self, body):
        has_ret, has_brk, has_cnt = _loop_escapes(body)
        if not (has_ret or has_brk or has_cnt):
            return None
        if _escape_inside_finally(body) or _returns_inside_loops(body):
            # nested loop kept its returns (it was itself unliftable):
            # rewriting them here would change the inner loop's meaning
            return None
        return has_ret, has_brk, has_cnt

    def visit_While(self, node):
        self.generic_visit(node)
        esc = self._liftable(node.body)
        if esc is None:
            return node
        return self._lower(node.test, node.body, [], [], node.orelse,
                           esc)

    def visit_For(self, node):
        self.generic_visit(node)
        esc = self._liftable(node.body)
        if esc is None:
            return node
        n = self._next()
        ivar = f"__d2s_fi{n}"
        parts = _range_for_parts(node, ivar)
        if parts is None:
            parts = _seq_for_parts(node, ivar, f"__d2s_fq{n}")
        if parts is None:
            return node
        init, test, bind, bump = parts
        out = self._lower(test, node.body, [bind], [bump], node.orelse,
                          esc)
        return init + out

    def _lower(self, test, body, head, tail, orelse, esc):
        has_ret, has_brk, has_cnt = esc
        n = self._next()
        brk, cnt = f"__d2s_brk{n}", f"__d2s_cnt{n}"
        ret, rv = f"__d2s_ret{n}", f"__d2s_rv{n}"

        def guard_expr():
            e = _name(brk)
            if has_cnt:
                e = ast.BoolOp(op=ast.Or(),
                               values=[e, _name(cnt)])
            return ast.UnaryOp(op=ast.Not(), operand=e)

        flag_names = {brk, cnt, ret}

        def xf(stmts):
            out = []
            for i, s in enumerate(stmts):
                if isinstance(s, ast.Break):
                    repl = [_assign(brk, ast.Constant(True))]
                elif isinstance(s, ast.Continue):
                    repl = [_assign(cnt, ast.Constant(True))]
                elif isinstance(s, ast.Return):
                    repl = [_assign(rv, s.value or ast.Constant(None)),
                            _assign(ret, ast.Constant(True)),
                            _assign(brk, ast.Constant(True))]
                else:
                    if isinstance(s, ast.If):
                        s.body = xf(s.body)
                        s.orelse = xf(s.orelse)
                    elif isinstance(s, ast.With):
                        s.body = xf(s.body)
                    elif isinstance(s, ast.Match):
                        for c in s.cases:
                            c.body = xf(c.body)
                    elif isinstance(s, ast.Try):
                        # escapes in try BODIES/handlers/else lower: the
                        # flag form never jumps, so the finally runs at
                        # exactly Python's pre-escape point (escapes IN
                        # finalbody were rejected by _liftable)
                        s.body = xf(s.body)
                        body_sets = any(
                            isinstance(m, ast.Name)
                            and isinstance(m.ctx, ast.Store)
                            and m.id in flag_names
                            for st in s.body for m in ast.walk(st))
                        for h in s.handlers:
                            h.body = xf(h.body)
                        s.orelse = xf(s.orelse)
                        if body_sets and s.orelse:
                            # Python skips `else` when the try suite
                            # exits via an escape; the flag form exits
                            # normally, so gate the else on the flags
                            s.orelse = [ast.If(test=guard_expr(),
                                               body=s.orelse,
                                               orelse=[])]
                    repl = [s]
                out.extend(repl)
                sets_flag = any(
                    isinstance(m, ast.Name)
                    and isinstance(m.ctx, ast.Store)
                    and m.id in flag_names
                    for r in repl for m in ast.walk(r))
                if sets_flag and i + 1 < len(stmts):
                    out.append(ast.If(test=guard_expr(),
                                      body=xf(stmts[i + 1:]),
                                      orelse=[]))
                    break
            return out

        new_body = ([_assign(cnt, ast.Constant(False))] if has_cnt
                    else []) + head + xf(body) + tail
        new_test = ast.BoolOp(
            op=ast.And(),
            values=[ast.UnaryOp(op=ast.Not(), operand=_name(brk)),
                    test])
        init = [_assign(brk, ast.Constant(False))]
        if has_cnt:
            init.append(_assign(cnt, ast.Constant(False)))
        if has_ret:
            init += [_assign(ret, ast.Constant(False)),
                     _assign(rv, _name("_d2s_auto"))]
        out = init + [ast.While(test=new_test, body=new_body, orelse=[])]
        if has_ret:
            out.append(ast.If(test=_name(ret),
                              body=[ast.Return(value=_name(rv))],
                              orelse=[]))
        if orelse:
            # while/for-else: runs only when the loop exited without
            # break (a lowered return also sets brk, and exits above)
            out.append(ast.If(
                test=ast.UnaryOp(op=ast.Not(), operand=_name(brk)),
                body=orelse, orelse=[]))
        return out


def _lower_loop_escapes(body):
    tr = _LoopEscapeLowerer()
    new = []
    for s in body:
        o = tr.visit(s)
        new.extend(o if isinstance(o, list) else [o])
    return new


def _ld_tuple(names):
    """(_d2s_ld(lambda: a), _d2s_ld(lambda: b), ...)"""
    elts = [
        ast.Call(func=_name("_d2s_ld"),
                 args=[ast.Lambda(
                     args=ast.arguments(posonlyargs=[], args=[],
                                        kwonlyargs=[], kw_defaults=[],
                                        defaults=[]),
                     body=_name(n))],
                 keywords=[])
        for n in names
    ]
    return ast.Tuple(elts=elts, ctx=ast.Load())


def _fn_def(name, params, body, returns):
    args = ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=p) for p in params],
        kwonlyargs=[], kw_defaults=[], defaults=[])
    ret = ast.Return(value=ast.Tuple(
        elts=[_name(r) for r in returns], ctx=ast.Load()))
    return ast.FunctionDef(name=name, args=args, body=body + [ret],
                           decorator_list=[], returns=None,
                           type_params=[])


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0
        self.bail = None

    def _next(self):
        self.counter += 1
        return self.counter

    # nested scopes keep their own control flow untouched
    def visit_FunctionDef(self, node):
        return node

    def visit_AsyncFunctionDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_Global(self, node):
        self.bail = "uses global"
        return node

    def visit_Nonlocal(self, node):
        self.bail = "uses nonlocal"
        return node

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = "_d2s_and" if isinstance(node.op, ast.And) else "_d2s_or"
        out = node.values[0]
        for v in node.values[1:]:
            thunk = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[],
                                   kwonlyargs=[], kw_defaults=[],
                                   defaults=[]),
                body=v)
            out = ast.Call(func=_name(fn), args=[out, thunk], keywords=[])
        return out

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(func=_name("_d2s_not"), args=[node.operand],
                            keywords=[])
        return node

    def visit_If(self, node):
        self.generic_visit(node)
        if _has_escape(node.body) or _has_escape(node.orelse):
            ret = self._try_returning_if(node)
            if ret is not None:
                return ret
            # other early-exit shapes stay Python `if` (correct for
            # concrete preds; a traced pred raises jax's tracer-bool
            # error)
            return node
        outs = _stored_names(node.body + node.orelse)
        n = self._next()
        tname, fname = f"__d2s_true_{n}", f"__d2s_false_{n}"
        tdef = _fn_def(tname, outs, node.body, outs)
        return self._finish_if(node, n, tname, fname, tdef, outs)

    def _try_returning_if(self, node):
        """`if c: ...; return A else: ...; return B` (tail returns on
        both sides) lowers to `return _d2s_if(...)`."""
        if not (_tail_return_only(node.body) and node.orelse
                and _tail_return_only(node.orelse)
                and not _has_break_continue(node.body)
                and not _has_break_continue(node.orelse)):
            return None
        params = _stored_names(node.body[:-1] + node.orelse[:-1])
        n = self._next()
        tname, fname = f"__d2s_rtrue_{n}", f"__d2s_rfalse_{n}"

        def mk(name, body):
            val = body[-1].value or ast.Constant(None)
            d = _fn_def(name, params, body[:-1], [])
            d.body[-1] = ast.Return(value=val)
            return d

        tdef, fdef = mk(tname, node.body), mk(fname, node.orelse)
        call = ast.Call(func=_name("_d2s_if"),
                        args=[node.test, _name(tname), _name(fname),
                              _ld_tuple(params)],
                        keywords=[])
        return [tdef, fdef, ast.Return(value=call)]

    def _finish_if(self, node, n, tname, fname, tdef, outs):
        fbody = node.orelse if node.orelse else [ast.Pass()]
        fdef = _fn_def(fname, outs, fbody, outs)
        call = ast.Call(func=_name("_d2s_if"),
                        args=[node.test, _name(tname), _name(fname),
                              _ld_tuple(outs)],
                        keywords=[])
        target = ast.Tuple(elts=[_name(o, ast.Store()) for o in outs],
                           ctx=ast.Store())
        if outs:
            assign = ast.Assign(targets=[target], value=call)
        else:
            assign = ast.Expr(value=call)
        return [tdef, fdef, assign]

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _has_escape(node.body, loop_level=True):
            return node
        carried = _stored_names(node.body)
        n = self._next()
        cname, bname = f"__d2s_cond_{n}", f"__d2s_body_{n}"
        cdef = _fn_def(cname, carried, [ast.Pass()], [])
        cdef.body = [ast.Return(value=node.test)]
        bdef = _fn_def(bname, carried, node.body, carried)
        call = ast.Call(func=_name("_d2s_while"),
                        args=[_name(cname), _name(bname),
                              _ld_tuple(carried)],
                        keywords=[])
        target = ast.Tuple(
            elts=[_name(c, ast.Store()) for c in carried],
            ctx=ast.Store())
        if carried:
            assign = ast.Assign(targets=[target], value=call)
        else:
            assign = ast.Expr(value=call)
        return [cdef, bdef, assign]

    def visit_For(self, node):
        # only `for <name> in range(...)` desugars; everything else stays
        self.generic_visit(node)
        if node.orelse or _has_escape(node.body, loop_level=True):
            return node
        n = self._next()
        parts = _range_for_parts(node, f"__d2s_i_{n}")
        if parts is None:
            return node
        init, test, bind, bump = parts
        wl = ast.While(test=test, body=[bind] + node.body + [bump],
                       orelse=[])
        out = self.visit_While(wl)
        stmts = out if isinstance(out, list) else [out]
        return init + stmts


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def _lower_cell_vars(fdef):
    """Lower `global`/`nonlocal` declarations to cell passing (ref
    variable_trans_func.py's nonlocal/cell machinery): declarations are
    stripped, each declared name is entry-loaded into a plain local (so
    the lifting/loop machinery threads it like any stored name, incl.
    through lax control flow), and every exit packs the finals into the
    return value (`_d2s_cpack`) — the caller-side wrapper performs the
    actual cell/global stores, OUTSIDE any jit trace.

    Known divergence (documented): the store becomes visible at
    function EXIT, not at each assignment — a nested call observing the
    cell mid-execution sees the entry value.

    Returns (nonlocal_names, global_names)."""
    gnames, nnames = set(), set()
    for n in _walk_scope(fdef.body):
        if isinstance(n, ast.Global):
            gnames.update(n.names)
        elif isinstance(n, ast.Nonlocal):
            nnames.update(n.names)
    if not gnames and not nnames:
        return (), ()
    nnames, gnames = sorted(nnames), sorted(gnames)

    class _Strip(ast.NodeTransformer):
        def visit_FunctionDef(self, node):
            return node

        def visit_AsyncFunctionDef(self, node):
            return node

        def visit_Lambda(self, node):
            return node

        def visit_ClassDef(self, node):
            return node

        def visit_Global(self, node):
            return ast.Pass()

        def visit_Nonlocal(self, node):
            return ast.Pass()

    def pack_call(value):
        return ast.Call(
            func=_name("_d2s_cpack"),
            args=[value if value is not None else ast.Constant(None),
                  ast.Tuple(elts=[_name(x) for x in nnames],
                            ctx=ast.Load()),
                  ast.Tuple(elts=[_name(x) for x in gnames],
                            ctx=ast.Load())],
            keywords=[])

    class _WrapReturns(_Strip):
        def visit_Return(self, node):
            return ast.Return(value=pack_call(node.value))

    fdef.body = [_WrapReturns().visit(s) for s in fdef.body]
    if not _definitely_returns(fdef.body):
        fdef.body.append(ast.Return(value=pack_call(None)))
    # entry values arrive as KEYWORD-ONLY parameters (declared names
    # cannot collide with existing params — Python forbids
    # global/nonlocal of a parameter; keyword-only also cannot disturb
    # positional binding of defaults or *args), so each call threads
    # the CURRENT cell/global values through jit as inputs instead of
    # baking trace-time constants into the cached program
    for x in list(nnames) + list(gnames):
        fdef.args.kwonlyargs.append(ast.arg(arg=x))
        fdef.args.kw_defaults.append(None)
    return tuple(nnames), tuple(gnames)


def rewrite(fn):
    """AST-rewrite `fn`'s control flow. Raises on untransformable input;
    use maybe_rewrite for the fall-back-to-trace behavior."""
    bound_self = getattr(fn, "__self__", None)
    raw = fn.__func__ if bound_self is not None else fn
    src = textwrap.dedent(inspect.getsource(raw))
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        raise ValueError("to_static target is not a function")
    fdef.decorator_list = []
    nnames, gnames = _lower_cell_vars(fdef)
    cells = ()
    if nnames:
        free = raw.__code__.co_freevars
        if raw.__closure__ is None or any(x not in free for x in nnames):
            raise ValueError(
                f"nonlocal names {nnames} have no closure cells")
        cells = tuple(raw.__closure__[free.index(x)] for x in nnames)
    # lower loop-body return/break/continue to escape flags first, so
    # the early-return normalisation below sees loop-free returns
    fdef.body = _lower_loop_escapes(fdef.body)
    body_returns = _returns_in(fdef.body)
    non_tail = [r for r in body_returns if r is not (
        fdef.body[-1] if fdef.body else None)]
    if (non_tail and not _returns_inside_loops(fdef.body)
            and not _has_break_continue(fdef.body)):
        # general early returns: normalise to all-paths-tail-return by
        # duplicating continuations, so every branching return lowers
        # through _try_returning_if instead of trace fallback
        if not _definitely_returns(fdef.body):
            fdef.body.append(ast.Return(value=ast.Constant(value=None)))
        fdef.body = _flatten_returns(fdef.body, [])
        # duplication is O(2^k) over k partially-returning ifs; a deep
        # chain must fall back to trace capture, not hang in compile()
        n_nodes = sum(1 for _ in ast.walk(fdef))
        if n_nodes > 20_000:
            raise ValueError(
                f"early-return normalisation grew the AST to {n_nodes} "
                "nodes (deeply chained partial returns); use explicit "
                "if/else structure or the trace path")
    else:
        fdef.body = _absorb_tail_returns(fdef.body)
    tr = _ControlFlowTransformer()
    new_body = []
    for stmt in fdef.body:
        out = tr.visit(stmt)
        new_body.extend(out if isinstance(out, list) else [out])
    if tr.bail:
        raise ValueError(f"dy2static cannot rewrite: {tr.bail}")
    fdef.body = new_body
    ast.fix_missing_locations(tree)
    code = compile(tree, filename=f"<dy2static {raw.__name__}>",
                   mode="exec")
    ns = dict(raw.__globals__)
    ns.update(_HELPERS)
    if raw.__closure__:
        ns.update(zip(raw.__code__.co_freevars,
                      [c.cell_contents for c in raw.__closure__]))
    exec(code, ns)
    new_fn = ns[raw.__name__]
    if nnames or gnames:
        inner = new_fn
        gdict = raw.__globals__

        cell_names = tuple(nnames) + tuple(gnames)

        def read_entry():
            return tuple(_d2s_cget(c) for c in cells) + tuple(
                _d2s_gget(gdict, n) for n in gnames)

        def writeback(cvals, gvals):
            _write_cells(cells, cvals, gdict, gnames, gvals)

        def outer(*a, **k):
            entry = dict(zip(cell_names, read_entry()))
            out, cvals, gvals = inner(*a, **k, **entry)
            writeback(cvals, gvals)
            return out

        # to_static jits __d2s_inner__ (packed returns), reads the
        # LIVE entry values per call via __d2s_read_entry__ (threading
        # them as keyword jit inputs named __d2s_cell_names__), and
        # applies __d2s_writeback__ to the CONCRETE outputs outside
        # the trace
        outer.__d2s_inner__ = inner
        outer.__d2s_read_entry__ = read_entry
        outer.__d2s_cell_names__ = cell_names
        outer.__d2s_writeback__ = writeback
        new_fn = outer
    new_fn = functools.wraps(raw)(new_fn)
    if bound_self is not None:
        return types.MethodType(new_fn, bound_self)
    return new_fn


def maybe_rewrite(fn):
    """rewrite(fn), falling back to the original (trace-based capture)
    when the source is unavailable or uses unsupported constructs."""
    try:
        return rewrite(fn)
    except (OSError, TypeError, SyntaxError, ValueError) as e:
        warnings.warn(
            f"dy2static: AST rewrite of {getattr(fn, '__name__', fn)} "
            f"failed ({e}); falling back to trace-based capture — "
            "tensor-dependent Python control flow will not compile")
        return fn


class ProgramTranslator:
    """ref ProgramTranslator singleton: global enable/disable switch."""

    _instance = None
    enable_to_static = True

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    @classmethod
    def get_instance(cls):
        return cls()

    def enable(self, flag: bool):
        ProgramTranslator.enable_to_static = bool(flag)


def enable_to_static(flag: bool):
    ProgramTranslator().enable(flag)
