"""paddle_tpu — a TPU-native deep-learning framework with the capabilities
of PaddlePaddle (~v2.1, the fluid+dygraph era), rebuilt from scratch on
JAX/XLA/Pallas.

Usage mirrors paddle: `import paddle_tpu as paddle`.

Architecture (see SURVEY.md §7 for the full mapping):
- eager Tensor API over jax.Array + tape autograd (dygraph parity)
- compiled execution via the functional engine / paddle_tpu.jit (static &
  distributed parity; one XLA computation per train step)
- parallelism via jax.sharding Mesh + GSPMD specs + shard_map pipelines
  (Fleet parity: dp / tensor / pipeline / sharding hybrid)
"""

from __future__ import annotations

# -- jax version compat ----------------------------------------------------
# top-level `jax.shard_map` (with the `axis_names=` / `check_vma=`
# keywords) only exists in newer jax; on older installs adapt the
# experimental API (`auto=` complement, `check_rep=`) so the pipeline /
# context-parallel shard_map call sites run unchanged on either version.
import jax as _jax

if not hasattr(_jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    def _shard_map(f, mesh, in_specs, out_specs, axis_names=None,
                   check_vma=True):
        manual = frozenset(axis_names) if axis_names is not None \
            else frozenset(mesh.axis_names)
        # promote trivial (size-1) non-manual axes to manual: their
        # per-shard view IS the full array, so semantics are unchanged.
        # Genuinely partial-manual programs (auto axes of size > 1) hit
        # XLA check failures on this jax — refuse cleanly instead.
        auto = frozenset(a for a in mesh.axis_names
                         if a not in manual and mesh.shape[a] > 1)
        if auto:
            raise NotImplementedError(
                f"partial-manual shard_map (auto axes {sorted(auto)}) "
                "requires a newer jax than this install")
        # NB: `bool` is shadowed at module scope by the dtype handle —
        # pass the flag through untouched (call sites pass a plain bool)
        return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs,
                               check_rep=True if check_vma else False,
                               auto=frozenset())

    _shard_map.__paddle_tpu_compat__ = True
    _jax.shard_map = _shard_map

if not hasattr(_jax.sharding, "get_abstract_mesh"):
    from jax._src import mesh as _mesh_lib

    _jax.sharding.get_abstract_mesh = _mesh_lib.get_abstract_mesh

try:
    from jax.experimental.pallas import tpu as _pltpu

    if not hasattr(_pltpu, "CompilerParams") \
            and hasattr(_pltpu, "TPUCompilerParams"):
        _pltpu.CompilerParams = _pltpu.TPUCompilerParams
except ImportError:  # pallas backend not present on this install
    pass

# -- core ------------------------------------------------------------------
from .core.tensor import Parameter, Tensor  # noqa: F401
from .core.config import (  # noqa: F401
    enable_grad, get_default_dtype, no_grad, set_default_dtype,
    set_grad_enabled,
)
from .core.autograd import grad  # noqa: F401
from .core.dtype import dtype_handle as _dtype_handle

# dtype singletons: paddle.float32, ...
bool = _dtype_handle("bool")  # noqa: A001
uint8 = _dtype_handle("uint8")
int8 = _dtype_handle("int8")
int16 = _dtype_handle("int16")
int32 = _dtype_handle("int32")
int64 = _dtype_handle("int64")
float16 = _dtype_handle("float16")
bfloat16 = _dtype_handle("bfloat16")
float32 = _dtype_handle("float32")
float64 = _dtype_handle("float64")
complex64 = _dtype_handle("complex64")
complex128 = _dtype_handle("complex128")

# -- ops must register before the tensor API is used -----------------------
from . import ops  # noqa: F401,E402

# -- functional tensor API (also attaches Tensor methods) ------------------
from .tensor.creation import (  # noqa: F401,E402
    arange, assign, clone, complex, diag, diagflat, empty, empty_like, eye,
    full, full_like, linspace, logspace, meshgrid, ones, ones_like,
    to_tensor, tril, triu, zeros, zeros_like,
)
from .tensor.math import (  # noqa: F401,E402
    abs, acos, acosh, add, addmm, all, allclose, amax, amin, any, asin,
    asinh, atan, atan2, atanh, bmm, ceil, clip, conj, cos, cosh,
    count_nonzero, cross, cumprod, cumsum, diagonal, digamma, divide, dot,
    equal_all, erf, erfinv, exp, expm1, floor, floor_divide, floor_mod,
    fmax, fmin, frac, heaviside, imag, increment, inner, isclose, isfinite,
    isinf, isnan, kron, lerp, lgamma, log, log1p, log2, log10, logaddexp,
    logcumsumexp, logsumexp, matmul, max, maximum, mean, min, minimum, mm,
    mod, multiply, nanmean, nansum, neg, nextafter, outer, pow, prod, real,
    reciprocal, remainder, round, rsqrt, scale, sign, sin, sinh, sqrt,
    square, stanh, subtract, sum, tan, tanh, trace, trunc,
)
from .tensor.manipulation import (  # noqa: F401,E402
    as_complex, as_real, broadcast_tensors, broadcast_to, cast, chunk,
    concat, crop, diag_embed, expand, expand_as, flatten, flip, gather,
    gather_nd, index_sample, index_select, masked_fill, masked_select,
    moveaxis, nonzero, put_along_axis, repeat_interleave, reshape, roll,
    rot90, scatter, scatter_nd, scatter_nd_add, slice, split, squeeze,
    stack, strided_slice, swapaxes, t, take_along_axis, tensordot, tile,
    transpose, unique, unsqueeze, unstack, where,
)
from .tensor.logic import (  # noqa: F401,E402
    equal, greater_equal, greater_than, is_empty, is_tensor, less_equal,
    less_than, logical_and, logical_not, logical_or, logical_xor, not_equal,
)
from .tensor.search import (  # noqa: F401,E402
    argmax, argmin, argsort, bucketize, index_put, kthvalue, mode,
    searchsorted, sort, topk,
)
from .tensor.random import (  # noqa: F401,E402
    bernoulli, multinomial, normal, poisson, rand, randint, randint_like,
    randn, randperm, standard_normal, uniform,
)
from .tensor.stat import (  # noqa: F401,E402
    bincount, histogram, median, numel, quantile, std, var,
)
from .tensor.einsum import einsum  # noqa: F401,E402
from .tensor import linalg  # noqa: F401,E402
from . import tensor  # noqa: F401,E402

# -- framework -------------------------------------------------------------
from .framework import get_rng_state, seed, set_rng_state  # noqa: F401,E402
from . import framework  # noqa: F401,E402

# -- device management -----------------------------------------------------
from .device import (  # noqa: F401,E402
    get_device, is_compiled_with_cuda, is_compiled_with_npu,
    is_compiled_with_rocm, is_compiled_with_xpu, set_device,
)
from . import device  # noqa: F401,E402

# -- subsystem namespaces (imported lazily to keep import light) -----------
from . import nn  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import amp  # noqa: F401,E402
from . import jit  # noqa: F401,E402
from . import metric  # noqa: F401,E402
from . import vision  # noqa: F401,E402
from . import distribution  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from . import autograd  # noqa: F401,E402
from . import static  # noqa: F401,E402
from . import text  # noqa: F401,E402
from . import rec  # noqa: F401,E402
from . import inference  # noqa: F401,E402
from . import serving  # noqa: F401,E402
from . import observe  # noqa: F401,E402
from . import quantization  # noqa: F401,E402
from . import utils  # noqa: F401,E402
from .framework.flags import get_flags, set_flags  # noqa: F401,E402
from .framework import monitor  # noqa: F401,E402
from .framework import errors  # noqa: F401,E402
from .framework.io import load, save  # noqa: F401,E402
from .hapi.model import Model  # noqa: F401,E402
from .nn.layer.layers import Layer  # noqa: F401,E402
from .dataparallel import DataParallel  # noqa: F401,E402

__version__ = "0.1.0"


def disable_static(place=None):
    """Leave static-graph (op capture) mode; eager execution resumes."""
    from .static.program import _disable_static

    _disable_static()


def enable_static():
    """Enter static-graph mode: paddle ops called on `static.data`
    Variables record into the default main Program instead of executing
    (ref fluid/framework.py enable_static). Run with static.Executor."""
    from .static.program import _enable_static

    _enable_static()


def in_dynamic_mode():
    from .static.program import in_static_mode

    return not in_static_mode()


def backward(tensors, grad_tensors=None, retain_graph=False):
    from .core.autograd import backward as _b

    return _b(tensors, grad_tensors, retain_graph)


# importing the clip/device submodules above rebound the package
# attributes to the modules; the paddle API names are the functions
from .tensor.math import clip as clip  # noqa: F401,E402

from . import reader  # noqa: F401,E402
from . import onnx  # noqa: F401,E402
from .reader import batch  # noqa: F401,E402
from . import dataset  # noqa: F401,E402
from . import regularizer  # noqa: F401,E402
from . import sysconfig  # noqa: F401,E402
from . import compat  # noqa: F401,E402
from . import callbacks  # noqa: F401,E402
from . import hub  # noqa: F401,E402
