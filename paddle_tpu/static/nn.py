"""Control-flow ops: cond / while_loop / switch_case / case.

Ref parity: paddle/fluid/operators/controlflow/conditional_block_op.cc,
while_op.cc and python/paddle/fluid/layers/control_flow.py. TPU-native:
in eager mode predicates are concrete, so the chosen branch simply runs
(fully taped — autograd works through it); under jit tracing the ops
lower to `lax.cond` / `lax.while_loop` / `lax.switch` — compiled XLA
control flow with no Python unrolling.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["cond", "while_loop", "switch_case", "case", "fc", "embedding",
           "conv2d", "batch_norm", "sequence_pad", "sequence_unpad",
           "sequence_pool", "sequence_softmax", "sequence_reverse",
           "sequence_expand", "sequence_first_step", "sequence_last_step",
           "sequence_conv"]


# ---------------------------------------------------------------------------
# sequence ops (ref python/paddle/fluid/layers/sequence_lod.py; kernels in
# ops/sequence_ops.py — padded+mask replaces LoD, SURVEY §7 hard part #4)
# ---------------------------------------------------------------------------


def _seq(name):
    from ..core.dispatch import apply

    def wrapper(*args, **kwargs):
        return apply(name, *args, **kwargs)

    wrapper.__name__ = name
    wrapper.__doc__ = (f"{name}(data, lengths, ...) — see "
                       "paddle_tpu/ops/sequence_ops.py")
    return wrapper


sequence_pad = _seq("sequence_pad")
sequence_unpad = _seq("sequence_unpad")
sequence_pool = _seq("sequence_pool")
sequence_softmax = _seq("sequence_softmax")
sequence_reverse = _seq("sequence_reverse")
sequence_expand = _seq("sequence_expand")
sequence_first_step = _seq("sequence_first_step")
sequence_last_step = _seq("sequence_last_step")
sequence_conv = _seq("sequence_conv")


# ---------------------------------------------------------------------------
# layer builders (ref python/paddle/static/nn/common.py fc, conv2d, ...).
# Each call creates fresh eager parameters (the "startup program") and runs
# the forward — under enable_static the ops record into the main program
# and the parameters are interned as persistable vars.
# ---------------------------------------------------------------------------


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    import numpy as _np

    from ..nn import Linear
    from ..nn import functional as F

    in_features = int(_np.prod(x.shape[num_flatten_dims:]))
    layer = Linear(in_features, size, weight_attr=weight_attr,
                   bias_attr=bias_attr)
    h = x
    if x.ndim > num_flatten_dims + 1:
        from ..tensor.manipulation import reshape

        # dim0 is -1: capture-time shapes may carry a placeholder batch
        # dim (None -> 1), which must never be baked into the recorded
        # reshape attr; the remaining leading dims are user-declared
        h = reshape(h, [-1] + list(x.shape[1:num_flatten_dims])
                    + [in_features])
    out = layer(h)
    if activation is not None:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              weight_attr=None, name=None):
    from ..nn import Embedding

    layer = Embedding(size[0], size[1], padding_idx=padding_idx,
                      weight_attr=weight_attr, sparse=is_sparse)
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None,
           data_format="NCHW"):
    from ..nn import Conv2D
    from ..nn import functional as F

    in_channels = input.shape[1 if data_format == "NCHW" else -1]
    layer = Conv2D(in_channels, num_filters, filter_size, stride=stride,
                   padding=padding, dilation=dilation, groups=groups,
                   weight_attr=param_attr, bias_attr=bias_attr,
                   data_format=data_format)
    out = layer(input)
    if act is not None:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-05,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               is_test=False, name=None):
    from ..nn import BatchNorm2D
    from ..nn import functional as F

    layer = BatchNorm2D(input.shape[1 if data_layout == "NCHW" else -1],
                        momentum=momentum, epsilon=epsilon,
                        weight_attr=param_attr, bias_attr=bias_attr,
                        data_format=data_layout)
    if is_test:
        layer.eval()
    out = layer(input)
    if act is not None:
        out = getattr(F, act)(out)
    return out


def _raw(x):
    return x._value if isinstance(x, Tensor) else x


def _is_traced(v):
    return isinstance(v, jax.core.Tracer)


def _unwrap_tree(tree):
    return jax.tree.map(
        lambda t: t._value if isinstance(t, Tensor) else jnp.asarray(t),
        tree, is_leaf=lambda t: isinstance(t, Tensor))


def _wrap_tree(tree):
    return jax.tree.map(Tensor, tree)


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Run true_fn() or false_fn() by `pred`
    (ref control_flow.py cond / conditional_block_op.cc).

    Eager (concrete pred): executes the chosen branch — differentiable
    through the tape. Traced: lowers to lax.cond (both branches traced
    once; output structures must match)."""
    pv = _raw(pred)
    if not _is_traced(pv):
        fn = true_fn if bool(pv) else false_fn
        return fn() if fn is not None else None

    def t_branch(_):
        return _unwrap_tree(true_fn())

    def f_branch(_):
        return _unwrap_tree(false_fn())

    out = jax.lax.cond(jnp.asarray(pv, bool), t_branch, f_branch,
                       operand=None)
    return _wrap_tree(out)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """Repeat body while cond holds (ref while_op.cc).

    Eager: Python loop over Tensors (taped — backward works, trip count
    becomes part of the tape). Traced: lax.while_loop (forward-only, like
    XLA; use lax.scan-style bounded loops for differentiable recurrences).
    """
    if not isinstance(loop_vars, (list, tuple)):
        raise TypeError("loop_vars must be a list/tuple")
    loop_vars = list(loop_vars)

    probe = cond_fn(*loop_vars)
    pv = _raw(probe)
    if not _is_traced(pv) and not any(
            _is_traced(_raw(v)) for v in loop_vars
            if isinstance(v, Tensor)):
        keep_going = bool(pv)
        while keep_going:
            out = body_fn(*loop_vars)
            loop_vars = list(out) if isinstance(out, (list, tuple)) \
                else [out]
            keep_going = bool(_raw(cond_fn(*loop_vars)))
        return loop_vars

    def c(vs):
        return jnp.asarray(_raw(cond_fn(*_wrap_tree(vs))), bool)

    def b(vs):
        out = body_fn(*_wrap_tree(vs))
        out = list(out) if isinstance(out, (list, tuple)) else [out]
        return _unwrap_tree(out)

    final = jax.lax.while_loop(c, b, _unwrap_tree(loop_vars))
    return [_wrap_tree(v) for v in final]


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Select one of branch_fns by integer index
    (ref control_flow.py switch_case)."""
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        fns = [branch_fns[k] for k in keys]
        index_map = {k: i for i, k in enumerate(keys)}
    else:
        pairs = list(branch_fns)
        if pairs and isinstance(pairs[0], (tuple, list)):
            keys = [k for k, _ in pairs]
            fns = [f for _, f in pairs]
            index_map = {k: i for i, k in enumerate(keys)}
        else:
            fns = pairs
            index_map = None

    iv = _raw(branch_index)
    if not _is_traced(iv):
        key = int(iv)
        if index_map is not None:
            if key in index_map:
                return fns[index_map[key]]()
        elif 0 <= key < len(fns):
            return fns[key]()
        if default is None:
            raise ValueError(f"branch_index {key} out of range and no "
                             "default branch given")
        return default()

    all_fns = list(fns) + ([default] if default is not None else [])
    iv_arr = jnp.asarray(iv)
    if index_map is not None:
        # map arbitrary keys to dense positions; unknown -> default slot
        lut_keys = jnp.asarray(list(index_map.keys()))
        pos = jnp.argmax(lut_keys == iv_arr)
        hit = jnp.any(lut_keys == iv_arr)
        dense = jnp.where(hit, pos, len(fns))
    else:
        in_range = (iv_arr >= 0) & (iv_arr < len(fns))
        # out-of-range goes to the default slot when one exists; without a
        # default XLA cannot raise, so it clamps to the last branch
        fallback = len(fns) if default is not None else len(fns) - 1
        dense = jnp.where(in_range, jnp.clip(iv_arr, 0, len(fns) - 1),
                          fallback)
    branches = [lambda _, f=f: _unwrap_tree(f()) for f in all_fns]
    out = jax.lax.switch(dense, branches, None)
    return _wrap_tree(out)


def case(pred_fn_pairs, default=None, name=None):
    """First-match conditional chain (ref control_flow.py case)."""
    pairs = list(pred_fn_pairs)
    if not pairs:
        raise ValueError("pred_fn_pairs must not be empty")

    if all(not _is_traced(_raw(p)) for p, _ in pairs):
        for p, fn in pairs:
            if bool(_raw(p)):
                return fn()
        if default is None:
            _, last_fn = pairs[-1]
            return last_fn()
        return default()

    # traced: nested lax.cond chain
    def build(i):
        if i == len(pairs):
            if default is not None:
                return lambda: _unwrap_tree(default())
            return lambda: _unwrap_tree(pairs[-1][1]())
        p, fn = pairs[i]
        rest = build(i + 1)
        return lambda: jax.lax.cond(
            jnp.asarray(_raw(p), bool),
            lambda _: _unwrap_tree(fn()),
            lambda _: rest(), operand=None)

    return _wrap_tree(build(0)())
