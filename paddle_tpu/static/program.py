"""Static-graph surface: Program / Block / Variable / Executor / Scope.

Ref parity: python/paddle/fluid/framework.py (Program/Block/Operator/
Variable, program_guard, default_main_program), python/paddle/fluid/
executor.py (Executor.run feed/fetch), python/paddle/static/__init__.py.

TPU-native design — *not* an op-by-op interpreter: building code runs
under a capture hook in the eager dispatch funnel, so every paddle op
called on a symbolic `Variable` records an `OpDesc` into the current
`Program` instead of executing.  `Executor.run` then compiles the whole
recorded block into ONE jitted XLA computation (replaying the op list
with real arrays inside `jax.jit`), caches it by (program version, feed
signature, fetch names), and keeps persistable state in a `Scope` across
runs — the reference's Program/Scope/Executor contract, with XLA playing
the role of `framework/executor.cc` and every IR fusion pass.

Autograd: `append_backward` (ref fluid/backward.py:1377) records a single
`@backward` op; at replay it becomes `jax.vjp` over the forward section —
the reference generates per-op grad ops from GradOpMakers, XLA's AD
transform generates the whole backward program at once.

Randomness: ops that consume an explicit PRNG-key input (dropout, random
ops — see ops/nn_ops.py) get the key re-derived per run from a fresh
executor key, `fold_in`-ed with the op index, so a captured dropout does
not bake one mask into the graph.
"""

from __future__ import annotations

import contextlib
import pickle

import numpy as np

import jax
import jax.numpy as jnp

from ..core import config
from ..core.dtype import to_jax_dtype
from ..core.op_registry import lookup
from ..core.tensor import Tensor

__all__ = [
    "Variable", "OpDesc", "Block", "Program", "Scope", "Executor",
    "CompiledProgram", "program_guard", "default_main_program",
    "default_startup_program", "global_scope", "scope_guard", "data",
    "append_backward", "save", "load", "save_inference_model",
    "load_inference_model", "InputSpec",
]

from ..jit import InputSpec  # noqa: E402  (re-export, paddle.static.InputSpec)


# ---------------------------------------------------------------------------
# symbolic Variable
# ---------------------------------------------------------------------------


class Variable(Tensor):
    """Symbolic tensor inside a Program (ref framework.py:805 Variable).

    `_value` holds a `jax.ShapeDtypeStruct` — shape/dtype flow through the
    whole eager Tensor API, but any attempt to read data raises, as in the
    reference ("variable has no data in static mode").
    """

    def __init__(self, name, shape, dtype, *, persistable=False,
                 stop_gradient=True, is_data=False, block=None):
        # bypass Tensor._coerce: no concrete array exists
        self._value = jax.ShapeDtypeStruct(
            tuple(int(s) if s is not None and s >= 0 else 1 for s in shape),
            to_jax_dtype(dtype))
        self.stop_gradient = stop_gradient
        self._grad = None
        self._tape = None
        self.name = name
        self.persistable = persistable
        self._hooks = []
        self.is_data = is_data
        self.block = block

    def numpy(self):
        raise RuntimeError(
            f"Variable '{self.name}' has no data in static mode; run it "
            "through Executor.run(fetch_list=[...])")

    __array__ = numpy

    def __float__(self):
        raise RuntimeError(f"Variable '{self.name}' is symbolic")

    __int__ = __bool__ = __index__ = __float__

    def item(self, *a):
        raise RuntimeError(f"Variable '{self.name}' is symbolic")

    def __repr__(self):
        return (f"Variable(name={self.name!r}, shape={self.shape}, "
                f"dtype={self._value.dtype.name}, "
                f"persistable={self.persistable})")


class OpDesc:
    """One recorded op (ref framework.py:1921 Operator / proto OpDesc).

    inputs: list of slots — ("var", name) | ("const", value) |
    ("rngkey", salt).  attrs are the op's keyword attributes verbatim.
    """

    __slots__ = ("type", "inputs", "outputs", "attrs", "extra")

    def __init__(self, type, inputs, outputs, attrs, extra=None):
        self.type = type
        self.inputs = inputs
        self.outputs = outputs
        self.attrs = attrs
        self.extra = extra or {}

    def input_names(self):
        return [s[1] for s in self.inputs if s[0] == "var"]

    def __repr__(self):
        ins = ", ".join(s[1] if s[0] == "var" else f"<{s[0]}>"
                        for s in self.inputs)
        outs = ", ".join(self.outputs)
        return f"{{{self.type}: ({ins}) -> ({outs})}}"


class Block:
    """Op list + var map (ref framework.py BlockDesc)."""

    def __init__(self, program, idx=0):
        self.program = program
        self.idx = idx
        self.vars: dict[str, Variable] = {}
        self.ops: list[OpDesc] = []

    def var(self, name):
        v = self.vars.get(name)
        if v is None:
            raise ValueError(f"var '{name}' not in block {self.idx}")
        return v

    def has_var(self, name):
        return name in self.vars

    def create_var(self, name=None, shape=(), dtype="float32", **kw):
        name = name or self.program._unique_name("tmp")
        v = Variable(name, shape, dtype, block=self, **kw)
        self.vars[name] = v
        return v

    def append_op(self, op):
        self.ops.append(op)
        self.program._version += 1
        return op

    def all_parameters(self):
        return [v for v in self.vars.values()
                if v.persistable and not v.stop_gradient]


class Program:
    """Recorded graph (ref framework.py:185 Program)."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self._version = 0
        self._name_counter = 0
        self.backward_index = None  # op index of the @backward op
        self._is_test = False
        self._lr_getter = None
        # Tensors interned as persistable vars: id(tensor) -> (tensor, var).
        # The Tensor is kept alive so a recycled CPython id can never alias
        # a new tensor onto a stale Variable.
        self._interned: dict[int, tuple] = {}

    def global_block(self):
        return self.blocks[0]

    @property
    def num_blocks(self):
        return len(self.blocks)

    def current_block(self):
        return self.blocks[0]

    def list_vars(self):
        return list(self.global_block().vars.values())

    def all_parameters(self):
        return self.global_block().all_parameters()

    def _unique_name(self, stem):
        self._name_counter += 1
        return f"{stem}_{self._name_counter}"

    def clone(self, for_test=False):
        """Copy the program; for_test drops backward + optimizer ops
        (everything from the @backward op on), ref Program.clone."""
        p = Program()
        b = p.global_block()
        src = self.global_block()
        ops = src.ops
        if for_test and self.backward_index is not None:
            ops = ops[: self.backward_index]
        b.vars = dict(src.vars)
        b.ops = list(ops)
        p._name_counter = self._name_counter
        p._version = self._version
        p._is_test = for_test
        p._interned = dict(self._interned)
        if not for_test:
            p.backward_index = self.backward_index
            p._lr_getter = self._lr_getter
        return p

    def __str__(self):
        lines = [f"Program(version={self._version})"]
        for b in self.blocks:
            lines.append(f" block {b.idx}:")
            for v in b.vars.values():
                tag = ("data" if getattr(v, "is_data", False) else
                       "persist" if v.persistable else "tmp")
                lines.append(
                    f"  var {v.name} : {list(v._value.shape)} "
                    f"{v._value.dtype.name} [{tag}]")
            for i, op in enumerate(b.ops):
                lines.append(f"  op {i}: {op!r}")
        return "\n".join(lines)

    to_string = __str__


class Scope:
    """name -> concrete value store (ref framework/scope.h:52)."""

    def __init__(self):
        self._values: dict[str, jax.Array] = {}

    def var(self, name):
        return self._values.setdefault(name, None)

    def find_var(self, name):
        return self._values.get(name)

    def set(self, name, value):
        self._values[name] = value

    def keys(self):
        return self._values.keys()


# ---------------------------------------------------------------------------
# capture state
# ---------------------------------------------------------------------------

_main_program = Program()
_startup_program = Program()
_scope = Scope()
_static_mode = False


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


def global_scope():
    return _scope


@contextlib.contextmanager
def scope_guard(scope):
    global _scope
    prev, _scope = _scope, scope
    try:
        yield
    finally:
        _scope = prev


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _main_program, _startup_program
    prev = (_main_program, _startup_program)
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    _install_capture()
    try:
        yield
    finally:
        _main_program, _startup_program = prev


def _enable_static():
    global _static_mode
    _static_mode = True
    _install_capture()


def _disable_static():
    global _static_mode
    _static_mode = False
    from ..core import dispatch

    dispatch._capture_fn = None


def in_static_mode():
    return _static_mode


def _install_capture():
    from ..core import dispatch

    if _static_mode:
        dispatch._capture_fn = capture_op


def data(name, shape, dtype="float32", lod_level=0):
    """Feed placeholder (ref python/paddle/static/input.py data)."""
    blk = _main_program.global_block()
    v = Variable(name, shape, dtype, is_data=True, block=blk)
    blk.vars[name] = v
    return v


# ---------------------------------------------------------------------------
# op capture (called from core.dispatch when static mode is on)
# ---------------------------------------------------------------------------


def _is_prng_key(a):
    if isinstance(a, (jax.Array, np.ndarray)):
        if jnp.issubdtype(a.dtype, jax.dtypes.prng_key):
            return True
        return a.dtype == jnp.uint32 and a.shape == (2,)
    return False


def _intern(t: Tensor):
    """Concrete Tensor flowing into a captured op -> persistable var whose
    value lives in the global scope (parameters, buffers)."""
    blk = _main_program.global_block()
    hit = _main_program._interned.get(id(t))
    if hit is not None:
        return hit[1]
    name = t.name or _main_program._unique_name("persist")
    if blk.has_var(name):
        name = _main_program._unique_name(name)
    v = Variable(name, t._value.shape, t._value.dtype,
                 persistable=True, stop_gradient=t.stop_gradient, block=blk)
    blk.vars[name] = v
    _main_program._interned[id(t)] = (t, v)
    _scope.set(name, t._value)
    return v


def capture_op(op_name, inputs, attrs):
    """Record `op_name` into the default main program.

    Returns NotImplemented when no input is symbolic — the dispatch funnel
    then executes eagerly (parameter initialisation etc. stays concrete).
    """
    if not any(isinstance(x, Variable) for x in inputs):
        return NotImplemented

    opdef = lookup(op_name)
    blk = _main_program.global_block()
    op_idx = len(blk.ops)

    slots = []
    abstract = []
    for x in inputs:
        raw = x._value if isinstance(x, Tensor) else x
        if isinstance(x, Variable):
            slots.append(("var", x.name))
            abstract.append(x._value)
        elif _is_prng_key(raw):
            # PRNG-key inputs (dropout, random ops) are re-derived per run
            # from a fresh executor key — never baked into the graph
            slots.append(("rngkey", op_idx))
            abstract.append(raw)
        elif isinstance(x, Tensor):
            v = _intern(x)
            slots.append(("var", v.name))
            abstract.append(v._value)
        else:
            slots.append(("const", x))
            abstract.append(x)

    out_shapes = jax.eval_shape(
        lambda *a: opdef.fn(*a, **attrs), *abstract)

    # flatten outputs exactly like dispatch._wrap_outputs does
    if opdef.has_aux:
        diff_out, aux = out_shapes
    else:
        diff_out, aux = out_shapes, None

    any_grad_in = any(
        isinstance(x, Variable) and not x.stop_gradient for x in inputs)
    requires_grad = (config.is_grad_enabled() and not opdef.no_grad
                     and any_grad_in)

    def mk_out(sds, stop_grad):
        v = blk.create_var(
            name=_main_program._unique_name(f"{op_name}.tmp"),
            shape=sds.shape, dtype=sds.dtype, stop_gradient=stop_grad)
        return v

    out_names = []
    if isinstance(diff_out, tuple):
        outs = tuple(mk_out(o, not requires_grad) for o in diff_out)
        out_names += [o.name for o in outs]
    else:
        outs = mk_out(diff_out, not requires_grad)
        out_names.append(outs.name)

    aux_struct = None
    if aux is not None:
        aux_leaves, aux_struct = jax.tree.flatten(aux)
        aux_vars = [mk_out(a, True) for a in aux_leaves]
        out_names += [a.name for a in aux_vars]
        aux_t = jax.tree.unflatten(aux_struct, aux_vars)
        if isinstance(outs, tuple):
            result = outs + (aux_t if isinstance(aux_t, tuple) else (aux_t,))
        else:
            result = (outs,) + (aux_t if isinstance(aux_t, tuple)
                                else (aux_t,))
    else:
        result = outs

    blk.append_op(OpDesc(op_name, slots, out_names, dict(attrs),
                         extra={"has_aux": opdef.has_aux,
                                "aux_struct": aux_struct}))
    return result


# ---------------------------------------------------------------------------
# backward + optimizer recording
# ---------------------------------------------------------------------------


def append_backward(loss, parameter_list=None, no_grad_set=None):
    """Record the AD transform over the forward section
    (ref fluid/backward.py:1377).  Returns [(param_var, grad_var)]."""
    prog = _main_program
    blk = prog.global_block()
    if prog.backward_index is not None:
        raise RuntimeError("append_backward already called on this program")
    if not isinstance(loss, Variable):
        raise TypeError("append_backward needs a symbolic loss Variable")

    if parameter_list is None:
        params = [v for v in blk.vars.values()
                  if v.persistable and not v.stop_gradient]
    else:
        params = []
        for p in parameter_list:
            if isinstance(p, Variable):
                params.append(p)
            elif isinstance(p, Tensor):
                hit = prog._interned.get(id(p))
                if hit is None:
                    raise ValueError(
                        f"parameter {p.name!r} was never used in this "
                        "program")
                params.append(hit[1])
            else:
                params.append(blk.var(p))
    if no_grad_set:
        drop = {v.name if isinstance(v, Variable) else str(v)
                for v in no_grad_set}
        params = [p for p in params if p.name not in drop]

    pairs = []
    grad_names = []
    for p in params:
        g = blk.create_var(name=p.name + "@GRAD", shape=p._value.shape,
                           dtype=p._value.dtype)
        pairs.append((p, g))
        grad_names.append(g.name)

    prog.backward_index = len(blk.ops)
    blk.append_op(OpDesc(
        "@backward",
        [("var", loss.name)] + [("var", p.name) for p in params],
        grad_names,
        {"loss": loss.name, "params": [p.name for p in params]}))
    return pairs


def append_global_norm_clip(params_grads, clip_norm, decay_coeffs=None):
    """Record a global-norm clip over all grads (ref fluid/clip.py
    ClipGradByGlobalNorm) — rebinds each grad var to its clipped value.

    decay_coeffs (optional, aligned with params_grads): coupled L2 decay
    folded into each grad BEFORE the norm, matching the eager
    _preprocess order (decay first, clip sees decay-included grads)."""
    blk = _main_program.global_block()
    out_names = []
    slots = []
    for p, g in params_grads:
        slots.append(("var", g.name))
        slots.append(("var", p.name))
        out_names.append(g.name)  # rebind in place
    blk.append_op(OpDesc("@global_norm_clip", slots, out_names,
                         {"clip_norm": float(clip_norm),
                          "decay_coeffs": list(decay_coeffs or [])}))
    return params_grads


def append_optimizer_update(optimizer, param_var, grad_var, lr_scale=1.0,
                            decay_coeff=0.0, clip=None):
    """Record one parameter update as an op whose replay calls the
    optimizer's pure `_rule` (the reference registers sgd/adam/... as ops;
    here the rule itself is the kernel)."""
    prog = _main_program
    blk = prog.global_block()
    pname = param_var.name

    # moment state as persistable vars, initialised in the scope
    pval_abstract = param_var._value
    init_state = optimizer._init_state(
        jnp.zeros(pval_abstract.shape, pval_abstract.dtype))
    state_names = []
    for k, v in init_state.items():
        sname = f"{pname}@{optimizer.__class__.__name__}.{k}"
        if not blk.has_var(sname):
            blk.create_var(name=sname, shape=v.shape, dtype=v.dtype,
                           persistable=True)
            _scope.set(sname, v)
        state_names.append((k, sname))

    slots = ([("var", pname), ("var", grad_var.name), ("const", lr_scale)]
             + [("var", s) for _, s in state_names])
    out_names = [pname] + [s for _, s in state_names]
    prog._lr_getter = optimizer.get_lr
    blk.append_op(OpDesc(
        "@update", slots, out_names,
        {"rule": optimizer._rule, "hyper": optimizer._hyper(),
         "state_keys": [k for k, _ in state_names],
         "optimizer": optimizer.__class__.__name__,
         "decay_coeff": float(decay_coeff), "clip": clip}))


# ---------------------------------------------------------------------------
# Executor: compile the recorded block into one XLA computation
# ---------------------------------------------------------------------------


def _run_ops(ops, env, rng_key, start=0, stop=None):
    """Replay a slice of the op list over concrete/traced arrays."""
    stop = len(ops) if stop is None else stop
    for i in range(start, stop):
        op = ops[i]
        if op.type.startswith("@"):
            raise RuntimeError(
                f"internal: pseudo-op {op.type} inside plain replay")
        opdef = lookup(op.type)
        args = []
        for kind, val in op.inputs:
            if kind == "var":
                args.append(env[val])
            elif kind == "rngkey":
                args.append(jax.random.fold_in(rng_key, val))
            else:
                args.append(val)
        out = opdef.fn(*args, **op.attrs)
        if op.extra.get("has_aux"):
            diff, aux = out
            leaves = (list(diff) if isinstance(diff, tuple) else [diff])
            leaves += jax.tree.leaves(aux)
        else:
            leaves = list(out) if isinstance(out, tuple) else [out]
        for name, val in zip(op.outputs, leaves):
            env[name] = val
    return env


def _split_sections(ops, backward_index):
    """fwd ops | @backward | tail (clip + updates)."""
    if backward_index is None:
        return ops, None, []
    return ops[:backward_index], ops[backward_index], ops[backward_index + 1:]


def _run_tail(ops, env, rng_key):
    """Replay the post-backward section: grad clip + optimizer updates +
    any further plain ops."""
    for i, op in enumerate(ops):
        if op.type == "@global_norm_clip":
            gnames = [s[1] for s in op.inputs[0::2]]
            pnames = [s[1] for s in op.inputs[1::2]]
            coeffs = op.attrs.get("decay_coeffs") or [0.0] * len(gnames)
            grads = []
            for gn, pn, c in zip(gnames, pnames, coeffs):
                g = env[gn]
                if c:
                    g = g + c * env[pn].astype(g.dtype)
                grads.append(g)
            sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                     for g in grads)
            gnorm = jnp.sqrt(sq)
            clip = op.attrs["clip_norm"]
            scale = jnp.minimum(1.0, clip / jnp.maximum(gnorm, 1e-12))
            for name, g in zip(gnames, grads):
                env[name] = (g.astype(jnp.float32) * scale).astype(g.dtype)
        elif op.type == "@update":
            pname = op.inputs[0][1]
            gname = op.inputs[1][1]
            lr_scale = op.inputs[2][1]
            state = {k: env[s[1]] for k, s in
                     zip(op.attrs["state_keys"], op.inputs[3:])}
            p, g = env[pname], env[gname]
            if op.attrs.get("decay_coeff"):
                g = g + op.attrs["decay_coeff"] * p
            clip_spec = op.attrs.get("clip")
            if clip_spec is not None:
                if clip_spec[0] == "value":
                    g = jnp.clip(g, clip_spec[1], clip_spec[2])
                elif clip_spec[0] == "norm":
                    norm = jnp.sqrt(jnp.sum(
                        jnp.square(g.astype(jnp.float32))))
                    scale = jnp.minimum(
                        1.0, clip_spec[1] / jnp.maximum(norm, 1e-12))
                    g = (g.astype(jnp.float32) * scale).astype(g.dtype)
            # lr arrives as a traced scalar ("@lr" in env), fed fresh each
            # run — LR schedulers step without recompiling
            lr = env["@lr"] * lr_scale
            new_p, new_state = op.attrs["rule"](
                p, g, state, lr, **op.attrs["hyper"])
            env[pname] = new_p
            for k, s in zip(op.attrs["state_keys"], op.inputs[3:]):
                env[s[1]] = new_state[k]
        else:
            _run_ops(ops, env, rng_key, start=i, stop=i + 1)
    return env


class Executor:
    """Compiles + runs Programs (ref fluid/executor.py:475 Executor)."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_prune=False):
        if program is None:
            program = _main_program
        if isinstance(program, CompiledProgram):
            program = program._program
        scope = scope or _scope
        feed = feed or {}
        fetch_list = fetch_list or []
        if program is _startup_program and not fetch_list:
            # startup: parameter values are already materialised in the
            # scope at intern time (eager init = the startup program)
            return []

        fetch_names = []
        for f in fetch_list:
            fetch_names.append(f.name if isinstance(f, Variable) else str(f))

        blk = program.global_block()
        persist = sorted(
            n for n, v in blk.vars.items()
            if v.persistable and scope.find_var(n) is not None)
        feed_names = sorted(feed.keys())

        feed_vals = {}
        for n in feed_names:
            a = feed[n]
            a = a._value if isinstance(a, Tensor) else jnp.asarray(a)
            feed_vals[n] = a

        # the Program object itself is part of the key (identity hash) —
        # keeping it referenced in the cache means a GC'd program's id can
        # never be recycled into a stale cache hit
        sig = (program, program._version, tuple(fetch_names),
               tuple(feed_names),
               tuple((n,) + tuple(feed_vals[n].shape) for n in feed_names))
        fn = self._cache.get(sig)
        if fn is None:
            fn = self._build(program, persist, feed_names, fetch_names)
            self._cache[sig] = fn

        pvals = {n: scope.find_var(n) for n in persist}
        from ..framework import random as fr

        rng = fr.next_key()
        lr = getattr(program, "_lr_getter", None)
        lr_val = jnp.asarray(lr() if lr is not None else 0.0, jnp.float32)
        fetched, new_pvals = fn(pvals, feed_vals, rng, lr_val)
        for n, v in new_pvals.items():
            scope.set(n, v)
        if return_numpy:
            return [np.asarray(v) for v in fetched]
        return [Tensor(v) for v in fetched]

    def train_from_dataset(self, program=None, dataset=None,
                           fetch_list=None, thread=1, debug=False, **kw):
        """ref Executor::RunFromDataset (framework/executor.h:137) via
        the Trainer/DeviceWorker loop (framework/trainer.py)."""
        from ..framework.trainer import train_from_dataset as _tfd

        return _tfd(program or _main_program, dataset,
                    fetch_list=fetch_list, thread=thread, executor=self,
                    debug=debug)

    def infer_from_dataset(self, program=None, dataset=None,
                           fetch_list=None, thread=1, debug=False, **kw):
        """Like train_from_dataset but runs NO parameter updates (ref
        Executor.infer_from_dataset): a training program is replayed
        through its for_test clone (backward + optimizer ops dropped)."""
        from ..framework.trainer import train_from_dataset as _tfd

        program = program or _main_program
        if program.backward_index is not None:
            program = program.clone(for_test=True)
        return _tfd(program, dataset, fetch_list=fetch_list,
                    thread=thread, executor=self, debug=debug)

    def close(self):
        self._cache.clear()

    def _build(self, program, persist, feed_names, fetch_names):
        blk = program.global_block()
        fwd_ops, bwd_op, tail_ops = _split_sections(
            blk.ops, program.backward_index)

        if bwd_op is None:
            # dead-code elimination: an inference program only runs the
            # ops its fetches need (XLA would DCE anyway; pruning first
            # means un-fed data vars that feed only pruned ops are fine)
            fwd_ops = _backward_slice(fwd_ops, fetch_names)

        def compiled(pvals, feed_vals, rng_key, lr):
            env = dict(pvals)
            env.update(feed_vals)
            env["@lr"] = lr

            if bwd_op is None:
                env = _run_ops(fwd_ops, env, rng_key)
            else:
                loss_name = bwd_op.attrs["loss"]
                param_names = bwd_op.attrs["params"]
                base_env = dict(env)

                def fwd(trainable):
                    e = dict(base_env)
                    e.update(trainable)
                    e = _run_ops(fwd_ops, e, rng_key)
                    return e[loss_name], e

                trainable = {n: env[n] for n in param_names}
                loss, vjp_fn, env = jax.vjp(fwd, trainable, has_aux=True)
                (grads,) = vjp_fn(jnp.ones_like(loss))
                env = dict(env)
                env["@lr"] = lr
                for pname, gname in zip(param_names, bwd_op.outputs):
                    env[gname] = grads[pname]
                _run_tail(tail_ops, env, rng_key)

            fetched = [env[n] for n in fetch_names]
            new_pvals = {n: env[n] for n in persist if n in env}
            return fetched, new_pvals

        return jax.jit(compiled)


class CompiledProgram:
    """Thin wrapper kept for API parity (ref fluid/compiler.py
    CompiledProgram) — XLA compilation happens inside Executor.run."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy

    def with_data_parallel(self, loss_name=None, places=None, **kw):
        # multi-device execution goes through the GSPMD engine
        # (paddle_tpu.engine / distributed.hybrid); single-program replay
        # stays single-device here
        return self


# ---------------------------------------------------------------------------
# persistence (ref fluid/io.py:286-1042 save/load_persistables,
# save_inference_model:1246)
# ---------------------------------------------------------------------------


def save(program, model_path, protocol=4):
    """Save all persistable var values of `program` -> {path}.pdparams."""
    blk = program.global_block()
    state = {}
    for n, v in blk.vars.items():
        if v.persistable and _scope.find_var(n) is not None:
            state[n] = np.asarray(_scope.find_var(n))
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    """Restore persistable var values saved by `save` into the scope."""
    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    keep = None if var_list is None else {
        v.name if isinstance(v, Variable) else str(v) for v in var_list}
    for n, val in state.items():
        if keep is None or n in keep:
            _scope.set(n, jnp.asarray(val))


def _backward_slice(ops, fetch_names):
    """Keep only the ops a backward walk from `fetch_names` reaches."""
    needed = set(fetch_names)
    kept = []
    for op in reversed(ops):
        if any(o in needed for o in op.outputs):
            kept.append(op)
            needed.update(op.input_names())
    kept.reverse()
    return kept


def _prune_for_fetch(program, feed_names, fetch_names):
    """Backward slice: keep only ops needed to compute the fetches from
    feeds + persistables (ref Program._prune)."""
    blk = program.global_block()
    fwd_ops = blk.ops
    if program.backward_index is not None:
        fwd_ops = fwd_ops[: program.backward_index]
    kept = _backward_slice(fwd_ops, fetch_names)
    var_names = set(feed_names) | set(fetch_names)
    for op in kept:
        var_names.update(op.input_names())
        var_names.update(op.outputs)
    return kept, var_names


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **configs):
    """Serialize the pruned inference graph + params
    (ref fluid/io.py:1246).  Produces {path}.pdmodel (op list + var metas,
    pickled) and {path}.pdiparams (persistable values)."""
    import os

    program = program or _main_program
    feed_names = [v.name if isinstance(v, Variable) else str(v)
                  for v in feed_vars]
    fetch_names = [v.name if isinstance(v, Variable) else str(v)
                   for v in fetch_vars]
    ops, var_names = _prune_for_fetch(program, feed_names, fetch_names)
    blk = program.global_block()

    op_records = []
    for op in ops:
        if op.type.startswith("@"):
            raise ValueError(
                f"inference graph contains training pseudo-op {op.type}; "
                "prune with clone(for_test=True) first")
        # only literal attrs survive serialization
        attrs = {k: v for k, v in op.attrs.items() if not callable(v)}
        op_records.append((op.type, op.inputs, op.outputs, attrs,
                           op.extra.get("has_aux", False)))

    var_metas = {}
    params = {}
    for n in sorted(var_names):
        v = blk.vars.get(n)
        if v is None:
            continue
        var_metas[n] = (list(v._value.shape), v._value.dtype.name,
                        v.persistable, getattr(v, "is_data", False))
        if v.persistable and _scope.find_var(n) is not None:
            params[n] = np.asarray(_scope.find_var(n))

    from ..framework.op_version import get_op_version

    op_versions = {rec[0]: get_op_version(rec[0]) for rec in op_records}
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump({"ops": op_records, "vars": var_metas,
                     "feed": feed_names, "fetch": fetch_names,
                     "op_versions": op_versions}, f, protocol=4)
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump(params, f, protocol=4)


def load_inference_model(path_prefix, executor=None, **configs):
    """Returns (program, feed_names, fetch_names); the program's
    persistables are loaded into the global scope."""
    with open(path_prefix + ".pdmodel", "rb") as f:
        meta = pickle.load(f)
    with open(path_prefix + ".pdiparams", "rb") as f:
        params = pickle.load(f)

    from ..framework.op_version import check_compatibility

    check_compatibility(meta.get("op_versions"))

    prog = Program()
    blk = prog.global_block()
    for n, (shape, dtype, persistable, is_data) in meta["vars"].items():
        blk.create_var(name=n, shape=shape, dtype=dtype,
                       persistable=persistable, is_data=is_data)
    for type_, inputs, outputs, attrs, has_aux in meta["ops"]:
        blk.append_op(OpDesc(type_, [tuple(s) for s in inputs],
                             list(outputs), attrs,
                             extra={"has_aux": has_aux}))
    for n, val in params.items():
        _scope.set(n, jnp.asarray(val))
    return prog, meta["feed"], meta["fetch"]
