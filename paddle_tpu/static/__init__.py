"""paddle.static — static-graph-style surface.

Ref parity: python/paddle/static/__init__.py. On TPU there is no separate
Program/Executor runtime — `paddle.jit.to_static` capture plays that role
— but the static namespace keeps API compatibility: control-flow ops
(`nn.cond`, `nn.while_loop`, ...) lower to XLA control flow, and InputSpec
re-exports from paddle.jit.
"""

from __future__ import annotations

from ..jit import InputSpec  # noqa: F401
from . import nn  # noqa: F401

__all__ = ["InputSpec", "nn"]
