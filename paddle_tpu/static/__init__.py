"""paddle.static — static-graph surface.

Ref parity: python/paddle/static/__init__.py + fluid/framework.py +
fluid/executor.py. TPU-native: Program building is an op-capture mode in
the eager dispatch funnel (see program.py); Executor.run compiles the
recorded block into ONE XLA computation, with persistable state in a
Scope across runs. Control-flow ops (`nn.cond`, `nn.while_loop`, ...)
lower to XLA control flow.
"""

from __future__ import annotations

from ..jit import InputSpec  # noqa: F401
from . import nn  # noqa: F401
from .program import (  # noqa: F401
    Block, CompiledProgram, Executor, OpDesc, Program, Scope, Variable,
    append_backward, data, default_main_program, default_startup_program,
    global_scope, load, load_inference_model, program_guard, save,
    save_inference_model, scope_guard,
)

# re-export the control-flow ops at the paddle.static.nn level they live
# at in the reference
cond = nn.cond
while_loop = nn.while_loop
case = nn.case
switch_case = nn.switch_case

__all__ = [
    "InputSpec", "nn", "Program", "Block", "OpDesc", "Variable", "Scope",
    "Executor", "CompiledProgram", "program_guard", "scope_guard",
    "default_main_program", "default_startup_program", "global_scope",
    "data", "append_backward", "save", "load", "save_inference_model",
    "load_inference_model", "cond", "while_loop", "case", "switch_case",
]
