"""Profiler: host-side event recording + device trace capture.

Ref parity: paddle/fluid/platform/profiler.h (RecordEvent RAII, event
aggregation), platform/device_tracer.cc (CUPTI device tracing),
python/paddle/fluid/profiler.py:190 (profiler context + summary table),
tools/timeline.py (chrome-trace export). TPU-native mapping:

- RecordEvent           -> host wall-clock spans (thread-aware), doubling
                           as jax.profiler.TraceAnnotation so annotations
                           show up inside XProf device traces
- DeviceTracer/CUPTI    -> jax.profiler.start_trace/stop_trace (XProf
                           xplane capture; the PJRT runtime records device
                           ops — no CUPTI analogue needed)
- profiler.profiler ctx -> profiler.profile(...)
- tools/timeline.py     -> export_chrome_tracing(path) from host events
- op-time table         -> summary() — per-op totals/avg/max/min, fed by
                           dispatch instrumentation (enable_op_profiling)
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time

__all__ = [
    "RecordEvent", "RecordMemEvent", "enable_op_profiling",
    "disable_op_profiling", "is_op_profiling_enabled", "reset", "events",
    "mem_events", "record_device_memory", "summary", "percentiles",
    "export_chrome_tracing", "profile", "start_trace", "stop_trace",
    "device_op_table", "device_op_events",
]

# rolling windows: the always-on step timeline (paddle_tpu.observe)
# records a handful of spans per train/decode step, so an unbounded
# list would leak over a long run — keep the newest spans only (same
# policy as serving.metrics' latency windows)
_MAX_EVENTS = 100_000
_MAX_MEM_EVENTS = 10_000

_lock = threading.Lock()
_events: collections.deque = collections.deque(maxlen=_MAX_EVENTS)
_op_profiling = False
_tls = threading.local()


def _now_us():
    return time.perf_counter_ns() / 1000.0


class RecordEvent:
    """Named host-side span (ref platform/profiler.h RecordEvent).

    Context manager; nests. Also emits a jax TraceAnnotation so the name
    appears in XProf device timelines captured via start_trace."""

    def __init__(self, name, cat="host"):
        self.name = name
        self.cat = cat
        self._t0 = None
        self._jax_ann = None

    def __enter__(self):
        depth = getattr(_tls, "depth", 0)
        _tls.depth = depth + 1
        self._t0 = _now_us()
        try:
            import jax.profiler as jp

            self._jax_ann = jp.TraceAnnotation(self.name)
            self._jax_ann.__enter__()
        except Exception:
            self._jax_ann = None
        return self

    def __exit__(self, *exc):
        if self._jax_ann is not None:
            self._jax_ann.__exit__(*exc)
        dur = _now_us() - self._t0
        _tls.depth -= 1
        with _lock:
            _events.append({
                "name": self.name, "cat": self.cat, "ts": self._t0,
                "dur": dur, "tid": threading.get_ident(),
                "depth": _tls.depth,
            })
        return False


_mem_events: collections.deque = collections.deque(maxlen=_MAX_MEM_EVENTS)


class RecordMemEvent:
    """Memory event (ref platform/profiler.proto:38 MemEvent): a named
    allocation/deallocation or snapshot with byte counts and place.
    Usable directly (`RecordMemEvent("alloc", bytes=..., place=...)`)
    or via record_device_memory() snapshots."""

    def __init__(self, annotation, *, bytes=0, place=None, kind="alloc",
                 extra=None):
        ev = {
            "annotation": annotation, "kind": kind,
            "bytes": int(bytes), "place": str(place or "device:0"),
            "ts": _now_us(), "tid": threading.get_ident(),
        }
        if extra:
            ev.update(extra)
        with _lock:
            _mem_events.append(ev)


def record_device_memory(annotation="snapshot", device=None):
    """Snapshot the device's MEASURED memory (device.memory_stats) as a
    MemEvent and roll the high-watermark into framework.monitor
    (STAT_ADD analogue of the reference's GPU mem stat)."""
    from ..device import memory_stats
    from ..framework import monitor

    stats = memory_stats(device)
    in_use = int(stats.get("bytes_in_use", 0))
    peak = int(stats.get("peak_bytes_in_use", -1))
    RecordMemEvent(annotation, bytes=in_use, kind="snapshot",
                   place="device", extra={
                       "peak_bytes_in_use": peak,
                       "host_bytes_in_use":
                           int(stats.get("host_bytes_in_use", 0)),
                   })
    monitor.stat_max("device_mem_bytes_in_use_peak",
                     peak if peak >= 0 else in_use)
    return stats


def reset():
    with _lock:
        _events.clear()
        _mem_events.clear()


def events():
    with _lock:
        return list(_events)


def mem_events():
    with _lock:
        return list(_mem_events)


def enable_op_profiling():
    """Record a span per dispatched op (ref imperative/profiler.cc)."""
    global _op_profiling
    _op_profiling = True


def disable_op_profiling():
    global _op_profiling
    _op_profiling = False


def is_op_profiling_enabled():
    return _op_profiling


@contextlib.contextmanager
def profile(*, op_detail=True, trace_dir=None):
    """Profiler scope (ref fluid/profiler.py:257 profiler ctx).

    op_detail: record per-op dispatch spans for summary().
    trace_dir: also capture an XProf device trace there."""
    reset()
    if op_detail:
        enable_op_profiling()
    if trace_dir:
        start_trace(trace_dir)
    try:
        yield
    finally:
        if trace_dir:
            stop_trace()
        if op_detail:
            disable_op_profiling()


def summary(sorted_by="total", limit=None):
    """Aggregate events by name into the reference's op-time table
    (fluid/profiler.py:190 print_profiler). Returns the table string."""
    agg: dict[str, list[float]] = {}
    for e in events():
        agg.setdefault(e["name"], []).append(e["dur"])
    rows = []
    for name, durs in agg.items():
        rows.append({
            "name": name, "calls": len(durs), "total": sum(durs),
            "avg": sum(durs) / len(durs), "max": max(durs),
            "min": min(durs),
        })
    key = {"total": "total", "calls": "calls", "avg": "avg",
           "max": "max", "min": "min"}.get(sorted_by, "total")
    rows.sort(key=lambda r: r[key], reverse=True)
    if limit:
        rows = rows[:limit]
    lines = [
        f"{'Event':<40}{'Calls':>8}{'Total(us)':>14}{'Avg(us)':>12}"
        f"{'Max(us)':>12}{'Min(us)':>12}"
    ]
    lines.append("-" * len(lines[0]))
    for r in rows:
        lines.append(
            f"{r['name'][:39]:<40}{r['calls']:>8}{r['total']:>14.1f}"
            f"{r['avg']:>12.1f}{r['max']:>12.1f}{r['min']:>12.1f}")
    mems = mem_events()
    if mems:
        # device-memory section (ref fluid/profiler.py mem table /
        # profiler.proto MemEvent): measured snapshots, peak first
        lines.append("")
        lines.append("Device memory (measured)")
        lines.append(f"{'Annotation':<32}{'Kind':>10}{'Bytes':>16}"
                     f"{'Peak':>16}{'HostBytes':>14}")
        lines.append("-" * 88)
        peak_all = max((m.get("peak_bytes_in_use", -1) for m in mems),
                       default=-1)
        in_use_max = max((m["bytes"] for m in mems), default=0)
        host_max = max((m.get("host_bytes_in_use", 0) for m in mems),
                       default=0)
        for m in mems[-20:]:
            lines.append(
                f"{m['annotation'][:31]:<32}{m['kind']:>10}"
                f"{m['bytes']:>16}"
                f"{m.get('peak_bytes_in_use', -1):>16}"
                f"{m.get('host_bytes_in_use', 0):>14}")
        lines.append(
            f"{'== high watermark ==':<32}{'':>10}{in_use_max:>16}"
            f"{peak_all:>16}{host_max:>14}")
    return "\n".join(lines)


def percentiles(name, ps=(50, 95, 99)):
    """Latency percentiles (microseconds) over the recorded host spans
    named `name` — {p: duration_us} with linear interpolation (numpy's
    'linear' method). The serving runtime computes its p50/p95/p99
    through this over its per-request/per-step RecordEvent spans."""
    from ..utils import stats as _stats

    durs = [e["dur"] for e in events() if e["name"] == name]
    if not durs:
        raise ValueError(f"no recorded events named {name!r}")
    return _stats.percentiles(durs, ps)


def export_chrome_tracing(path):
    """Write host events as a chrome://tracing JSON file
    (ref tools/timeline.py).

    Spans are sorted by start time and carry their recorded nesting
    `depth` (spans land in `_events` at EXIT, so inner spans precede
    their parents in recording order — the sort restores enclosure
    order so chrome stacks nested spans correctly). Memory events are
    emitted as counter (``ph:"C"``) rows so the measured
    bytes-in-use/peak series renders as a track under the spans."""
    pid = os.getpid()
    trace_events = [
        {
            "name": e["name"], "cat": e["cat"], "ph": "X",
            "ts": e["ts"], "dur": e["dur"], "pid": pid,
            "tid": e["tid"], "args": {"depth": e.get("depth", 0)},
        }
        for e in sorted(events(), key=lambda e: (e["tid"], e["ts"]))
    ]
    for m in mem_events():
        args = {"bytes_in_use": m["bytes"]}
        if "host_bytes_in_use" in m:
            args["host_bytes_in_use"] = m["host_bytes_in_use"]
        if m.get("peak_bytes_in_use", -1) >= 0:
            args["peak_bytes_in_use"] = m["peak_bytes_in_use"]
        trace_events.append({
            "name": f"memory ({m['place']})", "cat": "memory", "ph": "C",
            "ts": m["ts"], "pid": pid, "tid": 0, "args": args,
        })
    trace = {"traceEvents": trace_events, "displayTimeUnit": "ms"}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


# -- device (XProf) trace ----------------------------------------------------


def device_op_table(logdir, top=None, sorted_by="total"):
    """Per-op DEVICE-TIME table from an XProf capture (ref
    platform/device_tracer.cc — the reference correlates CUPTI device
    spans per op; here the xplane.pb the PJRT runtime wrote is parsed
    directly with the wire-format reader, no tensorboard needed).

    Aggregates every event on the device planes ("/device:..." when an
    accelerator recorded; "/host:CPU" as the fallback on the host
    backend) by op name: calls / total / avg / max (microseconds).
    Returns (table_string, rows)."""
    import glob as _glob

    from ..utils.protowire import fields

    paths = sorted(_glob.glob(
        os.path.join(logdir, "**", "*.xplane.pb"), recursive=True))
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {logdir}")
    agg: dict[str, list[float]] = {}

    def plane_name(buf):
        for f, w, v in fields(buf):
            if f == 2 and w == 2:
                return v.decode(errors="replace")
        return ""

    def walk_plane(buf):
        meta = {}
        for f, w, v in fields(buf):
            if f == 4 and w == 2:          # event_metadata map entry
                mid, name = None, None
                for f2, w2, v2 in fields(v):
                    if f2 == 1 and w2 == 0:
                        mid = v2
                    elif f2 == 2 and w2 == 2:  # XEventMetadata
                        for f3, w3, v3 in fields(v2):
                            if f3 == 1 and w3 == 0:
                                mid = v3
                            elif f3 == 2 and w3 == 2:
                                name = v3.decode(errors="replace")
                if mid is not None and name:
                    meta[mid] = name
        for f, w, v in fields(buf):
            if f != 3 or w != 2:           # XLine
                continue
            for f2, w2, v2 in fields(v):
                if f2 != 4 or w2 != 2:     # XEvent
                    continue
                mid, dur = None, 0
                for f3, w3, v3 in fields(v2):
                    if f3 == 1 and w3 == 0:
                        mid = v3
                    elif f3 == 3 and w3 == 0:
                        dur = v3               # picoseconds
                name = meta.get(mid)
                if name and not name.startswith("$"):
                    # "$file:line fn" entries are python-frame spans on
                    # the host plane, not ops
                    agg.setdefault(name, []).append(dur / 1e6)  # -> us

    for path in paths:
        with open(path, "rb") as f:
            space = f.read()
        planes = [v for fno, w, v in fields(space) if fno == 1 and w == 2]
        device = [p for p in planes if plane_name(p).startswith("/device:")]
        for p in device or [p for p in planes
                            if plane_name(p) == "/host:CPU"]:
            walk_plane(p)

    rows = [{"name": n, "calls": len(d), "total": sum(d),
             "avg": sum(d) / len(d), "max": max(d)}
            for n, d in agg.items()]
    key = {"total": "total", "calls": "calls", "avg": "avg",
           "max": "max"}.get(sorted_by, "total")
    rows.sort(key=lambda r: r[key], reverse=True)
    if top:
        rows = rows[:top]
    lines = [f"{'Device op':<52}{'Calls':>8}{'Total(us)':>14}"
             f"{'Avg(us)':>12}{'Max(us)':>12}"]
    lines.append("-" * len(lines[0]))
    for r in rows:
        lines.append(
            f"{r['name'][:51]:<52}{r['calls']:>8}{r['total']:>14.1f}"
            f"{r['avg']:>12.1f}{r['max']:>12.1f}")
    return "\n".join(lines), rows


def device_op_events(logdir):
    """Per-event DEVICE intervals from an XProf capture: a flat list of
    ``{name, line, start_us, dur_us}`` rows with start times absolute
    within the capture (XLine.timestamp_ns + XEvent.offset_ps — one
    shared clock across the capture's lines). Where `device_op_table`
    aggregates totals per op name, this keeps every occurrence so the
    overlap report can intersect collective intervals with the
    concurrently-resident compute intervals."""
    import glob as _glob

    from ..utils.protowire import fields

    paths = sorted(_glob.glob(
        os.path.join(logdir, "**", "*.xplane.pb"), recursive=True))
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {logdir}")
    out = []

    def plane_name(buf):
        for f, w, v in fields(buf):
            if f == 2 and w == 2:
                return v.decode(errors="replace")
        return ""

    def walk_plane(buf):
        meta = {}
        for f, w, v in fields(buf):
            if f == 4 and w == 2:          # event_metadata map entry
                mid, name = None, None
                for f2, w2, v2 in fields(v):
                    if f2 == 1 and w2 == 0:
                        mid = v2
                    elif f2 == 2 and w2 == 2:  # XEventMetadata
                        for f3, w3, v3 in fields(v2):
                            if f3 == 1 and w3 == 0:
                                mid = v3
                            elif f3 == 2 and w3 == 2:
                                name = v3.decode(errors="replace")
                if mid is not None and name:
                    meta[mid] = name
        for f, w, v in fields(buf):
            if f != 3 or w != 2:           # XLine
                continue
            line_name, ts_ns = "", 0
            evs = []
            for f2, w2, v2 in fields(v):
                if f2 == 2 and w2 == 2:
                    line_name = v2.decode(errors="replace")
                elif f2 == 3 and w2 == 0:
                    ts_ns = v2
                elif f2 == 4 and w2 == 2:  # XEvent
                    evs.append(v2)
            for ev in evs:
                mid, off_ps, dur_ps = None, 0, 0
                for f3, w3, v3 in fields(ev):
                    if f3 == 1 and w3 == 0:
                        mid = v3
                    elif f3 == 2 and w3 == 0:
                        off_ps = v3            # picoseconds
                    elif f3 == 3 and w3 == 0:
                        dur_ps = v3            # picoseconds
                name = meta.get(mid)
                if name and not name.startswith("$"):
                    out.append({
                        "name": name, "line": line_name,
                        "start_us": ts_ns / 1e3 + off_ps / 1e6,
                        "dur_us": dur_ps / 1e6,
                    })

    for path in paths:
        with open(path, "rb") as f:
            space = f.read()
        planes = [v for fno, w, v in fields(space) if fno == 1 and w == 2]
        device = [p for p in planes if plane_name(p).startswith("/device:")]
        for p in device or [p for p in planes
                            if plane_name(p) == "/host:CPU"]:
            walk_plane(p)
    return out


def start_trace(logdir):
    """Capture an XProf/xplane device trace (ref device_tracer.cc — here
    the PJRT runtime does the recording)."""
    import jax.profiler as jp

    jp.start_trace(logdir)


def stop_trace():
    import jax.profiler as jp

    jp.stop_trace()
