"""Reader decorators (ref python/paddle/reader/decorator.py).

A "reader" is a zero-arg callable returning an iterable of samples; the
decorators compose them.  Original generator-based implementations —
`xmap_readers`/`multiprocess_reader` use threads (the io.DataLoader owns
the real multiprocess path; these exist for fluid-era API parity).
"""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading

__all__ = [
    "cache", "map_readers", "shuffle", "chain", "compose", "buffered",
    "firstn", "xmap_readers", "multiprocess_reader", "batch",
]


def cache(reader):
    """Materialise the full stream once; replay from memory after.  A
    source failure mid-load leaves the cache EMPTY (not a stale prefix
    that a retry would duplicate)."""
    all_data = []
    loaded = False

    def rd():
        nonlocal loaded
        if not loaded:
            fresh = list(reader())  # only commit a complete load
            all_data.extend(fresh)
            loaded = True
        return iter(all_data)

    return rd


def map_readers(func, *readers):
    """Yield func(*samples) zipped across readers."""
    def rd():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return rd


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer of `buf_size` samples."""
    def rd():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return rd


def chain(*readers):
    """Concatenate streams (ref chain: outputs one after another)."""
    def rd():
        return itertools.chain(*[r() for r in readers])

    return rd


def compose(*readers, **kwargs):
    """Zip readers into flattened tuples: (a, b1, b2) from a-reader and
    (b1,b2)-reader. check_alignment=True (default) raises on length
    mismatch."""
    check_alignment = kwargs.pop("check_alignment", True)

    def _flatten(items):
        out = []
        for it in items:
            if isinstance(it, tuple):
                out.extend(it)
            else:
                out.append(it)
        return tuple(out)

    def rd():
        its = [r() for r in readers]
        for items in (zip(*its) if not check_alignment
                      else itertools.zip_longest(*its)):
            if check_alignment and any(i is None for i in items):
                raise ValueError("readers have different lengths")
            yield _flatten(items)

    return rd


class _ReaderError:
    """Exception envelope crossing a reader thread boundary."""

    def __init__(self, exc):
        self.exc = exc


def buffered(reader, size):
    """Decouple producer/consumer through a bounded queue fed by a
    background thread; a producer exception re-raises in the consumer
    (never a silently truncated stream)."""
    end = object()

    def rd():
        q = queue.Queue(maxsize=size)

        def fill():
            try:
                for s in reader():
                    q.put(s)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                q.put(_ReaderError(e))
                return
            q.put(end)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            s = q.get()
            if s is end:
                break
            if isinstance(s, _ReaderError):
                raise s.exc
            yield s

    return rd


def firstn(reader, n):
    def rd():
        return itertools.islice(reader(), n)

    return rd


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with `process_num` worker threads.
    order=True preserves input order (sequence-tagged heap merge)."""
    end = object()

    def rd():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)

        def feed():
            try:
                for i, s in enumerate(reader()):
                    in_q.put((i, s))
            except BaseException as e:  # noqa: BLE001 — re-raised below
                out_q.put(_ReaderError(e))
            finally:
                # sentinels flow regardless: a dead feed must not leave
                # workers (and through them the consumer) blocked forever
                for _ in range(process_num):
                    in_q.put(end)

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is end:
                        break
                    i, s = item
                    out_q.put((i, mapper(s)))
            except BaseException as e:  # noqa: BLE001 — re-raised below
                out_q.put(_ReaderError(e))
            finally:
                out_q.put(end)

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        if not order:
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                if isinstance(item, _ReaderError):
                    raise item.exc
                yield item[1]
        else:
            import heapq

            heap, want = [], 0
            while finished < process_num or heap:
                if heap and heap[0][0] == want:
                    _, v = heapq.heappop(heap)
                    want += 1
                    yield v
                    continue
                if finished >= process_num:
                    # stream ended with a gap: impossible unless a
                    # worker died; drain what exists
                    _, v = heapq.heappop(heap)
                    want = heap[0][0] if heap else want
                    yield v
                    continue
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                if isinstance(item, _ReaderError):
                    raise item.exc
                heapq.heappush(heap, item)

    return rd


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave several readers concurrently (thread-backed here; the
    reference forks processes — io.DataLoader owns that machinery)."""
    def rd():
        q = queue.Queue(queue_size)
        end = object()

        def fill(r):
            try:
                for s in r():
                    q.put(s)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                q.put(_ReaderError(e))
                return
            finally:
                q.put(end)

        for r in readers:
            threading.Thread(target=fill, args=(r,), daemon=True).start()
        finished = 0
        while finished < len(readers):
            s = q.get()
            if s is end:
                finished += 1
                continue
            if isinstance(s, _ReaderError):
                raise s.exc
            yield s

    return rd


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of `batch_size` (ref python/paddle/
    batch.py:18; exposed as paddle.batch)."""
    if batch_size <= 0:
        raise ValueError(f"batch_size must be positive, got {batch_size}")

    def rd():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return rd
