"""Dynamic batcher: coalesce concurrent single-sample requests into
shape-bucketed, padded batches.

Ref parity: paddle_serving's batching proxy in front of
AnalysisPredictor clones. The TPU-native concern is *compilation*: XLA
specialises per shape, so an arbitrary batch size would recompile on
every new occupancy. The batcher therefore pads every flush up to a
bucket ladder (powers of two capped at `max_batch`) — each rung
compiles exactly once, and after warmup the hot path never traces
again. `compile_counts` exposes the per-bucket trace counter the tests
assert on (the counter increments inside the traced function, i.e. at
trace time only).

Fault site: ``serving.batch`` fires once per flush (delay = slow model,
raise = batch-level failure propagated to every member request).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from .. import profiler
from ..framework import faults
from ..framework.flags import flag
from .queueing import AdmissionQueue, Request

__all__ = ["bucket_ladder", "bucket_for", "pad_batch", "DynamicBatcher"]


def bucket_ladder(max_batch):
    """Powers of two up to and including `max_batch` (which is always
    the top rung even when not a power of two)."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    ladder, b = [], 1
    while b < max_batch:
        ladder.append(b)
        b *= 2
    ladder.append(max_batch)
    return ladder


def bucket_for(n, ladder):
    """Smallest rung >= n."""
    for b in ladder:
        if n <= b:
            return b
    raise ValueError(f"batch of {n} exceeds top bucket {ladder[-1]}")


def pad_batch(batch, bucket):
    """Stack samples into [n, ...] and pad axis 0 up to `bucket` by
    repeating the last sample (repeat, not zeros: keeps padded rows
    numerically tame for models with normalisation over the batch)."""
    x = np.stack([np.asarray(s) for s in batch])
    if x.shape[0] < bucket:
        fill = np.broadcast_to(x[-1:], (bucket - x.shape[0],) + x.shape[1:])
        x = np.concatenate([x, fill], axis=0)
    return x


class DynamicBatcher:
    """Queue + assembler + bucketed executor around a batch function.

    `fn` maps one batched array [n, ...] -> array/pytree with leading
    axis n. With jit=True (default) it must be jax-traceable and is
    wrapped in `jax.jit`; with jit=False it is called as-is (e.g. an
    exported Predictor program that manages its own compilation) and the
    compile counter counts first-use per bucket instead.
    """

    def __init__(self, fn, *, max_batch=None, max_wait_s=0.002,
                 queue_cap=None, metrics=None, jit=True,
                 strict_shapes=False):
        self._fn = fn
        # strict_shapes: once warmup() has traced every rung, run each
        # flush under observe.no_retrace() so shape drift fails loudly
        # at trace time instead of silently recompiling
        self._strict = strict_shapes
        self._warmed = False
        self.max_batch = max_batch or flag("FLAGS_serving_max_batch")
        self.max_wait_s = max_wait_s
        self.ladder = bucket_ladder(self.max_batch)
        self.metrics = metrics
        self.queue = AdmissionQueue(
            queue_cap or flag("FLAGS_serving_queue_cap"), metrics=metrics)
        self._compiles: dict = {}   # bucket -> trace count
        self._jit = jit
        if jit:
            import jax

            def traced(x):
                # trace-time side effect: bumps once per compilation
                self._compiles[x.shape[0]] = \
                    self._compiles.get(x.shape[0], 0) + 1
                from .. import observe

                observe.record_compile(
                    "serving.batch", signature=observe.signature_of(x))
                return fn(x)

            self._run = jax.jit(traced)
        else:
            self._seen_buckets: set = set()
            self._run = fn
        self._thread = None
        self._stop = threading.Event()

    @property
    def compile_counts(self):
        """bucket size -> number of compilations (trace events)."""
        return dict(self._compiles)

    # -- synchronous bucketed execution (also the worker's core) ------------

    def run_batch(self, samples):
        """Pad `samples` to their bucket, run once, return the first
        len(samples) outputs. Deterministic (no queue/thread involved) —
        this is what warmup and the compile-count tests call."""
        import contextlib

        bucket = bucket_for(len(samples), self.ladder)
        x = pad_batch(samples, bucket)
        if not self._jit and bucket not in self._seen_buckets:
            self._seen_buckets.add(bucket)
            self._compiles[bucket] = self._compiles.get(bucket, 0) + 1
        if self._strict and self._warmed:
            from .. import observe

            guard = observe.no_retrace()
        else:
            guard = contextlib.nullcontext()
        with profiler.RecordEvent("serving.batch", cat="serving"), guard:
            out = self._run(x)
        import jax

        n = len(samples)
        return [jax.tree.map(lambda a: np.asarray(a[i]), out)
                for i in range(n)]

    def warmup(self, sample):
        """Compile every rung of the ladder up front (one run per
        bucket shape) so the serving hot path never traces."""
        for bucket in self.ladder:
            self.run_batch([sample] * bucket)
        self._warmed = True
        return dict(self._compiles)

    # -- threaded serving ---------------------------------------------------

    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="serving-batcher", daemon=True)
        self._thread.start()
        return self

    def submit(self, sample, *, timeout=None):
        """Enqueue one sample; returns its `Request` future."""
        if timeout is None:
            timeout = flag("FLAGS_serving_default_timeout_s") or None
        return self.queue.submit(Request(sample, timeout=timeout))

    def __call__(self, sample, *, timeout=None):
        return self.submit(sample, timeout=timeout).result(timeout)

    def close(self, drain=True):
        self.queue.close(drain=drain)
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _collect(self):
        """One batch: block for the first member, then fill up to
        max_batch within max_wait_s."""
        first = self.queue.pop(timeout=0.1)
        if first is None:
            return []
        batch = [first]
        deadline = time.monotonic() + self.max_wait_s
        while len(batch) < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            nxt = self.queue.pop(timeout=remaining)
            if nxt is None:
                break
            batch.append(nxt)
        return batch

    def _loop(self):
        while not self.queue.drained():
            batch = self._collect()
            if not batch:
                continue
            try:
                faults.fault_point("serving.batch", batch)
                outs = self.run_batch([r.payload for r in batch])
            except Exception as e:  # noqa: BLE001 — fail members, live on
                for r in batch:
                    r._fail(e)
                if self.metrics is not None:
                    self.metrics.inc("failed", len(batch))
                continue
            now = time.monotonic()
            for r, out in zip(batch, outs):
                r._complete(out)
                if self.metrics is not None:
                    self.metrics.observe_latency("e2e", now - r.arrival)
            if self.metrics is not None:
                self.metrics.inc("completed", len(batch))
                self.metrics.inc("batches")
                self.metrics.observe_occupancy(len(batch), self.max_batch)
