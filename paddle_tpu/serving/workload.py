"""Open-loop traffic simulator: seeded, replayable arrival traces.

Ref parity: the reference grades its serving stack with fixed-size
closed-loop load (each client waits for its previous answer), which can
never exhibit the phenomena a million-user feed actually produces —
offered load keeps arriving whether or not the fleet keeps up. This
module is the *open-loop* counterpart and the one scenario language
every serving bench shares (bench_serving.py --trace, bench_fleet.py):

- **Scenario** — a JSON-able spec: phases of offered load (the diurnal
  curve / flash crowd / 10x swing), an arrival process per phase
  (``poisson`` exponential interarrivals, ``burst`` on/off clusters,
  ``heavy_tail`` Pareto gaps), a zipfian user population whose per-user
  token prefixes repeat across requests (so the radix PrefixCache sees
  realistic shared-prefix traffic), prompt/output length ranges, and
  weighted priority classes (feeding fleet brownout shedding). With
  ``multi_turn=True`` every base arrival opens a *session*: 2..N turns
  separated by think-time gaps, each turn's prompt a pure extension of
  the previous one — the resume-heavy shape that exercises prefix-
  affinity routing and the SSD KV spill tier (bench_serving.py
  --sessions). With ``tenants={name: {"weight": ..., "priority": ...}}``
  every arrival additionally bills to a tenant drawn from that weighted
  mix (bench_fleet.py --tenants), feeding weighted-fair admission and
  per-tenant SLO accounting.
- **Scenario.trace()** — expands the spec into a concrete arrival list,
  bit-deterministic in the seed: the same JSON replays the exact same
  trace on any machine, which is what lets a chaos re-run be compared
  against its clean baseline request-for-request.
- **replay()** — the open-loop driver: submits each arrival at its
  scheduled time (scaled by ``time_scale``) regardless of completions,
  so queue growth, shedding, brownout, and autoscaling are exercised
  honestly instead of being hidden by client back-pressure.

No wall-clock, hostname, or RNG state leaks into a trace — `Scenario`
round-trips through JSON and `trace()` is a pure function of the spec.
"""

from __future__ import annotations

import json
import time

import numpy as np

__all__ = ["Arrival", "Scenario", "replay"]

#: arrival processes a phase may name
ARRIVAL_PROCESSES = ("poisson", "burst", "heavy_tail")


class Arrival:
    """One scheduled request of a trace (times are seconds from t=0).

    ``session``/``turn`` identify multi-turn traffic: every turn of a
    session shares the session id, and turn k's prompt is a pure
    extension of turn k-1's — the shape that makes prefix-affinity
    routing and the SSD KV spill tier earn their keep. Single-shot
    arrivals carry ``session=None, turn=0``.

    ``tenant`` names the paying tenant the request bills to (None on
    single-tenant scenarios); multi-tenant scenarios draw it zipfian
    from the spec's ``tenants`` mix."""

    __slots__ = ("t", "user", "prompt", "max_new", "priority",
                 "session", "turn", "tenant")

    def __init__(self, t, user, prompt, max_new, priority,
                 session=None, turn=0, tenant=None):
        self.t = float(t)
        self.user = int(user)
        self.prompt = np.asarray(prompt, np.int32)
        self.max_new = int(max_new)
        self.priority = int(priority)
        self.session = None if session is None else int(session)
        self.turn = int(turn)
        self.tenant = None if tenant is None else str(tenant)

    def __repr__(self):
        sess = "" if self.session is None \
            else f", session={self.session}, turn={self.turn}"
        ten = "" if self.tenant is None else f", tenant={self.tenant!r}"
        return (f"Arrival(t={self.t:.4f}, user={self.user}, "
                f"len={self.prompt.size}, max_new={self.max_new}, "
                f"priority={self.priority}{sess}{ten})")


def _normalize_phase(p):
    phase = {
        "duration_s": float(p["duration_s"]),
        "rate_rps": float(p["rate_rps"]),
        "arrival": str(p.get("arrival", "poisson")),
        "burst_n": int(p.get("burst_n", 8)),
        "pareto_alpha": float(p.get("pareto_alpha", 1.8)),
    }
    if phase["arrival"] not in ARRIVAL_PROCESSES:
        raise ValueError(
            f"unknown arrival process {phase['arrival']!r}; "
            f"one of {ARRIVAL_PROCESSES}")
    if phase["duration_s"] <= 0 or phase["rate_rps"] <= 0:
        raise ValueError(f"phase needs positive duration and rate: {p}")
    if phase["pareto_alpha"] <= 1.0:
        raise ValueError("pareto_alpha must be > 1 (finite mean)")
    return phase


class Scenario:
    """Replayable workload spec; `trace()` is deterministic in `seed`.

    ``phases`` is the offered-load curve: each entry is a dict with
    ``duration_s``, ``rate_rps``, and optionally ``arrival`` (one of
    ``poisson`` / ``burst`` / ``heavy_tail``), ``burst_n`` (requests
    per cluster for ``burst``), ``pareto_alpha`` (tail index for
    ``heavy_tail``; must be > 1 so the mean gap exists). Users are
    drawn zipfian over ``n_users``; each user carries a persistent
    ``user_prefix_len``-token prefix prepended to every one of its
    prompts, so hot users produce real prefix-cache traffic.
    ``priorities`` is a list of ``(priority, weight)`` pairs.
    """

    def __init__(self, name="scenario", seed=0, vocab=97, n_users=64,
                 zipf_s=1.2, user_prefix_len=8, prompt_len=(4, 12),
                 max_new=(4, 8), priorities=((0, 0.7), (1, 0.2), (2, 0.1)),
                 phases=None, multi_turn=False, session_turns=(2, 4),
                 think_time=(0.05, 0.2), tenants=None):
        self.name = str(name)
        self.seed = int(seed)
        self.vocab = int(vocab)
        self.n_users = int(n_users)
        self.zipf_s = float(zipf_s)
        self.user_prefix_len = int(user_prefix_len)
        self.prompt_len = (int(prompt_len[0]), int(prompt_len[1]))
        self.max_new = (int(max_new[0]), int(max_new[1]))
        self.priorities = [(int(p), float(w)) for p, w in priorities]
        if phases is None:
            phases = [{"duration_s": 10.0, "rate_rps": 4.0}]
        self.phases = [_normalize_phase(p) for p in phases]
        if self.vocab < 2 or self.n_users < 1:
            raise ValueError("vocab must be >= 2 and n_users >= 1")
        if self.zipf_s <= 1.0:
            raise ValueError("zipf_s must be > 1")
        if not self.priorities or \
                sum(w for _, w in self.priorities) <= 0:
            raise ValueError("priorities need positive total weight")
        if self.prompt_len[0] < 1 or self.prompt_len[1] < self.prompt_len[0]:
            raise ValueError(f"bad prompt_len range {self.prompt_len}")
        if self.max_new[0] < 1 or self.max_new[1] < self.max_new[0]:
            raise ValueError(f"bad max_new range {self.max_new}")
        # multi-turn sessions (ISSUE 18): each base arrival opens a
        # session of `session_turns` turns separated by `think_time`
        # gaps; every turn's prompt extends the previous turn's with a
        # fresh tail, so the radix caches (and the spill tier) see
        # genuine resume traffic at the same zipfian popularity
        self.multi_turn = bool(multi_turn)
        self.session_turns = (int(session_turns[0]),
                              int(session_turns[1]))
        self.think_time = (float(think_time[0]), float(think_time[1]))
        if self.session_turns[0] < 1 or \
                self.session_turns[1] < self.session_turns[0]:
            raise ValueError(
                f"bad session_turns range {self.session_turns}")
        if self.think_time[0] < 0 or \
                self.think_time[1] < self.think_time[0]:
            raise ValueError(f"bad think_time range {self.think_time}")
        # multi-tenant mix (ISSUE 20): name -> {"weight": draw weight,
        # "priority": optional override of the drawn priority class}.
        # None keeps the trace single-tenant AND bit-identical to every
        # pre-tenancy trace (the tenant draw consumes RNG only when a
        # mix is configured).
        self.tenants = None
        if tenants:
            self.tenants = {}
            for tname in sorted(tenants):
                spec = dict(tenants[tname])
                spec["weight"] = float(spec.get("weight", 1.0))
                if spec["weight"] <= 0:
                    raise ValueError(
                        f"tenant {tname!r} needs positive weight")
                if "priority" in spec:
                    spec["priority"] = int(spec["priority"])
                self.tenants[str(tname)] = spec

    # -- spec (de)serialization ---------------------------------------------

    def to_dict(self):
        d = {
            "name": self.name, "seed": self.seed, "vocab": self.vocab,
            "n_users": self.n_users, "zipf_s": self.zipf_s,
            "user_prefix_len": self.user_prefix_len,
            "prompt_len": list(self.prompt_len),
            "max_new": list(self.max_new),
            "priorities": [list(pw) for pw in self.priorities],
            "phases": [dict(p) for p in self.phases],
            "multi_turn": self.multi_turn,
            "session_turns": list(self.session_turns),
            "think_time": list(self.think_time),
        }
        if self.tenants is not None:
            d["tenants"] = {t: dict(s) for t, s in self.tenants.items()}
        return d

    def to_json(self, path=None, **kw):
        text = json.dumps(self.to_dict(), sort_keys=True, **kw)
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text

    @classmethod
    def from_dict(cls, d):
        return cls(**d)

    @classmethod
    def from_json(cls, path_or_text):
        text = path_or_text
        if "{" not in text:           # a path, not inline JSON
            with open(path_or_text) as f:
                text = f.read()
        return cls.from_dict(json.loads(text))

    @classmethod
    def swing(cls, low_rps=2.0, high_rps=20.0, low_s=3.0, high_s=4.0,
              arrival="poisson", **kw):
        """The canonical traffic-swing scenario: low -> high -> low
        (default 10x — the flash-crowd shape bench_fleet.py sweeps)."""
        phases = [
            {"duration_s": low_s, "rate_rps": low_rps, "arrival": arrival},
            {"duration_s": high_s, "rate_rps": high_rps,
             "arrival": arrival},
            {"duration_s": low_s, "rate_rps": low_rps, "arrival": arrival},
        ]
        kw.setdefault("name", f"swing{high_rps / low_rps:g}x")
        return cls(phases=phases, **kw)

    @classmethod
    def diurnal(cls, base_rps=2.0, peak_rps=10.0, period_s=12.0,
                n_phases=6, arrival="poisson", **kw):
        """A sinusoidal day: `n_phases` slices of one period between
        base and peak rate (piecewise-constant diurnal curve)."""
        phases = []
        for i in range(int(n_phases)):
            frac = 0.5 - 0.5 * np.cos(2 * np.pi * (i + 0.5) / n_phases)
            phases.append({
                "duration_s": period_s / n_phases,
                "rate_rps": base_rps + (peak_rps - base_rps) * float(frac),
                "arrival": arrival,
            })
        kw.setdefault("name", "diurnal")
        return cls(phases=phases, **kw)

    # -- trace generation ---------------------------------------------------

    @property
    def duration_s(self):
        return sum(p["duration_s"] for p in self.phases)

    def user_prefix(self, user):
        """The persistent token prefix of one user — a deterministic
        function of (seed, user), NOT of the trace RNG stream, so the
        same user shares the same prefix across scenarios and phases."""
        if self.user_prefix_len == 0:
            return np.zeros((0,), np.int32)
        rng = np.random.RandomState(
            (self.seed * 1000003 + user * 7919) % (2 ** 31 - 1))
        return rng.randint(0, self.vocab,
                           (self.user_prefix_len,)).astype(np.int32)

    def _gaps(self, rng, phase):
        """Generator of interarrival gaps for one phase (mean 1/rate
        for every process — the processes differ in variance/shape,
        not offered load)."""
        rate = phase["rate_rps"]
        mean = 1.0 / rate
        kind = phase["arrival"]
        if kind == "poisson":
            while True:
                yield float(rng.exponential(mean))
        elif kind == "heavy_tail":
            # Pareto with minimum xm and tail alpha has mean
            # xm * a / (a - 1); solve xm for the target mean gap
            a = phase["pareto_alpha"]
            xm = mean * (a - 1.0) / a
            while True:
                yield float(xm * (1.0 + rng.pareto(a)))
        else:  # burst: clusters of burst_n back-to-back arrivals
            n = max(phase["burst_n"], 1)
            intra = mean / 50.0
            # inter-burst gap keeps the phase's average rate: each
            # cluster spends (n-1)*intra inside itself
            inter = max(n * mean - (n - 1) * intra, intra)
            i = 0
            while True:
                yield intra if i % n else float(rng.exponential(inter))
                i += 1

    def trace(self):
        """Expand the spec into the concrete arrival list (sorted by
        time). Bit-deterministic: one RandomState seeded on `seed`
        consumed in a fixed order."""
        rng = np.random.RandomState(self.seed)
        ranks = np.arange(1, self.n_users + 1, dtype=np.float64)
        zipf_p = ranks ** -self.zipf_s
        zipf_p /= zipf_p.sum()
        prio_vals = np.asarray([p for p, _ in self.priorities])
        prio_w = np.asarray([w for _, w in self.priorities], np.float64)
        prio_w /= prio_w.sum()
        tnames, tw = None, None
        if self.tenants is not None:
            tnames = list(self.tenants)          # sorted at construction
            tw = np.asarray([self.tenants[t]["weight"] for t in tnames],
                            np.float64)
            tw /= tw.sum()
        prefixes = {}
        arrivals = []
        t0, session_id = 0.0, 0
        for phase in self.phases:
            end = t0 + phase["duration_s"]
            gaps = self._gaps(rng, phase)
            t = t0
            while True:
                t += next(gaps)
                if t >= end:
                    break
                user = int(rng.choice(self.n_users, p=zipf_p))
                if user not in prefixes:
                    prefixes[user] = self.user_prefix(user)
                lo, hi = self.prompt_len
                tail = rng.randint(0, self.vocab,
                                   (int(rng.randint(lo, hi + 1)),))
                lo, hi = self.max_new
                max_new = int(rng.randint(lo, hi + 1))
                priority = int(prio_vals[rng.choice(len(prio_vals),
                                                    p=prio_w)])
                tenant = None
                if tnames is not None:
                    # the tenant draw consumes RNG only in multi-tenant
                    # mode, so legacy seeded traces stay bit-identical
                    tenant = tnames[int(rng.choice(len(tnames), p=tw))]
                    tprio = self.tenants[tenant].get("priority")
                    if tprio is not None:
                        priority = int(tprio)
                prompt = np.concatenate(
                    [prefixes[user], tail.astype(np.int32)])
                if not self.multi_turn:
                    arrivals.append(Arrival(t, user, prompt, max_new,
                                            priority, tenant=tenant))
                    continue
                # multi-turn: this arrival opens a session; turn k's
                # prompt extends turn k-1's with a fresh tail after a
                # think-time gap (all draws from the same stream, so
                # the trace stays bit-deterministic in the seed)
                sid, session_id = session_id, session_id + 1
                lo, hi = self.session_turns
                n_turns = int(rng.randint(lo, hi + 1))
                tt = t
                for turn in range(n_turns):
                    if turn:
                        tlo, thi = self.think_time
                        tt += float(rng.uniform(tlo, thi))
                        lo, hi = self.prompt_len
                        ext = rng.randint(
                            0, self.vocab,
                            (int(rng.randint(lo, hi + 1)),))
                        prompt = np.concatenate(
                            [prompt, ext.astype(np.int32)])
                        lo, hi = self.max_new
                        max_new = int(rng.randint(lo, hi + 1))
                    arrivals.append(Arrival(tt, user, prompt, max_new,
                                            priority, session=sid,
                                            turn=turn, tenant=tenant))
            t0 = end
        if self.multi_turn:
            # session turns overrun their phase slot; restore global
            # time order (stable sort keeps the per-time-tie draw order)
            arrivals.sort(key=lambda a: a.t)
        return arrivals


def replay(submit, trace, *, time_scale=1.0, stop=None):
    """Open-loop replay of a trace against a serving front.

    ``submit(arrival)`` places one request and returns its future (any
    object; a synchronous raise is recorded as the submit error — e.g.
    a brownout shed). Arrivals are issued at ``arrival.t * time_scale``
    seconds after the replay starts, NEVER waiting on completions —
    that open loop is the point. Returns one record per arrival:
    ``{"arrival", "t_submit", "future", "error"}`` with ``t_submit``
    seconds from replay start. ``stop`` (an optional callable) aborts
    the replay early when it returns True.
    """
    t0 = time.monotonic()
    records = []
    for arrival in trace:
        if stop is not None and stop():
            break
        delay = arrival.t * time_scale - (time.monotonic() - t0)
        if delay > 0:
            time.sleep(delay)
        rec = {"arrival": arrival, "t_submit": time.monotonic() - t0,
               "future": None, "error": None}
        try:
            rec["future"] = submit(arrival)
        except Exception as e:  # noqa: BLE001 — shed/closed are outcomes
            rec["error"] = e
        records.append(rec)
    return records
