"""Continuous-batching decode engine over a block-paged KV cache.

Orca-style iteration-level scheduling (PAPERS.md: continuous batching)
mapped onto XLA's compile-per-shape reality, with vLLM-style paged KV
allocation and SGLang-style prefix sharing:

- ONE physical block pool per layer, shape
  ``[num_blocks, nh, block_size, hd]``, plus a per-slot block table
  ``[max_slots, blocks_per_slot]``. A request holds only the blocks its
  actual length needs, so pool HBM caps *total tokens in flight*, not
  ``max_slots * max_seq`` — short requests no longer pay for long ones
  and concurrency scales with the pool, not the worst case.
- ONE compiled step. Every iteration runs the whole pool through a
  single jitted function over a fixed ``[max_slots, chunk]`` token
  matrix: decoding slots occupy one column, *prefilling* slots up to
  ``chunk`` prompt columns (chunked prefill), padding routes to the
  reserved null block. The old per-rung prefill ladder — one compile
  per padded prompt length, each stalling the decode loop — is gone;
  the decode program compiles exactly once, certified by the trace-time
  compile counters and `observe.no_retrace()`.
- Prefix sharing: finished sequences index their fully written blocks
  in a radix `PrefixCache` keyed on cumulative token-prefix hashes.
  A new request reuses every matching block physically (refcounted),
  prefills only the tail, and a divergence *inside* a cached block
  triggers copy-on-write: the block is copied once (second compiled
  helper, also traced exactly once) and the divergent rows overwritten.
- Admission is by free blocks, not free slots: a request needing more
  blocks than the whole pool sheds with the retriable 429
  `CapacityExhaustedError`; one that merely has to wait for in-flight
  frees stays queued and joins at a later step boundary.

Eviction on EOS / max_new_tokens / deadline / cancel frees the slot and
releases its block references at the next step boundary. Stale KV from
a previous occupant of a recycled block is harmless: the per-row causal
mask only admits keys <= the request's own position, all of which its
own prefill/decode overwrote first (same argument covers chunked-
prefill padding rows, whole-block CoW copies, and the rejected-suffix
rows of speculative verify steps — see below).

Fast decode (ISSUE 16) rides the same one-trace contract:

- Speculative decoding (``spec_len`` / FLAGS_serving_spec_len = k > 0):
  each decode round proposes up to k tokens per slot from a draft model
  (self-draft when none is given) and verifies them IN the unified step
  — the slot stages ``[next, d_1..d_k]`` across the chunk columns it
  already owns, and the step additionally projects the first k+1
  columns to logits so the host can run Leviathan-style accept /
  residual-resample per slot. Accepted tokens were already scattered
  into the paged pool in bulk by that same step; a rejected suffix
  leaves garbage KV above the committed position, which the next
  round's staging always overwrites before any row can attend it (the
  per-row causal mask covers the degraded-round gap). The draft model
  runs its own compiled micro-step over separate pools sharing THIS
  engine's block tables; its cache trails the committed sequence
  (per-slot ``dfill``) and self-heals by catch-up, so a faulted draft
  phase simply degrades the round to plain decode. Compile counters
  certify ``{decode: 1, draft: 1, cow: 1}`` for life; spec-disabled
  engines build no draft trace at all and keep ``{decode: 1, cow: 1}``.
  Greedy speculative decode is bitwise token-identical to plain greedy:
  rejection hands the verify logits to the normal `_pick` path instead
  of eagerly committing, so every emitted token is an argmax of the
  same-valued logits row the plain engine would have produced.
- Int8 weight path (``quantize`` / FLAGS_serving_quantize): weights are
  frozen per-tensor to int8 + `@scale` companions
  (quantization.quantize_state_int8) and cross the jit boundary as
  int8 — the HBM win. The trace dequantizes in-body via the one
  canonical formula (ops.quant_ops.dequant_int8) and routes the tied
  LM head through the `dequant_matmul` epilogue kernel. Engines handed
  a pre-frozen values dict (rollout artifacts) adopt it as-is.

Durable sessions (ISSUE 18): when ``FLAGS_serving_kv_spill_dir`` names
a directory, the engine attaches the process-shared `KVSpillStore`
(kvstore.py) as the radix cache's spill hook — a cold block evicted
from the cache persists its KV rows to SSD *before* the allocator frees
it, and a later request whose token prefix extends a spilled record
restores the blocks through `_maybe_restore` (the same all-or-nothing
alloc→scatter→insert staging as KV adoption). A torn, bit-rotted, or
generation-fenced record degrades to re-prefill, never to wrong tokens;
the session "handle" is the token prefix itself — content-addressed, so
a session resumes on ANY replica sharing the spill directory, including
after its original replica died between turns.

Fault sites: ``serving.step`` fires once per decode step (a `raise`
action fails every in-flight request deterministically while the engine
stays up); ``serving.alloc_block`` on every physical block allocation
(deterministic pool exhaustion); ``serving.cow_split`` before every
copy-on-write block copy; ``serving.draft`` before each speculative
draft phase (raise = degrade that round to plain decode, slots survive
with no lost or duplicated tokens); ``serving.verify`` before each
speculative verify dispatch (raise = step error, fails in-flight
requests like serving.step); ``serving.dequant`` once per step on an
int8-frozen engine; ``serving.kv_restore`` before each spilled-block
restore (raise = restore abort, leak-free, the request re-prefills);
``serving.adapter_swap`` before each adapter-bank hot-swap mutates
anything (raise = all-or-nothing abort, the OLD adapter bank keeps
serving bitwise).
Supervised (fleet-owned) engines additionally
fire ``serving.replica_heartbeat`` every loop iteration and
``serving.replica_step`` before each decode step, both tagged with the
replica name — the fleet chaos sites (see framework/faults.py).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from .. import observe, profiler
from ..core.tensor import Tensor
from ..engine import functional_apply, state_values
from ..framework import faults
from ..framework.flags import flag
from . import kvstore
from .metrics import ServingMetrics
from .paging import NULL_BLOCK, BlockAllocator, PoolExhausted, PrefixCache
from .queueing import (
    AdmissionQueue, CapacityExhaustedError, DeadlineExceededError, Request,
    RequestCancelled,
)

__all__ = ["SlotEngine", "speculative_accept"]


def speculative_accept(p_list, q_list, proposals, rng):
    """Leviathan-style rejection sampling over one drafted chain.

    `p_list[j]` / `q_list[j]` are the (identically warped) target and
    draft probability vectors at the position of `proposals[j]`. Accept
    d_j while ``u_j < min(1, p_j(d_j) / q_j(d_j))``; on first rejection
    resample from the residual ``normalize(max(p - q, 0))``. Returns
    ``(accepted_count, resampled_token_or_None)`` — None means every
    proposal survived (the caller then samples the bonus token from the
    verify step's final logits row, completing the k+1-per-round
    upside). The emitted-token distribution equals sampling from p
    directly — certified by the histogram test in
    tests/test_serving_spec.py. Pure host-side numpy so the invariant
    is testable without an engine."""
    for j, d in enumerate(proposals):
        p, q = p_list[j], q_list[j]
        if rng.random_sample() < min(1.0, float(p[d]) / max(float(q[d]),
                                                            1e-20)):
            continue
        residual = np.maximum(p - q, 0.0)
        tot = residual.sum()
        if tot <= 0.0:
            # p == q exactly and still rejected (u landed on the
            # boundary): any residual draw is p-distributed; use p
            residual, tot = p, p.sum()
        return j, int(rng.choice(residual.size, p=residual / tot))
    return len(proposals), None


class _Slot:
    """One in-flight request's decode state (host side)."""

    def __init__(self, req, ids, fill, blocks):
        self.req = req
        self.prompt = np.asarray(ids, np.int32)
        self.prompt_len = int(self.prompt.size)
        self.tokens = [int(t) for t in ids]  # full sequence so far
        self.fill = fill        # prompt positions already in the cache
        self.blocks = blocks    # physical block ids, table order
        self.state = "prefill" if fill < self.prompt_len else "decode"
        self.advance = 0        # positions this step will write
        self.produced = 0
        self.next_logits = None  # np [V] feeding the next pick
        self.rng = None
        if req.gen.get("do_sample"):
            self.rng = np.random.RandomState(req.gen.get("seed", 0))
        # speculative-decoding state (unused when spec_len == 0):
        # the draft cache trails the committed sequence — positions
        # [0, dfill) hold draft KV for tokens[0:dfill]; `fed` logs every
        # token fed to it this round (committed catch-up AND proposals)
        # so dfill advances exactly as far as the commit agreed with
        # what was fed, whatever the round's outcome (accept, reject,
        # degrade, mid-phase fault)
        self.dfill = 0
        self.fed: list = []
        self.drafted: list = []   # this round's proposals d_1..d_s
        self.qdists: list = []    # warped draft dists per proposal
        self.spec_staged: list = []  # proposals actually staged
        # a residual-resampled token is appended at commit but its KV is
        # not yet written; the next consume must feed it, not re-pick
        self.unfed = False


class SlotEngine:
    """Continuous-batching greedy/sampling decode over a GPT model.

    `model` is a `GPTForPretraining` (eval mode is forced). Requests
    carry `max_new_tokens`, optional `eos_token_id`, and sampling
    params; results are the full [prompt + generated] int32 id array,
    token-identical to `generate()` / full re-forwarding for greedy.

    Ownership contract (same as the reference's one-predictor-per-
    thread rule): while the engine is serving, it owns the model —
    tracing temporarily swaps the model's parameter handles
    (engine.functional_apply), so run eager forwards on it only while
    the engine is idle, or on a separate instance.
    """

    def __init__(self, model, *, max_slots=None, max_seq_len=None,
                 block_size=None, num_blocks=None, prefill_chunk=None,
                 prefix_cache=None, cache_dtype=None, metrics=None,
                 queue=None, strict_shapes=False, name=None,
                 supervised=False, values=None, weight_version=0,
                 draft_model=None, spec_len=None, quantize=None,
                 w8a8=None, mesh=None, spill_dir=None,
                 max_adapters=None, lora_rank=None):
        import jax
        import jax.numpy as jnp

        from ..quantization import (
            SCALE_SUFFIX, dequantize_state, is_quantized_state,
            quantize_state_int8,
        )
        from .sharding import ShardingPlan, mesh_spec_of, resolve_mesh

        model.eval()
        self.model = model
        self.name = name or "engine"
        # mesh-sharded serving (ISSUE 17): None consults
        # FLAGS_serving_mesh; a 'dpD.mpM' string builds the 2-axis
        # serving mesh. Weights/pools are placed by the partition rules
        # in serving/sharding.py and the ONE compiled step carries
        # explicit in/out shardings — still exactly one trace per mesh
        # shape for engine life.
        self.mesh = resolve_mesh(mesh)
        self.mesh_spec = mesh_spec_of(self.mesh)
        self._plan = ShardingPlan(self.mesh) \
            if self.mesh is not None else None
        self.supervised = supervised
        self.last_beat = time.monotonic()
        self.heartbeats = 0
        self._abort_error = None
        self.max_slots = max_slots or flag("FLAGS_serving_max_batch")
        self.max_seq_len = min(max_seq_len or model.config.max_seq_len,
                               model.config.max_seq_len)
        self.block_size = block_size or flag("FLAGS_serving_kv_block_size")
        self.blocks_per_slot = -(-self.max_seq_len // self.block_size)
        if num_blocks is None:
            num_blocks = flag("FLAGS_serving_kv_blocks")
        if not num_blocks:   # auto: dense-equivalent worst case + null
            num_blocks = self.max_slots * self.blocks_per_slot + 1
        if num_blocks < 2:
            raise ValueError(f"num_blocks must be >= 2, got {num_blocks}")
        self.num_blocks = num_blocks
        self.prefill_chunk = min(
            prefill_chunk or flag("FLAGS_serving_prefill_chunk"),
            self.max_seq_len)
        self.spec_len = flag("FLAGS_serving_spec_len") \
            if spec_len is None else int(spec_len)
        if self.spec_len:
            # verify needs k+1 chunk columns per slot; the draft trace
            # is a separate, narrower program of the same width
            self.prefill_chunk = max(self.prefill_chunk, self.spec_len + 1)
            self.prefill_chunk = min(self.prefill_chunk, self.max_seq_len)
            if self.spec_len + 1 > self.max_seq_len:
                raise ValueError(
                    f"spec_len {self.spec_len} needs {self.spec_len + 1} "
                    f"chunk columns but max_seq_len is {self.max_seq_len}")
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.queue = queue if queue is not None else AdmissionQueue(
            flag("FLAGS_serving_queue_cap"), metrics=self.metrics)
        # weights are a jit ARGUMENT of the compiled step, not a trace
        # constant: an engine rebuilt with same-shape `values` from a
        # different weight version re-traces nothing beyond its own
        # fresh compile-once warmup
        self._values = dict(values) if values is not None \
            else dict(state_values(model))
        self.weight_version = int(weight_version)
        if quantize is None:
            quantize = flag("FLAGS_serving_quantize")
        if is_quantized_state(self._values):
            self.quantized = True   # pre-frozen artifact (e.g. rollout)
        elif quantize:
            self._values = quantize_state_int8(self._values)
            self.quantized = True
        else:
            self.quantized = False
        self._dequantize_state = dequantize_state
        # tied-embedding LM head on the dequant-matmul epilogue: find
        # the int8 table + scale once; fall back to the operand-dequant
        # head when untied or the table didn't freeze
        self._head_key = None
        if self.quantized:
            self.metrics.set_gauge("dequant_path", 1.0)
            for k in self._values:
                if k.endswith("word_embeddings.weight") and \
                        (k + SCALE_SUFFIX) in self._values and \
                        getattr(model.config, "tie_word_embeddings", False):
                    self._head_key = (k, k + SCALE_SUFFIX)
                    break
        # w8a8 (ISSUE 19): extend the weights-only int8 tied head to
        # activation quant — the decode matmul's input rows quantize
        # in-trace against a per-tensor scale calibrated over warmup +
        # the first few real steps, then frozen. The scale is a runtime
        # argument of the SAME compiled step (a lax.cond picks the
        # weights-only branch while it is 0), so compile counters stay
        # {decode: 1, cow: 1} and a faulted step degrades leak-free.
        if w8a8 is None:
            w8a8 = flag("FLAGS_serving_w8a8")
        self.w8a8 = bool(w8a8) and self._head_key is not None
        self._act_scale = jnp.zeros((), jnp.float32)
        self._act_calib = 0
        self._act_frozen = False
        self._w8a8_degraded = False
        if self.w8a8:
            self.metrics.set_gauge("w8a8_path", 1.0)
        cfg = model.config
        # batched LoRA adapters (ISSUE 20): stacked [n, r, H] / [n, V, r]
        # A/B banks ride the compiled step as swappable jit ARGUMENTS;
        # each slot carries an adapter_id (row 0 = base model, all-zero)
        # and the head's logits pick up a gathered low-rank delta inside
        # the ONE trace — compile counters stay {decode: 1, cow: 1} and
        # banks hot-swap with zero retraces (fixed shapes)
        if max_adapters is None:
            max_adapters = flag("FLAGS_serving_max_adapters")
        self.max_adapters = int(max_adapters or 0)
        if lora_rank is None:
            lora_rank = flag("FLAGS_serving_lora_rank")
        self.lora_rank = int(lora_rank)
        self.adapter_version = 0
        if self.max_adapters:
            if self.lora_rank < 1:
                raise ValueError(
                    f"lora_rank must be >= 1, got {self.lora_rank}")
            self._lora_a = jnp.zeros(
                (self.max_adapters, self.lora_rank, cfg.hidden_size),
                jnp.float32)
            self._lora_b = jnp.zeros(
                (self.max_adapters, cfg.vocab_size, self.lora_rank),
                jnp.float32)
            self.metrics.set_gauge("max_adapters",
                                   float(self.max_adapters))
        else:
            self._lora_a = None
            self._lora_b = None
        hd = cfg.hidden_size // cfg.num_heads
        dtype = cache_dtype or jnp.float32
        shape = (self.num_blocks, cfg.num_heads, self.block_size, hd)
        self._ks = [jnp.zeros(shape, dtype) for _ in range(cfg.num_layers)]
        self._vs = [jnp.zeros(shape, dtype) for _ in range(cfg.num_layers)]
        if self._plan is not None:
            # weights by partition rule, KV pools over the head axis
            # (replicated when heads don't divide mp); block tables and
            # the allocator stay host-side numpy — replica-global
            self._values = self._plan.place_values(self._values)
            pool_sh = self._plan.pool_sharding(cfg.num_heads)
            self._ks = [jax.device_put(k, pool_sh) for k in self._ks]
            self._vs = [jax.device_put(v, pool_sh) for v in self._vs]
            self.metrics.set_gauge("mesh_devices", float(self.mesh.size))
            self.metrics.note_mesh(self.mesh_spec, int(self.mesh.size))
        self.kv_pool_bytes = int(
            2 * cfg.num_layers * np.prod(shape) * jnp.zeros((), dtype).nbytes)
        self._alloc = BlockAllocator(self.num_blocks)
        if prefix_cache is None:
            prefix_cache = flag("FLAGS_serving_prefix_cache")
        self._cache = PrefixCache(self._alloc, self.block_size) \
            if prefix_cache else None
        # persistent KV spill tier (ISSUE 18): one shared store per
        # spill directory, so every replica of the process spills into
        # — and can resume from — the same tier. None = disabled.
        self.spill_store = kvstore.open_spill_store(
            spill_dir, metrics=self.metrics) \
            if self._cache is not None else None
        if self.spill_store is not None:
            self._cache.spill_hook = self._spill_block
        # per-engine prefix stats (the shared ServingMetrics registry
        # aggregates fleet-wide; per-replica hit rates need local ones)
        self.prefix_lookups = 0
        self.prefix_hit_tokens = 0
        self.prefix_prompt_tokens = 0
        self._pos = np.zeros((self.max_slots,), np.int32)
        # per-slot adapter row (0 = base model); a jit argument of the
        # one compiled step, so changing it never retraces
        self._aid = np.zeros((self.max_slots,), np.int32)
        self._bt = np.full((self.max_slots, self.blocks_per_slot),
                           NULL_BLOCK, np.int32)
        self._slots: list = [None] * self.max_slots
        self._free = list(range(self.max_slots))
        self._compiles: dict = {}
        self._strict = strict_shapes
        self._warmed = False
        self._abort = threading.Event()
        self._thread = None
        # KV adoptions (prefill->decode migration) land at step
        # boundaries: callers enqueue here and the serve loop applies,
        # so pool rebinds never race the compiled step's own updates
        self._migrate_q: list = []
        self._migrate_lock = threading.Lock()
        # adapter-bank hot-swaps land at step boundaries too (same
        # enqueue/drain contract as KV adoption), so a swap never races
        # the compiled step's reads
        self._adapter_q: list = []

        def _count(key):
            self._compiles[key] = self._compiles.get(key, 0) + 1

        def _head(m, values, hrows, act_scale=None):
            """Project hidden rows (.., H) to f32 logits (.., V): the
            dequant-matmul epilogue against the int8 tied table when
            frozen, the model's own head otherwise. With `act_scale`
            (w8a8) the rows also quantize to int8 — a lax.cond inside
            the one compiled step falls back to the weights-only
            epilogue while the scale is 0 (calibration, fault
            degrade)."""
            if self._head_key is not None:
                from ..ops.quant_ops import dequant_matmul

                qk, sk = self._head_key
                if act_scale is None:
                    return dequant_matmul(hrows, values[qk], values[sk])
                from ..ops import lowp as _lowp

                def quant_head(h):
                    # int8 x int8 with int32 accumulation; the frozen
                    # table is [V, H], contraction-ready as its
                    # transpose (XLA fuses the relayout into the read)
                    return _lowp.w8a8_matmul(
                        h, values[qk].T, values[sk], act_scale)

                def plain_head(h):
                    return dequant_matmul(h, values[qk], values[sk])

                from jax import lax
                return lax.cond(act_scale > 0.0, quant_head, plain_head,
                                hrows)
            squeeze = hrows.ndim == 2
            if squeeze:
                hrows = hrows[:, None, :]
            out = m.logits(Tensor(hrows))
            out = out._value if isinstance(out, Tensor) else out
            return (out[:, 0, :] if squeeze else out).astype(jnp.float32)

        def step_fn(values, tok, pos, nvalid, tables, ks, vs,
                    act_scale=None, aid=None, la=None, lb=None):
            # trace-time only: the compile counter + retrace registry
            _count("decode")
            observe.record_compile(
                "serving.step",
                signature=observe.signature_of(tok, pos, tables))
            caches = [(k, v, (pos, tables)) for k, v in zip(ks, vs)]
            # clamp padding rows' position ids into the embedding table;
            # their KV writes route to the null block regardless
            posmat = jnp.minimum(
                pos[:, None] + jnp.arange(tok.shape[1]),
                self.max_seq_len - 1)
            # int8-frozen weights dequantize IN-trace (one canonical
            # formula; XLA fuses it into operand reads) — except the
            # head, which _head routes through the epilogue kernel
            fvals = self._dequantize_state(values) if self.quantized \
                else values

            def run(m):
                h, new_caches = m.gpt(Tensor(tok), Tensor(posmat),
                                      caches=caches)
                hv = h._value if isinstance(h, Tensor) else h
                # only each slot's last valid position feeds sampling:
                # skip the full-vocab projection of the rest of the chunk
                last = hv[jnp.arange(hv.shape[0]), nvalid - 1]
                lv = _head(m, values, last, act_scale)
                # w8a8 calibration: this step's head-input abs-max
                # rides the outputs so the host can fold it into the
                # frozen activation scale without an extra device pass
                # (taken BEFORE any adapter delta — the scale calibrates
                # the shared trunk, not one tenant's adapter)
                amax = jnp.max(jnp.abs(last.astype(jnp.float32))) \
                    if act_scale is not None else None
                if la is not None:
                    # batched LoRA head delta: gather each slot's
                    # adapter row by index inside the trace; row 0 is
                    # all-zero so base-model slots add exactly 0.0
                    from ..nlp.transformers.gpt import lora_logits_delta

                    lv = lv + lora_logits_delta(last, aid, la, lb)
                if self.spec_len:
                    # speculative verify: the first k+1 chunk columns
                    # ([next, d_1..d_k]) all feed accept/reject
                    sv = _head(m, values, hv[:, :self.spec_len + 1],
                               act_scale)
                    if la is not None:
                        sv = sv + lora_logits_delta(
                            hv[:, :self.spec_len + 1], aid, la, lb)
                    return (lv, sv, amax), new_caches
                return (lv, lv, amax), new_caches

            (lv, sv, amax), new_caches = functional_apply(
                self.model, fvals, run, mesh=self.mesh)
            out_ks = [c[0] for c in new_caches]
            out_vs = [c[1] for c in new_caches]
            if self.spec_len:
                if act_scale is not None:
                    return lv, sv, amax, out_ks, out_vs
                return lv, sv, out_ks, out_vs
            if act_scale is not None:
                return lv, amax, out_ks, out_vs
            return lv, out_ks, out_vs

        def cow_fn(ks, vs, src, dst):
            from jax import lax

            _count("cow")
            observe.record_compile("serving.cow", signature="(block, block)")

            def copy(pool):
                blk = lax.dynamic_slice_in_dim(pool, src, 1, axis=0)
                return lax.dynamic_update_slice_in_dim(pool, blk, dst,
                                                       axis=0)

            return [copy(k) for k in ks], [copy(v) for v in vs]

        if self._plan is not None:
            # explicit in/out shardings: host-staged step inputs are
            # replicated, weights follow the partition rules, pools keep
            # their head sharding through the step (GSPMD then has no
            # freedom to reshard the hot loop between steps)
            rep = self._plan.replicated()
            vsh = self._plan.values_shardings(self._values)
            pools = [self._plan.pool_sharding(cfg.num_heads)] \
                * cfg.num_layers
            if self.w8a8:
                step_out = (rep, rep, rep, pools, pools) if self.spec_len \
                    else (rep, rep, pools, pools)
                step_in = (vsh, rep, rep, rep, rep, pools, pools, rep)
            else:
                step_out = (rep, rep, pools, pools) if self.spec_len \
                    else (rep, pools, pools)
                step_in = (vsh, rep, rep, rep, rep, pools, pools)
                if self.max_adapters:
                    # explicit act_scale=None slot (an empty pytree:
                    # the leaf sharding applies to zero leaves)
                    step_in = step_in + (rep,)
            if self.max_adapters:
                # per-slot adapter ids + replicated A/B banks
                step_in = step_in + (rep, rep, rep)
            self._decode = jax.jit(
                step_fn,
                in_shardings=step_in,
                out_shardings=step_out)
            self._cow = jax.jit(
                cow_fn,
                in_shardings=(pools, pools, rep, rep),
                out_shardings=(pools, pools))
        else:
            self._decode = jax.jit(step_fn)
            self._cow = jax.jit(cow_fn)

        # -- speculative draft trace (only when spec is on: a disabled
        # engine keeps compile counters {decode: 1, cow: 1} exactly) --
        if self.spec_len:
            self.draft_model = draft_model if draft_model is not None \
                else model
            self.draft_model.eval()
            dcfg = self.draft_model.config
            if dcfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {dcfg.vocab_size} != target vocab "
                    f"{cfg.vocab_size}")
            if dcfg.max_seq_len < self.max_seq_len:
                raise ValueError(
                    f"draft max_seq_len {dcfg.max_seq_len} < engine "
                    f"max_seq_len {self.max_seq_len}")
            # draft weights stay float (the draft is the small model);
            # separate per-layer pools share THIS engine's block tables
            # and allocator, so one block id addresses both caches
            self._dvalues = dict(state_values(self.draft_model)) \
                if draft_model is not None else dict(self._values)
            if is_quantized_state(self._dvalues):
                self._dvalues = {
                    k: v for k, v in self._dequantize_state(
                        self._dvalues).items()}
            dhd = dcfg.hidden_size // dcfg.num_heads
            dshape = (self.num_blocks, dcfg.num_heads, self.block_size,
                      dhd)
            self._dks = [jnp.zeros(dshape, dtype)
                         for _ in range(dcfg.num_layers)]
            self._dvs = [jnp.zeros(dshape, dtype)
                         for _ in range(dcfg.num_layers)]
            self.kv_pool_bytes += int(
                2 * dcfg.num_layers * np.prod(dshape)
                * jnp.zeros((), dtype).nbytes)
            self._draft_chunk = self.spec_len + 1

            def draft_fn(dvalues, tok, pos, nvalid, tables, ks, vs):
                _count("draft")
                observe.record_compile(
                    "serving.draft",
                    signature=observe.signature_of(tok, pos, tables))
                caches = [(k, v, (pos, tables)) for k, v in zip(ks, vs)]
                posmat = jnp.minimum(
                    pos[:, None] + jnp.arange(tok.shape[1]),
                    self.max_seq_len - 1)

                def run(m):
                    h, new_caches = m.gpt(Tensor(tok), Tensor(posmat),
                                          caches=caches)
                    hv = h._value if isinstance(h, Tensor) else h
                    last = hv[jnp.arange(hv.shape[0]), nvalid - 1]
                    return m.logits(Tensor(last[:, None, :])), new_caches

                logits, new_caches = functional_apply(
                    self.draft_model, dvalues, run)
                lv = jnp.asarray(logits)[:, 0, :].astype(jnp.float32)
                return (lv, [c[0] for c in new_caches],
                        [c[1] for c in new_caches])

            self._draft = jax.jit(draft_fn)

    # -- introspection ------------------------------------------------------

    @property
    def compile_counts(self):
        """'decode' -> traces of the unified prefill+decode step,
        'cow' -> traces of the copy-on-write block copy, 'draft' ->
        traces of the speculative draft micro-step (present only when
        spec_len > 0). The paged engine's compile invariant is every
        value == 1 — there is no prefill bucket ladder anymore, and
        draft/verify batches reuse the same two programs for life."""
        return dict(self._compiles)

    def mesh_info(self):
        """Mesh introspection for fleet snapshots: canonical spec label,
        device count, and whether the KV pool is actually head-sharded
        (heads % mp == 0) or silently replicated."""
        if self.mesh is None:
            return {"spec": "", "devices": 1, "kv_sharded": False}
        from ..distributed.topology import MP_AXIS

        mp = dict(self.mesh.shape).get(MP_AXIS, 1)
        return {
            "spec": self.mesh_spec,
            "devices": int(self.mesh.size),
            "kv_sharded": bool(
                mp > 1 and self.model.config.num_heads % mp == 0),
        }

    @property
    def active(self):
        return sum(1 for s in self._slots if s is not None)

    @property
    def free_blocks(self):
        """Currently unreferenced physical blocks."""
        return self._alloc.free_blocks

    @property
    def blocks_in_use(self):
        return self._alloc.blocks_in_use

    @property
    def prefix_cache_size(self):
        return len(self._cache) if self._cache is not None else 0

    def _blocks_needed(self, n_positions):
        return -(-int(n_positions) // self.block_size)

    # -- w8a8 activation scale (frozen after a short calibration) -----------

    # warmup + this many real steps feed the running abs-max before the
    # activation scale freezes; until the first absorb lands the scale
    # is 0 and the in-trace lax.cond keeps the weights-only epilogue
    _W8A8_CALIB_STEPS = 8

    def _act_arg(self):
        """This step's activation-scale argument: 0 degrades the step
        to the weights-only dequant path inside the same trace."""
        import jax.numpy as jnp

        if self._w8a8_degraded:
            return jnp.zeros((), jnp.float32)
        return self._act_scale

    def _absorb_act_amax(self, amax):
        """Fold one step's head-input abs-max into the frozen scale.
        Pure device ops (jnp.maximum on scalars) — no host sync, and
        the scale is an argument of the one compiled step, so the
        running update never retraces."""
        if self._act_frozen or self._w8a8_degraded:
            return
        import jax.numpy as jnp

        self._act_scale = jnp.maximum(self._act_scale, amax)
        self._act_calib += 1
        if self._act_calib > self._W8A8_CALIB_STEPS:
            self._act_frozen = True

    # -- batched adapter bank (ISSUE 20) ------------------------------------

    def _dispatch_decode(self, tok, pos, nvalid):
        """The ONE argument arity for the compiled decode step: every
        call site (warmup, plain step, speculative verify) builds its
        positional list here, so jax.jit sees exactly one signature per
        engine configuration — the compile-once invariant survives any
        mix of the w8a8 and adapter options."""
        import jax.numpy as jnp

        args = [self._values, jnp.asarray(tok), jnp.asarray(pos),
                jnp.asarray(nvalid), jnp.asarray(self._bt), self._ks,
                self._vs]
        if self.w8a8:
            args.append(self._act_arg())
        elif self.max_adapters:
            args.append(None)   # act_scale slot stays positional
        if self.max_adapters:
            args.extend((jnp.asarray(self._aid), self._lora_a,
                         self._lora_b))
        return self._decode(*args)

    def swap_adapters(self, lora_a, lora_b, version=None, timeout=5.0):
        """Hot-swap the stacked adapter bank (the rollout commit path).
        Applied at a step boundary when the serve loop is running (the
        bank rebind must not race the compiled step's reads), inline
        otherwise. All-or-nothing: a fault (``serving.adapter_swap``) or
        validation error leaves the OLD bank serving bitwise. Shapes
        are fixed by construction, so a swap never retraces. Returns
        the new adapter_version."""
        if not self.max_adapters:
            raise ValueError(
                "engine built without adapters (max_adapters=0 / "
                "FLAGS_serving_max_adapters)")
        if self._thread is not None and self._thread.is_alive():
            done = threading.Event()
            box: dict = {}
            with self._migrate_lock:
                self._adapter_q.append((lora_a, lora_b, version, done,
                                        box))
            if not done.wait(timeout):
                raise TimeoutError(
                    f"engine {self.name!r} did not reach a step boundary "
                    f"within {timeout:.3f}s to swap adapters")
            if "error" in box:
                raise box["error"]
            return box["version"]
        return self._apply_adapter_swap(lora_a, lora_b, version)

    def _drain_adapter_swaps(self):
        while True:
            with self._migrate_lock:
                if not self._adapter_q:
                    return
                la, lb, version, done, box = self._adapter_q.pop(0)
            try:
                box["version"] = self._apply_adapter_swap(la, lb,
                                                          version)
            except Exception as e:  # noqa: BLE001 — caller re-raises
                box["error"] = e
            finally:
                done.set()

    def _apply_adapter_swap(self, lora_a, lora_b, version):
        import jax.numpy as jnp

        # the fault fires BEFORE any mutation: a faulted swap leaves
        # the old adapter bank serving bitwise
        faults.fault_point("serving.adapter_swap", tag=self.name)
        la = jnp.asarray(lora_a, jnp.float32)
        lb = jnp.asarray(lora_b, jnp.float32)
        if la.shape != self._lora_a.shape or \
                lb.shape != self._lora_b.shape:
            raise ValueError(
                f"adapter bank shapes {la.shape}/{lb.shape} != engine "
                f"{self._lora_a.shape}/{self._lora_b.shape}: rebuild "
                "the engine to change adapter capacity or rank")
        if np.asarray(la[0]).any() or np.asarray(lb[0]).any():
            raise ValueError(
                "adapter row 0 is the base model and must stay all-zero")
        if self._plan is not None:
            import jax

            rep = self._plan.replicated()
            la = jax.device_put(la, rep)
            lb = jax.device_put(lb, rep)
        self._lora_a, self._lora_b = la, lb
        self.adapter_version = int(version) if version is not None \
            else self.adapter_version + 1
        self.metrics.inc("adapter_swaps")
        return self.adapter_version

    # -- warmup -------------------------------------------------------------

    def warmup(self, mesh=None):
        """Trace the unified step and the CoW copy before traffic so the
        hot path never compiles. All tables point at the null block, so
        the dummy step's writes land in reserved scratch; outputs are
        discarded. Returns `compile_counts`.

        `mesh` (optional) asserts the caller's mesh matches the one the
        engine compiled for — a shard restart that rebuilt topology must
        land on the same shape or it would silently retrace. A repeat
        warmup (re-entering the serve path after a shard restart) runs
        under `observe.no_retrace()`: same shapes + same mesh = zero new
        compiles for engine life."""
        import contextlib

        import jax.numpy as jnp

        if mesh is not None:
            from .sharding import mesh_spec_of, resolve_mesh

            want = mesh_spec_of(resolve_mesh(mesh))
            if want != self.mesh_spec:
                raise ValueError(
                    f"warmup mesh {want!r} != engine mesh "
                    f"{self.mesh_spec!r}: rebuild the engine for a new "
                    "mesh shape instead of re-warming")
        guard = observe.no_retrace() if self._warmed \
            else contextlib.nullcontext()
        with guard:
            tok = jnp.zeros((self.max_slots, self.prefill_chunk),
                            jnp.int32)
            pos = jnp.zeros((self.max_slots,), jnp.int32)
            nvalid = jnp.ones((self.max_slots,), jnp.int32)
            if self.w8a8:
                out = self._dispatch_decode(tok, pos, nvalid)
                self._absorb_act_amax(out[2 if self.spec_len else 1])
            else:
                self._dispatch_decode(tok, pos, nvalid)
            self._cow(self._ks, self._vs, jnp.int32(NULL_BLOCK),
                      jnp.int32(NULL_BLOCK))
            if self.spec_len:
                dtok = jnp.zeros((self.max_slots, self._draft_chunk),
                                 jnp.int32)
                self._draft(self._dvalues, dtok, pos, nvalid,
                            jnp.asarray(self._bt), self._dks, self._dvs)
        self._warmed = True
        return self.compile_counts

    # -- request lifecycle --------------------------------------------------

    def submit(self, prompt_ids, *, max_new_tokens=16, eos_token_id=None,
               timeout=None, priority=0, do_sample=False, temperature=1.0,
               top_k=0, seed=0, adapter_id=0, tenant=None):
        """Admit one request (or shed); returns its `Request` future.

        Length beyond the model's positional range is a hard
        `ValueError` (client error); a request whose block demand
        exceeds the whole physical pool sheds with the retriable
        `CapacityExhaustedError` (HTTP 429) instead — paged capacity,
        not slot count, is the admission limit."""
        if timeout is None:
            timeout = flag("FLAGS_serving_default_timeout_s") or None
        adapter_id = int(adapter_id or 0)
        if adapter_id < 0 or adapter_id >= max(self.max_adapters, 1):
            raise ValueError(
                f"adapter_id {adapter_id} outside the engine's bank "
                f"(max_adapters={self.max_adapters}; 0 = base model)")
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if ids.size == 0:
            raise ValueError("empty prompt")
        if ids.size + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt ({ids.size}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds engine max_seq_len {self.max_seq_len}")
        need = self._blocks_needed(ids.size + max_new_tokens)
        if need > self._alloc.usable:
            self.metrics.inc("rejected_capacity")
            raise CapacityExhaustedError(
                f"request needs {need} KV blocks but the pool holds "
                f"{self._alloc.usable} (block_size={self.block_size}); "
                "retry with a smaller request or grow "
                "FLAGS_serving_kv_blocks")
        return self.queue.submit(Request(
            ids, timeout=timeout, priority=priority,
            max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
            do_sample=do_sample, temperature=temperature, top_k=top_k,
            seed=seed, adapter_id=adapter_id, tenant=tenant))

    def _stage_blocks(self, ids, need_total):
        """Reserve the physical blocks for one admission: reuse every
        prefix-cached block, allocate the rest, copy-on-write when the
        divergence falls inside a cached block. Returns
        ``(blocks, fill)`` or raises (`PoolExhausted` = wait and retry;
        anything else = fail the request). All-or-nothing: partial
        reservations are rolled back."""
        import jax.numpy as jnp

        shared, n_shared, cow = [], 0, None
        if self._cache is not None:
            if self.spill_store is not None:
                # session resume: pull spilled records extending the
                # live cached prefix back into the pool first, so the
                # match below sees them as ordinary cache hits
                self._maybe_restore(ids)
            # always leave >= 1 prompt token to compute: the last
            # token's logits seed decode
            shared, n_shared, cow = self._cache.match(ids, ids.size - 1)
            self.metrics.inc("prefix_lookups")
            self.metrics.inc("prompt_tokens", int(ids.size))
            hit_tokens = n_shared + (cow[1] if cow else 0)
            if shared:
                self.metrics.inc("prefix_hit_blocks", len(shared))
            if hit_tokens:
                self.metrics.inc("prefix_hit_tokens", hit_tokens)
            self.prefix_lookups += 1
            self.prefix_prompt_tokens += int(ids.size)
            self.prefix_hit_tokens += hit_tokens
        n_new = need_total - len(shared)
        taken, new, pinned_src = [], [], None
        try:
            # pin every matched block (and the CoW source) BEFORE any
            # reclaim: eviction under pressure must never free a block
            # `match` just handed us — an unpinned matched leaf could be
            # reclaimed here and its id recycled by our own alloc loop,
            # turning a prefix hit into silent KV corruption
            for bid in shared:
                self._alloc.incref(bid)
                taken.append(bid)
            if cow is not None:
                self._alloc.incref(cow[0])
                pinned_src = cow[0]
            if self._alloc.free_blocks < n_new and self._cache is not None:
                self._cache.reclaim(n_new - self._alloc.free_blocks)
            if self._alloc.free_blocks < n_new:
                raise PoolExhausted(
                    f"need {n_new} free KV blocks, have "
                    f"{self._alloc.free_blocks}")
            for _ in range(n_new):
                new.append(self._alloc.alloc())
            fill = n_shared
            if cow is not None:
                src, rows = cow
                faults.fault_point("serving.cow_split")
                with profiler.RecordEvent("serving.cow", cat="serving"):
                    self._ks, self._vs = self._cow(
                        self._ks, self._vs, jnp.int32(src),
                        jnp.int32(new[0]))
                self.metrics.inc("cow_splits")
                fill += rows
        except Exception:
            for bid in taken:
                self._alloc.decref(bid)
            for bid in new:
                self._alloc.decref(bid)
            if pinned_src is not None:
                self._alloc.decref(pinned_src)
            raise
        if pinned_src is not None:
            self._alloc.decref(pinned_src)
        return taken + new, fill

    def _admit(self):
        """Join-at-step: fill free slots from the queue while block
        capacity lasts (no waiting). A request the pool cannot hold
        *right now* is pushed back to the queue head and retried after
        the next eviction frees blocks."""
        while self._free:
            req = self.queue.pop(timeout=0.0)
            if req is None:
                return
            ids = req.payload
            need = self._blocks_needed(
                ids.size + req.gen.get("max_new_tokens", 16))
            try:
                blocks, fill = self._stage_blocks(ids, need)
            except PoolExhausted:
                # FIFO head-of-line wait: blocks free at step boundaries
                self.queue.requeue(req)
                return
            except Exception as e:  # noqa: BLE001 — fail req, stay up
                self.metrics.inc("failed")
                req._fail(e)
                continue
            slot = self._free.pop()
            self._bt[slot, :] = NULL_BLOCK
            self._bt[slot, :len(blocks)] = blocks
            self._pos[slot] = fill
            self._aid[slot] = int(req.gen.get("adapter_id", 0) or 0)
            self._slots[slot] = _Slot(req, ids, fill, blocks)
            self.metrics.inc("admitted")
            self.metrics.observe_latency(
                "queue", time.monotonic() - req.arrival)

    # -- KV migration (prefill->decode disaggregation, ISSUE 17) ------------

    def export_prefix_blocks(self, prompt_ids):
        """Gather this engine's fully-written cached KV blocks covering
        `prompt_ids` into host numpy for migration. Returns a payload
        dict (tokens / per-layer (k_rows, v_rows) / geometry) or None
        when nothing is cached. The matched blocks are pinned (incref)
        for the duration of the gather so a concurrent reclaim cannot
        recycle them mid-copy; block tables were host-side all along, so
        only block payload bytes leave the engine."""
        if self._cache is None:
            return None
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if ids.size < 2:
            return None
        shared, n_shared, _cow = self._cache.match(ids, ids.size - 1)
        if not shared:
            return None
        for bid in shared:
            self._alloc.incref(bid)
        try:
            # snapshot the (immutable) pool arrays once: a concurrent
            # step rebinding self._ks cannot tear the gather, and the
            # pinned blocks' rows were fully written before the cache
            # ever indexed them
            ks, vs = list(self._ks), list(self._vs)
            idx = np.asarray(shared, np.int64)
            layers = [(np.asarray(k[idx]), np.asarray(v[idx]))
                      for k, v in zip(ks, vs)]
        finally:
            for bid in shared:
                self._alloc.decref(bid)
        return {
            "tokens": [int(t) for t in ids[:n_shared]],
            "n_tokens": int(n_shared),
            "block_size": self.block_size,
            "layers": layers,
        }

    def adopt_prefix_blocks(self, payload, timeout=5.0):
        """Adopt migrated KV blocks into this engine's pool + prefix
        cache. Applied at a step boundary when the serve loop is
        running (pool rebinds must not race the compiled step), inline
        otherwise. Returns the number of prompt tokens now served from
        cache (0 = incompatible payload). All-or-nothing: any fault
        mid-adoption frees every block taken so far — the pool is
        leak-free and the request simply prefills from scratch."""
        if self._thread is not None and self._thread.is_alive():
            done = threading.Event()
            box: dict = {}
            with self._migrate_lock:
                self._migrate_q.append((payload, done, box))
            if not done.wait(timeout):
                raise TimeoutError(
                    f"engine {self.name!r} did not reach a step boundary "
                    f"within {timeout:.3f}s to adopt migrated KV")
            if "error" in box:
                raise box["error"]
            return box["adopted"]
        return self._apply_adoption(payload)

    def _drain_adoptions(self):
        while True:
            with self._migrate_lock:
                if not self._migrate_q:
                    return
                payload, done, box = self._migrate_q.pop(0)
            try:
                box["adopted"] = self._apply_adoption(payload)
            except Exception as e:  # noqa: BLE001 — caller re-raises
                box["error"] = e
            finally:
                done.set()

    def _apply_adoption(self, payload):
        if self._cache is None or payload is None:
            return 0
        if payload.get("block_size") != self.block_size:
            return 0
        layers = payload["layers"]
        if len(layers) != len(self._ks):
            return 0
        nb = int(layers[0][0].shape[0]) if layers else 0
        if nb == 0 or layers[0][0].shape[1:] != self._ks[0].shape[1:]:
            return 0
        if self._alloc.free_blocks < nb and self._cache is not None:
            self._cache.reclaim(nb - self._alloc.free_blocks)
        taken: list = []
        try:
            for _ in range(nb):
                faults.fault_point("serving.kv_migrate", tag=self.name)
                taken.append(self._alloc.alloc())
            idx = np.asarray(taken, np.int64)
            for li, (krows, vrows) in enumerate(layers):
                self._ks[li] = self._ks[li].at[idx].set(krows)
                self._vs[li] = self._vs[li].at[idx].set(vrows)
            n_tokens = nb * self.block_size
            self._cache.insert(payload["tokens"][:n_tokens], taken,
                               n_tokens)
        except Exception:
            for bid in taken:
                self._alloc.decref(bid)
            raise
        # the cache increfed every NEW entry; dropping our allocation
        # refs hands ownership over (and frees duplicate-key blocks the
        # cache already held under another id)
        for bid in taken:
            self._alloc.decref(bid)
        return nb * self.block_size

    # -- persistent KV spill tier (ISSUE 18) --------------------------------

    def _spill_block(self, key, tokens, bid, n_rows):
        """PrefixCache donation hook: persist one evicted block's KV
        rows to the SSD tier BEFORE the freeing decref (append-before-
        evict). Best-effort by contract — a spill fault (full/failing
        disk, injected ``serving.spill``) loses durability for this
        block, never the eviction or the allocator balance."""
        if n_rows != self.block_size:
            return
        try:
            # snapshot the (immutable) pool arrays once; the block is
            # still cache-referenced, so its rows cannot be recycled
            # before the hook returns
            ks, vs = list(self._ks), list(self._vs)
            layers = [(np.asarray(k[bid]), np.asarray(v[bid]))
                      for k, v in zip(ks, vs)]
            self.spill_store.append(key, self.weight_version, tokens,
                                    layers)
        except Exception:  # noqa: BLE001 — durability is best-effort
            self.metrics.inc("kv_spill_errors")

    def _maybe_restore(self, ids):
        """Resume staging: walk the prompt's cumulative-prefix digest
        chain past the live cached prefix and re-stage every matching
        spilled record through the all-or-nothing admission path
        (alloc → scatter → cache.insert, exactly like KV adoption).
        Fault site ``serving.kv_restore`` fires per block, tagged with
        the engine name; any failure — fault, fenced generation, torn
        or bit-rotted record, geometry/token mismatch, pool pressure —
        stops the walk leak-free and the request re-prefills the rest.
        Returns the number of tokens restored."""
        store, cache = self.spill_store, self._cache
        ids = np.asarray(ids, np.int32).reshape(-1)
        if store is None or cache is None or ids.size < 2:
            return 0
        bs = self.block_size
        limit = ids.size - 1
        chain, n, _cow = cache.match(ids, limit)
        chain = list(chain)
        # gather every restorable record past the live chain first, then
        # stage them with ONE scatter per layer pool — per-block
        # .at[].set dispatches cost more host time than the prefill
        # chunks the restore is supposed to save
        recs = []
        while n + len(recs) * bs + bs <= limit:
            m = n + len(recs) * bs
            key = cache._digest(ids[:m + bs])
            if key in cache._blocks:
                if recs:
                    break   # restored gap already ends at a live entry
                chain.append(cache._blocks[key])
                n += bs
                continue
            try:
                rec = store.get(key)
            except kvstore.SpillFencedError:
                # rollout fenced this generation's records: the caller
                # re-prefills on the live weights (bitwise-safe)
                self.metrics.inc("kv_restore_fenced")
                break
            if rec is None:
                break
            if (rec["generation"] != self.weight_version
                    or rec["block_size"] != bs
                    or len(rec["layers"]) != len(self._ks)
                    or rec["layers"][0][0].shape != self._ks[0].shape[1:]
                    or not np.array_equal(rec["tokens"], ids[:m + bs])):
                break
            recs.append(rec)
        if self._alloc.free_blocks < len(recs):
            # no reclaim here: it could evict our own chain
            recs = recs[:max(self._alloc.free_blocks, 0)]
        if not recs:
            return 0
        bids, inserted = [], 0
        try:
            for _ in recs:
                faults.fault_point("serving.kv_restore", tag=self.name)
                bids.append(self._alloc.alloc())
            idx = np.asarray(bids, np.int64)
            for li in range(len(self._ks)):
                krows = np.stack([r["layers"][li][0] for r in recs])
                vrows = np.stack([r["layers"][li][1] for r in recs])
                self._ks[li] = self._ks[li].at[idx].set(krows)
                self._vs[li] = self._vs[li].at[idx].set(vrows)
            for bid in bids:
                chain.append(bid)
                cache.insert(ids[:n + bs], chain, n + bs)
                # the cache now owns its own ref; drop ours
                self._alloc.decref(bid)
                self.metrics.inc("kv_restored_blocks")
                n += bs
                inserted += 1
        except Exception:  # noqa: BLE001 — leak-free abort
            for bid in bids[inserted:]:
                if chain and chain[-1] == bid:
                    chain.pop()
                self._alloc.decref(bid)
        return inserted * bs

    def spill_cache(self):
        """Drain the radix cache through the spill tier (graceful-drain
        / bench pressure lever): every evictable entry takes the normal
        eviction path, so blocks whose last reference is the cache's
        persist to SSD before they free. Returns #entries dropped."""
        if self._cache is None:
            return 0
        n = len(self._cache)
        self._cache.clear()
        return n

    def prefix_hit_rate(self):
        """This engine's own prompt-token prefix hit rate (the shared
        metrics registry aggregates fleet-wide; this is per-replica)."""
        return self.prefix_hit_tokens / self.prefix_prompt_tokens \
            if self.prefix_prompt_tokens else 0.0

    @staticmethod
    def _warp_probs(logits, gen):
        """Temperature + top-k warped softmax, exactly the transform
        `_pick` samples from — speculative accept/reject must compare
        target and draft through the SAME warp or the emitted
        distribution shifts."""
        scaled = logits / max(gen.get("temperature", 1.0), 1e-6)
        top_k = gen.get("top_k", 0)
        if top_k:
            kth = np.sort(scaled)[-min(top_k, scaled.size)]
            scaled = np.where(scaled < kth, -np.inf, scaled)
        z = scaled - scaled.max()
        p = np.exp(z)
        p /= p.sum()
        return p

    def _pick(self, slot: _Slot):
        """Next token from the slot's pending logits (host-side so each
        request carries its own sampling config)."""
        logits = slot.next_logits
        gen = slot.req.gen
        if not gen.get("do_sample"):
            return int(logits.argmax())
        p = self._warp_probs(logits, gen)
        return int(slot.rng.choice(p.size, p=p))

    def _evict(self, idx, error=None):
        slot = self._slots[idx]
        self._slots[idx] = None
        self._free.append(idx)
        written = int(self._pos[idx])
        if error is None and self._cache is not None:
            # donate fully written blocks to the prefix index before
            # releasing our references — shared system prompts survive
            self._cache.insert(slot.tokens, slot.blocks, written)
        for bid in slot.blocks:
            self._alloc.decref(bid)
        self._bt[idx, :] = NULL_BLOCK
        self._pos[idx] = 0
        self._aid[idx] = 0
        tenant = slot.req.gen.get("tenant")
        if error is not None:
            self.metrics.inc("failed")
            if tenant:
                self.metrics.tenant_inc(tenant, "failed")
            slot.req._fail(error)
        else:
            self.metrics.inc("completed")
            self.metrics.observe_latency(
                "e2e", time.monotonic() - slot.req.arrival)
            if tenant:
                self.metrics.tenant_inc(tenant, "completed")
                self.metrics.tenant_inc(tenant, "tokens_out",
                                        slot.produced)
                self.metrics.tenant_observe_latency(
                    tenant, time.monotonic() - slot.req.arrival)
            slot.req._complete(np.asarray(slot.tokens, np.int32))

    def _fail_all_active(self, error):
        for i, slot in enumerate(self._slots):
            if slot is not None:
                self._evict(i, error)

    def _step(self):
        if self.mesh is not None:
            # raise here propagates to _loop like any step error: the
            # engine survives and the Router replays the in-flight work
            faults.fault_point("serving.shard_step", tag=self.name)
        if self.quantized:
            # raise here propagates to _loop like any step error
            faults.fault_point("serving.dequant")
        self._w8a8_degraded = False
        if self.w8a8:
            # a fault here degrades THIS step to the weights-only
            # dequant path (act scale 0 -> the lax.cond's plain branch
            # inside the same compiled step) — leak-free: no eviction,
            # no retrace, the step still commits its tokens
            try:
                faults.fault_point("serving.w8a8")
            except Exception:  # noqa: BLE001 — deterministic degrade
                self._w8a8_degraded = True
                self.metrics.inc("w8a8_degraded_steps")
        if self.spec_len:
            return self._step_spec()
        return self._step_plain()

    def _step_plain(self):
        """One continuous-batching iteration: consume each decoding
        slot's pending logits (finishing slots that hit
        EOS/max/deadline), stage the next chunk for prefilling slots,
        then ONE batched step over the whole pool."""
        import jax.numpy as jnp

        try:
            faults.fault_point("serving.step")
        except Exception as e:  # noqa: BLE001 — deterministic mid-decode
            self._fail_all_active(e)
            return
        now = time.monotonic()
        tok = np.zeros((self.max_slots, self.prefill_chunk), np.int32)
        nvalid = np.ones((self.max_slots,), np.int32)
        live: list = []
        with observe.phase("sample", cat="serving"):
            prefill_tokens = self._consume_slots(now, tok, nvalid, live)
        if not live:
            return
        n_pref = sum(1 for i in live
                     if self._slots[i].state == "prefill")
        t0 = time.monotonic()
        with profiler.RecordEvent("serving.step", cat="serving"):
            with observe.phase("device-step", cat="serving"):
                if self.w8a8:
                    logits, amax, self._ks, self._vs = \
                        self._dispatch_decode(tok, self._pos, nvalid)
                    self._absorb_act_amax(amax)
                else:
                    logits, self._ks, self._vs = \
                        self._dispatch_decode(tok, self._pos, nvalid)
        logits = np.asarray(logits)
        self._observe_step_latency(time.monotonic() - t0,
                                   prefill_tokens, len(live) - n_pref)
        for i in live:
            slot = self._slots[i]
            self._pos[i] += slot.advance
            if slot.state == "prefill":
                slot.fill += slot.advance
                if slot.fill >= slot.prompt_len:
                    slot.state = "decode"
                    slot.next_logits = logits[i]
                    self.metrics.inc("prefills")
            else:
                slot.next_logits = logits[i]
        self.metrics.inc("steps")
        if prefill_tokens:
            self.metrics.inc("prefill_tokens", prefill_tokens)
        self.metrics.observe_occupancy(len(live), self.max_slots)
        self.metrics.observe_blocks(self._alloc.blocks_in_use,
                                    self._alloc.usable)

    def _observe_step_latency(self, dt, prefill_tokens, n_decoding):
        """Attribute one device step to the phase-latency series: a step
        staging prompt tokens is a 'prefill' sample, a step advancing at
        least one decoding slot is a 'decode' sample (a mixed colocated
        step is honestly both — decoding slots really did wait for the
        chunk-wide prefill program). These feed the decode p99 /
        prefill p50 columns the disaggregation bench compares."""
        if prefill_tokens:
            self.metrics.observe_latency("prefill", dt)
        if n_decoding:
            self.metrics.observe_latency("decode", dt)

    def _consume_slots(self, now, tok, nvalid, live):
        """Host-side half of a step: sample each decoding slot's pending
        logits (finish/evict on EOS/max/deadline/cancel), stage the next
        prompt chunk for prefilling slots, and fill the fixed
        [max_slots, chunk] token matrix for the unified dispatch.
        Returns the number of prompt tokens staged this step."""
        prefill_tokens = 0
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            req = slot.req
            if req.cancelled:
                self.metrics.inc("cancelled")
                self._evict(i, RequestCancelled(
                    f"request {req.id} cancelled mid-decode"))
                continue
            if req.expired(now):
                self.metrics.inc("timeouts")
                self._evict(i, DeadlineExceededError(
                    f"request {req.id} deadline exceeded mid-decode "
                    f"after {slot.produced} tokens"))
                continue
            if slot.state == "prefill":
                n = min(self.prefill_chunk, slot.prompt_len - slot.fill)
                tok[i, :n] = slot.prompt[slot.fill:slot.fill + n]
                nvalid[i] = n
                slot.advance = n
                prefill_tokens += n
                live.append(i)
                continue
            nxt = self._pick(slot)
            slot.tokens.append(nxt)
            slot.produced += 1
            self.metrics.inc("tokens_out")
            gen = req.gen
            eos = gen.get("eos_token_id")
            if (eos is not None and nxt == eos) or \
                    slot.produced >= gen.get("max_new_tokens", 16):
                self._evict(i)
                continue
            tok[i, 0] = nxt
            slot.advance = 1
            live.append(i)
        return prefill_tokens

    # -- speculative decoding (spec_len > 0) --------------------------------

    def _step_spec(self):
        """One speculative iteration: pick each decoding slot's
        committed next token, draft up to spec_len proposals per slot
        with the compiled draft micro-step (catch-up + propose over the
        shared block tables), stage ``[next, d_1..d_s]`` across the
        chunk columns, run ONE verify dispatch on the unified decode
        trace, then accept/commit host-side. A fault in the draft phase
        degrades the round to plain decode: proposals are dropped, the
        draft cache keeps whatever catch-up landed, and every slot
        still commits exactly its picked token — no losses, no dups."""
        import jax.numpy as jnp

        try:
            faults.fault_point("serving.step")
        except Exception as e:  # noqa: BLE001 — deterministic mid-decode
            self._fail_all_active(e)
            return
        now = time.monotonic()
        tok = np.zeros((self.max_slots, self.prefill_chunk), np.int32)
        nvalid = np.ones((self.max_slots,), np.int32)
        live: list = []
        plan: list = []   # (slot_idx, slot, next_token, s_i)
        with observe.phase("sample", cat="serving"):
            prefill_tokens = self._consume_spec(now, tok, nvalid, live,
                                                plan)
        if not live:
            return
        # prefilling slots join the draft phase with s_i = 0 so the
        # draft cache ingests their prompt alongside the target prefill
        work = [(i, slot, s_i) for i, slot, _, s_i in plan]
        work += [(i, self._slots[i], 0) for i in live
                 if self._slots[i].state == "prefill"]
        drafted_ok = True
        try:
            faults.fault_point("serving.draft")
            with observe.phase("draft", cat="serving"):
                self._run_draft(work)
        except Exception:  # noqa: BLE001 — degrade to plain decode
            drafted_ok = False
            self.metrics.inc("spec_draft_faults")
        for i, slot, nxt, s_i in plan:
            props = slot.drafted[:s_i] if drafted_ok else []
            slot.spec_staged = props
            tok[i, 0] = nxt
            if props:
                tok[i, 1:1 + len(props)] = props
            nvalid[i] = 1 + len(props)
        faults.fault_point("serving.verify")
        n_pref = sum(1 for i in live
                     if self._slots[i].state == "prefill")
        t0 = time.monotonic()
        with profiler.RecordEvent("serving.step", cat="serving"):
            with observe.phase("device-step", cat="serving"):
                if self.w8a8:
                    lv, sv, amax, self._ks, self._vs = \
                        self._dispatch_decode(tok, self._pos, nvalid)
                    self._absorb_act_amax(amax)
                else:
                    lv, sv, self._ks, self._vs = \
                        self._dispatch_decode(tok, self._pos, nvalid)
        lv = np.asarray(lv)
        sv = np.asarray(sv)
        self._observe_step_latency(time.monotonic() - t0,
                                   prefill_tokens, len(live) - n_pref)
        for i in live:
            slot = self._slots[i]
            if slot.state == "prefill":
                self._pos[i] += slot.advance
                slot.fill += slot.advance
                self._advance_dfill(slot)
                if slot.fill >= slot.prompt_len:
                    slot.state = "decode"
                    slot.next_logits = lv[i]
                    self.metrics.inc("prefills")
            else:
                self._commit_spec(i, slot, lv[i], sv[i])
        self.metrics.inc("steps")
        if plan:
            self.metrics.inc("spec_rounds")
        if prefill_tokens:
            self.metrics.inc("prefill_tokens", prefill_tokens)
        self.metrics.observe_occupancy(len(live), self.max_slots)
        self.metrics.observe_blocks(self._alloc.blocks_in_use,
                                    self._alloc.usable)

    def _consume_spec(self, now, tok, nvalid, live, plan):
        """Speculative twin of `_consume_slots`: same cancel / deadline
        / EOS handling and prefill staging, but decoding slots defer
        their token-matrix staging until after the draft phase.  Caps
        each slot's draft length at its remaining token budget so every
        staged position stays inside its allocated blocks."""
        prefill_tokens = 0
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            req = slot.req
            if req.cancelled:
                self.metrics.inc("cancelled")
                self._evict(i, RequestCancelled(
                    f"request {req.id} cancelled mid-decode"))
                continue
            if req.expired(now):
                self.metrics.inc("timeouts")
                self._evict(i, DeadlineExceededError(
                    f"request {req.id} deadline exceeded mid-decode "
                    f"after {slot.produced} tokens"))
                continue
            if slot.state == "prefill":
                n = min(self.prefill_chunk, slot.prompt_len - slot.fill)
                tok[i, :n] = slot.prompt[slot.fill:slot.fill + n]
                nvalid[i] = n
                slot.advance = n
                prefill_tokens += n
                live.append(i)
                continue
            gen = req.gen
            if slot.unfed:
                # a residual-resampled token: already committed and
                # EOS-checked last round, its KV write happens now
                nxt = slot.tokens[-1]
                slot.unfed = False
            else:
                nxt = self._pick(slot)
                slot.tokens.append(nxt)
                slot.produced += 1
                self.metrics.inc("tokens_out")
                eos = gen.get("eos_token_id")
                if (eos is not None and nxt == eos) or \
                        slot.produced >= gen.get("max_new_tokens", 16):
                    self._evict(i)
                    continue
            s_i = min(self.spec_len,
                      gen.get("max_new_tokens", 16) - slot.produced)
            plan.append((i, slot, nxt, s_i))
            live.append(i)
        return prefill_tokens

    def _run_draft(self, work):
        """Drive the ONE compiled draft micro-step until every working
        slot has caught its draft cache up to the committed sequence and
        sampled its proposals. Each iteration batches one [max_slots,
        spec_len+1] call: catch-up slots feed their next committed
        segment, proposing slots feed their latest proposal; idle rows
        route beyond the table so their writes land in the null block.
        Successful feeds are logged to `slot.fed` AFTER the call
        returns, so a mid-phase fault leaves bookkeeping consistent
        with what actually landed in the draft pools."""
        import jax.numpy as jnp

        width = self._draft_chunk
        idle_pos = self.blocks_per_slot * self.block_size
        qlast: dict = {}
        limit = -(-self.max_seq_len // width) + self.spec_len + 4
        for _ in range(limit):
            dtok = np.zeros((self.max_slots, width), np.int32)
            dpos = np.full((self.max_slots,), idle_pos, np.int32)
            dnval = np.ones((self.max_slots,), np.int32)
            feeds: dict = {}
            for i, slot, s_i in work:
                base = slot.dfill + len(slot.fed)
                target = slot.tokens
                if base < len(target):
                    n = min(width, len(target) - base)
                    seg = target[base:base + n]
                    dtok[i, :n] = seg
                    dpos[i] = base
                    dnval[i] = n
                    feeds[i] = (slot, seg)
                elif s_i and len(slot.drafted) < s_i:
                    d = self._draft_pick(slot, qlast[i])
                    slot.drafted.append(d)
                    # the FINAL proposal is never fed back: no later
                    # proposal conditions on it, verify recomputes p
                    if len(slot.drafted) < s_i:
                        dtok[i, 0] = d
                        dpos[i] = base
                        dnval[i] = 1
                        feeds[i] = (slot, [d])
            if not feeds:
                return
            with profiler.RecordEvent("serving.draft", cat="serving"):
                lv, self._dks, self._dvs = self._draft(
                    self._dvalues, jnp.asarray(dtok), jnp.asarray(dpos),
                    jnp.asarray(dnval), jnp.asarray(self._bt),
                    self._dks, self._dvs)
            lv = np.asarray(lv)
            for i, (slot, seg) in feeds.items():
                slot.fed.extend(int(t) for t in seg)
                qlast[i] = lv[i]
        raise RuntimeError(
            f"draft catch-up did not converge in {limit} micro-steps")

    def _draft_pick(self, slot, qrow):
        """Sample one proposal from the draft distribution, recording
        the warped probs (sampling requests) for accept/reject."""
        gen = slot.req.gen
        if not gen.get("do_sample"):
            slot.qdists.append(None)
            return int(qrow.argmax())
        p = self._warp_probs(qrow, gen)
        slot.qdists.append(p)
        return int(slot.rng.choice(p.size, p=p))

    def _advance_dfill(self, slot):
        """Advance the draft-cache coverage mark exactly as far as this
        round's feeds agree with the (post-commit) token sequence:
        committed catch-up and ACCEPTED proposals advance it, a
        rejected suffix or degraded round stops it — the next round's
        catch-up rewrites from there. Clears the round scratch."""
        base, fed, seq = slot.dfill, slot.fed, slot.tokens
        j = 0
        while j < len(fed) and base + j < len(seq) \
                and fed[j] == seq[base + j]:
            j += 1
        slot.dfill = base + j
        slot.fed = []
        slot.drafted = []
        slot.qdists = []

    def _commit_spec(self, i, slot, lv_i, sv_i):
        """Host-side accept/commit for one slot after a verify step.
        Greedy: accept the longest prefix of proposals that match the
        verify argmaxes, then hand the first-mismatch logits row to the
        NEXT round's `_pick` — every emitted token is an argmax of the
        same logits the plain engine would compute, hence bitwise
        parity. Sampling: Leviathan accept / residual-resample through
        the identical `_warp_probs` transform (`speculative_accept`).
        All staged positions were already scattered into the paged pool
        in bulk by the verify step; `self._pos` advances only over the
        committed prefix, and the garbage KV above it is overwritten by
        the next round's staging before any row can attend it."""
        props = slot.spec_staged
        slot.spec_staged = []
        gen = slot.req.gen
        eos = gen.get("eos_token_id")
        max_new = gen.get("max_new_tokens", 16)
        s = len(props)
        L = int(self._pos[i])   # position nxt was written at
        if s == 0:
            # plain-decode round (spec budget exhausted or degraded)
            self._pos[i] = L + 1
            slot.next_logits = lv_i
            self._advance_dfill(slot)
            return
        if not gen.get("do_sample"):
            a = 0
            while a < s and int(sv_i[a].argmax()) == props[a]:
                a += 1
            resampled = None
            # rejection: sv_i[a] is p(. | accepted prefix) — the next
            # _pick's argmax IS the rejection token; all-accept: the
            # bonus row
            nl = sv_i[a] if a < s else sv_i[s]
        else:
            p_list = [self._warp_probs(sv_i[j], gen) for j in range(s)]
            a, resampled = speculative_accept(p_list, slot.qdists[:s],
                                              props, slot.rng)
            nl = None if resampled is not None else sv_i[s]
        self.metrics.observe_spec(i, s, a)
        finished = False
        m = 0
        for t in props[:a]:
            slot.tokens.append(int(t))
            slot.produced += 1
            self.metrics.inc("tokens_out")
            m += 1
            if (eos is not None and t == eos) or \
                    slot.produced >= max_new:
                finished = True
                break
        self._pos[i] = L + 1 + m
        self._advance_dfill(slot)
        if finished:
            self._evict(i)
            return
        if resampled is not None:
            slot.tokens.append(int(resampled))
            slot.produced += 1
            self.metrics.inc("tokens_out")
            slot.next_logits = None
            slot.unfed = True
            if (eos is not None and resampled == eos) or \
                    slot.produced >= max_new:
                slot.unfed = False
                self._evict(i)
            return
        slot.next_logits = nl

    # -- serve loop ---------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return self
        self._abort.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="serving-engine", daemon=True)
        self._thread.start()
        return self

    def _beat(self):
        """One liveness heartbeat per loop iteration. The fault point
        fires only for supervised (fleet-owned) engines so a standalone
        engine's loop never consumes fleet fault occurrences; a `delay`
        action here stalls the beat (watchdog declares the replica
        dead), a `raise` kills the engine THREAD (detected as a crash)."""
        if self.supervised:
            faults.fault_point("serving.replica_heartbeat", tag=self.name)
        self.heartbeats += 1
        self.last_beat = time.monotonic()

    def _loop(self):
        import contextlib

        guard = observe.no_retrace() if self._strict and self._warmed \
            else contextlib.nullcontext()
        with guard:
            while True:
                self._beat()
                self._drain_adoptions()
                self._drain_adapter_swaps()
                if self._abort.is_set():
                    self._fail_all_active(
                        self._abort_error or RequestCancelled(
                            "server aborted (non-drain shutdown)"))
                    return
                self._admit()
                if self.active == 0:
                    if self.queue.drained():
                        return
                    self.queue.wait_nonempty(0.02)
                    continue
                try:
                    if self.supervised:
                        faults.fault_point("serving.replica_step",
                                           tag=self.name)
                    self._step()
                except Exception as e:  # noqa: BLE001 — engine stays up
                    self.metrics.inc("step_errors")
                    self._fail_all_active(e)

    def abandon(self, error):
        """Supervisor-side takeover of a dead/hung replica: stop the
        loop at its next boundary, fail every in-flight and queued
        request with `error` (typically `ReplicaDiedError`, which the
        fleet Router intercepts and replays elsewhere). Never joins the
        thread — a hung replica's thread may be sleeping inside an
        injected delay (or real stuck I/O) for a long time; the replica
        object is simply discarded and rebuilt."""
        self._abort_error = error
        self._abort.set()
        self.queue.close(drain=False)
        # a thread already dead (crashed loop) never reaches the abort
        # branch — sweep its stranded slots from the supervisor thread
        if self._thread is not None and not self._thread.is_alive():
            self._fail_all_active(error)

    def shutdown(self, drain=True, timeout=None):
        """Stop. drain=True finishes queued + in-flight requests first;
        drain=False sheds the queue and evicts in-flight requests at the
        next step boundary."""
        self.queue.close(drain=drain)
        if not drain:
            self._abort.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        if drain and self.spill_store is not None:
            # graceful drain persists the radix cache through the SSD
            # tier, so sessions resume decode-only after a clean
            # restart (a crash only keeps what eviction already wrote)
            self.spill_cache()
