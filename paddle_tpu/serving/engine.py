"""Continuous-batching decode engine: a fixed pool of batch slots over
the GPT static-shape KV cache.

Orca-style iteration-level scheduling (PAPERS.md: continuous batching)
mapped onto XLA's compile-per-shape reality:

- ONE pooled KV cache per layer, shape [max_slots, nh, max_seq, hd].
  Each slot row belongs to at most one in-flight request; `pos[slot]`
  tracks how far that request has decoded. The whole pool steps through
  a single jitted decode function with a PER-ROW position vector
  (gpt.py `_attend_cached` vector-pos path), so the step shape never
  changes and the decode program compiles exactly once.
- Join-at-step admission: whenever a slot is free and the queue is
  non-empty, the new request's prompt is prefilled into that slot's
  rows (prompt padded up to a prefill bucket ladder — one compile per
  rung) while every other slot keeps decoding. The step loop never
  drains between requests.
- Eviction on EOS / max_new_tokens / deadline / cancel frees the slot
  at the next step boundary. Stale KV from the previous occupant is
  harmless: the vector-pos causal mask only admits keys <= the new
  request's position, all of which its own prefill/decode overwrote.

Fault site: ``serving.step`` fires once per decode step; a `raise`
action fails every in-flight request deterministically (mid-decode
cancellation path) while the engine itself stays up.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from .. import observe, profiler
from ..core.tensor import Tensor
from ..engine import functional_apply, state_values
from ..framework import faults
from ..framework.flags import flag
from .metrics import ServingMetrics
from .queueing import (
    AdmissionQueue, DeadlineExceededError, Request, RequestCancelled,
)

__all__ = ["SlotEngine", "prefill_ladder"]


def prefill_ladder(max_seq_len, spec=None):
    """Padded prompt-length rungs <= max_seq_len, from the
    FLAGS_serving_prefill_buckets spec (comma-separated ints), always
    topped by max_seq_len itself."""
    spec = spec if spec is not None else flag("FLAGS_serving_prefill_buckets")
    if isinstance(spec, str):
        rungs = [int(tok) for tok in spec.split(",") if tok.strip()]
    else:
        rungs = [int(tok) for tok in spec]
    rungs = sorted({r for r in rungs if 0 < r < max_seq_len})
    rungs.append(max_seq_len)
    return rungs


class _Slot:
    """One in-flight request's decode state (host side)."""

    def __init__(self, req, tokens, next_logits):
        self.req = req
        self.tokens = tokens            # full sequence so far (list[int])
        self.produced = 0
        self.next_logits = next_logits  # np [V] feeding the next pick
        self.rng = None
        if req.gen.get("do_sample"):
            self.rng = np.random.RandomState(req.gen.get("seed", 0))


class SlotEngine:
    """Continuous-batching greedy/sampling decode over a GPT model.

    `model` is a `GPTForPretraining` (eval mode is forced). Requests
    carry `max_new_tokens`, optional `eos_token_id`, and sampling
    params; results are the full [prompt + generated] int32 id array,
    token-identical to `generate()` / full re-forwarding for greedy.

    Ownership contract (same as the reference's one-predictor-per-
    thread rule): while the engine is serving, it owns the model —
    tracing a new bucket temporarily swaps the model's parameter
    handles (engine.functional_apply), so run eager forwards on it
    only while the engine is idle, or on a separate instance.
    """

    def __init__(self, model, *, max_slots=None, max_seq_len=None,
                 prefill_buckets=None, cache_dtype=None, metrics=None,
                 queue=None):
        import jax
        import jax.numpy as jnp

        model.eval()
        self.model = model
        self.max_slots = max_slots or flag("FLAGS_serving_max_batch")
        self.max_seq_len = min(max_seq_len or model.config.max_seq_len,
                               model.config.max_seq_len)
        self.ladder = prefill_ladder(self.max_seq_len, prefill_buckets)
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.queue = queue if queue is not None else AdmissionQueue(
            flag("FLAGS_serving_queue_cap"), metrics=self.metrics)
        self._values = dict(state_values(model))
        cfg = model.config
        hd = cfg.hidden_size // cfg.num_heads
        dtype = cache_dtype or jnp.float32
        shape = (self.max_slots, cfg.num_heads, self.max_seq_len, hd)
        self._ks = [jnp.zeros(shape, dtype) for _ in range(cfg.num_layers)]
        self._vs = [jnp.zeros(shape, dtype) for _ in range(cfg.num_layers)]
        self._pos = np.zeros((self.max_slots,), np.int32)
        self._slots: list = [None] * self.max_slots
        self._free = list(range(self.max_slots))
        self._compiles: dict = {}
        self._abort = threading.Event()
        self._thread = None

        def _count(key):
            self._compiles[key] = self._compiles.get(key, 0) + 1

        def decode_fn(values, tok, pos, ks, vs):
            _count("decode")     # trace-time only: the compile counter
            observe.record_compile(
                "serving.decode", signature=observe.signature_of(tok, pos))
            caches = [(k, v, pos) for k, v in zip(ks, vs)]

            def run(m):
                h, new_caches = m.gpt(Tensor(tok), Tensor(pos[:, None]),
                                      caches=caches)
                return m.logits(h), new_caches

            logits, new_caches = functional_apply(self.model, values, run)
            lv = jnp.asarray(logits)[:, -1, :].astype(jnp.float32)
            return (lv, [c[0] for c in new_caches],
                    [c[1] for c in new_caches])

        def prefill_fn(values, ks, vs, tok_pad, slot, true_len):
            from jax import lax

            _count(("prefill", tok_pad.shape[1]))
            observe.record_compile(
                "serving.prefill", signature=observe.signature_of(tok_pad))
            rows = [(lax.dynamic_slice_in_dim(k, slot, 1, axis=0),
                     lax.dynamic_slice_in_dim(v, slot, 1, axis=0), 0)
                    for k, v in zip(ks, vs)]
            length = tok_pad.shape[1]

            def run(m):
                h, new_rows = m.gpt(
                    Tensor(tok_pad),
                    Tensor(jnp.arange(length, dtype=jnp.int32)),
                    caches=rows)
                return m.logits(h), new_rows

            logits, new_rows = functional_apply(self.model, values, run)
            last = lax.dynamic_slice_in_dim(
                jnp.asarray(logits), true_len - 1, 1, axis=1)
            ks2 = [lax.dynamic_update_slice_in_dim(k, r[0], slot, axis=0)
                   for k, r in zip(ks, new_rows)]
            vs2 = [lax.dynamic_update_slice_in_dim(v, r[1], slot, axis=0)
                   for v, r in zip(vs, new_rows)]
            return last[:, 0, :].astype(jnp.float32)[0], ks2, vs2

        self._decode = jax.jit(decode_fn)
        self._prefill = jax.jit(prefill_fn)

    # -- introspection ------------------------------------------------------

    @property
    def compile_counts(self):
        """'decode' -> traces of the step fn; ('prefill', L) -> traces
        of the prefill fn at padded length L. The slot-engine compile
        invariant is every value == 1."""
        return dict(self._compiles)

    @property
    def active(self):
        return sum(1 for s in self._slots if s is not None)

    # -- request lifecycle --------------------------------------------------

    def submit(self, prompt_ids, *, max_new_tokens=16, eos_token_id=None,
               timeout=None, do_sample=False, temperature=1.0, top_k=0,
               seed=0):
        """Admit one request (or shed); returns its `Request` future."""
        if timeout is None:
            timeout = flag("FLAGS_serving_default_timeout_s") or None
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if ids.size == 0:
            raise ValueError("empty prompt")
        if ids.size + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt ({ids.size}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds engine max_seq_len {self.max_seq_len}")
        return self.queue.submit(Request(
            ids, timeout=timeout, max_new_tokens=max_new_tokens,
            eos_token_id=eos_token_id, do_sample=do_sample,
            temperature=temperature, top_k=top_k, seed=seed))

    def _admit(self):
        """Join-at-step: fill free slots from the queue (no waiting)."""
        import jax.numpy as jnp

        while self._free:
            req = self.queue.pop(timeout=0.0)
            if req is None:
                return
            slot = self._free.pop()
            ids = req.payload
            s0 = int(ids.size)
            bucket = next(r for r in self.ladder if r >= s0)
            tok_pad = np.zeros((1, bucket), np.int32)
            tok_pad[0, :s0] = ids
            try:
                with profiler.RecordEvent("serving.prefill", cat="serving"):
                    logits, self._ks, self._vs = self._prefill(
                        self._values, self._ks, self._vs,
                        jnp.asarray(tok_pad), jnp.int32(slot),
                        jnp.int32(s0))
            except Exception as e:  # noqa: BLE001 — fail req, keep slot
                self._free.append(slot)
                self.metrics.inc("failed")
                req._fail(e)
                continue
            self._pos[slot] = s0
            self._slots[slot] = _Slot(req, list(int(t) for t in ids),
                                      np.asarray(logits))
            self.metrics.inc("prefills")
            self.metrics.observe_latency(
                "queue", time.monotonic() - req.arrival)

    def _pick(self, slot: _Slot):
        """Next token from the slot's pending logits (host-side so each
        request carries its own sampling config)."""
        logits = slot.next_logits
        gen = slot.req.gen
        if not gen.get("do_sample"):
            return int(logits.argmax())
        scaled = logits / max(gen.get("temperature", 1.0), 1e-6)
        top_k = gen.get("top_k", 0)
        if top_k:
            kth = np.sort(scaled)[-min(top_k, scaled.size)]
            scaled = np.where(scaled < kth, -np.inf, scaled)
        z = scaled - scaled.max()
        p = np.exp(z)
        p /= p.sum()
        return int(slot.rng.choice(scaled.size, p=p))

    def _evict(self, idx, error=None):
        slot = self._slots[idx]
        self._slots[idx] = None
        self._free.append(idx)
        if error is not None:
            self.metrics.inc("failed")
            slot.req._fail(error)
        else:
            self.metrics.inc("completed")
            self.metrics.observe_latency(
                "e2e", time.monotonic() - slot.req.arrival)
            slot.req._complete(np.asarray(slot.tokens, np.int32))

    def _fail_all_active(self, error):
        for i, slot in enumerate(self._slots):
            if slot is not None:
                self._evict(i, error)

    def _step(self):
        """One continuous-batching iteration: consume each slot's
        pending logits (finishing slots that hit EOS/max/deadline), then
        one batched single-token decode for whatever remains."""
        import jax.numpy as jnp

        try:
            faults.fault_point("serving.step")
        except Exception as e:  # noqa: BLE001 — deterministic mid-decode
            self._fail_all_active(e)
            return
        now = time.monotonic()
        tok = np.zeros((self.max_slots,), np.int32)
        live = []
        with observe.phase("sample", cat="serving"):
            self._consume_slots(now, tok, live)
        if not live:
            return
        with profiler.RecordEvent("serving.step", cat="serving"):
            with observe.phase("device-step", cat="serving"):
                logits, self._ks, self._vs = self._decode(
                    self._values, jnp.asarray(tok[:, None]),
                    jnp.asarray(self._pos), self._ks, self._vs)
        logits = np.asarray(logits)
        for i in live:
            self._pos[i] += 1
            self._slots[i].next_logits = logits[i]
        self.metrics.inc("steps")
        self.metrics.observe_occupancy(len(live), self.max_slots)

    def _consume_slots(self, now, tok, live):
        """Host-side half of a step: sample each slot's pending logits,
        finish/evict slots that hit EOS/max/deadline/cancel, and stage
        the next-token batch for the decode dispatch."""
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            req = slot.req
            if req.cancelled:
                self.metrics.inc("cancelled")
                self._evict(i, RequestCancelled(
                    f"request {req.id} cancelled mid-decode"))
                continue
            if req.expired(now):
                self.metrics.inc("timeouts")
                self._evict(i, DeadlineExceededError(
                    f"request {req.id} deadline exceeded mid-decode "
                    f"after {slot.produced} tokens"))
                continue
            nxt = self._pick(slot)
            slot.tokens.append(nxt)
            slot.produced += 1
            self.metrics.inc("tokens_out")
            gen = req.gen
            eos = gen.get("eos_token_id")
            if (eos is not None and nxt == eos) or \
                    slot.produced >= gen.get("max_new_tokens", 16):
                self._evict(i)
                continue
            tok[i] = nxt
            live.append(i)

    # -- serve loop ---------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return self
        self._abort.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="serving-engine", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while True:
            if self._abort.is_set():
                self._fail_all_active(RequestCancelled(
                    "server aborted (non-drain shutdown)"))
                return
            self._admit()
            if self.active == 0:
                if self.queue.drained():
                    return
                self.queue.wait_nonempty(0.02)
                continue
            try:
                self._step()
            except Exception as e:  # noqa: BLE001 — engine must stay up
                self.metrics.inc("step_errors")
                self._fail_all_active(e)

    def shutdown(self, drain=True, timeout=None):
        """Stop. drain=True finishes queued + in-flight requests first;
        drain=False sheds the queue and evicts in-flight requests at the
        next step boundary."""
        self.queue.close(drain=drain)
        if not drain:
            self._abort.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
