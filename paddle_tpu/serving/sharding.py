"""Partition-rule-driven weight sharding for mesh-sharded serving.

Serving reuses the training TP conventions (ISSUE 17 tentpole a): the
GPT/ERNIE layers name their projections identically whether built with
`nn.Linear` or the `Column/RowParallelLinear` pair from
`distributed/fleet/meta_parallel/mp_layers.py`, so a small ordered rule
table over *parameter names* is enough to recover the GSPMD layout the
hybrid trainer derives from `Parameter.param_spec`:

    qkv_proj / fc1        column-parallel  -> weight P(None, "mp"),
                                              bias   P("mp")
    out_proj / fc2        row-parallel     -> weight P("mp", None)
    word_embeddings       vocab-parallel   -> weight P("mp", None)
    everything else       replicated       -> P()

The serving mesh is a 2-axis (dp, mp) slice of the training topology
(`distributed/topology.py` axis names), specified as ``dpD.mpM`` via
`FLAGS_serving_mesh`. GSPMD pads uneven dimensions (e.g. a vocab of 97
on mp=4), so no divisibility guard is needed on weights; the paged KV
pool is sharded over attention heads only when the head count divides
the mp degree — otherwise it stays replicated and the engine still
serves (block tables are host-side numpy either way, so they remain
replica-global; see `ShardingPlan.pool_sharding`).
"""

from __future__ import annotations

import re

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..distributed.topology import DP_AXIS, MP_AXIS

__all__ = [
    "GPT_PARTITION_RULES", "ShardingPlan", "build_mesh",
    "match_partition_rules", "mesh_spec_of", "parse_mesh_spec",
    "resolve_mesh",
]

_SPEC_RE = re.compile(r"^dp(\d+)\.mp(\d+)$")


def parse_mesh_spec(spec):
    """'dpD.mpM' -> {'dp': D, 'mp': M} (both >= 1)."""
    m = _SPEC_RE.match(spec.strip())
    if not m:
        raise ValueError(
            f"bad serving mesh spec {spec!r}: want 'dpD.mpM', e.g. "
            "'dp1.mp2'")
    dp, mp = int(m.group(1)), int(m.group(2))
    if dp < 1 or mp < 1:
        raise ValueError(f"mesh degrees must be >= 1: {spec!r}")
    return {"dp": dp, "mp": mp}


def build_mesh(spec):
    """Build the 2-axis (dp, mp) serving mesh from a 'dpD.mpM' spec."""
    deg = parse_mesh_spec(spec) if isinstance(spec, str) else dict(spec)
    total = deg["dp"] * deg["mp"]
    devices = jax.devices()
    if total > len(devices):
        raise ValueError(
            f"mesh {deg} needs {total} devices, have {len(devices)}")
    grid = np.array(devices[:total]).reshape(deg["dp"], deg["mp"])
    return Mesh(grid, (DP_AXIS, MP_AXIS))


def resolve_mesh(mesh):
    """Normalize an engine's mesh argument: None -> FLAGS_serving_mesh
    ('' -> no mesh), 'dpD.mpM' string -> built Mesh, Mesh -> as-is."""
    if mesh is None:
        from ..framework.flags import flag

        mesh = flag("FLAGS_serving_mesh") or None
    if mesh is None or isinstance(mesh, Mesh):
        return mesh
    return build_mesh(mesh)


def mesh_spec_of(mesh):
    """Mesh -> canonical 'dpD.mpM' label (for metrics / compile keys)."""
    if mesh is None:
        return ""
    shape = dict(mesh.shape)
    return f"dp{shape.get(DP_AXIS, 1)}.mp{shape.get(MP_AXIS, 1)}"


#: ordered (regex, PartitionSpec) pairs over state-dict names; first
#: match wins, so the catch-all replicates layernorms / position
#: embeddings / biases of row-parallel layers. Mirrors the param_spec
#: assignments in mp_layers.py (paddle Linear weights are [in, out]).
GPT_PARTITION_RULES = (
    (r"qkv_proj\.weight$", P(None, MP_AXIS)),
    (r"qkv_proj\.bias$", P(MP_AXIS)),
    (r"fc1\.weight$", P(None, MP_AXIS)),
    (r"fc1\.bias$", P(MP_AXIS)),
    (r"out_proj\.weight$", P(MP_AXIS, None)),
    (r"fc2\.weight$", P(MP_AXIS, None)),
    (r"word_embeddings\.weight$", P(MP_AXIS, None)),
    (r".*", P()),
)


def match_partition_rules(rules, params):
    """Map each param name to the PartitionSpec of the first matching
    rule (re.search). Scalar leaves are always replicated. Raises on an
    unmatched name so a renamed layer cannot silently lose its layout —
    keep a catch-all ``.*`` rule last for the replicated remainder."""
    specs = {}
    for name, value in params.items():
        if getattr(value, "ndim", 0) == 0:
            specs[name] = P()
            continue
        for rule, spec in rules:
            if re.search(rule, name):
                specs[name] = spec
                break
        else:
            raise ValueError(f"no partition rule matches param {name!r}")
    return specs


class ShardingPlan:
    """All NamedShardings a mesh-sharded SlotEngine needs, in one place.

    Weights follow `rules` (default GPT_PARTITION_RULES); a spec naming
    an axis a tensor is too small or too low-rank for degrades to
    replicated rather than failing (GSPMD handles uneven *padding*, but
    a rank-1 bias cannot take a rank-2 spec). The paged KV pool
    ``[num_blocks, num_heads, block_size, head_dim]`` shards over the
    head axis iff ``num_heads % mp == 0``; block tables / allocator
    stay host-side numpy and therefore replica-global.
    """

    def __init__(self, mesh, rules=GPT_PARTITION_RULES):
        self.mesh = mesh
        self.rules = rules
        self.spec = mesh_spec_of(mesh)
        self.mp = dict(mesh.shape).get(MP_AXIS, 1)

    def _named(self, spec):
        return NamedSharding(self.mesh, spec)

    def replicated(self):
        return self._named(P())

    def _fit(self, spec, value):
        """Degrade a rule spec to what this tensor can actually carry:
        a rank-1 bias cannot take a rank-2 spec, and an explicitly
        placed array (device_put / jit in_shardings) must divide the
        mesh axis exactly — GSPMD only pads *internal* values, so an
        uneven dim (e.g. a vocab of 97 on mp=2) falls back to
        replicated on that dim while the rest stay sharded."""
        if len(spec) > getattr(value, "ndim", 0):
            return P()
        fitted = []
        for dim, axis in enumerate(spec):
            if axis is not None:
                size = dict(self.mesh.shape).get(axis, 1)
                if value.shape[dim] % size != 0:
                    axis = None
            fitted.append(axis)
        return P(*fitted)

    def values_shardings(self, values):
        """name -> NamedSharding for a weight-values dict (quantized
        int8 companions like ``<name>.scale`` fall through the rules to
        the scalar/replicated cases)."""
        specs = match_partition_rules(self.rules, values)
        return {k: self._named(self._fit(specs[k], values[k]))
                for k in values}

    def place_values(self, values):
        sh = self.values_shardings(values)
        return {k: jax.device_put(v, sh[k]) for k, v in values.items()}

    def pool_sharding(self, num_heads):
        """KV pool sharding: heads over mp when divisible, else
        replicated (the engine still serves; it just stops saving KV
        memory — same silent-guard stance as the overlap kernels)."""
        if self.mp > 1 and num_heads % self.mp == 0:
            return self._named(P(None, MP_AXIS, None, None))
        return self.replicated()

    def place_pool(self, pool, num_heads):
        return jax.device_put(pool, self.pool_sharding(num_heads))
