"""Zero-downtime model rollout: versioned weights, rolling canary
upgrades, and bitwise auto-rollback across a live replica fleet.

The reference Paddle stack shipped new weights by restarting the
inference process against a fresh ProgramDesc + params dir — full
downtime per deploy. Our fleet already owns every primitive a rolling
upgrade needs (drain-then-evict membership, single-trace restart,
first-wins failover replay, SLO-windowed autoscaling, deterministic
chaos), so this module only adds the missing coordination:

`WeightVersion`
    One immutable weight set: pytree values + a monotonically
    increasing version id + a per-leaf sha256 manifest. Loadable from
    `distributed/checkpoint.py` dirs with the existing
    READABLE/checksum verification — a torn or tampered dir is
    rejected at the registry, before any replica can see it.

`WeightRegistry`
    The version store. `load_dir()` ingests a committed checkpoint
    dir (fault site ``serving.rollout_load``); `watch()` polls a
    trainer's checkpoint directory and picks up new committed
    ``ckpt-N`` dirs as versions; `begin`/`commit`/`abort` pin the
    previous version for rollback until the rollout commits, after
    which it is retired (pinned replays against it fail retriable —
    `VersionRetiredError` — instead of re-decoding on new weights).

`RolloutController`
    Upgrades a live ReplicaSet one replica at a time behind the
    existing drain→rebuild path (`_build` under `_build_lock`;
    compile-once per rebuilt replica). Phase machine: **canary** (one
    replica takes the new version and must pass the golden-prompt
    bitwise gate and an SLO burn gate over the autoscaler's windowed
    p99) → **waves** of `wave_size` replicas with a sustain period
    between waves → **commit** (retarget + retire previous). Any gate
    failure, or `rollback()`, drains upgraded replicas back to the
    pinned previous version (fault site ``serving.rollback``).

The golden gate is the bitwise teeth: reference digests come from an
EAGER full-re-forward greedy chain over the new values (no compiled
trace, no KV cache), so corrupt or mis-activated weights can never
self-certify — the canary's served decode must match them exactly.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time

import numpy as np

from ..engine import functional_apply
from ..framework import faults, monitor
from ..framework.flags import flag

__all__ = ["WeightVersion", "WeightRegistry", "RolloutController",
           "RolloutError", "RolloutGateError", "golden_digests",
           "artifact_digest"]


class RolloutError(RuntimeError):
    """A rollout phase failed (gate, timeout, or operator abort)."""


class RolloutGateError(RolloutError):
    """The canary/sustain gate rejected the new version."""


def _digest_ids(ids):
    a = np.ascontiguousarray(np.asarray(ids, np.int32))
    return hashlib.sha256(a.tobytes()).hexdigest()


def artifact_digest(manifest):
    """One sha256 identifying a whole artifact: the hash of its sorted
    per-leaf digest lines. Two artifacts (weight sets, adapter banks)
    are bitwise-identical iff their artifact digests match — the
    identity key the multi-tenant `ArtifactCatalog` (serving/tenancy.py)
    and `WeightVersion.digest` share."""
    h = hashlib.sha256()
    for name in sorted(manifest):
        h.update(f"{name}={manifest[name]}\n".encode())
    return h.hexdigest()


def golden_digests(model, values, prompts, *, max_new=6):
    """Reference digests for the canary gate: an eager full-re-forward
    greedy argmax chain over `values` — no compiled trace, no KV cache —
    so the digests are independent of everything the canary could get
    wrong. Padded to the model's one reference shape (the same
    convention the serving parity tests certify bitwise against the
    engine's paged decode).

    Int8-frozen values (``@scale`` companion leaves present) chain with
    the engine's exact arithmetic: the body runs on
    `quantization.dequantize_state` (the one canonical dequant formula)
    and the tied LM head goes through the `dequant_matmul` epilogue on
    the raw int8 table — so an int8 canary still gates bitwise, not
    "close enough".

    Caller must hold the fleet's `_build_lock`: `functional_apply`
    swaps the model's parameter handles and must not race a trace.
    """
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    from ..quantization import (SCALE_SUFFIX, dequantize_state,
                                is_quantized_state)

    quantized = is_quantized_state(values)
    fvals = dequantize_state(values) if quantized else values
    head_key = None   # (int8 table, scale) for the tied epilogue head
    if quantized and getattr(model.config, "tie_word_embeddings", False):
        for k in values:
            if k.endswith("word_embeddings.weight") and \
                    (k + SCALE_SUFFIX) in values:
                head_key = (k, k + SCALE_SUFFIX)
                break

    def _logits_row(ids, last):
        if head_key is None:
            logits = functional_apply(
                model, fvals,
                lambda m: m(Tensor(jnp.asarray(ids, jnp.int32))))
            return np.asarray(logits._value, np.float32)[0, last]

        def run(m):
            from ..ops.quant_ops import dequant_matmul

            h = m.gpt(Tensor(jnp.asarray(ids, jnp.int32)))
            hv = h._value if isinstance(h, Tensor) else h
            return dequant_matmul(hv[:, last], values[head_key[0]],
                                  values[head_key[1]])

        return np.asarray(functional_apply(model, fvals, run),
                          np.float32)[0]

    pad = model.config.max_seq_len
    out = {}
    for pi, prompt in enumerate(prompts):
        toks = [int(t) for t in prompt]
        if len(toks) + max_new > pad:
            raise ValueError(
                f"golden prompt {pi}: {len(toks)} + {max_new} new tokens "
                f"exceeds max_seq_len {pad}")
        for _ in range(max_new):
            ids = np.zeros((1, pad), np.int32)
            ids[0, :len(toks)] = toks
            row = _logits_row(ids, len(toks) - 1)
            toks.append(int(row.argmax()))
        out[f"p{pi}"] = _digest_ids(toks)
    return out


class WeightVersion:
    """One immutable weight set: flat ``name -> array`` values, a
    monotonically increasing id, and a per-leaf sha256 manifest.
    `golden` holds the precomputed golden-prompt digests once
    `RolloutController.ensure_golden` (or the caller) fills them.

    Quantized artifacts are first-class versions: values frozen by
    `quantization.quantize_state_int8` carry int8 tables plus
    ``@scale`` companion leaves, all covered by the same per-leaf
    sha256 manifest, and `quant` records the ``{leaf: {dtype, scale}}``
    summary (auto-derived from the companions when not given). A
    rollout to — or bitwise rollback from — an int8 version goes
    through the exact same drain→rebuild path as a float one; the
    engine adopts a pre-frozen values dict as-is, so no retrace beyond
    the per-rebuild compile the float path already pays."""

    def __init__(self, version, values, *, manifest=None, source=None,
                 golden=None, quant=None, act_schema=None):
        from ..distributed import checkpoint as ckpt
        from ..quantization import SCALE_SUFFIX

        self.version = int(version)
        self.values = dict(values)
        self.manifest = dict(manifest) if manifest else \
            ckpt.leaf_digests(self.values)
        self.digest = artifact_digest(self.manifest)
        self.source = source
        self.golden = dict(golden) if golden else None
        if quant is None:
            scales = [k for k in self.values if k.endswith(SCALE_SUFFIX)]
            if scales:
                quant = {}
                for sk in scales:
                    leaf = sk[:-len(SCALE_SUFFIX)]
                    quant[leaf] = {
                        "dtype": str(np.asarray(
                            self.values[leaf]).dtype),
                        "scale": float(np.asarray(self.values[sk])),
                    }
        self.quant = dict(quant) if quant else None
        # w8a8 artifacts additionally record the activation-quant
        # schema (per-tensor dtype + frozen scales by site). Golden
        # digests stay weights-only — activations quantize in-trace at
        # serve time against these scales, so the quant summary (not
        # the values manifest) is where the schema is certified.
        self.act_schema = dict(act_schema) if act_schema else None
        if self.act_schema is not None and self.quant is not None:
            self.quant = dict(self.quant)
            self.quant["__activations__"] = dict(self.act_schema)

    @classmethod
    def from_model(cls, model, version=0):
        from ..engine import state_values

        return cls(version, state_values(model), source="model")

    @classmethod
    def quantized_from(cls, wv, version, act_scales=None):
        """Freeze an existing float version's 2-D weights to int8 (+
        ``@scale`` companions) as a NEW version with its own manifest:
        the artifact the fleet serves is the artifact the registry
        certifies, not its float parent.

        `act_scales` ({site: float} — e.g. the engine's frozen head
        activation scale) marks the artifact w8a8: the activation-quant
        schema is recorded in the quant summary (per-tensor int8,
        scale = representable abs-max, q = clip(round(x/s*127))) so the
        version rolls through the bitwise canary gate with its serving
        contract attached, like PR 16's weights-only ones."""
        from ..quantization import quantize_state_int8

        schema = None
        src = f"int8(v{wv.version})"
        if act_scales:
            schema = {
                "dtype": "int8",
                "granularity": "per_tensor",
                "scales": {str(k): float(v)
                           for k, v in dict(act_scales).items()},
            }
            src = f"w8a8(v{wv.version})"
        return cls(version, quantize_state_int8(wv.values),
                   source=src, act_schema=schema)

    def __repr__(self):
        q = ", int8" if self.quant else ""
        return (f"WeightVersion(v{self.version}, {len(self.values)} leaves"
                f"{q}, source={self.source!r})")


class WeightRegistry:
    """Versioned weight store for a serving fleet.

    Version ids only ever grow; a retired id never comes back. During a
    rollout the previous version stays pinned (`previous`) so rollback
    always has a target; `commit()` retires it and notifies subscribers
    (e.g. `rec.RankingService.refresh_dense`) of the new current
    version.
    """

    def __init__(self, model=None, *, template=None):
        if model is None and template is None:
            raise ValueError("WeightRegistry needs a model or a template")
        self._lock = threading.RLock()
        self.versions: dict = {}
        self.retired: list = []
        self.current = None
        self.previous = None       # rollback pin while a rollout runs
        self._high = -1            # highest id ever seen (monotonicity)
        self._skip: set = set()    # watch(): steps that failed to load
        self._subs: list = []
        self._watch_stop = None
        self._watch_thread = None
        if model is not None:
            base = WeightVersion.from_model(model)
            self.versions[0] = base
            self.current = 0
            self._high = 0
            if template is None:
                template = base.values
        self._template = dict(template)

    # -- store ---------------------------------------------------------------

    def get(self, version):
        with self._lock:
            if version not in self.versions:
                raise KeyError(f"no weight version {version} "
                               f"(live: {sorted(self.versions)}, "
                               f"retired: {self.retired})")
            return self.versions[version]

    def is_live(self, version):
        with self._lock:
            return version in self.versions

    def latest(self):
        with self._lock:
            return max(self.versions) if self.versions else None

    def subscribe(self, fn):
        """Call ``fn(weight_version)`` on every commit (the version
        boundary downstream consumers swap at)."""
        self._subs.append(fn)

    def add(self, wv):
        """Register an in-memory `WeightVersion` (tests / handcrafted
        versions); same monotonic-id rule as `load_dir`."""
        with self._lock:
            if wv.version <= self._high:
                raise ValueError(
                    f"version ids are monotonic: {wv.version} <= "
                    f"high-water {self._high}")
            self.versions[wv.version] = wv
            self._high = wv.version
            return wv

    # -- checkpoint ingestion ------------------------------------------------

    def load_dir(self, path, *, version=None, golden=None):
        """Ingest one committed checkpoint dir as a new version.

        Reuses the CheckpointManager READABLE semantics (a committed
        dir always holds the manifest/metadata; staging ``.tmp`` dirs
        and torn writes never qualify) and `load_state`'s per-leaf
        sha256 verification — a tampered leaf raises and the registry
        (and therefore the fleet) never sees the bad version. Fault
        site ``serving.rollout_load`` fires per ingestion attempt."""
        from ..distributed import checkpoint as ckpt

        faults.fault_point("serving.rollout_load", path)
        norm = os.path.normpath(path)
        base, parent = os.path.basename(norm), os.path.dirname(norm) or "."
        readable = False
        if base.startswith("ckpt-"):
            try:
                step = int(base.split("-", 1)[1])
            except ValueError:
                step = None
            if step is not None:
                readable = ckpt.CheckpointManager(parent).is_readable(step)
        else:
            readable = os.path.isdir(norm) and (
                os.path.exists(os.path.join(norm, ckpt.MANIFEST_NAME))
                or os.path.exists(os.path.join(norm, ckpt.META_NAME)))
        if not readable:
            monitor.stat_add("fleet.rollout_load_failures")
            raise ValueError(
                f"{path} is not a committed checkpoint dir (torn write, "
                "staging .tmp, or missing manifest/metadata) — refusing "
                "to register it as a weight version")
        with self._lock:
            vid = version if version is not None else self._high + 1
            if vid <= self._high:
                raise ValueError(
                    f"version ids are monotonic: {vid} <= high-water "
                    f"{self._high}")
        try:
            # per-leaf sha256 verification against the saved manifest
            restored = ckpt.load_state(norm, self._template, verify=True)
        except Exception:
            monitor.stat_add("fleet.rollout_load_failures")
            raise
        saved = ckpt.load_manifest(norm)
        manifest = {k: v["sha256"] for k, v in saved.items()} if saved \
            else None
        wv = WeightVersion(vid, restored, manifest=manifest, source=norm,
                           golden=golden)
        with self._lock:
            if wv.version <= self._high:   # raced another load
                raise ValueError(
                    f"version ids are monotonic: {wv.version} <= "
                    f"high-water {self._high}")
            self.versions[wv.version] = wv
            self._high = wv.version
        monitor.stat_add("fleet.rollout_loads")
        return wv

    def watch(self, directory, *, poll_s=0.25, on_version=None):
        """Background poller: pick up new committed ``ckpt-N`` dirs
        from a live trainer's checkpoint directory (version id = the
        checkpoint step). Uncommitted staging dirs are invisible; a
        dir that fails checksum verification is skipped for good."""
        from ..distributed import checkpoint as ckpt

        mgr = ckpt.CheckpointManager(directory)
        stop = threading.Event()

        def loop():
            while True:
                self.poll_dir(mgr, on_version)
                if stop.wait(poll_s):
                    return

        self.stop_watch()
        self._watch_stop = stop
        self._watch_thread = threading.Thread(
            target=loop, name="rollout-watch", daemon=True)
        self._watch_thread.start()
        return self

    def poll_dir(self, mgr, on_version=None):
        """One watch pass over a CheckpointManager's directory."""
        found = []
        for step in mgr.readable_steps():
            with self._lock:
                if step <= self._high or step in self._skip:
                    continue
            try:
                wv = self.load_dir(
                    os.path.join(mgr.directory, f"ckpt-{step}"),
                    version=step)
            except Exception:  # noqa: BLE001 — bad dirs never re-tried
                self._skip.add(step)
                continue
            found.append(wv)
            if on_version is not None:
                try:
                    on_version(wv)
                except Exception:  # noqa: BLE001 — observer-only
                    monitor.stat_add("fleet.rollout_sub_errors")
        return found

    def stop_watch(self):
        if self._watch_stop is not None:
            self._watch_stop.set()
            self._watch_thread.join(timeout=5.0)
            self._watch_stop = self._watch_thread = None

    # -- rollout transaction -------------------------------------------------

    def begin(self, target):
        """Start a rollout toward `target`: pin the current version as
        the rollback target until commit/abort."""
        with self._lock:
            if target not in self.versions:
                raise KeyError(f"no weight version {target}")
            self.previous = self.current

    def commit(self, target):
        """Make `target` current, retire the pinned previous version,
        and notify subscribers (the version boundary)."""
        with self._lock:
            if target not in self.versions:
                raise KeyError(f"no weight version {target}")
            prev = self.previous
            self.current = target
            self.previous = None
            if prev is not None and prev != target:
                self._retire(prev)
            wv = self.versions[target]
            subs = list(self._subs)
        for fn in subs:
            try:
                fn(wv)
            except Exception:  # noqa: BLE001 — observer-only
                monitor.stat_add("fleet.rollout_sub_errors")

    def abort(self, target=None):
        """Abandon a begun rollout: unpin, and retire the (bad) target
        so it can never be rolled to again."""
        with self._lock:
            self.previous = None
            if target is not None and target != self.current \
                    and target in self.versions:
                self._retire(target)

    def retire(self, version):
        with self._lock:
            if version == self.current:
                raise ValueError("cannot retire the current version")
            self._retire(version)

    def _retire(self, version):
        if self.versions.pop(version, None) is not None:
            self.retired.append(version)

    def snapshot(self):
        with self._lock:
            return {"current": self.current, "previous": self.previous,
                    "live": sorted(self.versions),
                    "retired": list(self.retired)}


class RolloutController:
    """Drives a rolling upgrade of a live Router fleet.

    Attaches itself as ``router.rollout`` (the same pattern the
    Autoscaler uses), so `/v1/version` and `Router.snapshot()` can see
    rollout state. One rollout at a time; `roll_to(version)` runs the
    canary → waves → commit machine and auto-rolls-back on any gate
    failure. All replica mutation goes through the ReplicaSet's
    drain→rebuild path, so in-flight requests always finish on the
    weights they started on.
    """

    def __init__(self, router, registry, *, canary_secs=None,
                 sustain_s=None, wave_size=None, golden_prompts=None,
                 golden_max_new=6, slo_p99_ms=None, window=64,
                 poll_s=0.01, replica_timeout_s=120.0,
                 gate_timeout_s=60.0):
        self.router = router
        self.registry = registry
        self.canary_secs = flag("FLAGS_rollout_canary_secs") \
            if canary_secs is None else canary_secs
        self.sustain_s = self.canary_secs if sustain_s is None \
            else sustain_s
        self.wave_size = max(int(flag("FLAGS_rollout_wave_size")
                                 if wave_size is None else wave_size), 1)
        self.golden_max_new = golden_max_new
        self.slo_p99_ms = flag("FLAGS_fleet_slo_p99_ms") \
            if slo_p99_ms is None else slo_p99_ms
        self.window = window
        self.poll_s = poll_s
        self.replica_timeout_s = replica_timeout_s
        self.gate_timeout_s = gate_timeout_s
        self._given_prompts = golden_prompts
        self._prompt_cache = None
        self.state = "idle"
        self.target = None
        self.error = None
        self.history: list = []
        self._abort_reason = None
        self._lock = threading.Lock()   # one rollout at a time
        router.rollout = self
        # generation-fence the SSD KV spill tier (serving/kvstore.py):
        # every registry commit fences spilled records of the retired
        # versions, so a session can never resume attention state
        # computed under weights the rollout replaced
        fenced = set()
        for r in router.replica_set.replicas:
            store = getattr(r.engine, "spill_store", None)
            if store is not None and id(store) not in fenced:
                fenced.add(id(store))
                store.attach_registry(registry)

    # -- public API ----------------------------------------------------------

    def roll_to(self, version, *, block=True):
        """Upgrade the fleet to `version`. Returns True on commit,
        False on auto-rollback (see `.state`/`.error`). With
        ``block=False`` runs in a background thread and returns it."""
        wv = self.registry.get(version)
        if block:
            return self._run(wv)
        t = threading.Thread(target=self._run, args=(wv,),
                             name=f"{self.router.name}-rollout",
                             daemon=True)
        t.start()
        return t

    def rollback(self, reason="operator rollback"):
        """Abort the in-progress rollout; the running `roll_to` drains
        every upgraded replica back to the pinned previous version."""
        if self.state in ("idle", "committed", "rolled_back", "failed"):
            raise RolloutError(f"no rollout in progress (state "
                               f"{self.state!r})")
        self._abort_reason = reason

    def ensure_golden(self, wv):
        """Precompute `wv.golden` from its own values (eager reference
        chain) — called automatically before the canary, or explicitly
        right after `load_dir` to freeze the digests early."""
        if wv.golden is not None:
            return wv.golden
        rs = self.router.replica_set
        with rs._build_lock:
            wv.golden = golden_digests(rs.model, wv.values,
                                       self._prompts(),
                                       max_new=self.golden_max_new)
        return wv.golden

    def snapshot(self):
        return {"state": self.state, "target": self.target,
                "error": self.error, "registry": self.registry.snapshot(),
                "history": list(self.history)}

    # -- phase machine -------------------------------------------------------

    def _run(self, wv):
        with self._lock:
            rs = self.router.replica_set
            prev = self.registry.get(self.registry.current)
            self.registry.begin(wv.version)
            self.target, self.error = wv.version, None
            self._abort_reason = None
            upgraded = []
            try:
                plan = sorted((r for r in rs.replicas
                               if r.state == "healthy"),
                              key=lambda r: r.index)
                if not plan:
                    raise RolloutError("no healthy replicas to roll")
                self.state = "canary"
                self.ensure_golden(wv)
                canary = plan[0]
                self._upgrade(canary, wv)
                upgraded.append(canary)
                faults.fault_point("serving.canary", tag=canary.name)
                ok, why = self._golden_gate(canary, wv)
                if ok:
                    ok, why = self._slo_gate(self.canary_secs, "canary")
                if not ok:
                    raise RolloutGateError(why)
                rest, w = plan[1:], self.wave_size
                waves = [rest[i:i + w] for i in range(0, len(rest), w)]
                for wi, wave in enumerate(waves):
                    self.state = f"wave-{wi + 1}/{len(waves)}"
                    for r in wave:     # one replica at a time, even
                        self._upgrade(r, wv)   # within a wave
                        upgraded.append(r)
                    self.state = "sustain"
                    ok, why = self._slo_gate(self.sustain_s,
                                             f"wave {wi + 1}")
                    if not ok:
                        raise RolloutGateError(why)
                # stragglers: replicas that were in backoff at planning
                # time, or added by the autoscaler mid-rollout
                self._sweep(wv)
                rs.retarget(wv)
                self.registry.commit(wv.version)
                self.state = "committed"
                monitor.stat_set("fleet.weight_version", wv.version)
                monitor.stat_add("fleet.rollouts")
                self.history.append({"target": wv.version, "ok": True})
                return True
            except Exception as e:  # noqa: BLE001 — any failure rolls back
                self.error = f"{type(e).__name__}: {e}"
                self._rollback(upgraded, prev)
                self.history.append({"target": wv.version, "ok": False,
                                     "error": self.error})
                return False

    def _rollback(self, upgraded, prev):
        """Drain every upgraded replica back to the pinned previous
        version. Fault site ``serving.rollback`` fires per attempt; a
        raise there fails the attempt and it is retried."""
        self.state = "rolling_back"
        monitor.stat_add("fleet.rollbacks")
        rs = self.router.replica_set
        rs.retarget(prev)   # crash-restarts must land on prev, not target
        err = None
        for _ in range(3):
            try:
                faults.fault_point("serving.rollback",
                                   tag=f"v{prev.version}")
                for r in upgraded:
                    self._upgrade(r, prev, abortable=False)
                self._sweep(prev, abortable=False)
                err = None
                break
            except Exception as e:  # noqa: BLE001 — retry the rollback
                err = e
                self.router.metrics.inc("rollback_retries")
        self.registry.abort(self.target)
        monitor.stat_set("fleet.weight_version", self.registry.current or 0)
        if err is not None:
            self.state = "failed"
            self.error = f"{self.error}; rollback failed: {err}"
        else:
            self.state = "rolled_back"

    def _check_abort(self):
        if self._abort_reason is not None:
            reason, self._abort_reason = self._abort_reason, None
            raise RolloutError(reason)

    def _upgrade(self, replica, wv, *, abortable=True):
        """Drive one replica to `wv` through drain→rebuild, riding out
        crashes: a replica that dies mid-drain restarts pinned to its
        assigned target, one that dies before the command comes back
        healthy on its old version and is re-commanded."""
        rs = self.router.replica_set
        deadline = time.monotonic() + self.replica_timeout_s
        while time.monotonic() < deadline:
            if abortable:
                self._check_abort()
            if replica.state == "stopped":
                return   # scaled away mid-rollout: nothing to upgrade
            if replica.state == "healthy":
                if replica.engine.weight_version == wv.version:
                    return
                try:
                    rs.rebuild_replica(replica.name, wv)
                except (KeyError, ValueError):
                    pass   # raced the watchdog; re-check next tick
            time.sleep(self.poll_s)
        raise RolloutError(
            f"replica {replica.name} did not reach weight version "
            f"{wv.version} within {self.replica_timeout_s}s")

    def _sweep(self, wv, *, abortable=True):
        """Converge every non-stopped replica onto `wv` (single-version
        fleet before commit/after rollback)."""
        rs = self.router.replica_set
        deadline = time.monotonic() + self.replica_timeout_s
        while time.monotonic() < deadline:
            if abortable:
                self._check_abort()
            off = [r for r in rs.replicas if r.state != "stopped"
                   and (r.weight_version != wv.version
                        or (r.state == "healthy"
                            and r.engine.weight_version != wv.version))]
            if not off:
                return
            for r in off:
                if r.state == "healthy":
                    try:
                        rs.rebuild_replica(r.name, wv)
                    except (KeyError, ValueError):
                        pass
            time.sleep(self.poll_s)
        raise RolloutError(
            f"fleet did not converge to weight version {wv.version} "
            f"within {self.replica_timeout_s}s")

    # -- gates ---------------------------------------------------------------

    def _prompts(self):
        if self._given_prompts is not None:
            return [tuple(int(t) for t in p) for p in self._given_prompts]
        if self._prompt_cache is None:
            # deterministic pinned prompt set, synthesized from a fixed
            # seed: same model config -> same prompts forever
            vocab = self.router.replica_set.model.config.vocab_size
            n = max(int(flag("FLAGS_rollout_golden_prompts")), 1)
            rng = np.random.RandomState(0xC0DE)
            self._prompt_cache = [
                tuple(int(t) for t in rng.randint(1, vocab, size=5))
                for _ in range(n)]
        return self._prompt_cache

    def _golden_gate(self, canary, wv):
        """Greedy-decode the pinned prompts ON THE CANARY (the real
        serving path: paged KV, compiled step) and compare bitwise
        against the reference digests of the new checkpoint."""
        engine = canary.engine
        if engine is None or engine.weight_version != wv.version:
            return False, f"canary {canary.name} lost its engine"
        want = wv.golden or {}
        got = {}
        for pi, prompt in enumerate(self._prompts()):
            try:
                req = engine.submit(list(prompt),
                                    max_new_tokens=self.golden_max_new,
                                    timeout=self.gate_timeout_s)
                got[f"p{pi}"] = _digest_ids(req.result(self.gate_timeout_s))
            except Exception as e:  # noqa: BLE001 — gate failure
                return False, (f"canary golden decode failed on prompt "
                               f"{pi}: {e}")
        bad = sorted(k for k in want if got.get(k) != want[k])
        if bad or not want:
            self.router.metrics.inc("canary_failures")
            return False, (
                f"golden-prompt digest mismatch on {bad or 'all'} — the "
                "canary's served decode does not match the checkpoint's "
                "reference chain (corrupt/mis-activated weights)")
        return True, None

    def _slo_gate(self, duration, label):
        """Hold the SLO burn gate for `duration`: the autoscaler's own
        freshness-gated windowed p99 must stay under the SLO."""
        from .autoscale import SLOWindow

        slo = SLOWindow(self.router.metrics, window=self.window,
                        freshness_s=max(4.0 * duration, 1.0))
        end = time.monotonic() + duration
        while time.monotonic() < end:
            self._check_abort()
            p99 = slo.p99_s()
            if p99 is not None and p99 * 1e3 > self.slo_p99_ms:
                self.router.metrics.inc("canary_failures")
                return False, (
                    f"SLO burn during {label}: windowed e2e p99 "
                    f"{p99 * 1e3:.1f}ms > {self.slo_p99_ms:g}ms")
            time.sleep(self.poll_s)
        return True, None
