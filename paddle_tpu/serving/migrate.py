"""Prefill->decode KV-block migration (ISSUE 17 tentpole c).

A disaggregated fleet runs prefill-specialized and decode-specialized
replicas; after a prefill replica finishes a prompt, its fully-written
KV blocks move to the decode replica that will produce the response
tokens. In-process replicas share no device state (each engine owns its
pool), so migration is an explicit export -> stream -> adopt pipeline:

  * export: the prefill engine gathers the prompt's cached prefix
    blocks from its pool into host numpy (`export_prefix_blocks`);
  * stream: the payload rides `KVMailbox`, an in-process loopback that
    mirrors the gang-layer ``dist.p2p_*`` mailbox contract exactly —
    `deadline_guard("dist.p2p_send")` before the enqueue and
    `deadline_guard("dist.p2p_recv")` before the dequeue wait — so the
    PR-14 chaos specs (delay eats the deadline, drop, raise) hit the
    serving migration path with no launcher env required. Multi-host
    fleets swap in the real `dist.p2p` mailbox behind the same shape.
  * adopt: the decode engine allocates blocks, writes the rows into its
    own (possibly head-sharded) pool and indexes them in its
    PrefixCache (`adopt_prefix_blocks`) — all-or-nothing: a fault
    mid-adoption (site ``serving.kv_migrate``) frees every block taken
    so far, so the decode pool stays leak-free and the Router falls
    back to ordinary colocated dispatch.

The unit of migration is the *block table entry*, which is why the
paged pool made disaggregation cheap: block tables are host-side numpy
and replica-global, so only the block payload bytes cross the wire.
"""

from __future__ import annotations

import queue
import threading

from ..distributed.gang import PeerGoneError, deadline_guard
from ..framework import monitor

__all__ = ["KVMailbox", "migrate_prefix"]

#: default per-leg deadline for the in-process loopback (seconds); the
#: fleet Router passes its own, derived from the request budget
DEFAULT_DEADLINE_S = 5.0


class KVMailbox:
    """Deadline-guarded in-process loopback mailbox keyed by engine
    name. Same guard-then-enqueue / guard-then-get shape as
    `distributed.p2p._Mailbox`, so the ``dist.p2p_send`` /
    ``dist.p2p_recv`` fault sites cover KV streaming too."""

    def __init__(self):
        self._queues = {}
        self._lock = threading.Lock()

    def _queue(self, name):
        with self._lock:
            q = self._queues.get(name)
            if q is None:
                q = self._queues[name] = queue.Queue()
            return q

    def send(self, payload, dst, deadline_s=DEFAULT_DEADLINE_S):
        deadline_guard("dist.p2p_send", deadline_s)
        self._queue(dst).put(payload)

    def recv(self, dst, timeout=DEFAULT_DEADLINE_S):
        remaining = deadline_guard("dist.p2p_recv", timeout,
                                   tag=str(dst))
        try:
            return self._queue(dst).get(
                timeout=remaining if remaining is not None else timeout)
        except queue.Empty:
            monitor.stat_add("serving.kv_migrate_timeouts")
            raise PeerGoneError(
                f"no KV payload for {dst!r} within {timeout:.3f}s "
                "(prefill replica gone or wedged mid-migration)")


def payload_bytes(payload):
    return int(sum(k.nbytes + v.nbytes for k, v in payload["layers"]))


def migrate_prefix(src_engine, dst_engine, ids, mailbox=None,
                   deadline_s=DEFAULT_DEADLINE_S):
    """Move the cached KV prefix for token ids `ids` from `src_engine`
    to `dst_engine`. Returns the number of prompt tokens now cached on
    the destination (0 = nothing exportable or adoption aborted); any
    mailbox/adoption error propagates to the caller, which falls back
    to colocated dispatch — the request stays replayable either way."""
    payload = src_engine.export_prefix_blocks(ids)
    if payload is None:
        return 0
    box = mailbox if mailbox is not None else KVMailbox()
    box.send(payload, dst_engine.name, deadline_s=deadline_s)
    got = box.recv(dst_engine.name, timeout=deadline_s)
    adopted = dst_engine.adopt_prefix_blocks(got)
    if adopted:
        m = dst_engine.metrics
        nblocks = len(got["layers"][0][0]) if got["layers"] else 0
        m.inc("kv_migrations")
        m.inc("kv_migrate_blocks", nblocks)
        m.inc("kv_migrate_bytes", payload_bytes(got))
    return adopted
