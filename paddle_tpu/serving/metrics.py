"""Serving metrics registry: counters, occupancy, latency percentiles.

Ref parity: the reference's serving stack exports brpc/bvar counters
(qps, latency quantiles, queue depth); here one registry aggregates the
same signals host-side and exports them as JSON. Latency series are also
recorded as `profiler.RecordEvent` spans by the engine/batcher, so the
same numbers land in the chrome trace and `profiler.percentiles` agrees
with `snapshot()`.
"""

from __future__ import annotations

import json
import threading
import time

from ..framework import monitor
from ..utils.stats import percentile  # noqa: F401  (shared quantile math)

__all__ = ["ServingMetrics", "percentile"]

# keep at most this many samples per latency series (fifo window) so a
# long-lived server doesn't grow without bound
_MAX_SAMPLES = 65536


class ServingMetrics:
    """Thread-safe counters + occupancy + latency series.

    Counter names mirror the admission queue's (`submitted`, `accepted`,
    `rejected_queue_full`, `rejected_closed`, `timeouts`, `cancelled`)
    plus engine-side `completed`, `failed`, `steps`, `batches`,
    `tokens_out`, `prefills`, and the paged-KV set: `prefill_tokens`
    (prompt positions written by chunked prefill), `prompt_tokens` /
    `prefix_lookups` / `prefix_hit_blocks` / `prefix_hit_tokens` /
    `cow_splits` (prefix-cache traffic), `rejected_capacity` (429 sheds
    whose block demand exceeds the pool), and the fast-decode set:
    `spec_drafted_tokens` / `spec_accepted_tokens` /
    `spec_rejected_tokens` / `spec_rounds` / `spec_draft_faults`
    (speculative decoding, fed via `observe_spec`, surfaced under
    snapshot()["speculative"] with per-slot acceptance rates and the
    `dequant_path` gauge). The fleet (fleet.py) adds its
    own family over the same registry: `fleet_submitted` /
    `fleet_completed` / `fleet_failed` (client-level, exactly-once),
    `routed`, `retries`, `replays`, `hedges`, `hedge_wins`,
    `duplicates_suppressed`, `stale_attempts`, `parked`,
    `replica_deaths`, `replica_restarts`, `brownout_entries`,
    `brownout_sheds`, `retry_budget_exhausted`, `supervisor_errors`,
    and the elastic set: `replicas_added` / `replicas_removed` (scale
    events that landed), `drains_started`, `drain_errors`,
    `scale_failures` (autoscaler actions that raised). Mesh-sharded
    serving adds `kv_migrations` / `kv_migrate_blocks` /
    `kv_migrate_bytes` / `kv_migrate_faults` (prefill->decode KV block
    streaming) surfaced with the mesh shape, per-shard occupancy and
    disaggregation role under snapshot()["mesh"] (see `note_mesh` /
    `note_role`). The persistent KV tier (kvstore.py) adds
    `kv_spilled_blocks` / `kv_restored_blocks` / `kv_invalidated_blocks`
    / `kv_spill_bytes` / `kv_restore_corrupt` / `kv_restore_fenced` /
    `kv_spill_errors`, surfaced under snapshot()["kvstore"], and the
    prefix-affinity Router adds `affinity_hits` / `affinity_faults`.
    Multi-tenant serving bills per-tenant counters/latency/gauges via
    `tenant_inc` / `tenant_observe_latency` / `tenant_set_gauge`,
    surfaced under snapshot()["tenants"] and the paddle_tenant_*
    Prometheus families (qps, tokens, shed, p50/p95/p99, budget).
    Every inc() also bumps the global `framework.monitor` counter
    ``serving.<name>`` so serving shows up in the same stat registry as
    the rest of the runtime.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict = {}
        self._latency: dict = {}      # kind -> [seconds]
        self._mesh = None             # (spec, devices) when mesh-sharded
        self._role = None             # disagg role ('prefill'/'decode')
        self._occ_sum = 0.0
        self._occ_n = 0
        self._occ_max = 0.0
        self._blk_last = (0, 0)       # (in_use, total) at last step
        self._blk_sum = 0.0
        self._blk_n = 0
        self._blk_max = 0.0
        self._gauges: dict = {}       # name -> float (last-write-wins)
        self._spec_slots: dict = {}   # slot -> [drafted, accepted]
        # per-tenant accounting (ISSUE 20): tenant name ->
        # {"counters": {...}, "latency": [s], "gauges": {...}} — fed by
        # tenant_inc/tenant_observe_latency/tenant_set_gauge, surfaced
        # under snapshot()["tenants"] and the paddle_tenant_* Prometheus
        # families. Created lazily; absent in single-tenant serving.
        self._tenants: dict = {}
        self._started = time.monotonic()

    def set_gauge(self, name, value):
        """Last-write-wins scalar (e.g. `dequant_path` = 1.0 while an
        int8-frozen engine serves)."""
        with self._lock:
            self._gauges[name] = float(value)

    def note_mesh(self, spec, devices):
        """Record the serving mesh shape (e.g. 'dp1.mp2' over 2
        devices): turns on the snapshot()['mesh'] section and the
        paddle_serving_mesh_* Prometheus family."""
        with self._lock:
            self._mesh = (str(spec), int(devices))

    def note_role(self, role):
        """Disaggregation role of the replica this registry serves
        ('any' / 'prefill' / 'decode') — surfaced as the mesh-family
        role gauge."""
        with self._lock:
            self._role = str(role)

    def observe_spec(self, slot, drafted, accepted):
        """One speculative round's outcome for one slot: `drafted`
        proposals went into the verify step, `accepted` survived.
        Feeds the spec_* counters and the per-slot acceptance gauges."""
        with self._lock:
            cell = self._spec_slots.setdefault(int(slot), [0, 0])
            cell[0] += int(drafted)
            cell[1] += int(accepted)
        self.inc("spec_drafted_tokens", int(drafted))
        self.inc("spec_accepted_tokens", int(accepted))
        self.inc("spec_rejected_tokens", int(drafted) - int(accepted))

    def inc(self, name, n=1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n
        monitor.stat_add(f"serving.{name}", n)

    # -- per-tenant accounting (ISSUE 20) -----------------------------------

    def _tenant_cell(self, tenant):
        # caller holds self._lock
        cell = self._tenants.get(tenant)
        if cell is None:
            cell = {"counters": {}, "latency": [], "gauges": {}}
            self._tenants[str(tenant)] = cell
        return cell

    def tenant_inc(self, tenant, name, n=1):
        """Bump one tenant-scoped counter (`submitted`, `accepted`,
        `shed`, `completed`, `failed`, `tokens_out`, ...)."""
        if tenant is None:
            return
        with self._lock:
            c = self._tenant_cell(tenant)["counters"]
            c[name] = c.get(name, 0) + n
        monitor.stat_add(f"serving.tenant.{tenant}.{name}", n)

    def tenant_observe_latency(self, tenant, seconds):
        """One end-to-end latency sample billed to `tenant`."""
        if tenant is None:
            return
        with self._lock:
            series = self._tenant_cell(tenant)["latency"]
            series.append(float(seconds))
            if len(series) > _MAX_SAMPLES:
                del series[:len(series) - _MAX_SAMPLES]

    def tenant_set_gauge(self, tenant, name, value):
        """Last-write-wins tenant-scoped scalar (e.g. remaining token
        budget)."""
        if tenant is None:
            return
        with self._lock:
            self._tenant_cell(tenant)["gauges"][name] = float(value)

    def tenant_get(self, tenant, name):
        with self._lock:
            cell = self._tenants.get(tenant)
            return cell["counters"].get(name, 0) if cell else 0

    def tenant_latency_percentiles(self, tenant, ps=(50, 95, 99)):
        with self._lock:
            cell = self._tenants.get(tenant)
            series = list(cell["latency"]) if cell else []
        if not series:
            return {p: None for p in ps}
        return {p: percentile(series, p) for p in ps}

    def get(self, name):
        with self._lock:
            return self._counters.get(name, 0)

    def observe_latency(self, kind, seconds):
        with self._lock:
            series = self._latency.setdefault(kind, [])
            series.append(float(seconds))
            if len(series) > _MAX_SAMPLES:
                del series[:len(series) - _MAX_SAMPLES]

    def observe_occupancy(self, active, capacity):
        """One decode-step sample of slot utilisation (active/capacity)."""
        frac = active / max(capacity, 1)
        with self._lock:
            self._occ_sum += frac
            self._occ_n += 1
            self._occ_max = max(self._occ_max, frac)

    def observe_blocks(self, in_use, total):
        """One decode-step sample of KV block-pool utilisation."""
        frac = in_use / max(total, 1)
        with self._lock:
            self._blk_last = (int(in_use), int(total))
            self._blk_sum += frac
            self._blk_n += 1
            self._blk_max = max(self._blk_max, frac)

    def latency_percentiles(self, kind, ps=(50, 95, 99), last=None):
        """{p: seconds} over the recorded `kind` series. ``last``
        restricts to the most recent N samples — the autoscaler's
        sliding SLO window, so old congestion doesn't pin the signal
        high after the fleet recovers."""
        with self._lock:
            series = list(self._latency.get(kind, ()))
        if last is not None:
            series = series[-int(last):]
        if not series:
            return {p: None for p in ps}
        return {p: percentile(series, p) for p in ps}

    def snapshot(self, queue_depth=None):
        """One JSON-able view: counters, QPS, tokens/s, occupancy,
        p50/p95/p99 per latency series."""
        with self._lock:
            counters = dict(self._counters)
            latency = {k: list(v) for k, v in self._latency.items()}
            occ_avg = self._occ_sum / self._occ_n if self._occ_n else 0.0
            occ_max = self._occ_max
            blk_last, blk_n = self._blk_last, self._blk_n
            blk_avg = self._blk_sum / self._blk_n if self._blk_n else 0.0
            blk_max = self._blk_max
            elapsed = max(time.monotonic() - self._started, 1e-9)
        snap = {
            "counters": counters,
            "uptime_s": elapsed,
            "qps": counters.get("completed", 0) / elapsed,
            "tokens_per_s": counters.get("tokens_out", 0) / elapsed,
            "batch_occupancy": {"avg": occ_avg, "max": occ_max,
                                "samples": self._occ_n},
            "latency_s": {},
        }
        if blk_n:
            snap["kv_blocks"] = {
                "in_use": blk_last[0], "total": blk_last[1],
                "occupancy": blk_avg, "occupancy_max": blk_max,
                "samples": blk_n,
            }
        if counters.get("prefix_lookups"):
            prompt = counters.get("prompt_tokens", 0)
            hit = counters.get("prefix_hit_tokens", 0)
            snap["prefix_cache"] = {
                "lookups": counters["prefix_lookups"],
                "hit_blocks": counters.get("prefix_hit_blocks", 0),
                "hit_tokens": hit,
                "prompt_tokens": prompt,
                "hit_rate": hit / prompt if prompt else 0.0,
            }
        if counters.get("kv_spilled_blocks") \
                or counters.get("kv_restored_blocks") \
                or counters.get("kv_invalidated_blocks") \
                or counters.get("kv_restore_corrupt"):
            snap["kvstore"] = {
                "spilled_blocks": counters.get("kv_spilled_blocks", 0),
                "restored_blocks": counters.get("kv_restored_blocks", 0),
                "invalidated_blocks":
                    counters.get("kv_invalidated_blocks", 0),
                "spill_bytes": counters.get("kv_spill_bytes", 0),
                "restore_corrupt": counters.get("kv_restore_corrupt", 0),
                "restore_fenced": counters.get("kv_restore_fenced", 0),
                "spill_errors": counters.get("kv_spill_errors", 0),
            }
        if counters.get("prefill_tokens"):
            steps = counters.get("steps", 0)
            snap["chunked_prefill"] = {
                "tokens": counters["prefill_tokens"],
                "tokens_per_step":
                    counters["prefill_tokens"] / steps if steps else 0.0,
            }
        with self._lock:
            gauges = dict(self._gauges)
            spec_slots = {k: tuple(v) for k, v in self._spec_slots.items()}
        if counters.get("spec_drafted_tokens") or spec_slots \
                or gauges.get("dequant_path"):
            drafted = counters.get("spec_drafted_tokens", 0)
            accepted = counters.get("spec_accepted_tokens", 0)
            snap["speculative"] = {
                "drafted_tokens": drafted,
                "accepted_tokens": accepted,
                "rejected_tokens": counters.get("spec_rejected_tokens", 0),
                "rounds": counters.get("spec_rounds", 0),
                "draft_faults": counters.get("spec_draft_faults", 0),
                "acceptance_rate": accepted / drafted if drafted else 0.0,
                "per_slot_acceptance": {
                    str(s): a / d if d else 0.0
                    for s, (d, a) in sorted(spec_slots.items())},
                "dequant_path": gauges.get("dequant_path", 0.0),
            }
        with self._lock:
            mesh, role = self._mesh, self._role
        if mesh is not None or role is not None \
                or counters.get("kv_migrations") \
                or counters.get("kv_migrate_faults"):
            spec, devices = mesh if mesh is not None else ("", 1)
            snap["mesh"] = {
                "spec": spec,
                "devices": devices,
                "role": role or "any",
                # GSPMD runs the SAME program on every shard, so each
                # shard's slot occupancy equals the replica's — emitted
                # per shard anyway so a future uneven layout shows up
                "per_shard_occupancy": [
                    {"shard": i, "occupancy": occ_avg}
                    for i in range(devices)],
                "kv_migrations": counters.get("kv_migrations", 0),
                "kv_migrate_blocks": counters.get("kv_migrate_blocks", 0),
                "kv_migrate_bytes": counters.get("kv_migrate_bytes", 0),
                "kv_migrate_faults": counters.get("kv_migrate_faults", 0),
            }
        with self._lock:
            tenants = {
                t: {"counters": dict(c["counters"]),
                    "latency": list(c["latency"]),
                    "gauges": dict(c["gauges"])}
                for t, c in self._tenants.items()}
        if tenants:
            snap["tenants"] = {}
            for t in sorted(tenants):
                cell = tenants[t]
                c, series = cell["counters"], cell["latency"]
                entry = {
                    "counters": c,
                    "qps": c.get("completed", 0) / elapsed,
                    "tokens_per_s": c.get("tokens_out", 0) / elapsed,
                    "gauges": cell["gauges"],
                }
                if series:
                    entry["latency_s"] = {
                        "count": len(series),
                        "p50": percentile(series, 50),
                        "p95": percentile(series, 95),
                        "p99": percentile(series, 99),
                        "max": max(series),
                    }
                snap["tenants"][t] = entry
        if queue_depth is not None:
            snap["queue_depth"] = queue_depth
        for kind, series in latency.items():
            if series:
                snap["latency_s"][kind] = {
                    "count": len(series),
                    "p50": percentile(series, 50),
                    "p95": percentile(series, 95),
                    "p99": percentile(series, 99),
                    "max": max(series),
                }
        return snap

    def to_json(self, queue_depth=None, **dump_kw):
        return json.dumps(self.snapshot(queue_depth=queue_depth),
                          **dump_kw)
