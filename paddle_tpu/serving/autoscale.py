"""SLO-aware autoscaler: grow/shrink the serving fleet on error budget.

Ref parity: the reference's Fleet lineage treats elasticity as a
first-class robustness property on the *training* side (ElasticManager
heartbeats + rescale); this is the serving-side counterpart. The
`Autoscaler` watches the signals the fleet already exports — windowed
e2e p99 vs `FLAGS_fleet_slo_p99_ms`, in-flight / capacity utilisation,
backlog pressure (outstanding Router futures per decode slot — loud
even while a replica rebuild has stalled completions), and brownout
state — and converts sustained error-budget burn into membership
changes on the `ReplicaSet`:

- **Scale up** (overloaded, cooldown elapsed, below
  `FLAGS_fleet_max_replicas`): one `add_replica()` on a background
  thread — the build traces a fresh engine and must never block the
  supervisor tick that drives heartbeat watchdogs. The newcomer warms
  up behind the single-trace restart path and turns healthy with
  ``compile_counts == {"decode": 1, "cow": 1}``; at most one build is
  in flight at a time.
- **Scale down** (idle for a full cooldown, above
  `FLAGS_fleet_min_replicas`): drain-then-evict via
  `remove_replica(drain=True)` — non-blocking; the watchdog evicts the
  victim once its queue and slots empty, so shrinking the fleet loses
  and duplicates nothing.

Hysteresis is the pair of watermarks (`high_water`/`low_water` on
utilisation) plus the cooldown between *any* two actions; both
directions also require their condition to persist (`up_sustain_s`,
down = the cooldown itself), so a single slow request or one idle tick
never flaps the fleet. Every action failure increments
`scale_failures` and never kills the supervisor.

Gauges land in the global monitor registry each tick —
``fleet.target_replicas``, ``fleet.live_replicas``,
``fleet.slo_violation_ms`` (error-budget burn while windowed p99 is
over SLO) — next to the ``fleet.scale_events_up/down`` counters the
ReplicaSet bumps on every membership change (manual or autoscaled);
observe/export.py turns them into the ``paddle_fleet_*`` Prometheus
family.
"""

from __future__ import annotations

import threading
import time

from ..framework import monitor
from ..framework.flags import flag

__all__ = ["Autoscaler", "SLOWindow"]


class SLOWindow:
    """Freshness-gated windowed e2e p99 — the autoscaler's staleness
    rule factored out so the rollout canary/sustain SLO burn gate
    reads the IDENTICAL signal the autoscaler scales on.

    The percentile window is samples, not time: once traffic stops,
    old congested samples would pin p99 high forever. A window with no
    `fleet_completed` progress for `freshness_s` is stale — `p99_s()`
    returns None (no traffic means no SLO burn).
    """

    def __init__(self, metrics, *, kind="e2e", window=64,
                 freshness_s=5.0, counter="fleet_completed",
                 clock=time.monotonic):
        self.metrics = metrics
        self.kind = kind
        self.window = int(window)
        self.freshness_s = float(freshness_s)
        self.counter = counter
        self._clock = clock
        self._last = -1
        self._last_t = None

    def p99_s(self, now=None):
        """Windowed p99 in seconds, or None while the window is stale
        (no completions for `freshness_s`) or still empty."""
        now = self._clock() if now is None else now
        completed = self.metrics.get(self.counter)
        if completed != self._last:
            self._last = completed
            self._last_t = now
        if self._last_t is None or now - self._last_t >= self.freshness_s:
            return None
        return self.metrics.latency_percentiles(
            self.kind, (99,), last=self.window)[99]


class Autoscaler:
    """Drives `ReplicaSet.add_replica`/`remove_replica` from SLO burn.

    Constructed by `Router.start()` when the Router got `autoscale=`
    (True for flag defaults, or a kwargs dict), or by hand in tests:
    ``Autoscaler(router, ...)`` attaches itself as `router.autoscaler`
    and is then ticked by the Router's supervisor thread. `clock` is
    injectable so unit tests drive cooldowns without sleeping.
    """

    def __init__(self, router, *, min_replicas=None, max_replicas=None,
                 slo_p99_ms=None, cooldown_s=None, high_water=0.85,
                 low_water=0.30, backlog_factor=3.0, up_sustain_s=0.0,
                 window=64, clock=time.monotonic):
        self.router = router
        self.min_replicas = int(
            flag("FLAGS_fleet_min_replicas") if min_replicas is None
            else min_replicas)
        self.max_replicas = int(
            flag("FLAGS_fleet_max_replicas") if max_replicas is None
            else max_replicas)
        self.slo_p99_ms = float(
            flag("FLAGS_fleet_slo_p99_ms") if slo_p99_ms is None
            else slo_p99_ms)
        self.cooldown_s = float(
            flag("FLAGS_fleet_scale_cooldown_s") if cooldown_s is None
            else cooldown_s)
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas ({self.min_replicas}) <= "
                f"max_replicas ({self.max_replicas})")
        if not 0.0 <= low_water < high_water:
            raise ValueError(
                f"need 0 <= low_water ({low_water}) < high_water "
                f"({high_water})")
        self.high_water = float(high_water)
        self.low_water = float(low_water)
        self.backlog_factor = float(backlog_factor)
        self.up_sustain_s = float(up_sustain_s)
        self.window = int(window)
        self._clock = clock
        self._closed = False
        self._scale_thread = None     # at most one build in flight
        self._last_action = None      # clock time of last up/down
        self._over_since = None       # overload onset (sustain gate)
        self._idle_since = None       # idleness onset (sustain gate)
        self._last_tick = None
        # freshness-gated windowed p99 (shared with the rollout gate)
        self._slo = SLOWindow(router.metrics, window=self.window,
                              freshness_s=self.cooldown_s, clock=clock)
        self.target = None            # desired membership; set lazily
        self.violation_s = 0.0        # cumulative time over SLO
        self.decisions = {"up": 0, "down": 0}
        router.autoscaler = self

    # -- signals ------------------------------------------------------------

    def _signals(self, now):
        rs = self.router.replica_set
        # freshness-gated windowed p99 (SLOWindow): a window with no
        # completion for a full cooldown is stale and reads None — no
        # traffic means no SLO burn, so a quiet fleet never wedges at
        # peak size on old congested samples.
        p99 = self._slo.p99_s(now)
        over_slo = p99 is not None and p99 * 1e3 > self.slo_p99_ms
        util = rs.in_flight() / max(rs.capacity(), 1)
        # backlog pressure: outstanding Router futures per decode slot.
        # Unlike p99 (needs fresh completions) and util (diluted by the
        # queue caps in `capacity()`), this stays loud while a replica
        # rebuild has stalled completions — exactly when help is needed.
        pressure = self.router.in_flight / max(rs.slot_capacity(), 1)
        backlogged = pressure >= self.backlog_factor
        brown = self.router.brownout_active
        return {
            "p99_s": p99, "over_slo": over_slo, "util": util,
            "pressure": pressure, "brownout": brown,
            "overloaded": (over_slo or brown or backlogged
                           or util >= self.high_water),
            "idle": (util <= self.low_water and pressure <= 1.0
                     and not over_slo and not brown),
            "live": rs.live_replicas(), "members": rs.member_replicas(),
        }

    # -- the supervisor tick ------------------------------------------------

    def tick(self, now=None):
        """One control-loop pass; called from `Router._supervise` (and
        directly by tests). Never raises: action failures are counted
        and the fleet keeps serving at its current size."""
        if self._closed:
            return None
        now = self._clock() if now is None else now
        sig = self._signals(now)
        if self.target is None:
            self.target = sig["members"]
        # error-budget burn: integrate wall time spent over SLO
        if self._last_tick is not None and sig["over_slo"]:
            self.violation_s += max(now - self._last_tick, 0.0)
        self._last_tick = now
        monitor.stat_set("fleet.target_replicas", self.target)
        monitor.stat_set("fleet.live_replicas", sig["live"])
        monitor.stat_set("fleet.slo_violation_ms",
                         int(self.violation_s * 1e3))
        # sustain gates (hysteresis in time, not just level)
        self._over_since = (self._over_since or now) \
            if sig["overloaded"] else None
        self._idle_since = (self._idle_since or now) \
            if sig["idle"] else None
        in_cooldown = (self._last_action is not None
                       and now - self._last_action < self.cooldown_s)
        if in_cooldown:
            return sig
        building = (self._scale_thread is not None
                    and self._scale_thread.is_alive())
        if sig["overloaded"] and not building \
                and now - self._over_since >= self.up_sustain_s \
                and sig["members"] < self.max_replicas:
            self._scale_up(now, sig)
        elif sig["idle"] and not building \
                and now - self._idle_since >= self.cooldown_s \
                and sig["live"] > max(self.min_replicas, 1):
            self._scale_down(now, sig)
        return sig

    # -- actions ------------------------------------------------------------

    def _scale_up(self, now, sig):
        self.decisions["up"] += 1
        self.target = min(sig["members"] + 1, self.max_replicas)
        self._last_action = now

        def build():
            try:
                self.router.replica_set.add_replica()
            except Exception:  # noqa: BLE001 — fleet keeps serving
                self.router.metrics.inc("scale_failures")

        self._scale_thread = threading.Thread(
            target=build, name=f"{self.router.name}-scale-up",
            daemon=True)
        self._scale_thread.start()

    def _scale_down(self, now, sig):
        rs = self.router.replica_set
        # victim: least-loaded healthy replica, newest first — the
        # original floor replicas stay, scale-up surge capacity leaves
        victims = sorted(rs.healthy(),
                         key=lambda r: (r.load, -r.index))
        if not victims:
            return
        self.decisions["down"] += 1
        self.target = max(sig["members"] - 1, self.min_replicas)
        self._last_action = now
        try:
            rs.remove_replica(victims[0].name, drain=True)
        except Exception:  # noqa: BLE001 — e.g. lost a race with deaths
            self.router.metrics.inc("scale_failures")

    # -- lifecycle ----------------------------------------------------------

    def close(self, timeout=10.0):
        """Stop deciding; wait for an in-flight build to settle so a
        shutdown never races a half-built replica."""
        self._closed = True
        t = self._scale_thread
        if t is not None and t.is_alive():
            t.join(timeout)

    def snapshot(self):
        return {
            "target": self.target,
            "min": self.min_replicas, "max": self.max_replicas,
            "slo_p99_ms": self.slo_p99_ms,
            "cooldown_s": self.cooldown_s,
            "violation_s": self.violation_s,
            "decisions": dict(self.decisions),
            "building": (self._scale_thread is not None
                         and self._scale_thread.is_alive()),
        }
