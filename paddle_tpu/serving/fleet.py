"""Resilient serving fleet: replica supervision, failover, retry/hedge.

Ref parity: the reference serves through paddle_serving's brpc front
over a *pool* of predictors with health checks and fast rejection on
overload; a sidecar supervisor restarts dead workers. Here the pool is
in-process: a `ReplicaSet` supervises N thread-backed `SlotEngine`
replicas (shared weights, private KV pools/queues) with step-heartbeat
liveness watchdogs, and a `Router` fronts them with the full
availability toolkit:

- **Failover replay.** A replica that crashes or stops heartbeating is
  declared dead, evicted, and rebuilt with exponential backoff +
  deterministic jitter. Its in-flight requests are replayed *from the
  original prompt* on a healthy replica. The client future is
  first-wins (queueing.Request), so even if the "dead" replica was
  merely hung and later completes, exactly one outcome is delivered —
  dedup is on the client request id; greedy replay is bitwise
  token-identical because decode is deterministic in the weights.
- **Retries.** Retriable failures (`CapacityExhaustedError`, injected
  `FaultError`, transient routing errors) are retried under a
  per-request retry budget with deadline propagation: each attempt's
  timeout is the *remaining* client deadline, never a fresh one.
  Failover replays charge a separate replay budget, not the retry
  budget — a replica dying is the fleet's fault, not the request's.
- **Hedging.** A request whose single attempt outlives a p95-based
  delay (2x observed e2e p95, floored at `hedge_min_s`; or a fixed
  `hedge_after_s`) gets a second attempt on a *different* replica.
  First completion wins, the loser is cancelled (its slot is
  reclaimed at the next step boundary), and the late outcome is
  suppressed by the first-wins future.
- **Graceful degradation.** Per-replica circuit breakers open after
  `breaker_threshold` consecutive failures, park the replica for
  `breaker_cooloff_s`, then admit a single half-open probe whose
  outcome closes or re-opens the breaker. Brownout mode — entered on
  sustained load above `brownout_high` (fraction of total slot+queue
  capacity), exited below `brownout_low`, or forced via
  `set_brownout()` — clamps `max_new_tokens` and sheds requests whose
  `priority` is below the floor with the retriable 429
  `BrownoutShedError`.

- **Elastic membership.** `ReplicaSet.add_replica()` grows the fleet
  under load: the new replica is visible in `starting` state (never
  routed to) while its engine builds behind the same single-trace
  restart path every rebuild uses, then turns healthy with
  ``compile_counts == {"decode": 1, "cow": 1}``.
  `remove_replica(name, drain=True)` shrinks it as drain-then-evict:
  the victim turns `draining` (the Router stops picking it
  immediately), finishes its in-flight and queued requests, and is
  evicted by the watchdog once idle — so a scale-down loses and
  duplicates nothing, certified by the same first-wins futures that
  cover failover. A draining replica that dies mid-drain takes the
  normal failover-replay path and is then dropped instead of
  restarted. `serving/autoscale.py` drives both ends from the SLO
  error budget.

- **Disaggregated prefill/decode** (``FLAGS_serving_disagg`` or
  ``Router(disagg=True)``). Replicas carry a `role` ("prefill" /
  "decode" / "any", assigned via ``roles=`` and specialized via
  ``role_kw=`` engine overrides — typically a wide ``prefill_chunk``
  for prefill replicas, a narrow one for decode). Each new request's
  first leg goes to a prefill-role replica with ``max_new_tokens=1``
  (the produced token is discarded); on success a migration thread
  streams the finished KV blocks to a decode replica over the
  deadline-guarded mailbox (serving/migrate.py), then the decode leg
  dispatches with one-shot affinity to the adopting replica and a pin
  to the prefill leg's weight version — a wave can never mix weight
  versions within one request. Any failure (no roles healthy,
  migration fault/timeout, adoption abort) degrades the request to
  ordinary colocated dispatch; failover replay and first-wins dedup
  apply to both legs unchanged. Prefill legs are never hedged.

- **Prefix-cache-aware routing** (``FLAGS_serving_prefix_affinity``,
  on by default). Every submit computes the same cumulative sha1
  block-boundary digests the radix `PrefixCache` indexes on and
  consults a sticky digest -> replica table, steering the request to
  the replica holding the longest live match for its token prefix —
  multi-turn sessions keep landing where their KV already is, so turn
  N pays decode-only latency instead of a full re-prefill. This
  generalizes the one-shot adopted-KV ``prefer`` affinity into
  sticky-with-failover: when the affine replica is dead, draining,
  breaker-open, excluded, or version-mismatched, dispatch falls
  through to the normal least-loaded pick, and the table re-sticks to
  wherever the request actually lands (the new replica re-prefills —
  or restores from the SSD spill tier, serving/kvstore.py — and
  becomes the session's new home). The ``serving.affinity`` fault
  site fires per affinity decision; an injected raise degrades that
  one decision to least-loaded placement. Per-replica affinity hits
  and engine-local prefix hit rates export via
  ``snapshot()["affinity"]``.

Chaos sites (framework/faults.py): ``serving.replica_step`` and
``serving.replica_heartbeat`` fire inside supervised engine loops
(tagged with the replica name, so ``serving.replica_step[fleet.r0]``
hangs exactly one replica), ``serving.route`` on every Router dispatch,
``serving.replay`` on every failover replay, ``serving.scale_up`` /
``serving.scale_down`` on every membership change and ``serving.drain``
on every drained-victim eviction attempt (all three tagged with the
replica name). `faults.ChaosSchedule` certifies a scripted sweep
actually delivered every planned fire.

Threading/locking: one re-entrant Router lock guards flight state;
engine done-callbacks run on engine threads and re-enter the Router
through it. The ReplicaSet's own lock covers only replica state
transitions and is never held across Router calls; queue condition
locks never run callbacks (queueing.py resolves futures outside its
locks) — so the lock order Router -> queue is acyclic.
"""

from __future__ import annotations

import random
import threading
import time

from ..framework import faults, monitor
from ..framework.flags import flag
from .engine import SlotEngine
from .metrics import ServingMetrics
from .paging import PrefixCache
from .queueing import (
    AdmissionQueue, BrownoutShedError, DeadlineExceededError, Request,
    RequestCancelled, ReplicaDiedError, RetriesExhaustedError, ServerClosedError,
    ServingError, TenantBudgetError, TenantFairQueue, VersionRetiredError,
)

__all__ = ["CircuitBreaker", "Replica", "ReplicaSet", "Router", "retriable",
           "REPLICA_STATE_CODES"]

#: numeric encodings for the per-replica state gauge (observe/export.py);
#: "healthy" is the serving state, "draining" a scale-down victim
#: finishing its in-flight work before eviction
REPLICA_STATE_CODES = {"starting": 0, "healthy": 1, "dead": 2,
                       "backoff": 3, "stopped": 4, "draining": 5}


def retriable(error):
    """May the fleet transparently re-run the same request after this
    failure? Client-caused outcomes (cancel, deadline) never are;
    injected `FaultError`s model transient infrastructure errors and
    are; everything else consults the error's own `retriable` attr
    (see queueing.ServingError)."""
    if isinstance(error, (RequestCancelled, DeadlineExceededError)):
        return False
    if isinstance(error, TenantBudgetError):
        # the token bucket is the TENANT's, shared by every replica —
        # a retry elsewhere re-debits the same bucket and still fails;
        # surface the 429 + Retry-After to the client instead
        return False
    if isinstance(error, faults.FaultError):
        return True
    return bool(getattr(error, "retriable", False))


class CircuitBreaker:
    """Per-replica failure gate: closed -> open after `threshold`
    consecutive failures -> (after `cooloff_s`) half-open admitting one
    probe -> closed on probe success, re-open on probe failure.

    `clock` is injectable so unit tests drive the cooloff without
    sleeping. Thread-safe; `allow()` has the probe side effect (at most
    one caller wins the half-open slot per cooloff window).
    """

    def __init__(self, threshold=5, cooloff_s=1.0, clock=time.monotonic):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self.threshold = threshold
        self.cooloff_s = cooloff_s
        self._clock = clock
        self._lock = threading.Lock()
        self.state = "closed"
        self.failures = 0          # consecutive
        self._opened_at = None
        self._probing = False

    def allow(self):
        """May a request be routed here right now? In half-open state
        only the first caller gets True (the probe)."""
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open" and \
                    self._clock() - self._opened_at >= self.cooloff_s:
                self.state = "half-open"
                self._probing = False
            if self.state == "half-open" and not self._probing:
                self._probing = True
                return True
            return False

    def probe_ready(self):
        """Would `allow()` grant a half-open probe? (No side effect.)"""
        with self._lock:
            if self.state == "open":
                return self._clock() - self._opened_at >= self.cooloff_s
            return self.state == "half-open" and not self._probing

    def record_success(self):
        with self._lock:
            self.state = "closed"
            self.failures = 0
            self._probing = False

    def record_failure(self):
        with self._lock:
            self.failures += 1
            if self.state == "half-open" or self.failures >= self.threshold:
                self.state = "open"
                self._opened_at = self._clock()
                self._probing = False

    def snapshot(self):
        with self._lock:
            return {"state": self.state, "failures": self.failures}


class Replica:
    """One supervised engine slot: the engine itself (rebuilt across
    generations), liveness/restart bookkeeping, and its breaker.

    `role` is the disaggregation assignment: "any" (default) serves
    whole requests; "prefill" replicas only take the prefill leg of a
    disaggregated flight and stream their finished KV blocks out;
    "decode" replicas take everything except prefill legs. Roles are
    routing hints on the Router side — the engine itself is identical."""

    def __init__(self, index, name, breaker, role="any"):
        self.index = index
        self.name = name
        self.role = role
        self.engine: SlotEngine | None = None
        self.generation = 0       # bumped per (re)build
        self.state = "starting"   # REPLICA_STATE_CODES keys
        self.deaths = 0
        self.restarts = 0
        self.load = 0             # router-visible in-flight attempts
        self.breaker = breaker
        self.restart_at = None    # monotonic time the backoff expires
        self.built_at = None      # monotonic time the engine last built
        self.drain_started = None  # monotonic time draining began
        # rollout pinning: target_weights is the WeightVersion every
        # (re)build of this replica must load (None = the model's own
        # values, version 0); rebuild_to is set while the replica drains
        # toward an upgrade/downgrade and survives a mid-drain crash
        self.target_weights = None
        self.rebuild_to = None
        # deterministic per-replica jitter stream (seeded on the name)
        self._rng = random.Random(name)

    @property
    def alive(self):
        """Is the engine thread actually running?"""
        e = self.engine
        return (e is not None and e._thread is not None
                and e._thread.is_alive())

    def beat_age(self, now):
        e = self.engine
        return 0.0 if e is None else now - e.last_beat

    def uptime(self, now):
        return 0.0 if self.built_at is None else now - self.built_at

    def idle(self):
        """No router-visible attempts, no occupied slots, empty queue —
        the drain-complete condition for a scale-down victim."""
        e = self.engine
        return (self.load == 0 and e is not None
                and e.active == 0 and e.queue.depth == 0)

    @property
    def weight_version(self):
        """The weight version this replica serves (its live engine's)
        or — with no live engine — the one its next build targets."""
        if self.state in ("starting", "dead", "backoff"):
            wv = self.rebuild_to or self.target_weights
            if wv is not None:
                return wv.version
        if self.engine is not None:
            return self.engine.weight_version
        wv = self.rebuild_to or self.target_weights
        return wv.version if wv is not None else 0

    def snapshot(self):
        e = self.engine
        now = time.monotonic()
        return {
            "name": self.name, "state": self.state,
            "generation": self.generation, "deaths": self.deaths,
            "restarts": self.restarts, "load": self.load,
            "role": self.role,
            "mesh": "" if e is None else e.mesh_spec,
            "weight_version": self.weight_version,
            "heartbeats": 0 if e is None else e.heartbeats,
            "uptime_s": self.uptime(now),
            "beat_age_s": self.beat_age(now),
            "draining_s": (0.0 if self.drain_started is None
                           else now - self.drain_started),
            "breaker": self.breaker.snapshot(),
        }


class ReplicaSet:
    """Supervises N thread-backed `SlotEngine` replicas over one model.

    All replicas share the model weights (and the metrics registry) but
    own private KV pools, admission queues, and compiled callables —
    one fresh decode trace per (re)build, so the fleet's compile
    invariant is one 'decode'/'cow' trace per engine generation.

    Builds are serialized on an internal lock: tracing temporarily
    swaps the model's parameter handles (engine.functional_apply), so
    two replicas must never trace concurrently. Already-compiled
    engines never touch the model object again (fixed shapes, no
    retrace), so serving continues during a sibling's rebuild.

    `poll()` is the watchdog: a healthy replica whose engine thread
    died is a *crash*; one whose heartbeat is older than
    `liveness_timeout_s` is a *hang*. Both are declared dead — the
    `on_death(replica, error)` hook (the Router's failover entry) runs
    first, then `engine.abandon(error)` fails everything still on the
    dead engine, then a rebuild is scheduled after
    ``backoff_base_s * 2^(deaths-1)`` (capped at `backoff_max_s`,
    scaled by deterministic per-replica jitter in [0.5, 1.5)).
    """

    def __init__(self, model, n_replicas=2, *, engine_kw=None, metrics=None,
                 liveness_timeout_s=2.0, backoff_base_s=0.05,
                 backoff_max_s=2.0, breaker_threshold=5,
                 breaker_cooloff_s=1.0, breaker_clock=time.monotonic,
                 queue_cap=None, warmup=True, name="fleet", on_death=None,
                 roles=None, role_kw=None, tenancy=None):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self.model = model
        self.name = name
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.engine_kw = dict(engine_kw or {})
        # disaggregation: roles[i] assigns the i-th initial replica
        # ("any"/"prefill"/"decode"; later additions default to "any");
        # role_kw maps a role to engine_kw overrides so e.g. decode
        # replicas run a narrow prefill_chunk while prefill replicas
        # run a wide one — the specialization the bench measures
        self.roles = list(roles or [])
        self.role_kw = dict(role_kw or {})
        self.queue_cap = queue_cap or flag("FLAGS_serving_queue_cap")
        # multi-tenant admission (ISSUE 20): with a TenantDirectory
        # attached, every replica builds a TenantFairQueue (weighted
        # fair queueing + per-tenant budgets) instead of the plain FIFO
        self.tenancy = tenancy
        self.liveness_timeout_s = liveness_timeout_s
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self._breaker_kw = (breaker_threshold, breaker_cooloff_s,
                            breaker_clock)
        self._warmup = warmup
        self.on_death = on_death
        # committed WeightVersion newcomers build with (None = the
        # model's own values, version 0); RolloutController.commit sets
        # it via retarget() so scale-ups never resurrect an old version
        self.default_weights = None
        self.replicas = [self._new_replica() for _ in range(n_replicas)]
        # chip-time ledger (chip-hours = replica-seconds / 3600): time
        # already banked by evicted/removed engines; live engines add
        # their current uptime in replica_seconds()
        self._banked_replica_s = 0.0
        self._lock = threading.Lock()       # replica state transitions
        self._build_lock = threading.Lock()  # serialize traces
        self._started = False

    def _new_replica(self):
        """Allocate the next replica slot (monotonic index: names never
        recycle across scale-downs, so per-replica tagged fault specs
        and metrics labels stay unambiguous)."""
        i = self._next_index = getattr(self, "_next_index", -1) + 1
        threshold, cooloff_s, clock = self._breaker_kw
        role = self.roles[i] if i < len(self.roles) else "any"
        return Replica(i, f"{self.name}.r{i}",
                       CircuitBreaker(threshold, cooloff_s, clock=clock),
                       role=role)

    def start(self):
        if self._started:
            return self
        for r in self.replicas:
            self._build(r)
        self._started = True
        return self

    def _build(self, replica):
        """(Re)build one replica: fresh queue, fresh engine, fresh
        single trace. The replica turns healthy only once serving."""
        with self._build_lock:
            wv = replica.rebuild_to or replica.target_weights \
                or self.default_weights
            if wv is not None:
                replica.target_weights = wv
                replica.rebuild_to = None
            if self.tenancy is not None:
                q = TenantFairQueue(self.queue_cap,
                                    tenancy=self.tenancy,
                                    metrics=self.metrics)
            else:
                q = AdmissionQueue(self.queue_cap, metrics=self.metrics)
            kw = dict(self.engine_kw)
            kw.update(self.role_kw.get(replica.role, {}))
            eng = SlotEngine(self.model, metrics=self.metrics, queue=q,
                             name=replica.name, supervised=True,
                             values=None if wv is None else wv.values,
                             weight_version=0 if wv is None else wv.version,
                             **kw)
            if self._warmup:
                eng.warmup()
            eng.start()
            replica.engine = eng
            replica.generation += 1
            replica.built_at = time.monotonic()
            replica.state = "healthy"
            replica.restart_at = None

    def healthy(self):
        return [r for r in self.replicas if r.state == "healthy"]

    def live_replicas(self):
        """Replicas currently able to serve traffic (healthy; draining
        ones still *hold* work but take no new routes)."""
        return len(self.healthy())

    def member_replicas(self):
        """Fleet membership the autoscaler sizes against: every replica
        that is serving or will serve again (starting/backoff/dead are
        on their way back; draining/stopped are on their way out)."""
        return sum(1 for r in self.replicas
                   if r.state in ("starting", "healthy", "dead", "backoff"))

    def poll(self, now=None):
        """One watchdog pass: detect crashes/hangs, run due restarts,
        evict scale-down victims that finished draining."""
        now = time.monotonic() if now is None else now
        for r in self.replicas:
            if r.state == "healthy":
                if not r.alive:
                    self.declare_dead(r, "engine thread died")
                elif r.beat_age(now) > self.liveness_timeout_s:
                    self.declare_dead(
                        r, f"no heartbeat for {r.beat_age(now):.2f}s "
                           f"(liveness timeout {self.liveness_timeout_s}s)")
            elif r.state == "backoff" and now >= (r.restart_at or 0):
                self.restart(r)
            elif r.state == "draining":
                if not r.alive or r.beat_age(now) > self.liveness_timeout_s:
                    # a victim dying mid-drain takes the normal failover
                    # path (its in-flight work replays) and is dropped —
                    # unless it was draining toward a rebuild, in which
                    # case declare_dead keeps it pinned to its target
                    self.declare_dead(r, "died while draining")
                elif r.idle():
                    if r.rebuild_to is not None:
                        self._start_rebuild(r)
                    else:
                        self._finish_drain(r)

    def declare_dead(self, replica, reason):
        """Evict one replica: failover hook first (the Router replays
        its in-flight requests while their old attempts are still
        pending — first-wins futures make the race safe), then abandon
        the engine, then schedule the backed-off rebuild — or, for a
        scale-down victim that died mid-drain, drop it for good."""
        with self._lock:
            if replica.state not in ("healthy", "draining"):
                return False
            was_draining = replica.state == "draining"
            replica.state = "dead"
            replica.deaths += 1
            self._bank_uptime(replica)
        self.metrics.inc("replica_deaths")
        err = ReplicaDiedError(f"replica {replica.name} declared dead: "
                               f"{reason}")
        if self.on_death is not None:
            try:
                self.on_death(replica, err)
            except Exception:  # noqa: BLE001 — watchdog must survive
                self.metrics.inc("supervisor_errors")
        old = replica.engine
        if old is not None:
            old.abandon(err)
        if was_draining:
            if replica.rebuild_to is None:
                self._drop(replica)   # it was leaving anyway: no restart
                return True
            # died mid drain->rebuild: NOT a scale-down victim — keep
            # it, pin the restart to the version the rollout assigned
            # (a mid-wave crash must not drift the fleet's version map)
            with self._lock:
                replica.target_weights = replica.rebuild_to
                replica.rebuild_to = None
        with self._lock:
            backoff = min(self.backoff_base_s * (2 ** (replica.deaths - 1)),
                          self.backoff_max_s)
            backoff *= 0.5 + replica._rng.random()
            replica.restart_at = time.monotonic() + backoff
            replica.state = "backoff"
        return True

    def restart(self, replica):
        self._build(replica)
        replica.restarts += 1
        self.metrics.inc("replica_restarts")
        # a rebuilt replica starts with a clean slate
        replica.breaker.record_success()

    def kill(self, name, reason="killed (admin/chaos)"):
        """Admin/chaos hook: declare one replica dead right now, ahead
        of the watchdog. Returns the replica."""
        for r in self.replicas:
            if r.name == name:
                self.declare_dead(r, reason)
                return r
        raise KeyError(f"no replica named {name!r}")

    # -- elastic membership (scale events) ----------------------------------

    def add_replica(self):
        """Scale up by one replica. The newcomer is appended in
        `starting` state — visible to snapshots but never to the
        Router's `_pick` — then built behind the same single-trace
        restart path every rebuild uses (serialized on `_build_lock`,
        one fresh decode+cow trace), and only then turns healthy.
        Blocking (the build traces); run it off the supervisor thread.
        Fault site ``serving.scale_up`` fires before the build."""
        with self._lock:
            replica = self._new_replica()
            self.replicas = self.replicas + [replica]
        try:
            faults.fault_point("serving.scale_up", tag=replica.name)
            self._build(replica)
        except Exception:
            with self._lock:   # roll the membership change back
                replica.state = "stopped"
                self.replicas = [r for r in self.replicas
                                 if r is not replica]
            raise
        self.metrics.inc("replicas_added")
        monitor.stat_add("fleet.scale_events_up")
        return replica

    def remove_replica(self, name, drain=True):
        """Scale down by one replica: drain-then-evict. The victim
        turns `draining` immediately (the Router stops routing to it;
        its queued + in-flight requests keep running) and the watchdog
        evicts it once idle — zero requests lost, zero duplicated,
        certified by the first-wins future machinery. ``drain=False``
        evicts right now instead: in-flight requests take the failover
        replay path. Fault site ``serving.scale_down`` fires before the
        state flips. Returns the replica."""
        victim = None
        with self._lock:
            for r in self.replicas:
                if r.name == name:
                    victim = r
                    break
            if victim is None:
                raise KeyError(f"no replica named {name!r}")
            if victim.state not in ("healthy", "starting"):
                raise ValueError(
                    f"cannot remove replica {name!r} in state "
                    f"{victim.state!r}")
            if self.live_replicas() <= 1 and victim.state == "healthy":
                raise ValueError(
                    "cannot remove the last healthy replica")
        faults.fault_point("serving.scale_down", tag=victim.name)
        with self._lock:
            victim.state = "draining"
            victim.drain_started = time.monotonic()
        self.metrics.inc("drains_started")
        monitor.stat_add("fleet.scale_events_down")
        if not drain:
            self.declare_dead(victim, "evicted (non-drain scale-down)")
        return victim

    def _finish_drain(self, replica):
        """Evict one fully drained scale-down victim. The
        ``serving.drain`` fault site fires per eviction attempt: a
        `raise` leaves the replica draining (retried at the next poll),
        a `delay` models slow teardown."""
        try:
            faults.fault_point("serving.drain", tag=replica.name)
        except Exception:  # noqa: BLE001 — retry at the next poll
            self.metrics.inc("drain_errors")
            return False
        with self._lock:
            if replica.state != "draining":
                return False
            replica.state = "stopped"
            self._bank_uptime(replica)
        e = replica.engine
        if e is not None:
            try:
                e.shutdown(drain=True, timeout=5.0)
            except Exception:  # noqa: BLE001 — best-effort stop
                pass
        self._drop(replica)
        self.metrics.inc("replicas_removed")
        return True

    # -- rolling upgrades (serving.rollout) ----------------------------------

    def rebuild_replica(self, name, weights):
        """Rolling-upgrade entry: mark one healthy replica draining
        with a rebuild target. The Router stops routing to it, its
        in-flight requests FINISH ON THE OLD WEIGHTS (no mid-sequence
        version tear), and once idle the watchdog swaps in a fresh
        engine built on `weights` (a `rollout.WeightVersion`) behind
        the same single-trace `_build` path every restart uses."""
        with self._lock:
            victim = None
            for r in self.replicas:
                if r.name == name:
                    victim = r
                    break
            if victim is None:
                raise KeyError(f"no replica named {name!r}")
            if victim.state != "healthy":
                raise ValueError(
                    f"cannot rebuild replica {name!r} in state "
                    f"{victim.state!r}")
            victim.state = "draining"
            victim.drain_started = time.monotonic()
            victim.rebuild_to = weights
        self.metrics.inc("rollout_rebuilds")
        return victim

    def _start_rebuild(self, replica):
        """A drained upgrade victim: retire its old engine and build
        the replacement on the target weights, off the supervisor
        thread (the build traces — blocking the watchdog would blind
        the rest of the fleet's liveness checks)."""
        with self._lock:
            wv = replica.rebuild_to
            if replica.state != "draining" or wv is None:
                return False
            replica.state = "starting"
            replica.target_weights = wv
            replica.rebuild_to = None
            self._bank_uptime(replica)
        old = replica.engine

        def _swap():
            if old is not None:
                try:
                    old.shutdown(drain=True, timeout=5.0)
                except Exception:  # noqa: BLE001 — best-effort stop
                    pass
            try:
                self._build(replica)
                replica.breaker.record_success()
                self.metrics.inc("rollout_rebuilds_done")
            except Exception:  # noqa: BLE001 — retry via backoff
                self.metrics.inc("supervisor_errors")
                with self._lock:
                    replica.deaths += 1
                    replica.restart_at = (time.monotonic()
                                          + self.backoff_base_s)
                    replica.state = "backoff"

        threading.Thread(target=_swap, name=f"{replica.name}-rollout",
                         daemon=True).start()
        return True

    def versions_live(self):
        """Every weight version some replica serves or will serve after
        its pending (re)build — the Router's pinned-replay oracle: a
        pin outside this set can never be satisfied again."""
        out = set()
        for r in self.replicas:
            if r.state == "stopped":
                continue
            out.add(r.weight_version)
            if r.rebuild_to is not None:
                out.add(r.rebuild_to.version)
        return out

    def retarget(self, weights):
        """Pin the whole membership (and every future member) to one
        WeightVersion: rollout commit/abort calls this so backoff
        restarts and scale-ups land on the surviving version, never on
        one the registry retired."""
        with self._lock:
            self.default_weights = weights
            for r in self.replicas:
                if r.state == "stopped":
                    continue
                r.target_weights = weights
                if r.rebuild_to is not None:
                    r.rebuild_to = weights

    def _drop(self, replica):
        """Remove one replica from the membership list (atomic list
        swap: concurrent iterations keep walking the old snapshot)."""
        with self._lock:
            replica.state = "stopped"
            self.replicas = [r for r in self.replicas if r is not replica]

    def _bank_uptime(self, replica):
        """Move a replica's current engine uptime into the chip-time
        ledger (caller holds `_lock`)."""
        if replica.built_at is not None:
            self._banked_replica_s += time.monotonic() - replica.built_at
            replica.built_at = None

    def replica_seconds(self, now=None):
        """Cumulative engine-alive seconds across the fleet's life —
        the chip-hours denominator bench_fleet.py reports (a replica
        costs its chip whether busy or idle)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            live = sum(now - r.built_at for r in self.replicas
                       if r.built_at is not None)
            return self._banked_replica_s + live

    def compile_counts(self):
        """{replica name: engine compile counters} — the fleet compile
        invariant is every engine at one decode + one cow trace."""
        return {r.name: (r.engine.compile_counts if r.engine else {})
                for r in self.replicas}

    def queue_depth(self):
        return sum(r.engine.queue.depth for r in self.replicas
                   if r.state == "healthy" and r.engine is not None)

    def capacity(self):
        """Total (slots + queue) headroom across healthy replicas."""
        return sum(r.engine.max_slots + r.engine.queue.cap
                   for r in self.healthy() if r.engine is not None)

    def slot_capacity(self):
        """Decode slots across healthy replicas — how many requests the
        fleet can *run* right now, as opposed to merely queue."""
        return sum(r.engine.max_slots
                   for r in self.healthy() if r.engine is not None)

    def in_flight(self):
        return sum(r.engine.active + r.engine.queue.depth
                   for r in self.healthy() if r.engine is not None)

    def snapshot(self):
        now = time.monotonic()
        return {"name": self.name,
                "live_replicas": self.live_replicas(),
                "member_replicas": self.member_replicas(),
                "replica_seconds": self.replica_seconds(now),
                "replicas": [r.snapshot() for r in self.replicas]}

    def shutdown(self, drain=True, timeout=None):
        for r in self.replicas:
            e = r.engine
            if e is not None:
                try:
                    e.shutdown(drain=drain, timeout=timeout)
                except Exception:  # noqa: BLE001 — best-effort stop
                    pass
            r.state = "stopped"
        self._started = False


class _Flight:
    """Router-side state of one client request across its attempts."""

    __slots__ = ("client", "retries_left", "replays_left", "attempts",
                 "live", "stale", "hedge_ids", "hedged", "parked",
                 "first_dispatch", "last_dispatch", "retry_at",
                 "retry_exclude", "versions", "pin", "prefill_ids",
                 "kv_state", "prefer", "prefix_digests")

    def __init__(self, client, retries, replays):
        self.client = client
        self.retries_left = retries
        self.replays_left = replays
        self.attempts: dict = {}   # attempt id -> (replica, attempt req)
        self.live: set = set()     # attempt ids not yet resolved
        self.stale: set = set()    # live ids whose outcome is ignored
        self.hedge_ids: set = set()
        self.hedged = False
        self.parked = False        # no dispatchable replica right now
        self.first_dispatch = None
        self.last_dispatch = None
        self.retry_at = None       # deferred-retry due time
        self.retry_exclude = None
        self.versions: dict = {}   # attempt id -> engine weight version
        self.pin = None            # replay weight-version pin
        # disaggregated prefill/decode bookkeeping
        self.prefill_ids: set = set()  # attempt ids that are prefill legs
        self.kv_state = None       # None / "migrated" / "fallback"
        self.prefer = None         # one-shot replica affinity (adopted KV)
        # cumulative block-boundary prefix digests of the prompt,
        # ascending length — the sticky-affinity lookup keys (longest
        # match wins; empty = prompt shorter than one block)
        self.prefix_digests = ()

    def active(self):
        return [aid for aid in self.live if aid not in self.stale]


class Router:
    """Fleet front: routes client requests over a `ReplicaSet` with
    failover replay, budgeted retries, hedging, circuit breaking, and
    brownout shedding. See the module docstring for semantics.

    `submit()` mirrors `SlotEngine.submit` (plus `priority=`) and
    returns the same first-wins `Request` future, so `Server` and
    clients are agnostic to whether they talk to one engine or a fleet.
    """

    def __init__(self, model, replicas=2, *, engine_kw=None, metrics=None,
                 retry_budget=2, replay_budget=None, retry_backoff_s=0.0,
                 hedge=True, hedge_after_s=None, hedge_min_s=0.25,
                 liveness_timeout_s=2.0, tick_s=0.005,
                 brownout_high=0.95, brownout_low=0.5,
                 brownout_max_new=8, brownout_priority=1,
                 breaker_threshold=5, breaker_cooloff_s=1.0,
                 breaker_clock=time.monotonic,
                 backoff_base_s=0.05, backoff_max_s=2.0,
                 queue_cap=None, warmup=True, name="fleet",
                 autoscale=None, roles=None, role_kw=None, disagg=None,
                 migrate_deadline_s=5.0, prefix_affinity=None,
                 tenancy=None):
        from .migrate import KVMailbox

        self.metrics = metrics if metrics is not None else ServingMetrics()
        # multi-tenant mode (ISSUE 20): a TenantDirectory turns the
        # replica queues into weighted-fair TenantFairQueues and switches
        # brownout from a global priority floor to tier-based shedding
        self.tenancy = tenancy
        self.replica_set = ReplicaSet(
            model, replicas, engine_kw=engine_kw, metrics=self.metrics,
            liveness_timeout_s=liveness_timeout_s,
            backoff_base_s=backoff_base_s, backoff_max_s=backoff_max_s,
            breaker_threshold=breaker_threshold,
            breaker_cooloff_s=breaker_cooloff_s,
            breaker_clock=breaker_clock, queue_cap=queue_cap,
            warmup=warmup, name=name, on_death=self._on_replica_death,
            roles=roles, role_kw=role_kw, tenancy=tenancy)
        self.name = name
        # disaggregated prefill/decode (ISSUE 17): the Router sends each
        # request's prefill to a prefill-role replica, migrates the
        # finished KV blocks over the deadline-guarded mailbox, then
        # dispatches the decode leg (pinned to the prefill leg's weight
        # version) with affinity to the adopting replica. Degrades to
        # colocated dispatch whenever roles or migration are unavailable.
        self._disagg = bool(flag("FLAGS_serving_disagg")) \
            if disagg is None else bool(disagg)
        self._kv_mailbox = KVMailbox()
        self._migrate_deadline_s = migrate_deadline_s
        # prefix-cache-aware routing (ISSUE 18): sticky map from a
        # cumulative prompt-prefix digest (PrefixCache._digest at block
        # boundaries) to the name of the replica that last served a
        # request with that prefix. Longest-match lookup in _dispatch;
        # re-stuck on every placement, so failover moves the session's
        # home instead of pinning it to a corpse. Size-capped FIFO.
        self._affinity_on = None if prefix_affinity is None \
            else bool(prefix_affinity)
        self._affinity: dict = {}          # digest -> replica name
        self._affinity_cap = 4096
        self._affinity_lookups = 0
        self._affinity_hits: dict = {}     # replica name -> hits
        self._block_size = None
        self.retry_budget = retry_budget
        self.replay_budget = replay_budget if replay_budget is not None \
            else max(replicas, 2)
        self.retry_backoff_s = retry_backoff_s
        self._hedge_enabled = hedge and replicas > 1
        self._hedge_after_s = hedge_after_s
        self._hedge_min_s = hedge_min_s
        self._tick_s = tick_s
        self._brownout_high = brownout_high
        self._brownout_low = brownout_low
        self._brownout_max_new = brownout_max_new
        self._brownout_priority = brownout_priority
        self._lock = threading.RLock()
        self._flights: dict = {}        # client req id -> _Flight
        self._attempt_index: dict = {}  # attempt req id -> _Flight
        self._brownout = False
        self._brownout_force = None     # None = auto hysteresis
        self._stop = threading.Event()
        self._sup = None
        self._max_seq_len = None
        # autoscale=None/False: fixed fleet. autoscale=True: defaults
        # (flags). autoscale=dict: Autoscaler kwargs. Built in start()
        # so tests can also attach one by hand before starting.
        self._autoscale_spec = autoscale
        self.autoscaler = None
        # RolloutController attaches itself here (rollout.py), the same
        # way the Autoscaler does; /v1/version reads through it
        self.rollout = None

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        if self._sup is not None:
            return self
        self.replica_set.start()
        eng0 = self.replica_set.replicas[0].engine
        self._max_seq_len = eng0.max_seq_len
        self._block_size = eng0.block_size
        if self._affinity_on is None:
            self._affinity_on = bool(flag("FLAGS_serving_prefix_affinity"))
        if self._autoscale_spec and self.autoscaler is None:
            from .autoscale import Autoscaler
            kw = (dict(self._autoscale_spec)
                  if isinstance(self._autoscale_spec, dict) else {})
            self.autoscaler = Autoscaler(self, **kw)
        self._stop.clear()
        self._sup = threading.Thread(target=self._supervise,
                                     name=f"{self.name}-supervisor",
                                     daemon=True)
        self._sup.start()
        return self

    def shutdown(self, drain=True, timeout=None):
        """Stop the fleet. drain=True waits for in-flight flights to
        settle (bounded by `timeout`, default 30s) before stopping the
        supervisor and engines; drain=False fails every open flight."""
        if drain:
            deadline = time.monotonic() + (30.0 if timeout is None
                                           else timeout)
            while time.monotonic() < deadline:
                with self._lock:
                    if not self._flights:
                        break
                time.sleep(0.005)
        if self.autoscaler is not None:
            self.autoscaler.close()
        self._stop.set()
        if self._sup is not None:
            self._sup.join(timeout)
            self._sup = None
        if not drain:
            with self._lock:
                for flight in list(self._flights.values()):
                    self._finish_fail(flight, ServerClosedError(
                        f"request {flight.client.id} aborted: "
                        "fleet shutdown"))
        self.replica_set.shutdown(drain=drain, timeout=timeout)

    # -- client API ---------------------------------------------------------

    def submit(self, prompt_ids, *, max_new_tokens=16, eos_token_id=None,
               timeout=None, priority=0, do_sample=False, temperature=1.0,
               top_k=0, seed=0, adapter_id=0, tenant=None):
        """Route one request; returns its first-wins `Request` future.

        Client errors (empty/over-long prompt) raise synchronously;
        brownout sheds below-floor priorities with `BrownoutShedError`
        (429, retriable) — or, when a `TenantDirectory` is attached,
        below-tier tenants. Everything downstream — replica choice,
        retries, failover, hedging — is the Router's problem."""
        import numpy as np

        if self._sup is None:
            self.start()
        if timeout is None:
            timeout = flag("FLAGS_serving_default_timeout_s") or None
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if ids.size == 0:
            raise ValueError("empty prompt")
        if ids.size + max_new_tokens > self._max_seq_len:
            raise ValueError(
                f"prompt ({ids.size}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds fleet max_seq_len {self._max_seq_len}")
        if self.tenancy is not None:
            spec = self.tenancy.resolve(tenant)
            tenant = spec.name
            if priority == 0:
                priority = spec.priority
            if self.brownout_active and spec.tier < self.tenancy.brownout_tier:
                self.metrics.inc("brownout_sheds")
                if hasattr(self.metrics, "tenant_inc"):
                    self.metrics.tenant_inc(spec.name, "shed")
                raise BrownoutShedError(
                    f"request shed: fleet in brownout, tenant "
                    f"{spec.name!r} tier {spec.tier} below floor "
                    f"{self.tenancy.brownout_tier}")
        elif self.brownout_active and priority < self._brownout_priority:
            self.metrics.inc("brownout_sheds")
            raise BrownoutShedError(
                f"request shed: fleet in brownout, priority {priority} "
                f"below floor {self._brownout_priority}")
        client = Request(ids, timeout=timeout, priority=priority,
                         max_new_tokens=max_new_tokens,
                         eos_token_id=eos_token_id, do_sample=do_sample,
                         temperature=temperature, top_k=top_k, seed=seed,
                         adapter_id=adapter_id, tenant=tenant)
        self.metrics.inc("fleet_submitted")
        flight = _Flight(client, self.retry_budget, self.replay_budget)
        if self._affinity_on and self._block_size:
            # the same cumulative block-boundary digests the replicas'
            # radix caches index on — ascending length, so a reversed
            # walk finds the longest sticky match first
            bs = self._block_size
            flight.prefix_digests = tuple(
                PrefixCache._digest(ids[:k * bs])
                for k in range(1, ids.size // bs + 1))
        with self._lock:
            self._flights[client.id] = flight
            # single cleanup point: whatever resolves the client —
            # success, typed failure, or client-side cancel — cancels
            # every attempt still pending and drops the flight
            client.add_done_callback(self._client_done_cb)
            self._dispatch(flight)
        return client

    def generate(self, prompt_ids, timeout=None, **kw):
        """Blocking convenience: submit + result."""
        return self.submit(prompt_ids, **kw).result(timeout)

    # -- introspection ------------------------------------------------------

    @property
    def brownout_active(self):
        if self._brownout_force is not None:
            return self._brownout_force
        return self._brownout

    def set_brownout(self, on):
        """Force brownout on/off, or None to return to automatic
        load-fraction hysteresis."""
        self._brownout_force = on

    @property
    def queue_depth(self):
        return self.replica_set.queue_depth()

    @property
    def in_flight(self):
        with self._lock:
            return len(self._flights)

    def compile_counts(self):
        return self.replica_set.compile_counts()

    def kill(self, name, reason="killed (admin/chaos)"):
        return self.replica_set.kill(name, reason)

    def add_replica(self):
        return self.replica_set.add_replica()

    def remove_replica(self, name, drain=True):
        return self.replica_set.remove_replica(name, drain=drain)

    def snapshot(self):
        snap = self.replica_set.snapshot()
        snap["brownout"] = self.brownout_active
        with self._lock:
            snap["in_flight"] = len(self._flights)
            if self._affinity_on:
                hits = sum(self._affinity_hits.values())
                per = {}
                for r in self.replica_set.replicas:
                    e = r.engine
                    per[r.name] = {
                        "hits": self._affinity_hits.get(r.name, 0),
                        "prefix_hit_rate": (e.prefix_hit_rate()
                                            if e is not None else 0.0),
                    }
                snap["affinity"] = {
                    "lookups": self._affinity_lookups,
                    "hits": hits,
                    "hit_rate": (hits / self._affinity_lookups
                                 if self._affinity_lookups else 0.0),
                    "table_size": len(self._affinity),
                    "per_replica": per,
                }
        if self.autoscaler is not None:
            snap["autoscaler"] = self.autoscaler.snapshot()
        if self.rollout is not None:
            snap["rollout"] = self.rollout.snapshot()
        return snap

    def version_info(self):
        """Rollout-facing view (GET /v1/version): per-replica weight
        versions, the versions still live in the fleet, and — when a
        RolloutController is attached — registry current/previous plus
        the rollout state machine."""
        rs = self.replica_set
        per = {r.name: r.weight_version for r in rs.replicas
               if r.state != "stopped"}
        live = sorted(rs.versions_live())
        info = {"replicas": per, "versions_live": live,
                "state": "static", "target": None, "previous": None,
                "error": None, "current": max(live) if live else 0}
        ro = self.rollout
        if ro is not None:
            info.update(current=ro.registry.current,
                        previous=ro.registry.previous,
                        state=ro.state, target=ro.target, error=ro.error)
        return info

    # -- flight machinery ---------------------------------------------------

    def _dispatch(self, flight, exclude=frozenset(), hedge=False,
                  version=None):
        """Place one attempt. With `hedge` the exclusion is strict (no
        point hedging onto the replica already working the request);
        otherwise a lone excluded replica is better than parking.
        `version` (or the flight's replay pin) restricts placement to
        replicas serving that exact weight version — a replay or hedge
        must stay bitwise against its original attempt, never silently
        decode on different weights mid-rollout."""
        with self._lock:
            client = flight.client
            if client.done():
                return
            remaining = None
            if client.deadline is not None:
                remaining = client.deadline - time.monotonic()
                if remaining <= 0:
                    self._finish_fail(flight, DeadlineExceededError(
                        f"request {client.id} deadline exceeded before "
                        "dispatch"))
                    return
            try:
                if faults.fault_point("serving.route") is faults.DROP:
                    raise faults.FaultError(
                        "injected fault at serving.route (drop)")
            except Exception as e:  # noqa: BLE001 — routing failure
                self._route_failed(flight, e)
                return
            pin = version if version is not None else flight.pin
            prefill_leg = False
            replica = None
            if flight.prefer is not None:
                # one-shot affinity: the replica that adopted this
                # flight's migrated KV blocks serves its decode leg
                p, flight.prefer = flight.prefer, None
                if (p.state == "healthy" and p not in exclude
                        and p.breaker.state == "closed"
                        and p.engine is not None
                        and (pin is None
                             or p.engine.weight_version == pin)):
                    replica = p
            if replica is None and not hedge and not self._disagg_on():
                # sticky prefix affinity: the replica that last served
                # this token prefix holds its KV blocks live (or can
                # restore them from the spill tier) — decode-only TTFT
                # instead of a re-prefill. Any failure (injected
                # serving.affinity fault, dead/draining/breaker-open
                # replica, version mismatch) falls through to the
                # normal pick and the session re-sticks there. Under
                # live disaggregation the role split owns placement:
                # fresh requests must take the prefill->migrate leg
                # (the adopted-KV `prefer` handles decode affinity).
                replica = self._affinity_pick(flight, exclude, pin)
            if replica is None and not hedge and flight.kv_state is None \
                    and self._disagg_on():
                replica = self._pick(exclude, version=pin, role="prefill")
                prefill_leg = replica is not None
            if replica is None:
                replica = self._pick(exclude, version=pin)
            if replica is None:
                if hedge:
                    flight.hedged = False   # retry the hedge next tick
                    return
                if exclude:
                    replica = self._pick(frozenset(), version=pin)
                if replica is None:
                    if pin is not None and \
                            pin not in self.replica_set.versions_live():
                        # the pinned version is gone for good (rollout
                        # retired it): replaying on different weights
                        # would break bitwise semantics — fail retriable
                        self.metrics.inc("version_retired_failures")
                        self._finish_fail(flight, VersionRetiredError(
                            f"request {client.id} is pinned to weight "
                            f"version {pin}, which no replica serves or "
                            "targets any more (retired by rollout); "
                            "resubmit to decode on the current version"))
                        return
                    if not flight.active():
                        flight.parked = True
                        self.metrics.inc("parked")
                    return
            flight.parked = False
            gen = dict(client.gen)
            if prefill_leg:
                # the prefill leg only has to fill the KV cache and
                # donate its blocks; one produced token is the engine's
                # minimum request (its value is discarded — the decode
                # leg re-picks every output token itself)
                gen["max_new_tokens"] = 1
            if self.brownout_active:
                gen["max_new_tokens"] = min(
                    gen.get("max_new_tokens", 16), self._brownout_max_new)
            try:
                attempt = replica.engine.submit(
                    client.payload, timeout=remaining,
                    priority=client.priority, **gen)
            except ServingError as e:
                replica.breaker.record_failure()
                self._attempt_failed(flight, replica, e)
                return
            except Exception as e:  # noqa: BLE001 — client error
                self._finish_fail(flight, e)
                return
            flight.attempts[attempt.id] = (replica, attempt)
            flight.versions[attempt.id] = replica.engine.weight_version
            flight.live.add(attempt.id)
            if prefill_leg:
                flight.prefill_ids.add(attempt.id)
            else:
                # (re)stick the session's prefix chain to wherever it
                # actually landed — on failover this moves the home;
                # prefill legs don't stick (the decode leg will)
                self._affinity_stick(flight, replica)
            if hedge:
                flight.hedge_ids.add(attempt.id)
                self.metrics.inc("hedges")
            self._attempt_index[attempt.id] = flight
            replica.load += 1
            flight.last_dispatch = time.monotonic()
            if flight.first_dispatch is None:
                flight.first_dispatch = flight.last_dispatch
            self.metrics.inc("routed")
            attempt.add_done_callback(self._attempt_done_cb)

    def _affinity_pick(self, flight, exclude, pin):
        """The sticky-affinity replica for this flight's longest mapped
        prompt prefix, or None when affinity is off / no digest maps /
        the mapped replica cannot take the attempt (then the caller's
        least-loaded pick handles placement and re-sticks). The
        ``serving.affinity`` fault site fires once per decision; a
        raised fault degrades this one decision to least-loaded."""
        if not self._affinity_on or not flight.prefix_digests:
            return None
        self._affinity_lookups += 1
        try:
            faults.fault_point("serving.affinity")
        except Exception:  # noqa: BLE001 — degrade to least-loaded
            self.metrics.inc("affinity_faults")
            return None
        by_name = {r.name: r for r in self.replica_set.replicas}
        for digest in reversed(flight.prefix_digests):   # longest first
            name = self._affinity.get(digest)
            if name is None:
                continue
            p = by_name.get(name)
            if (p is not None and p.state == "healthy"
                    and p not in exclude
                    and p.breaker.state == "closed"
                    and p.engine is not None
                    and self._role_ok(p, None)
                    and (pin is None
                         or p.engine.weight_version == pin)):
                self._affinity_hits[name] = \
                    self._affinity_hits.get(name, 0) + 1
                self.metrics.inc("affinity_hits")
                return p
            return None   # mapped but unroutable: fail over cleanly
        return None

    def _affinity_stick(self, flight, replica):
        """Point every prefix digest of this flight at the replica it
        landed on (insertion-ordered FIFO cap keeps the table bounded;
        re-inserts refresh recency)."""
        if not self._affinity_on or not flight.prefix_digests:
            return
        for digest in flight.prefix_digests:
            self._affinity.pop(digest, None)
            self._affinity[digest] = replica.name
        while len(self._affinity) > self._affinity_cap:
            self._affinity.pop(next(iter(self._affinity)))

    def _role_ok(self, replica, role):
        """May `replica` take an attempt of this kind? role="prefill"
        wants a prefill-specialized replica; role=None is a whole or
        decode attempt, which prefill-specialized replicas never take
        while disaggregation is on (they'd pay the wide-chunk step for
        every decode token — the exact cost disaggregation removes)."""
        if role is not None:
            return replica.role == role
        return replica.role != "prefill" or not self._disagg

    def _disagg_on(self):
        """Disaggregate right now? Needs the flag AND both roles
        healthy — a fleet that lost all its prefill (or decode)
        replicas degrades to colocated dispatch instead of parking."""
        if not self._disagg:
            return False
        have_prefill = have_decode = False
        for r in self.replica_set.replicas:
            if r.state == "healthy":
                if r.role == "prefill":
                    have_prefill = True
                else:
                    have_decode = True
        return have_prefill and have_decode

    def _pick(self, exclude, version=None, role=None):
        """Deterministic replica choice: a breaker awaiting its
        half-open probe goes first (lowest index — otherwise an open
        breaker could starve forever behind healthy siblings), else the
        least-loaded replica with a closed breaker (ties to the lowest
        index). `version` restricts to replicas serving that exact
        weight version (pinned replays/hedges mid-rollout); `role`
        restricts by disaggregation role (see `_role_ok`)."""
        candidates = [r for r in self.replica_set.replicas
                      if r.state == "healthy" and r not in exclude
                      and self._role_ok(r, role)
                      and (version is None or (
                          r.engine is not None
                          and r.engine.weight_version == version))]
        for r in candidates:
            if r.breaker.state != "closed" and r.breaker.probe_ready() \
                    and r.breaker.allow():
                return r
        best = None
        for r in candidates:
            if r.breaker.state != "closed":
                continue
            if best is None or (r.load, r.index) < (best.load, best.index):
                best = r
        return best

    def _route_failed(self, flight, err):
        if flight.retries_left > 0:
            flight.retries_left -= 1
            self.metrics.inc("retries")
            self._defer(flight, frozenset())
            return
        self.metrics.inc("retry_budget_exhausted")
        self._finish_fail(flight, RetriesExhaustedError(
            f"request {flight.client.id} routing failed after exhausting "
            f"its retry budget: {err}", last_error=err))

    def _defer(self, flight, exclude):
        if self.retry_backoff_s > 0:
            flight.retry_at = time.monotonic() + self.retry_backoff_s
            flight.retry_exclude = exclude
        else:
            self._dispatch(flight, exclude)

    # -- disaggregated prefill/decode ---------------------------------------

    def _start_migration(self, flight, prefill_replica, version):
        """Kick the KV migration off the engine callback thread. The
        adoption blocks until the decode engine's next step boundary,
        and that engine's own done-callbacks need the Router lock —
        migrating under the lock would deadlock the fleet."""
        threading.Thread(
            target=self._migrate_then_decode,
            args=(flight, prefill_replica, version),
            name=f"{self.name}-kv-migrate", daemon=True).start()

    def _migrate_then_decode(self, flight, prefill_replica, version):
        """Stream the prefill replica's finished KV blocks to a decode
        replica, then dispatch the decode leg there (pinned to the
        prefill weight version — adopted KV must never meet different
        weights). Any migration failure degrades to ordinary colocated
        dispatch; the request stays replayable throughout."""
        from .migrate import migrate_prefix

        with self._lock:
            if flight.client.done():
                return
            target = self._pick(frozenset((prefill_replica,)),
                                version=version)
        adopted = 0
        if target is not None and target.engine is not None \
                and prefill_replica.engine is not None:
            try:
                adopted = migrate_prefix(
                    prefill_replica.engine, target.engine,
                    flight.client.payload, mailbox=self._kv_mailbox,
                    deadline_s=self._migrate_deadline_s)
            except Exception:  # noqa: BLE001 — degrade, don't fail
                self.metrics.inc("kv_migrate_faults")
        with self._lock:
            if flight.client.done():
                return
            if adopted:
                flight.kv_state = "migrated"
                flight.prefer = target
                if flight.pin is None and version is not None:
                    flight.pin = version
            else:
                flight.kv_state = "fallback"
            self._dispatch(flight, frozenset())

    def _attempt_done_cb(self, attempt):
        """Done-callback on every attempt future; runs on the engine
        (or cancelling) thread. First-wins on the client request makes
        duplicate outcomes — hedge losers, a hung replica's late
        completion — harmless, but we count them for certification."""
        with self._lock:
            flight = self._attempt_index.pop(attempt.id, None)
            if flight is None:
                return
            replica, _ = flight.attempts.get(attempt.id, (None, None))
            att_version = flight.versions.get(attempt.id)
            if replica is not None:
                replica.load = max(replica.load - 1, 0)
            was_stale = attempt.id in flight.stale
            flight.live.discard(attempt.id)
            flight.stale.discard(attempt.id)
            if was_stale:
                self.metrics.inc("stale_attempts")
                return
            err = attempt._error
            if err is None:
                if replica is not None:
                    replica.breaker.record_success()
                if attempt.id in flight.prefill_ids:
                    # disaggregated prefill leg: its one produced token
                    # is discarded — migrate the KV blocks and dispatch
                    # the decode leg (off-thread: migration waits on the
                    # decode engine's step boundary, which must not
                    # happen under the Router lock)
                    if not flight.client.done():
                        self._start_migration(flight, replica, att_version)
                    return
                if self._finish_ok(flight, attempt._value):
                    if attempt.id in flight.hedge_ids:
                        self.metrics.inc("hedge_wins")
                else:
                    self.metrics.inc("duplicates_suppressed")
                return
            if flight.client.done():
                return
            if replica is not None and not isinstance(
                    err, (RequestCancelled, DeadlineExceededError)):
                replica.breaker.record_failure()
            self._attempt_failed(flight, replica, err, version=att_version)

    def _attempt_failed(self, flight, replica, err, version=None):
        if flight.client.done():
            return
        if flight.active():
            # a sibling (hedge) attempt is still running — let it win
            # rather than charging the request's budgets
            return
        if isinstance(err, ReplicaDiedError):
            self._replay(flight, replica, err, version=version)
            return
        if retriable(err) and flight.retries_left > 0:
            flight.retries_left -= 1
            self.metrics.inc("retries")
            exclude = frozenset() if replica is None \
                else frozenset((replica,))
            self._defer(flight, exclude)
            return
        if retriable(err):
            self.metrics.inc("retry_budget_exhausted")
            err = RetriesExhaustedError(
                f"request {flight.client.id} failed after exhausting its "
                f"retry budget: {err}", last_error=err)
        self._finish_fail(flight, err)

    def _replay(self, flight, replica, err, version=None):
        """Failover: re-run a dead replica's request from its original
        prompt on a healthy sibling. Charged to the replay budget, not
        the retry budget. The replay is PINNED to the weight version
        the dead attempt decoded on: same version stays bitwise; a
        retired version fails retriable (`VersionRetiredError`) rather
        than silently re-decoding on different weights."""
        if flight.replays_left <= 0:
            self._finish_fail(flight, err)
            return
        flight.replays_left -= 1
        self.metrics.inc("replays")
        if flight.pin is None and version is not None:
            flight.pin = version
        if flight.pin is not None:
            self.metrics.inc("replays_pinned")
        try:
            faults.fault_point("serving.replay")
        except Exception as e:  # noqa: BLE001 — replay path failure
            self._finish_fail(flight, ReplicaDiedError(
                f"failover replay of request {flight.client.id} "
                f"failed: {e}"))
            return
        exclude = frozenset() if replica is None else frozenset((replica,))
        self._dispatch(flight, exclude)

    def _on_replica_death(self, replica, err):
        """ReplicaSet hook, called BEFORE the dead engine is abandoned:
        stale-mark every live attempt on it (their late outcomes must
        not reach clients or breakers) and replay each affected flight
        elsewhere. Runs on the supervisor (or kill-caller) thread."""
        with self._lock:
            affected = []
            for aid, flight in list(self._attempt_index.items()):
                rep, _ = flight.attempts.get(aid, (None, None))
                if rep is replica and aid in flight.live \
                        and aid not in flight.stale:
                    flight.stale.add(aid)
                    if flight not in (f for f, _ in affected):
                        affected.append((flight, aid))
            seen = set()
            for flight, aid in affected:
                if id(flight) in seen:
                    continue
                seen.add(id(flight))
                if flight.client.done():
                    continue
                self._replay(flight, replica, err,
                             version=flight.versions.get(aid))

    def _finish_ok(self, flight, value):
        if flight.client._complete(value):
            self.metrics.inc("fleet_completed")
            return True
        return False

    def _finish_fail(self, flight, err):
        if flight.client._fail(err):
            self.metrics.inc("fleet_failed")
            return True
        return False

    def _client_done_cb(self, client):
        """Runs once per client request, on whatever thread resolved it
        (engine success, router failure, or client cancel): cancel all
        still-pending attempts and drop the flight."""
        with self._lock:
            flight = self._flights.pop(client.id, None)
            if flight is None:
                return
            for aid in list(flight.live):
                if aid in flight.stale:
                    continue
                flight.stale.add(aid)
                _, att = flight.attempts[aid]
                att.cancel()

    # -- supervisor ---------------------------------------------------------

    def _supervise(self):
        while not self._stop.wait(self._tick_s):
            try:
                now = time.monotonic()
                self.replica_set.poll(now)
                self._brownout_tick()
                self._hedge_tick(now)
                self._flight_tick(now)
                if self.autoscaler is not None:
                    self.autoscaler.tick(now)
            except Exception:  # noqa: BLE001 — the supervisor never dies
                self.metrics.inc("supervisor_errors")

    def _brownout_tick(self):
        if self._brownout_force is not None:
            return
        cap = self.replica_set.capacity()
        if cap == 0:
            # nothing healthy: maximum degradation until a restart lands
            self._brownout = True
            return
        frac = self.replica_set.in_flight() / cap
        if not self._brownout and frac >= self._brownout_high:
            self._brownout = True
            self.metrics.inc("brownout_entries")
        elif self._brownout and frac <= self._brownout_low:
            self._brownout = False

    def _hedge_delay(self):
        if self._hedge_after_s is not None:
            return self._hedge_after_s
        p95 = self.metrics.latency_percentiles("e2e", (95,))[95]
        if p95 is None:
            return None   # no signal yet: don't hedge blind
        return max(self._hedge_min_s, 2.0 * p95)

    def _hedge_tick(self, now):
        if not self._hedge_enabled:
            return
        delay = self._hedge_delay()
        if delay is None:
            return
        with self._lock:
            for flight in list(self._flights.values()):
                if flight.hedged or flight.parked or flight.client.done():
                    continue
                active = flight.active()
                if len(active) != 1 or flight.last_dispatch is None:
                    continue
                if active[0] in flight.prefill_ids:
                    # never hedge a prefill leg: its value is discarded
                    # and a duplicate would double the KV migration
                    continue
                if now - flight.last_dispatch < delay:
                    continue
                flight.hedged = True
                exclude = frozenset(flight.attempts[aid][0]
                                    for aid in active)
                # hedge on the SAME weight version as the active
                # attempt: first-wins between the pair stays bitwise
                self._dispatch(flight, exclude, hedge=True,
                               version=flight.versions.get(active[0]))

    def _flight_tick(self, now):
        """Deferred retries, parked re-dispatch, deadline sweep."""
        with self._lock:
            for flight in list(self._flights.values()):
                client = flight.client
                if client.done():
                    continue
                if client.deadline is not None and now > client.deadline \
                        and not flight.active():
                    self._finish_fail(flight, DeadlineExceededError(
                        f"request {client.id} deadline exceeded while "
                        "awaiting redispatch"))
                    continue
                if flight.retry_at is not None and now >= flight.retry_at:
                    flight.retry_at, exclude = None, flight.retry_exclude
                    flight.retry_exclude = None
                    self._dispatch(flight, exclude or frozenset())
                elif flight.parked:
                    self._dispatch(flight)
