"""Host-side bookkeeping for the block-paged KV cache: a refcounted
block allocator over a fixed physical pool, and a radix-style prefix
cache that lets requests sharing a token prefix share physical blocks.

Design (vLLM PagedAttention + SGLang RadixAttention, collapsed to the
slot engine's needs):

- The device pool is `[num_blocks, nh, block_size, hd]` per layer;
  every logical sequence position `t` of a slot maps through its block
  table to physical row `(table[t // bs], t % bs)`. Block 0 is the
  reserved *null block*: it is never allocated, free slots point every
  table entry at it, and all padding/garbage scatter writes land there
  — so the compiled step can always write `[max_slots, chunk]` rows
  without host-side masking.
- `BlockAllocator` hands out blocks with a refcount. A block shared by
  N slots (prefix sharing) plus the prefix cache has refcount N+1 and
  returns to the free list only when the last reference drops.
- `PrefixCache` indexes *fully written* blocks by the cumulative hash
  of all tokens from position 0 (position-dependent KV means a chunk is
  only reusable under its exact left context, hence cumulative, not
  per-chunk, hashing — the radix property). Lookup walks the hash
  chain block by block; a partial match inside the next block yields a
  copy-on-write candidate: the caller copies the physical block and
  overwrites the divergent tail. Entries are evicted leaf-first in LRU
  order when the allocator runs dry (`reclaim`).

Fault sites: ``serving.alloc_block`` fires on every physical block
allocation (a `raise` action is deterministic pool exhaustion mid-
admission); ``serving.cow_split`` fires before every copy-on-write
block copy.
"""

from __future__ import annotations

import hashlib

import numpy as np

from ..framework import faults

__all__ = ["NULL_BLOCK", "PoolExhausted", "BlockAllocator", "PrefixCache",
           "positions_to_rows"]

#: physical block 0 — reserved scratch target for padding writes
NULL_BLOCK = 0

_ROOT = b"\x00root"


def positions_to_rows(table, positions, block_size):
    """Map logical sequence positions to physical pool rows through a
    slot's block table: ``(table[t // bs], t % bs)``.

    This is the same routing the compiled step's bulk KV scatter uses —
    a speculative round scatters all ``k+1`` staged columns (next token
    plus every draft proposal) through it in one dispatch, so the rows
    of a rejected suffix land in the pool too. They are harmless:
    per-row causal masking (``key_idx <= t``) hides them from every
    attend, and the next round's staging overwrites them before the
    coverage frontier reaches their positions. Tests use this helper to
    read pool rows back and certify scatter parity.
    """
    positions = np.asarray(positions)
    table = np.asarray(table)
    return table[positions // block_size], positions % block_size


class PoolExhausted(RuntimeError):
    """No free physical blocks (after reclaim); admission must wait."""


class BlockAllocator:
    """Refcounted free-list allocator over `num_blocks` physical blocks.

    Block 0 (`NULL_BLOCK`) is reserved and never handed out; `usable`
    is therefore `num_blocks - 1`.
    """

    def __init__(self, num_blocks):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 physical blocks (1 reserved), got {num_blocks}")
        self.num_blocks = num_blocks
        self._ref = np.zeros((num_blocks,), np.int64)
        self._ref[NULL_BLOCK] = 1      # pinned forever
        # pop() yields ascending ids — deterministic tests
        self._free = list(range(num_blocks - 1, 0, -1))

    @property
    def usable(self):
        return self.num_blocks - 1

    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def blocks_in_use(self):
        return self.usable - len(self._free)

    def alloc(self):
        """One fresh block (refcount 1). Fault site serving.alloc_block."""
        faults.fault_point("serving.alloc_block")
        if not self._free:
            raise PoolExhausted(
                f"all {self.usable} usable KV blocks are referenced")
        bid = self._free.pop()
        self._ref[bid] = 1
        return bid

    def incref(self, bid):
        if bid == NULL_BLOCK or self._ref[bid] <= 0:
            raise ValueError(f"incref on unallocated block {bid}")
        self._ref[bid] += 1

    def decref(self, bid):
        """Drop one reference; returns True when the block was freed."""
        if bid == NULL_BLOCK or self._ref[bid] <= 0:
            raise ValueError(f"decref on unallocated block {bid}")
        self._ref[bid] -= 1
        if self._ref[bid] == 0:
            self._free.append(bid)
            return True
        return False

    def refcount(self, bid):
        return int(self._ref[bid])


class PrefixCache:
    """Radix prefix index over fully written KV blocks.

    Each entry maps `digest(tokens[0 : k*block_size])` -> the physical
    block holding positions `[(k-1)*bs, k*bs)`. The cache holds one
    allocator reference per entry, so indexed blocks survive slot
    eviction and are physically shared by later requests with the same
    prefix (`match` -> the caller increfs per consuming slot).
    """

    def __init__(self, allocator: BlockAllocator, block_size):
        self._alloc = allocator
        self.block_size = block_size
        self._blocks: dict = {}     # key -> block id
        self._chunks: dict = {}     # key -> np.int32 chunk tokens
        self._parent: dict = {}     # key -> parent key
        self._children: dict = {}   # key -> set of child keys
        self._lru: dict = {}        # key -> last-touch tick
        self._clock = 0
        #: optional spill donation: ``hook(key, prefix_tokens, bid,
        #: n_rows)`` called on eviction of an entry whose block is about
        #: to be freed, BEFORE the freeing decref (append-before-evict —
        #: the spill tier persists the rows while they still exist).
        #: The hook must not raise: a failed spill loses durability for
        #: that block, never the eviction itself.
        self.spill_hook = None

    def __len__(self):
        return len(self._blocks)

    @staticmethod
    def _digest(ids):
        return hashlib.sha1(
            np.ascontiguousarray(ids, np.int32).tobytes()).digest()

    def _touch(self, key):
        self._clock += 1
        self._lru[key] = self._clock

    def match(self, ids, limit):
        """Longest indexed prefix of ``ids[:limit]``.

        Returns ``(blocks, n_tokens, cow)``: the shared full blocks (in
        table order, NOT yet increfed — the caller increfs one ref per
        slot), the token count they cover, and an optional
        ``(src_block, n_rows)`` copy-on-write candidate when a cached
        block matches only the first `n_rows` of the next chunk (the
        divergence point lies inside it)."""
        bs = self.block_size
        blocks, n, parent = [], 0, _ROOT
        while n + bs <= limit:
            key = self._digest(ids[:n + bs])
            bid = self._blocks.get(key)
            if bid is None:
                break
            blocks.append(bid)
            parent = key
            n += bs
            self._touch(key)
        cow = None
        want = np.asarray(ids[n:limit], np.int32)
        if want.size:
            best_key, best_c = None, 0
            for child in self._children.get(parent, ()):
                chunk = self._chunks[child]
                m = min(chunk.size, want.size)
                neq = np.nonzero(chunk[:m] != want[:m])[0]
                c = int(neq[0]) if neq.size else m
                if c > best_c:
                    best_key, best_c = child, c
            if best_key is not None and best_c < bs:
                cow = (self._blocks[best_key], best_c)
                self._touch(best_key)
        return blocks, n, cow

    def insert(self, tokens, blocks, written):
        """Index every fully written block of a finished sequence.

        `tokens` is the full id sequence, `blocks` its physical block
        list (table order), `written` how many positions hold real KV
        (the last sampled token is never written). Newly indexed blocks
        gain one allocator reference (the cache's own); already-indexed
        prefixes are just LRU-refreshed. Returns #new entries."""
        bs = self.block_size
        tokens = np.asarray(tokens, np.int32)
        parent, added = _ROOT, 0
        for k in range(1, written // bs + 1):
            key = self._digest(tokens[:k * bs])
            if key not in self._blocks:
                bid = blocks[k - 1]
                self._alloc.incref(bid)
                self._blocks[key] = bid
                self._chunks[key] = tokens[(k - 1) * bs:k * bs].copy()
                self._parent[key] = parent
                self._children.setdefault(parent, set()).add(key)
                added += 1
            self._touch(key)
            parent = key
        return added

    def prefix_tokens(self, key):
        """The full cumulative token prefix an entry covers (root chunk
        through this entry's own chunk, concatenated in order)."""
        chunks = []
        while key != _ROOT:
            chunks.append(self._chunks[key])
            key = self._parent[key]
        return np.concatenate(chunks[::-1]) if chunks else \
            np.zeros((0,), np.int32)

    def _evict(self, key):
        bid = self._blocks[key]
        if self.spill_hook is not None \
                and self._alloc.refcount(bid) == 1:
            # append-before-evict: persist the rows while the block
            # still exists — the decref below frees it for reuse
            self.spill_hook(key, self.prefix_tokens(key), bid,
                            len(self._chunks[key]))
        self._children.get(self._parent[key], set()).discard(key)
        self._children.pop(key, None)
        bid = self._blocks.pop(key)
        self._chunks.pop(key)
        self._parent.pop(key)
        self._lru.pop(key)
        return self._alloc.decref(bid)

    def reclaim(self, n_blocks):
        """Evict LRU leaf entries until `n_blocks` physical blocks were
        actually freed (entries whose block a live slot still references
        free nothing but are dropped last-resort too). Returns #freed."""
        freed = 0
        while freed < n_blocks:
            leaves = [k for k in self._blocks
                      if not self._children.get(k)]
            if not leaves:
                break
            # oldest leaf whose eviction frees a block, else oldest leaf
            freeing = [k for k in leaves
                       if self._alloc.refcount(self._blocks[k]) == 1]
            if not freeing:
                break
            victim = min(freeing, key=lambda k: self._lru[k])
            if self._evict(victim):
                freed += 1
        return freed

    def clear(self):
        """Drop every entry (and its allocator reference). Leaves go
        before parents so the spill hook can still resolve each
        entry's full token prefix through a live parent chain."""
        while self._blocks:
            for key in [k for k in self._blocks
                        if not self._children.get(k)]:
                self._evict(key)
