"""Bounded admission queue + request futures for the serving runtime.

Ref parity: the reference serves through AnalysisPredictor behind
paddle_serving's brpc front (bounded task queues, per-request deadlines,
fast rejection on overload). Here the queue is the in-process contract:
`submit` never blocks the engine — it either admits within capacity or
sheds immediately (429-style `QueueFullError`), and every request
carries an absolute deadline checked both while queued and mid-decode.

Fault sites (framework/faults.py grammar): ``serving.submit`` fires on
every admission attempt (a `drop` action sheds the request exactly as a
full queue would — deterministic overload), ``serving.dequeue`` on every
pop by the batch assembler / decode engine.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque

from ..framework import faults, monitor

__all__ = [
    "ServingError", "QueueFullError", "CapacityExhaustedError",
    "ServerClosedError", "DeadlineExceededError", "RequestCancelled",
    "Request", "AdmissionQueue",
]


class ServingError(RuntimeError):
    """Base of the serving-side request failures; `status` carries the
    HTTP status the optional front door maps it to."""

    status = 500


class QueueFullError(ServingError):
    """Load shed: the bounded admission queue is at capacity."""

    status = 429


class CapacityExhaustedError(ServingError):
    """The request's KV-block demand exceeds the whole physical pool —
    retriable (429): a smaller request, or a bigger
    FLAGS_serving_kv_blocks, would be admitted."""

    status = 429
    retriable = True


class ServerClosedError(ServingError):
    """Submitted after shutdown began (or pending at a non-drain stop)."""

    status = 503


class DeadlineExceededError(ServingError):
    """The request's deadline passed while queued or mid-decode."""

    status = 504


class RequestCancelled(ServingError):
    """The client cancelled; the engine evicts at the next step."""

    status = 499


_ids = itertools.count(1)


class Request:
    """One unit of serving work + its future.

    `payload` is mode-specific (a 1-D prompt id array for the decode
    engine, one unbatched sample for the dynamic batcher); generation
    parameters ride along in `gen`. The completing thread calls
    `_complete`/`_fail`; clients block in `result()`.
    """

    def __init__(self, payload, *, timeout=None, **gen):
        self.id = next(_ids)
        self.payload = payload
        self.gen = gen
        self.arrival = time.monotonic()
        self.deadline = self.arrival + timeout if timeout else None
        self._event = threading.Event()
        self._value = None
        self._error = None
        self._cancel = False

    # -- client side --------------------------------------------------------

    def cancel(self):
        """Request eviction; honoured at the engine's next step
        boundary (mid-decode cancellation)."""
        self._cancel = True

    @property
    def cancelled(self):
        return self._cancel

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.id} not done within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._value

    def exception(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.id} not done within {timeout}s")
        return self._error

    # -- engine side --------------------------------------------------------

    def expired(self, now=None):
        return self.deadline is not None and \
            (now if now is not None else time.monotonic()) > self.deadline

    def _complete(self, value):
        self._value = value
        self._event.set()

    def _fail(self, error):
        self._error = error
        self._event.set()


class AdmissionQueue:
    """Bounded FIFO with deadline-aware pops and graceful drain.

    submit() is the admission-control point: over-capacity submissions
    raise `QueueFullError` immediately (the fast 429) instead of
    blocking the client into an unbounded backlog; a closed queue raises
    `ServerClosedError`. pop() silently fails+skips requests whose
    deadline already passed — they never reach a slot.
    """

    def __init__(self, cap, *, metrics=None):
        if cap < 1:
            raise ValueError(f"queue cap must be >= 1, got {cap}")
        self.cap = cap
        self._items: deque = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._drain = True
        self._metrics = metrics

    def _count(self, name, n=1):
        monitor.stat_add(f"serving.{name}", n)
        if self._metrics is not None:
            self._metrics.inc(name, n)

    @property
    def depth(self):
        with self._cond:
            return len(self._items)

    @property
    def closed(self):
        return self._closed

    def drained(self):
        """True once closed and empty — the engine's exit condition."""
        with self._cond:
            return self._closed and not self._items

    def submit(self, request: Request):
        """Admit or shed. Returns `request` for chaining."""
        self._count("submitted")
        if faults.fault_point("serving.submit", request) is faults.DROP:
            # deterministic overload: the drop action sheds exactly as a
            # full queue would
            self._count("rejected_queue_full")
            raise QueueFullError(
                f"request {request.id} shed (injected overload)")
        with self._cond:
            if self._closed:
                self._count("rejected_closed")
                raise ServerClosedError(
                    f"request {request.id} rejected: server shutting down")
            if len(self._items) >= self.cap:
                self._count("rejected_queue_full")
                raise QueueFullError(
                    f"request {request.id} rejected: queue at capacity "
                    f"{self.cap}")
            self._items.append(request)
            self._cond.notify_all()
        self._count("accepted")
        return request

    def pop(self, timeout=0.0):
        """Next live request, or None when nothing arrived within
        `timeout` (or the queue is drained). Expired/cancelled requests
        are failed in place and skipped."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                while self._items:
                    req = self._items.popleft()
                    if req.cancelled:
                        self._count("cancelled")
                        req._fail(RequestCancelled(
                            f"request {req.id} cancelled while queued"))
                        continue
                    if req.expired():
                        self._count("timeouts")
                        req._fail(DeadlineExceededError(
                            f"request {req.id} deadline exceeded after "
                            f"{time.monotonic() - req.arrival:.3f}s in "
                            "queue"))
                        continue
                    faults.fault_point("serving.dequeue", req)
                    return req
                if self._closed:
                    return None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cond.wait(remaining)

    def requeue(self, request: Request):
        """Push an already-admitted request back to the queue *head*
        (FIFO order preserved). Used by the paged engine when the block
        pool can't hold the request right now — it waits for in-flight
        evictions instead of being shed. Works on a closed queue so a
        draining engine can still finish its backlog; no admission
        counters fire (the request was already counted)."""
        with self._cond:
            self._items.appendleft(request)
            self._cond.notify_all()

    def wait_nonempty(self, timeout):
        """Park until something is queued (or close/timeout)."""
        with self._cond:
            if self._items or self._closed:
                return
            self._cond.wait(timeout)

    def close(self, drain=True):
        """Stop admissions. drain=True leaves queued requests for the
        engine to finish; drain=False fails them all right now."""
        with self._cond:
            self._closed = True
            self._drain = drain
            if not drain:
                while self._items:
                    req = self._items.popleft()
                    self._count("rejected_closed")
                    req._fail(ServerClosedError(
                        f"request {req.id} dropped: non-drain shutdown"))
            self._cond.notify_all()
