"""Bounded admission queue + request futures for the serving runtime.

Ref parity: the reference serves through AnalysisPredictor behind
paddle_serving's brpc front (bounded task queues, per-request deadlines,
fast rejection on overload). Here the queue is the in-process contract:
`submit` never blocks the engine — it either admits within capacity or
sheds immediately (429-style `QueueFullError`), and every request
carries an absolute deadline checked both while queued and mid-decode.

Fault sites (framework/faults.py grammar): ``serving.submit`` fires on
every admission attempt (a `drop` action sheds the request exactly as a
full queue would — deterministic overload), ``serving.dequeue`` on every
pop by the batch assembler / decode engine.

Multi-tenant admission (ISSUE 20): `TenantFairQueue` keeps the same
submit/pop/requeue contract but runs deficit-round-robin weighted fair
queueing over per-tenant FIFOs — each scheduler visit credits a tenant
``quantum * weight`` tokens of deficit and serves its head while the
deficit covers the head's cost (prompt + max_new tokens), so a flash
crowd from one tenant cannot starve another's share. Per-tenant
token-bucket budgets shed over-budget submissions with the retriable
`TenantBudgetError` whose ``retry_after_s`` is derived from the
bucket's refill; fault site ``serving.admit_tenant`` fires per
admission decision (tagged with the tenant, ``drop`` = deterministic
budget shed).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque

from ..framework import faults, monitor
from ..framework.flags import flag

__all__ = [
    "ServingError", "QueueFullError", "CapacityExhaustedError",
    "ServerClosedError", "DeadlineExceededError", "RequestCancelled",
    "ReplicaDiedError", "RetriesExhaustedError", "BrownoutShedError",
    "TenantBudgetError", "Request", "AdmissionQueue", "TenantFairQueue",
]


class ServingError(RuntimeError):
    """Base of the serving-side request failures; `status` carries the
    HTTP status the optional front door maps it to, `retriable` whether
    a client (or the in-process fleet Router) may transparently retry
    the same request, and `retry_after_s` the backoff hint the HTTP
    front surfaces as a ``Retry-After`` header on 429/503."""

    status = 500
    retriable = False
    retry_after_s = 1.0


class QueueFullError(ServingError):
    """Load shed: the bounded admission queue is at capacity.
    Retriable — the overload is transient by construction."""

    status = 429
    retriable = True


class CapacityExhaustedError(ServingError):
    """The request's KV-block demand exceeds the whole physical pool —
    retriable (429): a smaller request, or a bigger
    FLAGS_serving_kv_blocks, would be admitted."""

    status = 429
    retriable = True


class ServerClosedError(ServingError):
    """Submitted after shutdown began (or pending at a non-drain stop).
    Retriable: a fresh server (or a restarted fleet replica) would
    accept the same request."""

    status = 503
    retriable = True


class DeadlineExceededError(ServingError):
    """The request's deadline passed while queued or mid-decode."""

    status = 504


class RequestCancelled(ServingError):
    """The client cancelled; the engine evicts at the next step."""

    status = 499


class ReplicaDiedError(ServingError):
    """The replica holding this request crashed or stopped heartbeating;
    the fleet Router replays the request from its original prompt on a
    healthy replica (failover), so a client normally never sees this —
    it surfaces only when every replay avenue is exhausted."""

    status = 503
    retriable = True


class VersionRetiredError(ServingError):
    """A failover replay was pinned to the weight version its original
    attempt decoded on, but no replica serves (or will rebuild to) that
    version any more — the rollout retired it. Replaying on different
    weights would silently break bitwise first-wins semantics, so the
    request fails retriable instead: the client resubmits and decodes
    cleanly on the current version."""

    status = 503
    retriable = True


class RetriesExhaustedError(ServingError):
    """A retriable failure outlived the request's retry budget; the
    final underlying error rides along as ``last_error``."""

    status = 503
    retriable = True

    def __init__(self, message, last_error=None):
        super().__init__(message)
        self.last_error = last_error


class BrownoutShedError(QueueFullError):
    """Shed by fleet brownout: under sustained overload, requests below
    the priority floor are rejected first (429, retriable)."""


class TenantBudgetError(QueueFullError):
    """Shed by per-tenant admission: the tenant's token-bucket budget
    is exhausted (429, retriable). ``retry_after_s`` is set per
    instance from the bucket's refill rate, so the HTTP front's
    ``Retry-After`` header tells the client exactly when the budget
    next covers a request."""

    def __init__(self, message, retry_after_s=1.0):
        super().__init__(message)
        self.retry_after_s = max(float(retry_after_s), 0.001)


_ids = itertools.count(1)


class Request:
    """One unit of serving work + its future.

    `payload` is mode-specific (a 1-D prompt id array for the decode
    engine, one unbatched sample for the dynamic batcher); generation
    parameters ride along in `gen`, and `priority` (higher = more
    important) steers fleet brownout shedding. The completing thread
    calls `_complete`/`_fail`; clients block in `result()`.

    Resolution is FIRST-WINS and exactly-once: `_complete`/`_fail`
    return True only for the call that actually resolved the future, so
    a fleet Router can race a failover replay against a hung replica's
    late completion and deliver exactly one outcome to the client.
    Done-callbacks registered via `add_done_callback` fire exactly once,
    on the resolving thread, after the event is set.
    """

    def __init__(self, payload, *, timeout=None, priority=0, **gen):
        self.id = next(_ids)
        self.payload = payload
        self.gen = gen
        self.priority = priority
        self.arrival = time.monotonic()
        self.deadline = self.arrival + timeout if timeout else None
        self._event = threading.Event()
        self._value = None
        self._error = None
        self._cancel = False
        self._lock = threading.Lock()
        self._callbacks: list = []
        self._wake = None     # queue-side nudge, attached on admission

    # -- client side --------------------------------------------------------

    def cancel(self):
        """Cancel: fails the future PROMPTLY with `RequestCancelled` (a
        client blocked in `result()` wakes immediately instead of at the
        engine's next step boundary) and flags the request so the queue
        sweeps it and the engine evicts its slot at the next boundary —
        the work is reclaimed, not just the wait."""
        self._cancel = True
        self._fail(RequestCancelled(f"request {self.id} cancelled"))
        wake = self._wake
        if wake is not None:
            wake()

    @property
    def cancelled(self):
        return self._cancel

    def done(self):
        return self._event.is_set()

    def add_done_callback(self, fn):
        """Run ``fn(self)`` once the future resolves (immediately if it
        already has). Exceptions from ``fn`` are swallowed — a broken
        observer must not corrupt the completing thread."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        try:
            fn(self)
        except Exception:  # noqa: BLE001 — observer-only
            pass

    def result(self, timeout=None, cancel_on_timeout=False):
        """Block for the outcome. With ``cancel_on_timeout`` a client
        that gives up also cancels the request, so its queue slot /
        decode slot is reclaimed instead of leaking until the deadline
        (or forever, if it had none)."""
        if not self._event.wait(timeout):
            if cancel_on_timeout:
                self.cancel()
            raise TimeoutError(
                f"request {self.id} not done within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._value

    def exception(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.id} not done within {timeout}s")
        return self._error

    # -- engine side --------------------------------------------------------

    def expired(self, now=None):
        return self.deadline is not None and \
            (now if now is not None else time.monotonic()) > self.deadline

    def _resolve(self, value, error):
        with self._lock:
            if self._event.is_set():
                return False          # first resolution won; drop this one
            self._value = value
            self._error = error
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            try:
                fn(self)
            except Exception:  # noqa: BLE001 — observer-only
                pass
        return True

    def _complete(self, value):
        return self._resolve(value, None)

    def _fail(self, error):
        return self._resolve(None, error)


class AdmissionQueue:
    """Bounded FIFO with deadline-aware pops and graceful drain.

    submit() is the admission-control point: over-capacity submissions
    raise `QueueFullError` immediately (the fast 429) instead of
    blocking the client into an unbounded backlog; a closed queue raises
    `ServerClosedError`. pop() silently fails+skips requests whose
    deadline already passed — they never reach a slot.
    """

    def __init__(self, cap, *, metrics=None):
        if cap < 1:
            raise ValueError(f"queue cap must be >= 1, got {cap}")
        self.cap = cap
        self._items: deque = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._drain = True
        self._metrics = metrics

    def _count(self, name, n=1):
        monitor.stat_add(f"serving.{name}", n)
        if self._metrics is not None:
            self._metrics.inc(name, n)

    @property
    def depth(self):
        with self._cond:
            return len(self._items)

    @property
    def closed(self):
        return self._closed

    def drained(self):
        """True once closed and empty — the engine's exit condition."""
        with self._cond:
            return self._closed and not self._items

    def submit(self, request: Request):
        """Admit or shed. Returns `request` for chaining."""
        self._count("submitted")
        if faults.fault_point("serving.submit", request) is faults.DROP:
            # deterministic overload: the drop action sheds exactly as a
            # full queue would
            self._count("rejected_queue_full")
            raise QueueFullError(
                f"request {request.id} shed (injected overload)")
        with self._cond:
            if self._closed:
                self._count("rejected_closed")
                raise ServerClosedError(
                    f"request {request.id} rejected: server shutting down")
            if len(self._items) >= self.cap:
                self._count("rejected_queue_full")
                raise QueueFullError(
                    f"request {request.id} rejected: queue at capacity "
                    f"{self.cap}")
            self._items.append(request)
            request._wake = self._notify
            self._cond.notify_all()
        self._count("accepted")
        return request

    def _notify(self):
        """Nudge the queue condition (a cancelled request wakes a
        blocked pop so its entry is swept promptly, not lazily)."""
        with self._cond:
            self._cond.notify_all()

    def pop(self, timeout=0.0):
        """Next live request, or None when nothing arrived within
        `timeout` (or the queue is drained). Expired/cancelled requests
        are failed in place and skipped — their futures resolve OUTSIDE
        the queue lock, so done-callbacks may safely touch queues."""
        deadline = time.monotonic() + timeout
        while True:
            got = None
            finished = False
            to_fail: list = []
            with self._cond:
                while self._items:
                    req = self._items.popleft()
                    if req.cancelled:
                        to_fail.append(("cancelled", req, RequestCancelled(
                            f"request {req.id} cancelled while queued")))
                        continue
                    if req.expired():
                        to_fail.append((
                            "timeouts", req, DeadlineExceededError(
                                f"request {req.id} deadline exceeded "
                                f"after "
                                f"{time.monotonic() - req.arrival:.3f}s "
                                "in queue")))
                        continue
                    got = req
                    break
                if got is None:
                    if self._closed:
                        finished = True
                    else:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            finished = True
                        else:
                            self._cond.wait(remaining)
            for name, req, err in to_fail:
                self._count(name)
                req._fail(err)
            if got is not None:
                faults.fault_point("serving.dequeue", got)
                return got
            if finished:
                return None

    def requeue(self, request: Request):
        """Push an already-admitted request back to the queue *head*
        (FIFO order preserved). Used by the paged engine when the block
        pool can't hold the request right now — it waits for in-flight
        evictions instead of being shed. Works on a closed queue so a
        draining engine can still finish its backlog; no admission
        counters fire (the request was already counted)."""
        with self._cond:
            self._items.appendleft(request)
            self._cond.notify_all()

    def wait_nonempty(self, timeout):
        """Park until something is queued (or close/timeout)."""
        with self._cond:
            if self._items or self._closed:
                return
            self._cond.wait(timeout)

    def close(self, drain=True):
        """Stop admissions. drain=True leaves queued requests for the
        engine to finish; drain=False fails them all right now (futures
        resolve outside the queue lock)."""
        dropped: list = []
        with self._cond:
            self._closed = True
            self._drain = drain
            if not drain:
                while self._items:
                    dropped.append(self._items.popleft())
            self._cond.notify_all()
        for req in dropped:
            self._count("rejected_closed")
            req._fail(ServerClosedError(
                f"request {req.id} dropped: non-drain shutdown"))


class TenantFairQueue(AdmissionQueue):
    """Weighted-fair admission over per-tenant FIFOs (ISSUE 20).

    Same external contract as `AdmissionQueue` — submit admits or sheds
    without blocking, pop fails expired/cancelled entries outside the
    lock, requeue preserves head-of-line order, close/drained drive the
    engine's exit — but the pop order is deficit-round-robin: each
    arrival at a tenant's queue credits ``quantum * weight`` tokens of
    deficit, and the queue keeps serving while the deficit covers its
    head's cost (prompt + max_new tokens). A tenant that floods only
    drains its own share; everyone else's heads keep flowing at their
    weighted rate.

    With a `TenantDirectory` attached (``tenancy=``), each submission
    first debits the tenant's token-bucket budget — an over-budget
    request sheds with `TenantBudgetError` carrying the exact refill
    wait as ``retry_after_s``. Fault site ``serving.admit_tenant``
    fires per admission decision (tag = tenant name; ``drop`` = shed
    with the same typed 429)."""

    def __init__(self, cap, *, tenancy=None, quantum=None, metrics=None):
        super().__init__(cap, metrics=metrics)
        self.tenancy = tenancy
        self.quantum = int(quantum or flag("FLAGS_tenant_wfq_quantum"))
        self._queues: dict = {}      # tenant -> deque of Requests
        self._deficit: dict = {}     # tenant -> DRR token deficit
        self._rr: deque = deque()    # tenant rotation order
        self._head: deque = deque()  # requeued items: served first
        self._front_credited = False
        self._size = 0

    @staticmethod
    def _cost(request):
        """DRR cost of one request in tokens: prompt + decode budget —
        the same unit the tenant token-bucket debits."""
        payload = request.payload
        n = getattr(payload, "size", None)
        if n is None:
            n = len(payload) if hasattr(payload, "__len__") else 1
        return float(int(n) + int(request.gen.get("max_new_tokens", 16)))

    def _weight(self, tenant):
        if self.tenancy is None:
            return 1.0
        return max(float(self.tenancy.resolve(tenant).weight), 1e-3)

    def _tenant_inc(self, tenant, name, n=1):
        if self._metrics is not None and \
                hasattr(self._metrics, "tenant_inc"):
            self._metrics.tenant_inc(tenant, name, n)

    @property
    def depth(self):
        with self._cond:
            return self._size

    def tenant_depths(self):
        """Per-tenant backlog snapshot {tenant: queued} (requeued
        head-of-line items count against their own tenant)."""
        with self._cond:
            out = {t: len(q) for t, q in self._queues.items() if q}
            for req in self._head:
                t = req.gen.get("tenant") or "default"
                out[t] = out.get(t, 0) + 1
            return out

    def drained(self):
        with self._cond:
            return self._closed and not self._size

    def submit(self, request: Request):
        """Admit or shed. Budget debit -> ``serving.admit_tenant`` ->
        enqueue on the tenant's FIFO. Returns `request` for chaining."""
        self._count("submitted")
        tenant = request.gen.get("tenant") or "default"
        if faults.fault_point("serving.submit", request) is faults.DROP:
            self._count("rejected_queue_full")
            raise QueueFullError(
                f"request {request.id} shed (injected overload)")
        wait_hint = 1.0
        if self.tenancy is not None:
            spec = self.tenancy.resolve(tenant)
            ok, wait = spec.try_debit(self._cost(request))
            wait_hint = wait or wait_hint
            if not ok:
                self._count("rejected_budget")
                self._tenant_inc(tenant, "shed")
                raise TenantBudgetError(
                    f"request {request.id} shed: tenant {tenant!r} over "
                    f"token budget (refill in {wait:.3f}s)",
                    retry_after_s=wait)
        if faults.fault_point("serving.admit_tenant", request,
                              tag=tenant) is faults.DROP:
            self._count("rejected_budget")
            self._tenant_inc(tenant, "shed")
            raise TenantBudgetError(
                f"request {request.id} shed (injected tenant overload "
                f"for {tenant!r})", retry_after_s=wait_hint)
        with self._cond:
            if self._closed:
                self._count("rejected_closed")
                raise ServerClosedError(
                    f"request {request.id} rejected: server shutting down")
            if self._size >= self.cap:
                self._count("rejected_queue_full")
                self._tenant_inc(tenant, "shed")
                raise QueueFullError(
                    f"request {request.id} rejected: queue at capacity "
                    f"{self.cap}")
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
                self._deficit[tenant] = 0.0
                self._rr.append(tenant)
            q.append(request)
            self._size += 1
            request._wake = self._notify
            self._cond.notify_all()
        self._count("accepted")
        self._tenant_inc(tenant, "submitted")
        return request

    def _dead(self, req):
        """to_fail entry for a cancelled/expired request, else None."""
        if req.cancelled:
            return ("cancelled", req, RequestCancelled(
                f"request {req.id} cancelled while queued"))
        if req.expired():
            return ("timeouts", req, DeadlineExceededError(
                f"request {req.id} deadline exceeded after "
                f"{time.monotonic() - req.arrival:.3f}s in queue"))
        return None

    def _advance(self):
        self._rr.rotate(-1)
        self._front_credited = False

    def _pop_locked(self, to_fail):
        """One DRR scheduling decision under the lock. The rotation
        front keeps serving while its deficit covers head costs;
        crediting happens exactly once per arrival at a queue, so a
        front tenant cannot out-earn its rotation share. Terminates:
        every full rotation credits each live queue a positive amount,
        so some deficit eventually covers its (finite) head cost, and a
        sweep leaving nothing live exits with None."""
        while self._head:
            req = self._head.popleft()
            self._size -= 1
            dead = self._dead(req)
            if dead is None:
                return req
            to_fail.append(dead)
        while self._size:
            progressed = False
            for _ in range(len(self._rr)):
                t = self._rr[0]
                q = self._queues[t]
                while q:
                    dead = self._dead(q[0])
                    if dead is None:
                        break
                    to_fail.append(dead)
                    q.popleft()
                    self._size -= 1
                if not q:
                    self._deficit[t] = 0.0
                    self._advance()
                    continue
                progressed = True
                if not self._front_credited:
                    self._deficit[t] += self.quantum * self._weight(t)
                    self._front_credited = True
                if self._deficit[t] >= self._cost(q[0]):
                    self._deficit[t] -= self._cost(q[0])
                    self._size -= 1
                    return q.popleft()
                self._advance()
            if not progressed:
                return None
        return None

    def pop(self, timeout=0.0):
        """Next live request in weighted-fair order, or None."""
        deadline = time.monotonic() + timeout
        while True:
            got = None
            finished = False
            to_fail: list = []
            with self._cond:
                got = self._pop_locked(to_fail)
                if got is None:
                    if self._closed:
                        finished = True
                    else:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            finished = True
                        else:
                            self._cond.wait(remaining)
            for name, req, err in to_fail:
                self._count(name)
                req._fail(err)
            if got is not None:
                faults.fault_point("serving.dequeue", got)
                return got
            if finished:
                return None

    def requeue(self, request: Request):
        """Head-of-line push-back (paged-engine pool-wait contract):
        requeued items are served before any DRR decision and carry no
        extra deficit charge — their cost was already debited."""
        with self._cond:
            self._head.appendleft(request)
            self._size += 1
            self._cond.notify_all()

    def wait_nonempty(self, timeout):
        with self._cond:
            if self._size or self._closed:
                return
            self._cond.wait(timeout)

    def close(self, drain=True):
        dropped: list = []
        with self._cond:
            self._closed = True
            self._drain = drain
            if not drain:
                while self._head:
                    dropped.append(self._head.popleft())
                for q in self._queues.values():
                    while q:
                        dropped.append(q.popleft())
                self._size = 0
            self._cond.notify_all()
        for req in dropped:
            self._count("rejected_closed")
            req._fail(ServerClosedError(
                f"request {req.id} dropped: non-drain shutdown"))
