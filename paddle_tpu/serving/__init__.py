"""paddle_tpu.serving — request-level inference runtime.

Ref parity: paddle/fluid/inference/api/ (AnalysisPredictor zero-copy
run loop, paddle_infer::services::PredictorPool) plus the serving shell
the reference deploys around it. The TPU-native redesign is
iteration-level ("continuous") batching in the Orca lineage:

- `AdmissionQueue` — bounded queue, per-request deadline, fast 429-style
  shed on overload, graceful drain (queueing.py);
- `DynamicBatcher` — coalesces concurrent requests into shape-bucketed,
  padded batches; every bucket compiles exactly once (batcher.py);
- `SlotEngine` — continuous-batching GPT decode over a block-paged KV
  cache (vLLM-style block tables + SGLang-style radix prefix sharing,
  paging.py) with chunked prefill folded into one compiled step,
  join-at-step admission by free blocks, and eviction on
  EOS/max-len/deadline — plus fast decode: draft-model speculative
  decoding with rejection sampling (FLAGS_serving_spec_len) and an
  int8 frozen-weight path through a dequant-matmul epilogue
  (FLAGS_serving_quantize) (engine.py);
- `ServingMetrics` — QPS, queue depth, batch occupancy, latency
  percentiles; JSON-exportable, spans mirrored into the profiler's
  chrome trace (metrics.py);
- `ReplicaSet` / `Router` — the resilient fleet: N supervised engine
  replicas with heartbeat watchdogs and backed-off restarts, fronted
  by failover replay, budgeted retries, hedging, per-replica circuit
  breakers, and brownout shedding (fleet.py) — now elastic:
  `add_replica`/`remove_replica(drain=True)` scale membership under
  load with zero lost/duplicated requests;
- `Autoscaler` — grows/shrinks the fleet from the SLO error budget
  (windowed p99 vs FLAGS_fleet_slo_p99_ms, utilisation watermarks,
  brownout) with hysteresis + cooldown (autoscale.py);
- `WeightRegistry` / `RolloutController` — zero-downtime model
  rollout: versioned checkpoint ingestion with READABLE/checksum
  gates, rolling canary upgrades through drain→rebuild, golden-prompt
  bitwise + SLO burn gates, and auto-rollback to the pinned previous
  version (rollout.py);
- `ShardingPlan` / `match_partition_rules` — mesh-sharded serving:
  partition-rule-driven TP/GSPMD weight + paged-KV sharding over a
  (dp, mp) device mesh, reusing the training Column/RowParallel
  layout conventions (sharding.py, FLAGS_serving_mesh);
- `KVMailbox` / `migrate_prefix` — disaggregated prefill/decode:
  deadline-guarded prefill→decode KV-block streaming behind the
  Router (migrate.py, FLAGS_serving_disagg);
- `KVSpillStore` / `open_spill_store` — the global KV fabric: cold
  KV blocks spill to a crash-safe, crc-framed SSD tier on eviction
  and restore on session resume through the all-or-nothing admission
  path; weight-rollout commits generation-fence stale records
  (`SpillFencedError`), and the Router's prefix-affinity routing
  steers each request to the replica holding the longest live prefix
  match (kvstore.py, FLAGS_serving_kv_spill_dir,
  FLAGS_serving_prefix_affinity);
- `TenantDirectory` / `TenantFairQueue` / `ArtifactCatalog` /
  `AdapterRollout` — the multi-tenant platform: batched LoRA adapter
  banks inside the one compiled decode step (``submit(...,
  adapter_id=k)``, hot-swapped with zero retraces through the
  rollout-commit path), a catalog of named (model, adapter, version)
  artifacts with sha256 manifests, weighted-fair (deficit round
  robin) per-tenant admission with token budgets, SLO classes, and
  tier-based brownout shedding (tenancy.py, queueing.py,
  FLAGS_serving_max_adapters, FLAGS_tenant_default_budget);
- `Scenario` / `Arrival` / `replay` — the seeded open-loop traffic
  simulator every serving bench replays (workload.py);
- `Server` / `http_front` — the user-facing shell (server.py);
  ``Server(model, replicas=2)`` serves through the fleet.

Everything runs and certifies on CPU (`JAX_PLATFORMS=cpu`) with
thread-based clients; no network required.
"""

from .autoscale import Autoscaler  # noqa: F401
from .batcher import (  # noqa: F401
    DynamicBatcher, bucket_for, bucket_ladder, pad_batch,
)
from .engine import SlotEngine  # noqa: F401
from .fleet import (  # noqa: F401
    CircuitBreaker, Replica, ReplicaSet, Router, retriable,
)
from .kvstore import (  # noqa: F401
    KVSpillStore, SpillFencedError, open_spill_store, reset_spill_stores,
)
from .metrics import ServingMetrics, percentile  # noqa: F401
from .migrate import KVMailbox, migrate_prefix  # noqa: F401
from .paging import (  # noqa: F401
    NULL_BLOCK, BlockAllocator, PoolExhausted, PrefixCache,
    positions_to_rows,
)
from .queueing import (  # noqa: F401
    AdmissionQueue, BrownoutShedError, CapacityExhaustedError,
    DeadlineExceededError, QueueFullError, ReplicaDiedError, Request,
    RequestCancelled, RetriesExhaustedError, ServerClosedError,
    ServingError, TenantBudgetError, TenantFairQueue,
    VersionRetiredError,
)
from .rollout import (  # noqa: F401
    RolloutController, RolloutError, RolloutGateError, WeightRegistry,
    WeightVersion, golden_digests,
)
from .autoscale import SLOWindow  # noqa: F401
from .server import Server, http_front  # noqa: F401
from .tenancy import (  # noqa: F401
    DEFAULT_TENANT, AdapterRollout, Artifact, ArtifactCatalog,
    TenantDirectory, TenantSpec,
)
from .sharding import (  # noqa: F401
    GPT_PARTITION_RULES, ShardingPlan, build_mesh, match_partition_rules,
    mesh_spec_of, parse_mesh_spec, resolve_mesh,
)
from .workload import Arrival, Scenario, replay  # noqa: F401

__all__ = [
    "AdapterRollout", "AdmissionQueue", "Arrival", "Artifact",
    "ArtifactCatalog", "Autoscaler", "BlockAllocator",
    "BrownoutShedError",
    "CapacityExhaustedError", "CircuitBreaker", "DEFAULT_TENANT",
    "DeadlineExceededError",
    "DynamicBatcher", "GPT_PARTITION_RULES", "KVMailbox", "KVSpillStore",
    "NULL_BLOCK",
    "PoolExhausted", "PrefixCache",
    "QueueFullError", "Replica", "ReplicaDiedError", "ReplicaSet",
    "Request", "RequestCancelled", "RetriesExhaustedError",
    "RolloutController", "RolloutError", "RolloutGateError", "Router",
    "SLOWindow", "Scenario", "Server", "ServerClosedError",
    "ServingError", "ServingMetrics", "ShardingPlan", "SlotEngine",
    "SpillFencedError", "TenantBudgetError", "TenantDirectory",
    "TenantFairQueue", "TenantSpec", "VersionRetiredError",
    "WeightRegistry", "WeightVersion",
    "bucket_for", "bucket_ladder", "build_mesh", "golden_digests",
    "http_front", "match_partition_rules", "mesh_spec_of",
    "migrate_prefix", "open_spill_store",
    "pad_batch", "parse_mesh_spec", "percentile", "positions_to_rows",
    "replay", "reset_spill_stores", "resolve_mesh", "retriable",
]
