"""In-process serving front: one object tying admission control, the
continuous-batching engine (or the dynamic batcher), and metrics.

Ref parity: paddle/fluid/inference/api + paddle_serving's server shell —
`Server` plays the role of the predictor-pool-plus-brpc-service pair,
collapsed to a thread-safe `submit()/result()` API so it runs anywhere
(CPU tier-1 included) with no network dependency. `http_front` is the
optional stdlib front door mapping the same API onto HTTP.

    cfg = GPTConfig(..., use_parallel=False)
    model = GPTForPretraining(cfg)
    with serving.Server(model, max_slots=4) as srv:
        fut = srv.submit([1, 2, 3], max_new_tokens=8)
        ids = fut.result()              # np.int32 [prompt + generated]
        print(srv.snapshot()["qps"])

Pass ``replicas=N`` (N >= 2) to serve through the resilient fleet
(fleet.Router): N supervised engine replicas with failover replay,
retries, hedging, circuit breakers, and brownout shedding — same
`submit()/generate()` API, plus `priority=` on submit. Extra Router
knobs ride in ``fleet=dict(...)``.
"""

from __future__ import annotations

import json
import threading

import numpy as np

from ..framework.flags import flag
from .batcher import DynamicBatcher
from .engine import SlotEngine
from .metrics import ServingMetrics
from .queueing import ServingError

__all__ = ["Server", "http_front"]


class Server:
    """Serving front over a model.

    mode="generate" (default): `model` is a GPTForPretraining; requests
    are prompts and the backend is the continuous-batching `SlotEngine`.
    mode="batch": `fn` is a batch function (or pass a jax-traceable
    callable as `model`); requests are single samples coalesced by the
    `DynamicBatcher`.

    Fast-decode knobs (forwarded to every engine, single or fleet):
    ``spec_len``/``draft_model`` enable speculative decoding (self-draft
    when no draft model is given), ``quantize`` freezes weights to int8
    for the dequant decode path. Defaults come from
    FLAGS_serving_spec_len / FLAGS_serving_quantize.

    Mesh-sharded serving: ``mesh='dpD.mpM'`` (or a prebuilt Mesh;
    default FLAGS_serving_mesh) shards every engine's weights and paged
    KV pool over a (dp, mp) device mesh via serving/sharding.py. Fleet
    mode composes with disaggregated prefill/decode — pass
    ``fleet=dict(roles=[...], role_kw={...}, disagg=True)``.

    Durable sessions: ``spill_dir=`` (default
    FLAGS_serving_kv_spill_dir) turns on the persistent SSD KV tier —
    every engine of the server spills evicted prefix-cache blocks
    there and restores them on session resume (serving/kvstore.py);
    fleet mode pairs it with prefix-affinity routing
    (FLAGS_serving_prefix_affinity or
    ``fleet=dict(prefix_affinity=...)``).

    Multi-tenant serving: ``max_adapters=N`` gives every engine an
    N-row batched LoRA adapter bank (``submit(..., adapter_id=k)``;
    row 0 = base model) and ``tenancy=TenantDirectory(...)`` switches
    admission to weighted-fair per-tenant queues with token budgets
    and tier-based brownout (``submit(..., tenant=name)``).
    """

    def __init__(self, model=None, *, mode="generate", fn=None,
                 max_slots=None, max_seq_len=None, block_size=None,
                 num_blocks=None, prefill_chunk=None, prefix_cache=None,
                 queue_cap=None, max_batch=None, max_wait_s=0.002,
                 cache_dtype=None, jit=True, strict_shapes=False,
                 warmup=True, replicas=1, fleet=None, spec_len=None,
                 draft_model=None, quantize=None, w8a8=None, mesh=None,
                 spill_dir=None, max_adapters=None, lora_rank=None,
                 tenancy=None):
        self.mode = mode
        self.metrics = ServingMetrics()
        self._warmup = warmup
        self.router = None
        self.tenancy = tenancy
        if mode == "generate" and (replicas > 1 or fleet is not None):
            if model is None:
                raise ValueError("generate mode needs a GPT model")
            from .fleet import Router

            engine_kw = dict(
                max_slots=max_slots, max_seq_len=max_seq_len,
                block_size=block_size, num_blocks=num_blocks,
                prefill_chunk=prefill_chunk, prefix_cache=prefix_cache,
                cache_dtype=cache_dtype, strict_shapes=strict_shapes,
                spec_len=spec_len, draft_model=draft_model,
                quantize=quantize, w8a8=w8a8, mesh=mesh,
                spill_dir=spill_dir, max_adapters=max_adapters,
                lora_rank=lora_rank)
            fleet_kw = dict(fleet or {})
            if tenancy is not None:
                fleet_kw.setdefault("tenancy", tenancy)
            self.router = Router(
                model, max(replicas, 1), engine_kw=engine_kw,
                metrics=self.metrics, queue_cap=queue_cap,
                warmup=warmup, **fleet_kw)
            self.engine = None
            self.batcher = None
        elif mode == "generate":
            if model is None:
                raise ValueError("generate mode needs a GPT model")
            from .queueing import AdmissionQueue, TenantFairQueue

            cap = queue_cap or flag("FLAGS_serving_queue_cap")
            if tenancy is not None:
                queue = TenantFairQueue(cap, tenancy=tenancy,
                                        metrics=self.metrics)
            else:
                queue = AdmissionQueue(cap, metrics=self.metrics)
            self.engine = SlotEngine(
                model, max_slots=max_slots, max_seq_len=max_seq_len,
                block_size=block_size, num_blocks=num_blocks,
                prefill_chunk=prefill_chunk, prefix_cache=prefix_cache,
                cache_dtype=cache_dtype, metrics=self.metrics,
                queue=queue, strict_shapes=strict_shapes,
                spec_len=spec_len, draft_model=draft_model,
                quantize=quantize, w8a8=w8a8, mesh=mesh,
                spill_dir=spill_dir, max_adapters=max_adapters,
                lora_rank=lora_rank)
            self.batcher = None
        elif mode == "batch":
            target = fn if fn is not None else model
            if target is None or not callable(target):
                raise ValueError("batch mode needs a callable fn")
            self.batcher = DynamicBatcher(
                target, max_batch=max_batch, max_wait_s=max_wait_s,
                queue_cap=queue_cap, metrics=self.metrics, jit=jit)
            self.engine = None
        else:
            raise ValueError(f"unknown serving mode {mode!r}")
        self._started = False

    @classmethod
    def from_router(cls, router):
        """Wrap an already-built (and possibly already-started) fleet
        Router so `http_front` / `version_info()` / `snapshot()` serve
        it — the rollout tests drive a Router directly and still want
        the HTTP surface. The wrapper shares the Router's metrics and
        never owns lifecycle beyond forwarding start/shutdown."""
        srv = cls.__new__(cls)
        srv.mode = "generate"
        srv.metrics = router.metrics
        srv._warmup = False
        srv.router = router
        srv.engine = None
        srv.batcher = None
        srv._started = router._sup is not None
        return srv

    @classmethod
    def from_predictor(cls, predictor, **kw):
        """Batch-mode server over an inference.Predictor's loaded
        program (shares its weights; the exported program manages its
        own compilation, so jit wrapping is off)."""
        layer = predictor._layer

        def fn(x):
            out = layer(x)
            return out._value if hasattr(out, "_value") else out

        kw.setdefault("jit", False)
        return cls(fn=fn, mode="batch", **kw)

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        if not self._started:
            if self.router is not None:
                self.router.start()
            else:
                if self.engine is not None and self._warmup \
                        and not self.engine._warmed:
                    self.engine.warmup()
                (self.engine or self.batcher).start()
            self._started = True
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown(drain=exc[0] is None)
        return False

    def shutdown(self, drain=True):
        """Graceful drain (finish queued + in-flight work) or fast stop
        (shed the queue, evict in-flight at the next step). Idempotent:
        a server never started — or already shut down — is a no-op, so
        double-shutdown (e.g. an explicit call inside a `with` block)
        never re-runs drain against stopped backends."""
        if not self._started:
            return
        self._started = False
        if self.router is not None:
            self.router.shutdown(drain=drain)
        elif self.engine is not None:
            self.engine.shutdown(drain=drain)
        else:
            self.batcher.close(drain=drain)

    # -- request API --------------------------------------------------------

    @property
    def queue(self):
        """The single backend's admission queue (engine/batcher modes).
        Fleet mode has one queue per replica — use `queue_depth()`."""
        backend = self.engine or self.batcher
        if backend is None:
            raise AttributeError(
                "fleet mode has a queue per replica; use queue_depth()")
        return backend.queue

    def queue_depth(self):
        if self.router is not None:
            return self.router.queue_depth
        return (self.engine or self.batcher).queue.depth

    def submit(self, payload, **kw):
        """Admit one request; returns a `Request` future. Generate mode
        takes a 1-D prompt + generation kwargs (plus `priority=` in
        fleet mode); batch mode one sample."""
        if not self._started:
            self.start()
        if self.router is not None:
            return self.router.submit(payload, **kw)
        if self.engine is not None:
            return self.engine.submit(payload, **kw)
        return self.batcher.submit(payload, **kw)

    def generate(self, prompt_ids, timeout=None, **kw):
        """Synchronous submit+wait."""
        return self.submit(prompt_ids, **kw).result(timeout)

    def snapshot(self):
        snap = self.metrics.snapshot(queue_depth=self.queue_depth())
        if self.router is not None:
            snap["fleet"] = self.router.snapshot()
        return snap

    def version_info(self):
        """Model-version view: current/previous version ids, rollout
        state, and the per-replica version map (`GET /v1/version` over
        `http_front` returns exactly this). Fleet mode delegates to the
        Router (which folds in an attached `RolloutController`); a
        single-engine server is always `static` on its build version."""
        if self.router is not None:
            return self.router.version_info()
        if self.engine is not None:
            return {"current": self.engine.weight_version,
                    "previous": None, "target": None,
                    "state": "static", "error": None,
                    "versions_live": [self.engine.weight_version],
                    "replicas": {self.engine.name:
                                 self.engine.weight_version}}
        return {"current": 0, "previous": None, "target": None,
                "state": "static", "error": None,
                "versions_live": [], "replicas": {}}

    def metrics_json(self, **kw):
        return json.dumps(self.snapshot(), **kw)

    def metrics_prometheus(self):
        """Prometheus text exposition of this server's metrics unified
        with the global monitor/timeline/goodput registries
        (observe.prometheus_text); fleet mode adds the per-replica
        state/restart/breaker gauges."""
        from .. import observe

        fleet = self.router.snapshot() if self.router is not None else None
        return observe.prometheus_text(serving=self.metrics,
                                       queue_depth=self.queue_depth(),
                                       fleet=fleet)


def http_front(server: Server = None, host="127.0.0.1", port=0, *,
               ranker=None):
    """Optional stdlib front door (bonus deliverable — the in-process
    API above is the contract). POST /v1/generate with a JSON body
    ``{"prompt": [ids...], "max_new_tokens": n, ...}`` returns
    ``{"ids": [...]}``; GET /metrics returns the snapshot and
    GET /v1/version the model-version view (current/previous ids,
    rollout state, per-replica version map). Serving errors map to
    their HTTP status (429 shed, 504 deadline, 503 version retired,
    ...), with a ``Retry-After`` backoff hint on 429/503. Requests may
    carry a tenant identity as an ``X-Tenant`` header or a ``tenant``
    body field (on both /v1/generate and /v1/rank); a tenant over its
    token budget gets a per-tenant 429 whose ``Retry-After`` is that
    tenant's own bucket refill time.

    Pass ``ranker=`` (a `rec.RankingService`) to also serve
    POST /v1/rank: ``{"dnn_ids": [...], "lr_ids": [...]}`` (wide&deep)
    or ``{"fields": [...]}`` (DeepFM) returns ``{"scores": [...]}``;
    2-D id arrays rank a whole candidate list in one call (the rows
    coalesce in the dynamic batcher). A front may serve both a `server`
    and a `ranker`; at least one is required.

    Returns the started `ThreadingHTTPServer`; its bound port is
    ``httpd.server_address[1]``. Call ``httpd.shutdown()`` to stop."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    if server is None and ranker is None:
        raise ValueError("http_front needs a server and/or a ranker")
    metrics_src = server if server is not None else ranker

    def rank_scores(req):
        timeout = req.pop("timeout", None)
        if "fields" in req:
            arrs = [np.asarray(req.pop("fields"), np.int64)]
        else:
            arrs = [np.asarray(req.pop("dnn_ids"), np.int64),
                    np.asarray(req.pop("lr_ids"), np.int64)]
        if arrs[0].ndim == 2:
            futs = [ranker.submit(*[a[i] for a in arrs], timeout=timeout)
                    for i in range(arrs[0].shape[0])]
            return [float(np.asarray(f.result(timeout)).reshape(-1)[0])
                    for f in futs]
        return [ranker.rank(*arrs, timeout=timeout)]

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _reply(self, code, obj, headers=None):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _reply_text(self, code, text,
                        ctype="text/plain; version=0.0.4; charset=utf-8"):
            body = text.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path, _, query = self.path.partition("?")
            if path == "/metrics":
                # content negotiation: JSON snapshot by default (the
                # original contract — a bare GET keeps working), the
                # Prometheus exposition when a scraper asks for it via
                # Accept: text/plain / openmetrics or ?format=prometheus
                accept = self.headers.get("Accept", "")
                if ("format=prometheus" in query
                        or "text/plain" in accept
                        or "openmetrics" in accept):
                    self._reply_text(200, metrics_src.metrics_prometheus())
                else:
                    self._reply(200, metrics_src.snapshot())
            elif path == "/v1/version" and server is not None:
                self._reply(200, server.version_info())
            else:
                self._reply(404, {"error": "not found"})

        def do_POST(self):
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                # tenant identity rides either as an `X-Tenant` header
                # or a `tenant` body field (body wins on conflict); the
                # tenant's admission budget answers 429s with its own
                # Retry-After refill time below
                xt = self.headers.get("X-Tenant")
                if xt and not req.get("tenant"):
                    req["tenant"] = xt
                if self.path == "/v1/generate" and server is not None:
                    prompt = req.pop("prompt")
                    timeout = req.pop("timeout", None)
                    out = server.generate(prompt, timeout=timeout, **req)
                    self._reply(200, {"ids": np.asarray(out).tolist()})
                elif self.path == "/v1/rank" and ranker is not None:
                    req.pop("tenant", None)   # ranker bills nothing yet
                    self._reply(200, {"scores": rank_scores(req)})
                else:
                    self._reply(404, {"error": "not found"})
            except ServingError as e:
                # clients get the same backoff contract the in-process
                # Router uses: `retriable` says whether resubmitting the
                # identical request can succeed, and overload/unavailable
                # responses carry a Retry-After hint
                headers = {}
                if e.status in (429, 503):
                    # instance attribute first: a TenantBudgetError
                    # carries the tenant's actual bucket refill time
                    headers["Retry-After"] = \
                        f"{e.retry_after_s:g}"
                self._reply(e.status, {
                    "error": str(e),
                    "type": type(e).__name__,
                    "retriable": bool(e.retriable),
                }, headers=headers)
            except Exception as e:  # noqa: BLE001 — bad request shape
                self._reply(400, {"error": str(e), "retriable": False})

    httpd = ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(target=httpd.serve_forever,
                              name="serving-http", daemon=True)
    thread.start()
    return httpd
