"""Persistent SSD KV tier: crash-safe cold storage for evicted
prefix-cache blocks.

The radix `PrefixCache` (paging.py) makes finished sequences' KV blocks
reusable — until pool pressure evicts them or the replica dies, at
which point a multi-turn session pays a full re-prefill. This module
applies the durable-state substrate the repo already trusts (the
crc-framed, torn-tail-tolerant WAL + tmp/rename snapshot machinery of
``distributed/ps/wal.py``) to attention state:

* **Spill on eviction** — when the cache evicts a cold block whose last
  reference is about to drop, the owning engine appends the block's KV
  rows here *before* the allocator frees it (append-before-evict: the
  record is durable by the time the bytes can be overwritten). Fault
  site ``serving.spill`` fires before each record write; a spill
  failure loses durability for that block, never correctness — the
  eviction proceeds and the allocator stays balanced.

* **Restore on resume** — a later request whose token prefix extends a
  spilled record re-stages the block through the engine's all-or-
  nothing admission path (`SlotEngine._maybe_restore`). Every record
  re-verifies its crc32 at read time, so a torn tail or bit-rotted
  record degrades to re-prefill, never to wrong tokens.

* **Generation fencing** — each record carries the weight version its
  KV was computed under. `attach_registry` subscribes to the
  `WeightRegistry` commit boundary: committing a rollout fences every
  record of a retired version, and a resume against a fenced record
  raises typed retriable `SpillFencedError` (the spilled-KV analogue of
  `VersionRetiredError`) so the caller falls back to re-prefill on the
  new weights.

Records are framed ``<I crc32> <I len> payload`` exactly like the PS
WAL; compaction rewrites the live records via tmp + fsync + rename when
the file crosses ``FLAGS_serving_kv_spill_cap_mb``. One store instance
is shared per directory (`open_spill_store`), so every replica of a
fleet spills into — and can resume from — the same tier: a session
whose affine replica died between turns restores its KV anywhere.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib

import numpy as np

from ..framework import faults, monitor
from ..framework.flags import flag
from .queueing import ServingError

__all__ = ["KVSpillStore", "SpillFencedError", "open_spill_store",
           "reset_spill_stores"]

_HDR = struct.Struct("<II")           # crc32(payload), len(payload)
#: digest(20B sha1), generation(int64), n_tokens, block_size, n_layers,
#: n_heads, head_dim, dtype tag (8B ascii, NUL-padded)
_META = struct.Struct("<20sq5i8s")

SPILL_FILE = "kv.spill"


class SpillFencedError(ServingError):
    """The spilled KV record was written under a weight version a
    rollout has since retired — its attention state is meaningless on
    the current weights. Retriable: the caller re-prefills on the live
    version (same contract as `VersionRetiredError` for replays)."""

    status = 503
    retriable = True


def _frame(payload: bytes) -> bytes:
    return _HDR.pack(zlib.crc32(payload), len(payload)) + payload


def _pack_record(digest, generation, tokens, layers):
    tokens = np.ascontiguousarray(tokens, np.int32)
    k0 = np.ascontiguousarray(layers[0][0])
    nh, bs, hd = k0.shape
    dtype = str(k0.dtype).encode()[:8]
    parts = [_META.pack(digest, int(generation), tokens.size, bs,
                        len(layers), nh, hd, dtype),
             tokens.tobytes()]
    for k, v in layers:
        parts.append(np.ascontiguousarray(k).tobytes())
        parts.append(np.ascontiguousarray(v).tobytes())
    return b"".join(parts)


def _unpack_record(payload):
    digest, gen, n_tok, bs, n_layers, nh, hd, dtype = \
        _META.unpack_from(payload, 0)
    pos = _META.size
    tokens = np.frombuffer(payload, np.int32, count=n_tok, offset=pos)
    pos += n_tok * 4
    dt = np.dtype(dtype.rstrip(b"\x00").decode())
    rows = nh * bs * hd
    layers = []
    for _ in range(n_layers):
        k = np.frombuffer(payload, dt, count=rows, offset=pos)
        pos += rows * dt.itemsize
        v = np.frombuffer(payload, dt, count=rows, offset=pos)
        pos += rows * dt.itemsize
        layers.append((k.reshape(nh, bs, hd), v.reshape(nh, bs, hd)))
    return {"digest": digest, "generation": gen,
            "tokens": tokens, "block_size": bs, "layers": layers}


class KVSpillStore:
    """Append-only, crc-framed store of spilled KV blocks, keyed by the
    same cumulative sha1 token-prefix digest the `PrefixCache` indexes
    on. Thread-safe; shared across every replica of a process."""

    def __init__(self, path, *, cap_mb=None, metrics=None):
        if os.path.isdir(path):
            path = os.path.join(path, SPILL_FILE)
        self.path = path
        self.cap_mb = flag("FLAGS_serving_kv_spill_cap_mb") \
            if cap_mb is None else cap_mb
        self.metrics = metrics
        self._lock = threading.RLock()
        #: digest -> (offset of payload, payload length, generation)
        self._index: dict = {}
        self._fenced: set = set()      # fenced generations
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        good_end = self._scan()
        self._f = open(path, "r+b" if os.path.exists(path) else "w+b")
        self._f.truncate(good_end)     # drop any torn tail for good
        self._f.seek(good_end)

    # -- scan / recovery -----------------------------------------------------

    def _scan(self):
        """Rebuild the index from an existing file; returns the offset
        of the first torn/corrupt byte (everything after is dead)."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except OSError:
            return 0
        pos = 0
        while pos + _HDR.size <= len(raw):
            crc, n = _HDR.unpack_from(raw, pos)
            body = raw[pos + _HDR.size:pos + _HDR.size + n]
            if len(body) < n or zlib.crc32(body) != crc:
                break                   # torn tail — end of durable data
            try:
                digest, gen = struct.unpack_from("<20sq", body, 0)
            except struct.error:
                break
            # later records supersede earlier ones for the same prefix
            self._index[digest] = (pos + _HDR.size, n, gen)
            pos += _HDR.size + n
        return pos

    # -- counters ------------------------------------------------------------

    def _inc(self, name, n=1):
        if self.metrics is not None:
            self.metrics.inc(name, n)
        else:
            monitor.stat_add(f"serving.{name}", n)

    # -- spill side ----------------------------------------------------------

    def append(self, digest, generation, tokens, layers):
        """Durably append one evicted block's KV rows. Fires the
        ``serving.spill`` fault site before the write; must be called
        *before* the allocator frees the block (append-before-evict)."""
        payload = _pack_record(digest, generation, tokens, layers)
        buf = _frame(payload)
        with self._lock:
            faults.fault_point("serving.spill")
            off = self._f.tell()
            self._f.write(buf)
            self._f.flush()
            os.fsync(self._f.fileno())
            self._index[digest] = (off + _HDR.size, len(payload),
                                   int(generation))
            self._inc("kv_spilled_blocks")
            self._inc("kv_spill_bytes", len(buf))
            if self.cap_mb and self._f.tell() > self.cap_mb * (1 << 20):
                self._compact_locked()
        return len(buf)

    def get(self, digest):
        """The record for a prefix digest, or None when absent or
        corrupt (bit rot re-verifies at read time and degrades to
        re-prefill). Raises `SpillFencedError` when the record's weight
        generation has been fenced by a rollout commit."""
        with self._lock:
            entry = self._index.get(digest)
            if entry is None:
                return None
            off, n, gen = entry
            if gen in self._fenced:
                raise SpillFencedError(
                    f"spilled KV for this prefix was written under "
                    f"retired weight version {gen}; re-prefill on the "
                    "live version")
            self._f.flush()
            with open(self.path, "rb") as f:
                f.seek(off - _HDR.size)
                hdr = f.read(_HDR.size)
                body = f.read(n)
            if len(hdr) < _HDR.size:
                crc = None
            else:
                crc, _n = _HDR.unpack(hdr)
            if crc is None or len(body) < n or zlib.crc32(body) != crc:
                # bit rot / tamper: the record can never produce wrong
                # tokens — it simply stops existing
                self._index.pop(digest, None)
                self._inc("kv_restore_corrupt")
                return None
            return _unpack_record(body)

    def __contains__(self, digest):
        with self._lock:
            return digest in self._index

    def __len__(self):
        with self._lock:
            return len(self._index)

    # -- generation fencing --------------------------------------------------

    def fence(self, generation):
        """Fence one weight generation: resumes against its records now
        raise `SpillFencedError` until compaction drops them."""
        with self._lock:
            self._fenced.add(int(generation))
            n = sum(1 for (_o, _n, g) in self._index.values()
                    if g == int(generation))
            if n:
                self._inc("kv_invalidated_blocks", n)
            return n

    def fence_retired(self, is_live):
        """Fence every indexed generation for which ``is_live(gen)`` is
        False — the rollout-commit hook."""
        with self._lock:
            gens = {g for (_o, _n, g) in self._index.values()}
        return sum(self.fence(g) for g in sorted(gens)
                   if g not in self._fenced and not is_live(g))

    def attach_registry(self, registry):
        """Subscribe to a `WeightRegistry`: every commit fences the
        spilled records of versions the commit retired."""
        registry.subscribe(
            lambda _wv: self.fence_retired(registry.is_live))
        return self

    # -- compaction ----------------------------------------------------------

    def compact(self):
        with self._lock:
            return self._compact_locked()

    def _compact_locked(self):
        """Rewrite only the live, unfenced records (tmp + fsync +
        rename — a crash leaves the old or the new complete file)."""
        live = []
        for digest, (off, n, gen) in sorted(self._index.items(),
                                            key=lambda kv: kv[1][0]):
            if gen in self._fenced:
                continue
            self._f.flush()
            with open(self.path, "rb") as f:
                f.seek(off, 0)
                body = f.read(n)
            if len(body) == n:
                live.append((digest, gen, body))
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            index = {}
            for digest, gen, body in live:
                index[digest] = (f.tell() + _HDR.size, len(body), gen)
                f.write(_frame(body))
            f.flush()
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._index = index
        self._f = open(self.path, "r+b")
        self._f.seek(0, os.SEEK_END)
        monitor.stat_add("serving.kv_spill_compactions")
        return len(index)

    # -- admin ---------------------------------------------------------------

    @property
    def nbytes(self):
        with self._lock:
            return self._f.tell()

    def stats(self):
        with self._lock:
            return {"records": len(self._index),
                    "bytes": self._f.tell(),
                    "fenced_generations": sorted(self._fenced)}

    def close(self):
        with self._lock:
            if self._f is not None and not self._f.closed:
                self._f.flush()
                self._f.close()


# one shared store per directory: every replica in the process spills
# into — and resumes from — the same tier (the cross-replica resume
# path after a replica dies between turns)
_stores: dict = {}
_stores_lock = threading.Lock()


def open_spill_store(directory=None, *, metrics=None):
    """The process-shared `KVSpillStore` for a spill directory (default
    ``FLAGS_serving_kv_spill_dir``); None when the tier is disabled."""
    if directory is None:
        directory = flag("FLAGS_serving_kv_spill_dir")
    if not directory:
        return None
    key = os.path.abspath(directory)
    with _stores_lock:
        store = _stores.get(key)
        if store is None or store._f.closed:
            store = _stores[key] = KVSpillStore(key, metrics=metrics)
        elif metrics is not None and store.metrics is None:
            store.metrics = metrics
        return store


def reset_spill_stores():
    """Close and forget every shared store (test isolation)."""
    with _stores_lock:
        for store in _stores.values():
            store.close()
        _stores.clear()
