"""Multi-tenant serving: tenant directory, SLO classes, token budgets,
the (model, adapter, version) artifact catalog, and batched-adapter
rollouts (ISSUE 20).

Every serving subsystem so far multiplexed one model for one anonymous
tenant. This module adds the platform layer on top of the primitives
the repo already proved:

`TenantSpec` / `TenantDirectory`
    One tenant's admission contract — weighted-fair-queueing weight,
    priority class, SLO class (``gold``/``silver``/``bronze`` mapping
    to brownout tiers 2/1/0), and a lazily refilled token-bucket
    budget in tokens/second. `TenantDirectory` resolves names to specs
    (auto-creating defaults from ``FLAGS_tenant_default_budget``) and
    owns the fleet brownout floor: during brownout, tenants whose tier
    is below ``brownout_tier`` shed instead of a global priority floor.

`ArtifactCatalog`
    `WeightRegistry` generalized to *named* artifact lines keyed
    ``(kind, name)`` — e.g. ``("model", "base")`` and
    ``("adapter", "support-bot")`` — each with monotonically increasing
    versions, a per-leaf sha256 manifest, and the whole-artifact
    `rollout.artifact_digest`. Lines roll out independently: committing
    a new adapter version never touches the model line.

`AdapterRollout`
    The canary→wave→commit machinery from `RolloutController` applied
    to the engine's stacked LoRA bank: one healthy replica hot-swaps
    first (``SlotEngine.swap_adapters`` — a step-boundary, zero-retrace
    rebind behind fault site ``serving.adapter_swap``), an optional
    probe request certifies it live, then the rest of the fleet swaps
    and the catalog commits. Any failure mid-fleet swaps the OLD bank
    back onto every already-swapped replica — all-or-nothing fleet-wide,
    and a faulted single swap is all-or-nothing per engine (the old
    bank keeps serving bitwise).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..framework.flags import flag
from .rollout import artifact_digest

__all__ = ["DEFAULT_TENANT", "TenantSpec", "TenantDirectory",
           "Artifact", "ArtifactCatalog", "AdapterRollout"]

#: tenant name used when a request carries none
DEFAULT_TENANT = "default"

#: SLO class -> brownout tier (higher survives longer under brownout)
SLO_TIERS = {"bronze": 0, "silver": 1, "gold": 2}


class TenantSpec:
    """One tenant's admission contract.

    ``weight`` scales the deficit-round-robin quantum in
    `TenantFairQueue`; ``priority`` is the default request priority the
    workload generator stamps; ``slo_class`` maps to the brownout tier
    (``gold``=2 / ``silver``=1 / ``bronze``=0); ``budget_tokens_per_s``
    is a token bucket (capacity = rate * ``burst_s``, lazily refilled)
    debited per admission with the request's prompt + decode budget —
    0 means unlimited. Thread-safe: many submitting threads debit one
    bucket."""

    def __init__(self, name, *, weight=1.0, priority=0,
                 slo_class="bronze", slo_p99_ms=None,
                 budget_tokens_per_s=None, burst_s=1.0):
        if slo_class not in SLO_TIERS:
            raise ValueError(
                f"slo_class must be one of {sorted(SLO_TIERS)}, "
                f"got {slo_class!r}")
        if weight <= 0:
            raise ValueError(f"weight must be > 0, got {weight}")
        self.name = str(name)
        self.weight = float(weight)
        self.priority = int(priority)
        self.slo_class = str(slo_class)
        self.tier = SLO_TIERS[self.slo_class]
        self.slo_p99_ms = float(slo_p99_ms) if slo_p99_ms else None
        if budget_tokens_per_s is None:
            budget_tokens_per_s = flag("FLAGS_tenant_default_budget")
        self.budget_tokens_per_s = float(budget_tokens_per_s or 0)
        self.burst_s = float(burst_s)
        self._capacity = self.budget_tokens_per_s * max(self.burst_s,
                                                        1e-3)
        self._tokens = self._capacity
        self._last = time.monotonic()
        self._lock = threading.Lock()

    @property
    def unlimited(self):
        return not self.budget_tokens_per_s

    def _refill(self, now):
        self._tokens = min(
            self._capacity,
            self._tokens + (now - self._last) * self.budget_tokens_per_s)
        self._last = now

    def try_debit(self, tokens):
        """Debit ``tokens`` from the bucket. Returns ``(ok, wait_s)``:
        on success ``(True, 0.0)``; on an empty bucket ``(False, s)``
        where ``s`` is exactly how long the refill needs to cover this
        request — the ``Retry-After`` the HTTP front surfaces."""
        if self.unlimited:
            return True, 0.0
        tokens = float(tokens)
        with self._lock:
            now = time.monotonic()
            self._refill(now)
            if self._tokens >= tokens:
                self._tokens -= tokens
                return True, 0.0
            short = min(tokens, self._capacity) - self._tokens
            return False, max(short / self.budget_tokens_per_s, 1e-3)

    def budget_remaining(self):
        """Tokens currently in the bucket (None when unlimited)."""
        if self.unlimited:
            return None
        with self._lock:
            self._refill(time.monotonic())
            return self._tokens

    def to_dict(self):
        d = {"name": self.name, "weight": self.weight,
             "priority": self.priority, "slo_class": self.slo_class,
             "budget_tokens_per_s": self.budget_tokens_per_s,
             "burst_s": self.burst_s}
        if self.slo_p99_ms is not None:
            d["slo_p99_ms"] = self.slo_p99_ms
        return d


class TenantDirectory:
    """Name -> `TenantSpec` resolution + the fleet brownout floor.

    `resolve` never fails: an unregistered tenant gets a default
    bronze/weight-1 spec with the flag-default budget, so "no tenant
    configured" behaves exactly like the anonymous pre-tenancy world.
    ``brownout_tier`` is the shedding floor the fleet Router consults
    while browned out: tenants with ``spec.tier < brownout_tier`` shed
    (default 1 — bronze sheds, silver and gold ride through)."""

    def __init__(self, tenants=None, *, brownout_tier=1):
        self._specs: dict = {}
        self._lock = threading.Lock()
        self.brownout_tier = int(brownout_tier)
        if isinstance(tenants, dict):
            # {name: TenantSpec | kwargs-dict} mapping form
            for name, t in tenants.items():
                if isinstance(t, TenantSpec):
                    self.register(t)
                else:
                    kw = dict(t)
                    kw.setdefault("name", name)
                    self.register(TenantSpec(**kw))
        else:
            for t in tenants or []:
                if isinstance(t, TenantSpec):
                    self.register(t)
                else:
                    self.register(TenantSpec(**dict(t)))

    def register(self, spec: TenantSpec):
        with self._lock:
            self._specs[spec.name] = spec
        return spec

    def resolve(self, name) -> TenantSpec:
        name = name or DEFAULT_TENANT
        with self._lock:
            spec = self._specs.get(name)
            if spec is None:
                spec = self._specs[name] = TenantSpec(name)
            return spec

    def names(self):
        with self._lock:
            return sorted(self._specs)

    def __contains__(self, name):
        with self._lock:
            return name in self._specs

    def snapshot(self):
        with self._lock:
            return {n: s.to_dict() for n, s in self._specs.items()}


class Artifact:
    """One immutable catalog entry: a ``(kind, name, version)`` triple
    plus its per-leaf sha256 manifest and whole-artifact digest. The
    payload (``values``) rides along for in-process rollouts but the
    identity is the digest — two artifacts are bitwise-equal iff their
    digests match."""

    def __init__(self, kind, name, version, manifest, *, values=None,
                 meta=None):
        self.kind = str(kind)
        self.name = str(name)
        self.version = int(version)
        self.manifest = dict(manifest)
        self.digest = artifact_digest(self.manifest)
        self.values = values
        self.meta = dict(meta or {})
        self.state = "registered"    # -> serving | retired

    @property
    def key(self):
        return (self.kind, self.name, self.version)

    def to_dict(self):
        return {"kind": self.kind, "name": self.name,
                "version": self.version, "digest": self.digest,
                "state": self.state, "leaves": len(self.manifest),
                "meta": dict(self.meta)}


class ArtifactCatalog:
    """Named ``(kind, name)`` artifact lines with independent versions.

    Each line is monotonic (`add` assigns ``last + 1`` unless a higher
    version is given) and tracks at most one ``serving`` version;
    `commit` marks a version serving (demoting the previous one to
    ``registered``), `retire` removes one from rotation permanently.
    Manifests come from `checkpoint.leaf_digests` when raw values are
    given, so catalog identity is the same sha256 story the rollout
    registry certifies bitwise."""

    def __init__(self):
        self._lines: dict = {}   # (kind, name) -> {version: Artifact}
        self._serving: dict = {}  # (kind, name) -> version
        self._lock = threading.Lock()

    @staticmethod
    def _manifest_of(values):
        from ..distributed import checkpoint as ckpt

        return ckpt.leaf_digests(
            {k: np.asarray(v) for k, v in dict(values).items()})

    def add(self, kind, name, *, values=None, manifest=None,
            version=None, meta=None) -> Artifact:
        """Register a new version on the ``(kind, name)`` line. Either
        ``values`` (manifest derived) or an explicit ``manifest`` must
        be given. Versions are monotonic per line."""
        if manifest is None:
            if values is None:
                raise ValueError("add() needs values or a manifest")
            manifest = self._manifest_of(values)
        with self._lock:
            line = self._lines.setdefault((kind, name), {})
            nxt = max(line) + 1 if line else 1
            if version is None:
                version = nxt
            elif int(version) < nxt:
                raise ValueError(
                    f"version {version} not monotonic for "
                    f"({kind}, {name}): next is {nxt}")
            art = Artifact(kind, name, version, manifest, values=values,
                           meta=meta)
            line[art.version] = art
            return art

    def get(self, kind, name, version=None) -> Artifact:
        """A specific version, or the serving one (falling back to the
        latest registered) when ``version`` is None."""
        with self._lock:
            line = self._lines.get((kind, name))
            if not line:
                raise KeyError(f"no artifact line ({kind}, {name})")
            if version is None:
                version = self._serving.get((kind, name)) or max(line)
            art = line.get(int(version))
            if art is None or art.state == "retired":
                raise KeyError(
                    f"({kind}, {name}) version {version} not available")
            return art

    def commit(self, kind, name, version) -> Artifact:
        """Mark ``version`` as the line's serving artifact."""
        with self._lock:
            line = self._lines.get((kind, name)) or {}
            art = line.get(int(version))
            if art is None or art.state == "retired":
                raise KeyError(
                    f"({kind}, {name}) version {version} not available")
            prev = self._serving.get((kind, name))
            if prev is not None and prev in line:
                line[prev].state = "registered"
            art.state = "serving"
            self._serving[(kind, name)] = art.version
            return art

    def serving_version(self, kind, name):
        with self._lock:
            return self._serving.get((kind, name))

    def retire(self, kind, name, version):
        with self._lock:
            line = self._lines.get((kind, name)) or {}
            art = line.get(int(version))
            if art is None:
                return
            art.state = "retired"
            if self._serving.get((kind, name)) == art.version:
                del self._serving[(kind, name)]

    def lines(self):
        with self._lock:
            return sorted(self._lines)

    def snapshot(self):
        with self._lock:
            return {
                f"{kind}/{name}": {
                    "serving": self._serving.get((kind, name)),
                    "versions": {v: a.to_dict()
                                 for v, a in sorted(line.items())},
                }
                for (kind, name), line in sorted(self._lines.items())
            }


class AdapterRollout:
    """Canary→wave→commit for the batched LoRA bank across a fleet.

    ``router`` is a `fleet.Router` whose replicas were built with
    ``max_adapters > 0``; ``catalog`` is the `ArtifactCatalog` the new
    bank registers into under ``("adapter", name)``. `roll_to` swaps
    one healthy replica first, optionally certifies it with a live
    probe request through that replica's own engine, then swaps the
    rest and commits the catalog line. A failure anywhere mid-fleet
    swaps the old bank back onto every already-swapped replica and the
    new version retires — all-or-nothing fleet-wide."""

    def __init__(self, router, catalog=None, *, name="adapters"):
        self.router = router
        self.catalog = catalog if catalog is not None else \
            ArtifactCatalog()
        self.name = str(name)
        self.state = "idle"
        self.error = None

    def _engines(self):
        rs = self.router.replica_set
        engines = [r.engine for r in rs.healthy()]
        if not engines:
            raise RuntimeError("no healthy replica to roll adapters on")
        if not engines[0].max_adapters:
            raise ValueError(
                "fleet engines were built without adapters "
                "(engine_kw max_adapters=0)")
        return engines

    def roll_to(self, lora_a, lora_b, *, probe=None, probe_max_new=4,
                timeout=30.0) -> Artifact:
        """Roll the fleet onto a new stacked adapter bank. Returns the
        committed `Artifact`; raises (after restoring the old bank on
        every already-swapped replica) on any canary/wave failure."""
        engines = self._engines()
        old = [(e, e._lora_a, e._lora_b, e.adapter_version)
               for e in engines]
        art = self.catalog.add(
            "adapter", self.name,
            values={"lora_a": np.asarray(lora_a),
                    "lora_b": np.asarray(lora_b)})
        swapped: list = []
        self.state = "canary"
        self.error = None
        try:
            canary = engines[0]
            canary.swap_adapters(lora_a, lora_b, version=art.version,
                                 timeout=timeout)
            swapped.append(canary)
            if probe is not None:
                # a live request through the canary's own engine: the
                # swap must not just land, it must serve
                canary.submit(
                    probe, max_new_tokens=probe_max_new,
                    timeout=timeout).result(timeout)
            self.state = "wave"
            for eng in engines[1:]:
                eng.swap_adapters(lora_a, lora_b, version=art.version,
                                  timeout=timeout)
                swapped.append(eng)
            self.catalog.commit("adapter", self.name, art.version)
            self.state = "committed"
            return art
        except Exception as e:
            self.error = f"{type(e).__name__}: {e}"
            for eng, la, lb, ver in old:
                if any(eng is s for s in swapped):
                    try:
                        eng.swap_adapters(la, lb, version=ver,
                                          timeout=timeout)
                    except Exception:  # noqa: BLE001 — best-effort
                        pass           # restore; original error wins
            self.catalog.retire("adapter", self.name, art.version)
            self.state = "rolled_back"
            raise
