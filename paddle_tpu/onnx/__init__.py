"""paddle.onnx (ref python/paddle/onnx/export.py).

The reference delegates to the external paddle2onnx package; this image
ships no onnx runtime, so export() is gated with guidance toward the
framework's native serving artifact (jit.save's StableHLO export, which
the inference Predictor consumes directly).
"""

from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Gated ONNX export (ref onnx/export.py:21, which requires the
    external paddle2onnx).  Uses the `onnx` package when importable;
    otherwise raises with the native alternative."""
    try:
        import onnx  # noqa: F401
    except ImportError as e:
        raise RuntimeError(
            "ONNX export needs the 'onnx' package, which is not "
            "installed. The TPU-native serving path is paddle.jit.save("
            "layer, prefix, input_spec=...) — a StableHLO artifact the "
            "paddle_tpu.inference Predictor (and any PJRT runtime) "
            "loads directly.") from e
    raise NotImplementedError(
        "onnx is importable but paddle_tpu does not convert StableHLO "
        "to ONNX graphs; serve the jit.save artifact instead")
