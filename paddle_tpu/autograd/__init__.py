"""paddle.autograd — user-facing autograd utilities + PyLayer.

Ref parity: python/paddle/autograd/ (PyLayer at
python/paddle/autograd/py_layer.py, C++ side
paddle/fluid/imperative/py_layer_fwd.h). A PyLayer is a user-defined
differentiable function: `forward` runs under no-grad and its taped
boundary is a single Node whose vjp calls the user's `backward`.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core import config as _config
from ..core.autograd import Node, backward, grad  # noqa: F401
from ..core.tensor import Tensor

__all__ = ["PyLayer", "PyLayerContext", "backward", "grad"]


class PyLayerContext:
    """Passed as `ctx` to forward/backward
    (ref py_layer.py PyLayerContext)."""

    def __init__(self):
        self._saved = ()
        self._materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved

    saved_tensors = property(lambda self: self._saved)


def _make_replay(cls, args, kwargs, tensor_args):
    """Pure-jax re-execution of a PyLayer for create_graph (double grad):
    a jax.custom_vjp whose forward re-runs cls.forward and whose backward
    calls the user's cls.backward — so higher-order grads respect the
    custom rule."""
    import jax

    tensor_slots = [i for i, t in enumerate(tensor_args) if t is not None]

    def _run(xs):
        ctx = PyLayerContext()
        full = list(args)
        for slot, x in zip(tensor_slots, xs):
            full[slot] = Tensor(x, stop_gradient=True)
        with _config.no_grad():
            out = cls.forward(ctx, *full, **kwargs)
        outs = (out,) if not isinstance(out, (tuple, list)) else tuple(out)
        out_arrays = tuple(o._value for o in outs)
        saved = tuple(
            t._value if isinstance(t, Tensor) else jnp.asarray(t)
            for t in ctx._saved)
        return out_arrays, saved

    # static grad shapes/dtypes (residuals may only carry jax arrays)
    shapes = [tensor_args[i]._value.shape for i in tensor_slots]
    dtypes = [tensor_args[i]._value.dtype for i in tensor_slots]

    def primal(*xs):
        return _run(xs)[0]

    def fwd(*xs):
        out_arrays, saved = _run(xs)
        return out_arrays, saved

    def bwd(saved, cots):
        ctx = PyLayerContext()
        ctx._saved = tuple(Tensor(a, stop_gradient=True) for a in saved)
        gin = cls.backward(
            ctx, *[Tensor(c, stop_gradient=True) for c in cots])
        gin = (gin,) if isinstance(gin, Tensor) or gin is None \
            else tuple(gin)
        if len(gin) != len(shapes):
            raise RuntimeError(
                f"{cls.__name__}.backward returned {len(gin)} grads "
                f"for {len(shapes)} Tensor inputs")
        out = []
        for g, shape, dtype in zip(gin, shapes, dtypes):
            if g is None:
                out.append(jnp.zeros(shape, dtype))
            else:
                out.append(g._value if isinstance(g, Tensor)
                           else jnp.asarray(g))
        return tuple(out)

    f = jax.custom_vjp(primal)
    f.defvjp(fwd, bwd)
    return f


class PyLayer:
    """Subclass with static `forward(ctx, *args)` and
    `backward(ctx, *grads)`; call via `MyFn.apply(*args)`.

    forward runs with gradients disabled (its internals are opaque to the
    tape); backward receives one grad per forward output and must return
    one grad (Tensor or None) per Tensor argument of forward, in order.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with _config.no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(out, (tuple, list))
        outs = (out,) if single else tuple(out)
        for o in outs:
            if not isinstance(o, Tensor):
                raise TypeError(
                    "PyLayer.forward must return Tensor(s), got "
                    f"{type(o).__name__}")

        tensor_args = tuple(a if isinstance(a, Tensor) else None
                            for a in args)
        needs_grad = _config.is_grad_enabled() and any(
            t is not None and not t.stop_gradient for t in tensor_args)
        if not needs_grad:
            return out

        out_meta = [(o._value.shape, o._value.dtype) for o in outs]
        n_inputs = len(tensor_args)

        def vjp_fn(cots):
            cots = cots if isinstance(cots, tuple) else (cots,)
            gin = cls.backward(
                ctx, *[Tensor(c, stop_gradient=True) for c in cots])
            gin = (gin,) if isinstance(gin, Tensor) or gin is None \
                else tuple(gin)
            n_tensor_args = sum(1 for t in tensor_args if t is not None)
            if len(gin) != n_tensor_args:
                raise RuntimeError(
                    f"{cls.__name__}.backward returned {len(gin)} grads "
                    f"for {n_tensor_args} Tensor inputs")
            # align with node.inputs: one slot per forward arg
            it = iter(gin)
            full = []
            for t in tensor_args:
                if t is None:
                    full.append(jnp.zeros(()))  # ignored (input is None)
                else:
                    g = next(it)
                    full.append(
                        jnp.zeros(t._value.shape, t._value.dtype)
                        if g is None else
                        (g._value if isinstance(g, Tensor)
                         else jnp.asarray(g)))
            return tuple(full)

        replay_fn = _make_replay(cls, args, kwargs, tensor_args)
        node = Node(vjp_fn, tensor_args, out_meta,
                    f"pylayer:{cls.__name__}", attrs=None,
                    replay_fn=replay_fn)
        wrapped = []
        for i, o in enumerate(outs):
            t = Tensor(o._value, stop_gradient=False)
            t._tape = (node, i)
            wrapped.append(t)
        return wrapped[0] if single else tuple(wrapped)