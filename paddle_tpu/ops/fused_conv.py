"""Pallas TPU convolution kernels with fused BN/ReLU(+residual) epilogues.

Ref parity: paddle/fluid/framework/ir/conv_bn_fuse_pass.cc +
conv_elementwise_add_act_fuse_pass.cc + operators/conv_cudnn_op.cu — the
reference folds BN into the conv and picks a cudnn fused algo; here the
same fusion is a Mosaic kernel whose epilogue applies the per-channel
affine + activation (+ residual add) on the f32 accumulator before it
ever leaves VMEM, and (in training) emits the per-channel sum/sum-sq
moments from the same accumulator so the BN statistics pass never
re-reads the conv output from HBM.

Kernel shape: ONE stride-1 VALID NHWC kernel covers every ResNet conv.
  * stride 2 lowers to stride 1 by space-to-depth parity decomposition:
    z[ho] = sum_{a,q} x_plane[a][ho+q] * w[2q+a], i.e. the same weight
    folding as vision.models.resnet.fold_conv7_stem, applied at trace
    time.  This is also what kills the C<=64 stem MXU underfill: the
    vanilla 7x7/s2 stem lowers to a 4x4/s1 conv over 12 channels.
  * 1x1 convs flatten (H, W) into a single (Ho*Wo, C) x (C, O) matmul
    (reusing the flash kernels' f32-accumulate dot_general idiom).
  * 3x3 convs unroll their taps as shifted row-matmuls from the padded
    image held in VMEM (im2col-in-VMEM without materialising patches).

The custom VJP rewrites the input-dilated strided-conv gradient as
parity-decomposed stride-1 transposed convs routed through the SAME
kernel (the second named conv loss from BENCH r5); dw transposes the
lax reference conv (jax.linear_transpose — exact, no extra forward).

Gating mirrors fused_ops: FLAGS_use_pallas_conv + on-TPU backend, with
PADDLE_TPU_CONV_FORCE=pallas|lax overriding (pallas off-TPU runs the
kernels in interpreter mode so CPU tier-1 certifies the exact kernel
math + backward).  On a real TPU the first use runs a tiny probe conv
and permanently falls back to the XLA path if Mosaic rejects the
lowering, so the bench can never be wedged by a kernel regression.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..core.op_registry import register_op
from .nn_ops import _bn_act_core, _conv_padding, _pair

# Per-block VMEM budget for the whole padded input plane + weight tile +
# output tile (v5e has 16 MB higher is risk of spills).  Every ResNet-50
# conv at batch-slice granularity fits: worst case layer1 dz plane
# 58*58*256*4B ~ 3.4 MB.
_VMEM_BUDGET = 10 * 2**20
_MAX_TAPS = 4  # per spatial dim, post stride-lowering (k<=8 at s=2)

# incremented whenever a pallas conv is traced (not the lax fallback) —
# the tpu-tier spy test asserts the compiled ResNet step goes through
# the kernel rather than silently falling back
_TRACE_COUNT = 0

_warned_no_pltpu = False
_probe_result = None  # None=untried, True=kernel lowers, False=disabled


def _use_pallas_conv() -> bool:
    force = os.environ.get("PADDLE_TPU_CONV_FORCE", "")
    if force == "pallas":
        if not _HAS_PLTPU:
            global _warned_no_pltpu
            if not _warned_no_pltpu:
                _warned_no_pltpu = True
                import warnings

                warnings.warn("pallas TPU backend unavailable; conv uses "
                              "the XLA path")
            return False
        return True
    if force == "lax":
        return False
    from ..framework.flags import flag

    if not flag("FLAGS_use_pallas_conv"):
        return False
    if not (_HAS_PLTPU and jax.default_backend() == "tpu"):
        return False
    return _probe()


def _interpret() -> bool:
    return (os.environ.get("PADDLE_TPU_CONV_FORCE", "") == "pallas"
            and jax.default_backend() != "tpu")


def _compiler_params(semantics):
    if not _HAS_PLTPU:
        return None
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams", None)
    return cls(dimension_semantics=tuple(semantics)) if cls else None


def _probe() -> bool:
    """One tiny conv through the kernel on first on-TPU use; a Mosaic
    lowering failure disables the pallas path for the session instead of
    wedging every subsequent step (this container is CPU-only, so the
    real-TPU lowering is exactly the part tier-1 cannot certify)."""
    global _probe_result
    if _probe_result is None:
        try:
            x = jnp.zeros((1, 8, 10, 16), jnp.float32)
            w = jnp.zeros((128, 16, 3, 3), jnp.float32)
            plan = _plan(x.shape, w.shape, (1, 1), ((1, 1), (1, 1)), 4)
            xp, wk = _lower(x, w, plan)
            _pallas_conv(xp, wk, plan)[0].block_until_ready()
            _probe_result = True
        except Exception as e:  # noqa: BLE001 — any lowering error
            _probe_result = False
            import warnings

            warnings.warn(f"pallas conv probe failed ({e!r}); convs use "
                          "the XLA path")
    return _probe_result


def _mm(a, b, ca: int, cb: int):
    """f32-accumulating matmul (see fused_ops._mm: dot_general reads
    either orientation natively on the MXU; .T would relayout)."""
    return lax.dot_general(a, b, (((ca,), (cb,)), ((), ())),
                           preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# plan: eligibility + static geometry of the stride-1 lowering
# ---------------------------------------------------------------------------


class _Plan:
    __slots__ = ("s", "pads", "ho", "wo", "ot", "kkh", "kkw", "flat")

    def __init__(self, s, pads, ho, wo, ot, kkh, kkw):
        self.s, self.pads = s, pads
        self.ho, self.wo, self.ot = ho, wo, ot
        self.kkh, self.kkw = kkh, kkw
        # 1x1 (post-lowering) convs run as one flattened (Ho*Wo, C) x
        # (C, Ot) matmul — per-row dots would underfill the MXU's M dim
        self.flat = kkh == 1 and kkw == 1


def _plan(xs, ws, strides, pads, itemsize):
    """Static plan for the NHWC stride-1 kernel, or None when the conv
    cannot take the pallas path (caller keeps lax).  Assumes the caller
    already verified NCHW / groups=1 / dilation=1."""
    if strides[0] != strides[1] or strides[0] not in (1, 2):
        return None
    s = strides[0]
    n, c, h, w = xs
    o, ci, kh, kw = ws
    if ci != c or n < 1:
        return None
    kkh, kkw = -(-kh // s), -(-kw // s)
    if kkh > _MAX_TAPS or kkw > _MAX_TAPS:
        return None
    ot = o if o <= 128 else 128
    if o % ot:
        return None
    ho = (h + pads[0][0] + pads[0][1] - kh) // s + 1
    wo = (w + pads[1][0] + pads[1][1] - kw) // s + 1
    if ho <= 0 or wo <= 0:
        return None
    ce = c * min(s, kh) * min(s, kw)
    xbytes = (ho + kkh - 1) * (wo + kkw - 1) * ce * itemsize
    wbytes = kkh * kkw * ce * ot * itemsize
    obytes = ho * wo * ot * 4
    if xbytes + wbytes + 2 * obytes > _VMEM_BUDGET:
        return None
    return _Plan(s, (tuple(pads[0]), tuple(pads[1])), ho, wo, ot, kkh, kkw)


def _lower(x, w, plan):
    """Trace-time lowering to an equivalent stride-1 VALID conv: returns
    (xp [N,Hp,Wp,Ce] pre-padded NHWC, wk [Kkh*Kkw, Ce, O]).

    stride 2: parity planes xp_a[i] = xpad[2i+a] become channels and the
    weight regroups as w'[o,(a,b,c),q,r] = w[o,c,2q+a,2r+b] (zero where
    2q+a >= k) — identical folding to fold_conv7_stem, done on-device."""
    n, c, h, wd = x.shape
    o, _, kh, kw = w.shape
    (plh, phh), (plw, phw) = plan.pads
    zero = jnp.zeros((), x.dtype)
    if plan.s == 1:
        xp = lax.pad(x, zero, ((0, 0, 0), (0, 0, 0), (plh, phh, 0),
                               (plw, phw, 0)))
        wk = w
    else:
        # one extra zero row/col parity-pads odd extents so both planes
        # have equal length (the zeros land on taps past the support)
        eh = (h + plh + phh) % 2
        ew = (wd + plw + phw) % 2
        xpad = lax.pad(x, zero, ((0, 0, 0), (0, 0, 0), (plh, phh + eh, 0),
                                 (plw, phw + ew, 0)))
        al = range(min(2, kh))
        bl = range(min(2, kw))
        xp = jnp.concatenate([xpad[:, :, a::2, b::2]
                              for a in al for b in bl], axis=1)
        wpad = lax.pad(w, jnp.zeros((), w.dtype),
                       ((0, 0, 0), (0, 0, 0), (0, 2 * plan.kkh - kh, 0),
                        (0, 2 * plan.kkw - kw, 0)))
        wk = jnp.concatenate([wpad[:, :, a::2, b::2]
                              for a in al for b in bl], axis=1)
    # trim to exactly the rows/cols the VALID conv reads (even-k lowering
    # can leave one unused trailing plane row)
    hp, wp = plan.ho + plan.kkh - 1, plan.wo + plan.kkw - 1
    assert xp.shape[2] >= hp and xp.shape[3] >= wp, (xp.shape, hp, wp)
    xp = xp[:, :, :hp, :wp].transpose(0, 2, 3, 1)
    ce = wk.shape[1]
    wk = wk.transpose(2, 3, 1, 0).reshape(plan.kkh * plan.kkw, ce, o)
    return xp, wk


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------


def _conv_kernel(x_ref, w_ref, *refs, kk, wo, act, fuse, has_res, moments):
    """grid (N, O/Ot); block = one image's padded plane x one O tile.
    fori over output rows, taps statically unrolled (kk <= 4 per dim
    post-lowering); per-row (Wo, Ce) x (Ce, Ot) dot with f32 accumulate.
    Epilogues on the accumulator: per-channel affine+act(+residual)
    (eval-fused form) or sum/sum-sq moments (training BN stats)."""
    kkh, kkw = kk
    i0 = 0
    if fuse:
        g_ref, b_ref = refs[0], refs[1]
        i0 = 2
    if has_res:
        r_ref = refs[i0]
        i0 += 1
    o_ref = refs[i0]
    if moments:
        s1_ref, s2_ref = refs[i0 + 1], refs[i0 + 2]
    ho = o_ref.shape[1]
    ot = o_ref.shape[-1]

    def row(i, carry):
        m1, m2 = carry
        acc = jnp.zeros((wo, ot), jnp.float32)
        for dh in range(kkh):
            # all-slice indices: int indices break interpret-mode
            # discharge on older jax
            xrow = pl.load(x_ref, (pl.dslice(0, 1), pl.dslice(i + dh, 1),
                                   slice(None), slice(None)))[0, 0]  # (Wp, Ce)
            for dw in range(kkw):
                acc += _mm(xrow[dw:dw + wo], w_ref[dh * kkw + dw], 1, 0)
        if moments:
            m1 = m1 + jnp.sum(acc, axis=0, keepdims=True)
            m2 = m2 + jnp.sum(acc * acc, axis=0, keepdims=True)
        z = acc
        if fuse:
            z = z * g_ref[...] + b_ref[...]
        if has_res:
            z = z + pl.load(
                r_ref, (pl.dslice(0, 1), pl.dslice(i, 1), slice(None),
                        slice(None)))[0, 0].astype(jnp.float32)
        if act == "relu":
            z = jnp.maximum(z, 0.0)
        pl.store(o_ref, (pl.dslice(0, 1), pl.dslice(i, 1), slice(None),
                         slice(None)),
                 z[None, None].astype(o_ref.dtype))
        return m1, m2

    z0 = jnp.zeros((1, ot), jnp.float32)
    m1, m2 = lax.fori_loop(0, ho, row, (z0, z0))
    if moments:
        # 8-sublane broadcast (not 128): HBM stores only 8 lanes' worth
        # per channel tile — same trick as the flash lse output
        s1_ref[...] = jnp.broadcast_to(m1, (8, ot))[None]
        s2_ref[...] = jnp.broadcast_to(m2, (8, ot))[None]


def _pallas_conv(xp, wk, plan, *, g=None, b=None, res=None,
                 act="identity", moments=False, out_dtype=None):
    """pallas_call wrapper (NHWC). Returns [y] / [y, msum, msq] with
    moments as (N, 8, O) f32 partials (summed over N by the caller)."""
    global _TRACE_COUNT
    _TRACE_COUNT += 1
    n, hp, wp, ce = xp.shape
    kk, _, o = wk.shape
    ot = plan.ot
    if plan.flat:
        hk, wo_k = 1, plan.ho * plan.wo
        xp = xp.reshape(n, 1, wo_k, ce)
        if res is not None:
            res = res.reshape(n, 1, wo_k, o)
    else:
        hk, wo_k = plan.ho, plan.wo
    hp, wp = xp.shape[1], xp.shape[2]
    out_dtype = out_dtype or xp.dtype

    def bspec(shape, imap):
        return pl.BlockSpec(shape, imap,
                            memory_space=pltpu.VMEM if _HAS_PLTPU else None)

    in_specs = [bspec((1, hp, wp, ce), lambda i, j: (i, 0, 0, 0)),
                bspec((kk, ce, ot), lambda i, j: (0, 0, j))]
    ops = [xp, wk]
    if g is not None:
        in_specs += [bspec((1, ot), lambda i, j: (0, j)),
                     bspec((1, ot), lambda i, j: (0, j))]
        ops += [g.reshape(1, o).astype(jnp.float32),
                b.reshape(1, o).astype(jnp.float32)]
    if res is not None:
        in_specs.append(bspec((1, hk, wo_k, ot), lambda i, j: (i, 0, 0, j)))
        ops.append(res)
    out_specs = [bspec((1, hk, wo_k, ot), lambda i, j: (i, 0, 0, j))]
    out_shape = [jax.ShapeDtypeStruct((n, hk, wo_k, o), out_dtype)]
    if moments:
        out_specs += [bspec((1, 8, ot), lambda i, j: (i, 0, j))] * 2
        out_shape += [jax.ShapeDtypeStruct((n, 8, o), jnp.float32)] * 2
    outs = pl.pallas_call(
        functools.partial(_conv_kernel, kk=(plan.kkh, plan.kkw), wo=wo_k,
                          act=act, fuse=g is not None,
                          has_res=res is not None, moments=moments),
        grid=(n, o // ot), in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_compiler_params(("parallel", "parallel")),
        interpret=_interpret())(*ops)
    y = outs[0].reshape(n, plan.ho, plan.wo, o)
    return [y] + list(outs[1:])


# ---------------------------------------------------------------------------
# pallas-or-lax forward dispatch
# ---------------------------------------------------------------------------


def _conv_ref(x, w, strides, pads):
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NCHW", "OIHW", "NCHW"))
    return lax.conv_general_dilated(x, w, window_strides=tuple(strides),
                                    padding=tuple(pads),
                                    dimension_numbers=dn)


def _fwd(x, w, s, pads, *, g=None, b=None, res=None, act="identity",
         moments=False):
    """Fused conv forward, NCHW in/out.  Identical epilogue formulation
    on both paths (f32 affine/act on the conv accumulator, cast once at
    the end) so pallas vs lax parity is a pure tiling question."""
    assert not (moments and (g is not None or res is not None))
    plan = _plan(x.shape, w.shape, (s, s), pads, x.dtype.itemsize)
    if plan is not None and _use_pallas_conv():
        xp, wk = _lower(x, w, plan)
        rs = res.transpose(0, 2, 3, 1) if res is not None else None
        outs = _pallas_conv(xp, wk, plan, g=g, b=b, res=rs, act=act,
                            moments=moments, out_dtype=x.dtype)
        y = outs[0].transpose(0, 3, 1, 2)
        if moments:
            return y, outs[1][:, 0, :].sum(0), outs[2][:, 0, :].sum(0)
        return y
    z = _conv_ref(x, w, (s, s), pads)
    if moments:
        z32 = z.astype(jnp.float32)
        return (z, jnp.sum(z32, axis=(0, 2, 3)),
                jnp.sum(z32 * z32, axis=(0, 2, 3)))
    if g is None and res is None and act == "identity":
        return z
    z32 = z.astype(jnp.float32)
    if g is not None:
        z32 = z32 * g.reshape(1, -1, 1, 1) + b.reshape(1, -1, 1, 1)
    if res is not None:
        z32 = z32 + res.astype(jnp.float32)
    if act == "relu":
        z32 = jnp.maximum(z32, 0.0)
    return z32.astype(x.dtype)


# ---------------------------------------------------------------------------
# custom VJP: transposed-conv dx by parity decomposition
# ---------------------------------------------------------------------------


def _taps_1d(k, s, a, pad_lo):
    """1-D taps of the transposed conv feeding dx rows u = a (mod s):
    kernel positions kh with kh = (a+pad_lo) mod s, whose shifts
    m = (kh-a-pad_lo)/s are consecutive integers — ordered by descending
    m the sum dxp[i] = sum dz[i-m]·w[kh] is a plain stride-1 correlation
    with low padding m_max.  Returns (taps, m_max) or None (no taps ->
    that parity plane receives no gradient)."""
    ks = [kh for kh in range(k) if (kh - a - pad_lo) % s == 0]
    if not ks:
        return None
    return list(reversed(ks)), (ks[-1] - a - pad_lo) // s


def _input_grad(dz, w, cfg, x_shape):
    """dx as stride-1 transposed convs routed back through _fwd (so the
    backward conv runs on the SAME pallas kernel).  This is the rewrite
    of the input-dilated strided gradient: instead of dilating dz with
    s-1 zeros (3/4 wasted MXU work at s=2), each input-parity plane gets
    its own dense small-kernel conv and the planes interleave back."""
    s, plh, phh, plw, phw = cfg
    n, c, h, wd = x_shape
    kh, kw = w.shape[2], w.shape[3]
    ho, wo = dz.shape[2], dz.shape[3]

    def plane(a, b, ha, wa):
        th, tw = _taps_1d(kh, s, a, plh), _taps_1d(kw, s, b, plw)
        if th is None or tw is None:
            return None
        rows, mh = th
        cols, mw = tw
        wab = w[:, :, rows][:, :, :, cols].transpose(1, 0, 2, 3)
        pads = ((mh, ha - ho - mh + len(rows) - 1),
                (mw, wa - wo - mw + len(cols) - 1))
        return _fwd(dz, wab, 1, pads)

    if s == 1:
        out = plane(0, 0, h, wd)
        return out if out is not None else jnp.zeros(x_shape, dz.dtype)
    dx = jnp.zeros(x_shape, dz.dtype)
    for a in range(s):
        ha = (h - a + s - 1) // s
        for b in range(s):
            wa = (wd - b + s - 1) // s
            if ha <= 0 or wa <= 0:
                continue
            p = plane(a, b, ha, wa)
            if p is not None:
                dx = dx.at[:, :, a::s, b::s].set(p)
    return dx


def _conv_grads(x, w, dz, cfg):
    s = cfg[0]
    pads = ((cfg[1], cfg[2]), (cfg[3], cfg[4]))
    dz = dz.astype(x.dtype)
    dx = _input_grad(dz, w.astype(x.dtype), cfg, x.shape)
    # dw: transpose the (linear-in-w) reference conv — exact, and unlike
    # jax.vjp it does not execute a throwaway forward
    dw, = jax.linear_transpose(
        lambda ww: _conv_ref(x, ww, (s, s), pads),
        jax.ShapeDtypeStruct(w.shape, x.dtype))(dz)
    return dx.astype(x.dtype), dw.astype(w.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _conv_core(cfg, moments, x, w):
    """Plain conv (optionally + moments) with the transposed-conv
    backward.  cfg = (s, plh, phh, plw, phw) — static and hashable."""
    return _fwd(x, w, cfg[0], ((cfg[1], cfg[2]), (cfg[3], cfg[4])),
                moments=moments)


def _conv_core_fwd(cfg, moments, x, w):
    return _conv_core(cfg, moments, x, w), (x, w)


def _conv_core_bwd(cfg, moments, saved, ct):
    x, w = saved
    # moment cotangents are structurally zero: every caller stops
    # gradients on the stats (the epilogue VJP owns the stats' dx term)
    dz = ct[0] if moments else ct
    return _conv_grads(x, w, dz, cfg)


_conv_core.defvjp(_conv_core_fwd, _conv_core_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _conv_affine(cfg, act, x, w, g, b, res):
    """Eval-fused y = act(conv(x,w)·g + b [+ res]) — the fully-folded BN
    epilogue (g = scale·rsqrt(var+eps), b = bias − mean·g).  res with
    ndim != 4 is the no-residual placeholder."""
    return _fwd(x, w, cfg[0], ((cfg[1], cfg[2]), (cfg[3], cfg[4])),
                g=g, b=b, res=res if res.ndim == 4 else None, act=act)


def _conv_affine_fwd(cfg, act, x, w, g, b, res):
    return _conv_affine(cfg, act, x, w, g, b, res), (x, w, g, b, res)


def _conv_affine_bwd(cfg, act, saved, dy):
    x, w, g, b, res = saved
    has_res = res.ndim == 4
    # flash-style recompute: one extra conv instead of saving z — the
    # fused path's backward never re-reads a stored pre-activation
    z32 = _fwd(x, w, cfg[0],
               ((cfg[1], cfg[2]), (cfg[3], cfg[4]))).astype(jnp.float32)
    u = z32 * g.reshape(1, -1, 1, 1) + b.reshape(1, -1, 1, 1)
    if has_res:
        u = u + res.astype(jnp.float32)
    dy32 = dy.astype(jnp.float32)
    du = jnp.where(u > 0.0, dy32, 0.0) if act == "relu" else dy32
    dg = jnp.sum(du * z32, axis=(0, 2, 3))
    db = jnp.sum(du, axis=(0, 2, 3))
    dx, dw = _conv_grads(x, w, du * g.reshape(1, -1, 1, 1), cfg)
    dres = du.astype(res.dtype) if has_res else jnp.zeros_like(res)
    return dx, dw, dg.astype(g.dtype), db.astype(b.dtype), dres


_conv_affine.defvjp(_conv_affine_fwd, _conv_affine_bwd)


# ---------------------------------------------------------------------------
# op surface
# ---------------------------------------------------------------------------


def _explicit_pads(pad, xs, ks, strides):
    if isinstance(pad, str):
        if pad == "VALID":
            return ((0, 0), (0, 0))
        out = []
        for size, k, s in ((xs[2], ks[0], strides[0]),
                           (xs[3], ks[1], strides[1])):
            total = max(0, (-(-size // s) - 1) * s + k - size)
            out.append((total // 2, total - total // 2))
        return tuple(out)
    return (tuple(pad[0]), tuple(pad[1]))


def _supported(x, w, strides, dilations, groups, data_format):
    return (data_format == "NCHW" and groups == 1
            and dilations == (1, 1) and strides[0] == strides[1]
            and strides[0] in (1, 2) and x.ndim == 4
            and jnp.issubdtype(x.dtype, jnp.floating)
            and x.dtype == w.dtype)


def conv2d_maybe_pallas(x, w, strides, pad, dilations, groups,
                        data_format):
    """Hook for nn_ops.conv2d: route a plain conv through the pallas
    kernel + custom VJP when the gates and plan allow; None keeps the
    caller on lax.conv_general_dilated (XLA AD)."""
    if not _use_pallas_conv():
        return None
    if not _supported(x, w, strides, dilations, groups, data_format):
        return None
    pads = _explicit_pads(pad, x.shape, (w.shape[2], w.shape[3]), strides)
    if _plan(x.shape, w.shape, strides, pads, x.dtype.itemsize) is None:
        return None
    cfg = (strides[0], pads[0][0], pads[0][1], pads[1][0], pads[1][1])
    return _conv_core(cfg, False, x, w)


def _amp_cast(op_name, *arrs):
    """The composed pair autocasts conv2d's x/w to the low dtype (AMP
    white list) while the BN params stay f32 (batch_norm is black
    listed); this op sits in neither list so it replicates that split
    itself: x/w/residual cast, scale/bias/mean/variance untouched."""
    from ..core import config

    level, amp_dtype, white, black = config.amp_state()
    if not level or (black and op_name in black):
        return arrs
    low = jnp.bfloat16 if amp_dtype == "bfloat16" else jnp.float16
    return tuple(a.astype(low) if a is not None
                 and jnp.issubdtype(a.dtype, jnp.floating) else a
                 for a in arrs)


@register_op("fused_conv2d_bn_act", has_aux=True)
def fused_conv2d_bn_act(x, weight, scale, bias, mean, variance,
                        residual=None, *, stride=1, padding=0, dilation=1,
                        groups=1, momentum=0.9, epsilon=1e-5, act="relu",
                        is_test=False, data_format="NCHW",
                        use_global_stats=False):
    """y = act(batch_norm(conv2d(x, weight)) [+ residual]); aux =
    updated running stats.

    Eval / global-stats: the BN folds to one per-channel affine applied
    in the conv epilogue (one kernel, no second HBM pass).  Training:
    the kernel emits (z, sum, sum_sq) in one pass — the stats reduction
    never re-reads z — then the existing _bn_act_core VJP normalizes and
    owns the full training dx (incl. the stats' dependence on z).
    Unsupported layouts compose conv2d + fused_bn_act unchanged."""
    strides = _pair(stride)
    dilations = _pair(dilation)
    x, weight, residual = _amp_cast("fused_conv2d_bn_act", x, weight,
                                    residual)
    if _supported(x, weight.astype(x.dtype), strides, dilations, groups,
                  data_format):
        weight = weight.astype(x.dtype)
        kh, kw = weight.shape[2], weight.shape[3]
        pad = _conv_padding(padding, 2, strides, dilations, (kh, kw))
        pads = _explicit_pads(pad, x.shape, (kh, kw), strides)
        cfg = (strides[0], pads[0][0], pads[0][1], pads[1][0], pads[1][1])
        if is_test or use_global_stats:
            inv = lax.rsqrt(variance.astype(jnp.float32) + epsilon)
            g = scale.astype(jnp.float32) * inv
            bb = bias.astype(jnp.float32) - mean.astype(jnp.float32) * g
            dummy = residual if residual is not None \
                else jnp.zeros((0,), x.dtype)
            y = _conv_affine(cfg, act, x, weight, g, bb, dummy)
            return y, (mean, variance)
        z, msum, msq = _conv_core(cfg, True, x, weight)
        cnt = z.shape[0] * z.shape[2] * z.shape[3]
        use_mean = lax.stop_gradient(msum / cnt)
        use_var = lax.stop_gradient(
            jnp.maximum(msq / cnt - use_mean * use_mean, 0.0))
        inv = lax.rsqrt(use_var + epsilon)
        y = _bn_act_core(act, 1, z, scale, bias, use_mean, inv, residual)
        new_mean = momentum * mean + (1 - momentum) * use_mean
        new_var = momentum * variance + (1 - momentum) * use_var
        return y, (lax.stop_gradient(new_mean),
                   lax.stop_gradient(new_var))
    from . import nn_ops

    z = nn_ops.conv2d(x, weight, stride=stride, padding=padding,
                      dilation=dilation, groups=groups,
                      data_format=data_format)
    return nn_ops.fused_bn_act(z, scale, bias, mean, variance, residual,
                               momentum=momentum, epsilon=epsilon, act=act,
                               is_test=is_test, data_format=data_format,
                               use_global_stats=use_global_stats)
