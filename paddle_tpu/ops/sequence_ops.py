"""Sequence ops: the LoD-era variable-length family, padded+mask style.

Ref parity: paddle/fluid/operators/sequence_ops/ (sequence_pad_op.cc,
sequence_pool_op.cc, sequence_expand_op.cc, sequence_softmax_op.cc,
sequence_reverse_op.cc, ...) and python/paddle/fluid/layers/
sequence_lod.py. The reference represents ragged batches with LoD offset
tables; XLA wants static shapes, so here every op takes (data, lengths):
`data` is the padded [B, T, ...] tensor and `lengths` [B] the valid
counts (SURVEY §7 hard part #4 — LoD := padding + mask). The "flat"
(LoD-concatenated) layout maps to padded via sequence_pad/unpad.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.op_registry import register_op


def _valid_mask(lengths, maxlen):
    return jnp.arange(maxlen)[None, :] < jnp.asarray(lengths)[:, None]


@register_op("sequence_pad")
def sequence_pad(x, lengths, *, pad_value=0.0, maxlen=None):
    """Flat rows -> padded batch (ref sequence_pad_op.cc).

    x: [sum(lengths), ...] concatenated rows; lengths: [B].
    Returns [B, maxlen, ...]. maxlen defaults to the largest length and
    must be static under jit (pass it explicitly there)."""
    import numpy as _np

    lengths = jnp.asarray(lengths, jnp.int32)
    if maxlen is None:
        maxlen = int(_np.asarray(jax.lax.stop_gradient(lengths)).max())
    b = lengths.shape[0]
    starts = jnp.cumsum(lengths) - lengths
    pos = jnp.arange(maxlen)
    # gather index per (b, t): start_b + t, clamped; invalid slots take
    # pad_value via where
    idx = starts[:, None] + pos[None, :]
    valid = pos[None, :] < lengths[:, None]
    idx = jnp.clip(idx, 0, x.shape[0] - 1)
    out = x[idx.reshape(-1)].reshape((b, maxlen) + x.shape[1:])
    pad = jnp.asarray(pad_value, out.dtype)
    shape = (b, maxlen) + (1,) * (out.ndim - 2)
    return jnp.where(valid.reshape(shape), out, pad)


@register_op("sequence_unpad")
def sequence_unpad(x, lengths, *, total=None):
    """Padded batch -> flat rows (ref sequence_unpad_op.cc). `total` is
    the static output row count (sum of lengths); defaults to B*T with
    tail rows zero-padded — callers that need the exact flat length pass
    `total` (static under jit)."""
    lengths = jnp.asarray(lengths, jnp.int32)
    b, t = x.shape[0], x.shape[1]
    if total is None:
        total = b * t
    starts = jnp.cumsum(lengths) - lengths
    valid = _valid_mask(lengths, t)
    flat_idx = jnp.where(valid, starts[:, None] + jnp.arange(t)[None, :],
                         total)
    out = jnp.zeros((total,) + x.shape[2:], x.dtype)
    return out.at[flat_idx.reshape(-1)].set(
        x.reshape((b * t,) + x.shape[2:]), mode="drop")


@register_op("sequence_pool")
def sequence_pool(x, lengths, *, pool_type="sum"):
    """Per-sequence pooling over the time axis with padding masked out
    (ref sequence_pool_op.cc; types: sum/mean/max/min/sqrt/first/last)."""
    lengths = jnp.asarray(lengths, jnp.int32)
    t = x.shape[1]
    mask = _valid_mask(lengths, t)
    mshape = mask.shape + (1,) * (x.ndim - 2)
    m = mask.reshape(mshape)
    pool = pool_type.lower()
    if pool == "sum":
        return jnp.sum(jnp.where(m, x, 0), axis=1)
    if pool == "mean":
        denom = jnp.maximum(lengths, 1).reshape(
            (-1,) + (1,) * (x.ndim - 2)).astype(x.dtype)
        return jnp.sum(jnp.where(m, x, 0), axis=1) / denom
    if pool == "sqrt":
        denom = jnp.sqrt(jnp.maximum(lengths, 1).astype(x.dtype)).reshape(
            (-1,) + (1,) * (x.ndim - 2))
        return jnp.sum(jnp.where(m, x, 0), axis=1) / denom
    if pool == "max":
        neg = jnp.asarray(jnp.finfo(x.dtype).min if jnp.issubdtype(
            x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min, x.dtype)
        return jnp.max(jnp.where(m, x, neg), axis=1)
    if pool == "min":
        pos = jnp.asarray(jnp.finfo(x.dtype).max if jnp.issubdtype(
            x.dtype, jnp.floating) else jnp.iinfo(x.dtype).max, x.dtype)
        return jnp.min(jnp.where(m, x, pos), axis=1)
    if pool == "first":
        return x[:, 0]
    if pool == "last":
        idx = jnp.maximum(lengths - 1, 0)
        return jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
        ).squeeze(1)
    raise ValueError(f"unknown pool_type {pool_type!r}")


@register_op("sequence_softmax")
def sequence_softmax(x, lengths):
    """Masked softmax over the time axis (ref sequence_softmax_op.cc):
    padding positions get probability 0."""
    lengths = jnp.asarray(lengths, jnp.int32)
    mask = _valid_mask(lengths, x.shape[1])
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    neg = jnp.asarray(-1e30, x.dtype)
    z = jnp.where(mask, x, neg)
    z = z - jax.lax.stop_gradient(jnp.max(z, axis=1, keepdims=True))
    e = jnp.exp(z) * mask.astype(x.dtype)
    return e / jnp.maximum(jnp.sum(e, axis=1, keepdims=True), 1e-30)


@register_op("sequence_reverse")
def sequence_reverse(x, lengths):
    """Reverse each sequence's valid prefix in place, keeping padding at
    the tail (ref sequence_reverse_op.cc)."""
    lengths = jnp.asarray(lengths, jnp.int32)
    t = x.shape[1]
    pos = jnp.arange(t)[None, :]
    rev = lengths[:, None] - 1 - pos
    idx = jnp.where(pos < lengths[:, None], rev, pos)
    return jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)


@register_op("sequence_expand")
def sequence_expand(x, repeats):
    """Repeat each row of x `repeats[i]` times into a padded layout
    (ref sequence_expand_op.cc, LoD-free variant): output [B, max_r, ...]
    where row b holds repeats[b] copies of x[b] and zero padding."""
    import numpy as _np

    repeats = jnp.asarray(repeats, jnp.int32)
    max_r = int(_np.asarray(jax.lax.stop_gradient(repeats)).max())
    tiled = jnp.broadcast_to(
        x[:, None], (x.shape[0], max_r) + x.shape[1:])
    mask = _valid_mask(repeats, max_r).reshape(
        (x.shape[0], max_r) + (1,) * (x.ndim - 1))
    return jnp.where(mask, tiled, 0)


@register_op("sequence_first_step")
def sequence_first_step(x, lengths):
    return sequence_pool(x, lengths, pool_type="first")


@register_op("sequence_last_step")
def sequence_last_step(x, lengths):
    return sequence_pool(x, lengths, pool_type="last")


@register_op("sequence_conv")
def sequence_conv(x, w, *, context_length=3, context_start=None,
                  lengths=None):
    """Context-window convolution over time (ref sequence_conv_op.cc):
    for each position t, concatenate rows [t+start, t+start+len) (zero
    outside the valid range) and project with w [len*D, out].

    x: [B, T, D] padded."""
    b, t, d = x.shape
    start = -((context_length - 1) // 2) if context_start is None \
        else context_start
    cols = []
    for k in range(context_length):
        off = start + k
        shifted = jnp.roll(x, -off, axis=1)
        pos = jnp.arange(t) + off
        ok = (pos >= 0) & (pos < t)
        if lengths is not None:
            ok = ok[None, :] & (pos[None, :] <
                                jnp.asarray(lengths, jnp.int32)[:, None])
            shifted = jnp.where(ok[..., None], shifted, 0)
        else:
            shifted = jnp.where(ok[None, :, None], shifted, 0)
        cols.append(shifted)
    ctx = jnp.concatenate(cols, axis=-1)  # [B, T, len*D]
    return ctx @ w
