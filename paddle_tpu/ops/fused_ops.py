"""Fused ops: Pallas TPU kernels for the hot paths.

Ref parity: paddle/fluid/operators/fused/ (multihead_matmul_op.cu,
fused_embedding_eltwise_layernorm_op.cu, ...) — the reference hand-writes
CUDA kernels for attention and friends; here the TPU equivalents are
Pallas/Mosaic kernels with custom-VJP backward passes.

flash_attention: blockwise online-softmax attention (fwd) + the standard
two-pass recompute backward on 3-D grids (dq: bh x q-block x k-block;
dkv: bh x k-block x q-block) whose innermost dim accumulates into f32
VMEM scratch, so VMEM use is bounded by block sizes and the kernel
scales to 8k+ sequences.  Layout [batch, heads, seq, head_dim].  A jnp
reference path with the identical log-sum-exp formulation runs on CPU so
the same op (and its gradients) is testable without a TPU; set
PADDLE_TPU_FLASH_FORCE=pallas to exercise the kernels in interpreter mode.
"""

from __future__ import annotations

import contextlib
import functools
import math
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..core.op_registry import register_op

_NEG_INF = -1e30

# Block sizes: MXU-aligned (128 lanes). Large tiles (up to 512) keep the
# MXU fed — at 128 the per-invocation matmuls are only 2 MFLOP and grid
# overhead dominates (measured 8.3ms vs 4.7ms XLA for one fwd+bwd at
# b*h=384 s=512 d=64; 512-tiles with bf16 operands bring it under XLA).
# VMEM check at 512: s tile f32 512*512*4 = 1MB + q/k/v streams << 16MB.
_BLOCK_Q = 512
_BLOCK_K = 512


def _mm(a, b, ca: int, cb: int):
    """Matmul contracting a's dim `ca` with b's dim `cb`, f32 accumulate.

    dot_general instead of `a @ b.T` / `a.T @ b`: the MXU reads either
    operand orientation natively, while an explicit .T materialises a
    full-tile relayout before the matmul."""
    return lax.dot_general(a, b, (((ca,), (cb,)), ((), ())),
                           preferred_element_type=jnp.float32)


def _ld(ref, sl=None):
    """Load a (rows, d) tile from a q/k/v/o-style (1, n, d) ref.

    NOTE on layouts: a zero-copy packed-QKV kernel ([b, s, 3, h, d]
    operand sliced by BlockSpec index maps) was tried and REVERTED —
    Mosaic requires a block's last two dims to tile the (sublane, lane)
    plane, so with `h`(=12) second-to-last the spec cannot lower; the
    bhsd transposes around the kernel are load-bearing for TPU tiling."""
    if sl is None:
        sl = slice(None)
    return ref[0, sl, :]


def _st(ref, val):
    """Store a (rows, d) tile (see _ld)."""
    ref[0] = val


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _round_up(a: int, b: int) -> int:
    return _cdiv(a, b) * b


def _block_q(sq: int) -> int:
    return min(_BLOCK_Q, _round_up(sq, 128))


def _block_k(sk: int) -> int:
    return min(_BLOCK_K, _round_up(sk, 128))


def _compiler_params(semantics):
    """Mosaic grid-dimension semantics ('parallel' dims never revisit
    state; 'arbitrary' dims run sequentially for accumulation)."""
    if not _HAS_PLTPU:
        return None
    return pltpu.CompilerParams(dimension_semantics=tuple(semantics))


_warned_no_pltpu = False
_gspmd_tracing = False


@contextlib.contextmanager
def gspmd_tracing():
    """Trace-time gate set by the meshed engines: inside a
    GSPMD-partitioned jit a raw Mosaic call cannot be automatically
    partitioned, so meshed programs route attention through the
    jax.custom_partitioning wrappers (_flash_fwd_cp/_flash_bwd_cp)
    whose partition rule declares batch/heads shardable and runs the
    SAME pallas-or-jnp dispatch per shard — the kernel stays on the
    multi-chip path (VERDICT r4 item 1)."""
    global _gspmd_tracing
    prev = _gspmd_tracing
    _gspmd_tracing = True
    try:
        yield
    finally:
        _gspmd_tracing = prev


def _use_pallas(seq_q=None) -> bool:
    force = os.environ.get("PADDLE_TPU_FLASH_FORCE", "")
    if force == "pallas":
        if not _HAS_PLTPU:
            # the kernels need pltpu (VMEM scratch, PRNG); without it
            # the numerically-identical jnp formulation serves
            global _warned_no_pltpu
            if not _warned_no_pltpu:
                _warned_no_pltpu = True
                import warnings

                warnings.warn("pallas TPU backend unavailable; "
                              "flash_attention uses the jnp path")
            return False
        return True
    if force == "jnp":
        return False
    from ..framework.flags import flag

    if not flag("FLAGS_use_pallas"):
        return False
    if seq_q is not None and seq_q < _pallas_min_seq():
        # below this the whole attention fits one XLA fusion; measured on
        # v5e at seq>=128 the kernel already wins (seq=512 fwd+bwd per
        # layer: pallas 2.6ms vs XLA 3.9-5.7ms), so the default gate is
        # only the sub-tile regime
        return False
    return _HAS_PLTPU and jax.default_backend() == "tpu"


def _pallas_min_seq() -> int:
    return int(os.environ.get("PADDLE_TPU_FLASH_MIN_SEQ", "128"))


def _interpret() -> bool:
    return (os.environ.get("PADDLE_TPU_FLASH_FORCE", "") == "pallas"
            and jax.default_backend() != "tpu")


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _drop_mask(seed, bh_idx, q_off, k_off, shape, dropout_p):
    """Deterministic keep-mask/(1-p) tile: seeded by (seed, bh, q_off,
    k_off) so the backward kernels regenerate the identical mask from the
    same global tile coordinates."""
    # mosaic accepts at most two 32-bit seed words: mix (seed, bh) into
    # one and pack the tile coordinates (seq < 2^16) into the other
    s1 = seed + bh_idx * jnp.int32(-1640531527)  # 2654435761 mod 2^32
    s2 = q_off * jnp.int32(65536) + k_off
    pltpu.prng_seed(s1, s2)
    bits = pltpu.prng_random_bits(shape)
    keep_prob = 1.0 - dropout_p
    thresh = jnp.uint32(int(keep_prob * float(2**32 - 1)))
    keep = bits.astype(jnp.uint32) < thresh
    return jnp.where(keep, 1.0 / keep_prob, 0.0).astype(jnp.float32)


def _fwd_kernel(qpos_ref, bhpos_ref, seed_ref, q_ref, k_ref, v_ref,
                o_ref, lse_ref, *, scale, causal, kv_len, block_k,
                causal_off, dropout_p):
    # q_ref: (1, bq, d), k/v_ref: (1, sk, d), o_ref: (1, bq, d),
    # lse_ref: (1, bq, 8) — per-row lse broadcast along a SMALL lane dim
    # (Mosaic pads lanes to 128 in VMEM, but HBM stores/loads only 8
    # lanes — 16x less traffic than a 128-lane broadcast).
    bq, d = q_ref.shape[1], q_ref.shape[-1]
    sk = k_ref.shape[1]
    nk = sk // block_k
    # operands stay bf16: the MXU natively multiplies bf16 with f32
    # accumulation — casting to f32 first halves matmul throughput. The
    # softmax scale moves onto the f32 scores instead of onto q.
    q = _ld(q_ref)
    # block offset arrives via an SMEM input: pl.program_id fails to
    # re-trace under nested AD (jax 0.9), positions-as-data does not
    q_off = qpos_ref[0, 0, 0]
    bh_idx = bhpos_ref[0, 0, 0]
    seed = seed_ref[0, 0, 0]
    q_idx = q_off + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(t, carry):
        acc, m_i, l_i = carry
        k = _ld(k_ref, pl.dslice(t * block_k, block_k))
        v = _ld(v_ref, pl.dslice(t * block_k, block_k))
        s = _mm(q, k, 1, 1) * scale
        k_idx = t * block_k + lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        mask = k_idx < kv_len
        if causal:
            # bottom-right alignment (KV-cache convention): query i sees
            # keys up to i + (kv_len - q_len), matching the sdpa fallback
            mask = mask & (q_idx + causal_off >= k_idx)
        s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_i - m_new)
        # the softmax denominator uses UNDROPPED p (dropout applies to
        # normalised probabilities); the value accumulation uses the
        # dropped+rescaled p
        l_new = l_i * alpha + jnp.sum(p, axis=-1)
        pv = p
        if dropout_p > 0.0:
            pv = p * _drop_mask(seed, bh_idx, q_off, t * block_k,
                                (bq, block_k), dropout_p)
        acc = acc * alpha[:, None] + jnp.dot(
            pv.astype(v.dtype), v, preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m_i, l_i = lax.fori_loop(0, nk, body, (acc0, m0, l0))
    l_safe = jnp.where(l_i == 0.0, 1.0, l_i)
    _st(o_ref, (acc / l_safe[:, None]).astype(o_ref.dtype))
    lse_ref[0] = jnp.broadcast_to((m_i + jnp.log(l_safe))[:, None],
                                  lse_ref.shape[1:])



def _pos_inputs(bh, n_blocks, block_size):
    """Position/seed inputs shared by the fwd and bwd pallas calls.

    The backward kernels REGENERATE the dropout mask from these tile
    coordinates, so fwd and bwd must build them identically — single
    construction point. Returns (pos, bhpos, specs) where specs maps
    kwargs for pallas in_specs."""
    vmem = pltpu.VMEM  # call sites gate on _HAS_PLTPU
    pos = jnp.broadcast_to(
        (jnp.arange(n_blocks, dtype=jnp.int32) * block_size)[
            :, None, None], (n_blocks, 8, 128))
    bhpos = jnp.broadcast_to(
        jnp.arange(bh, dtype=jnp.int32)[:, None, None], (bh, 8, 128))
    pos_spec = pl.BlockSpec((1, 8, 128), lambda i, j: (j, 0, 0),
                            memory_space=vmem)
    bh_spec = pl.BlockSpec((1, 8, 128), lambda i, j: (i, 0, 0),
                           memory_space=vmem)
    seed_spec = pl.BlockSpec((1, 8, 128), lambda i, j: (0, 0, 0),
                             memory_space=vmem)
    return pos, bhpos, pos_spec, bh_spec, seed_spec


def _seed_input(seed):
    return jnp.broadcast_to(
        seed.astype(jnp.int32)[None, None, None], (1, 8, 128))

def _flash_fwd_pallas(q, k, v, seed, scale, causal, dropout_p):
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq, bk = _block_q(sq), _block_k(sk)
    nq = _cdiv(sq, bq)
    grid = (bh, nq)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, kv_len=sk,
        block_k=bk, causal_off=sk - sq, dropout_p=dropout_p)
    sk_pad = _round_up(sk, bk)
    sq_pad = nq * bq
    q = jnp.pad(q, ((0, 0), (0, sq_pad - sq), (0, 0)))
    k = jnp.pad(k, ((0, 0), (0, sk_pad - sk), (0, 0)))
    v = jnp.pad(v, ((0, 0), (0, sk_pad - sk), (0, 0)))
    vmem = pltpu.VMEM  # call sites gate on _HAS_PLTPU
    bspec = lambda shape, imap: pl.BlockSpec(  # noqa: E731
        shape, imap, memory_space=vmem)
    qpos, bhpos, pos_spec, bh_spec, seed_spec = _pos_inputs(bh, nq, bq)
    seed_arr = _seed_input(seed)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pos_spec,
            bh_spec,
            seed_spec,
            bspec((1, bq, d), lambda i, j: (i, j, 0)),
            bspec((1, sk_pad, d), lambda i, j: (i, 0, 0)),
            bspec((1, sk_pad, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            bspec((1, bq, d), lambda i, j: (i, j, 0)),
            bspec((1, bq, 8), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq_pad, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq_pad, 8), jnp.float32),
        ],
        compiler_params=_compiler_params(("parallel", "parallel")),
        interpret=_interpret(),
    )(qpos, bhpos, seed_arr, q, k, v)
    return o[:, :sq], lse[:, :sq, 0]


# ---------------------------------------------------------------------------
# backward kernels (two-pass recompute, FlashAttention-2 style)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(qpos_ref, kpos_ref, bhpos_ref, seed_ref, q_ref, k_ref,
                   v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc_ref, *,
                   scale, causal, kv_len, last_k_off, causal_off,
                   dropout_p):
    # 3-D grid (bh, q block, k block): the k dim is innermost/sequential
    # and accumulates into an f32 VMEM scratch, so VMEM use is bounded
    # by the BLOCK sizes, not the sequence length.
    # lse_ref/delta_ref: (1, bq, 8) lane-broadcast (see _fwd_kernel)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]
    q = _ld(q_ref)
    do = _ld(do_ref)
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    q_off = qpos_ref[0, 0, 0]
    k_off = kpos_ref[0, 0, 0]
    bh_idx = bhpos_ref[0, 0, 0]
    seed = seed_ref[0, 0, 0]

    @pl.when(k_off == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q_idx = q_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_idx = k_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    k = _ld(k_ref)
    v = _ld(v_ref)
    s = _mm(q, k, 1, 1) * scale
    mask = k_idx < kv_len
    if causal:
        mask = mask & (q_idx + causal_off >= k_idx)
    p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
    dp = _mm(do, v, 1, 1)
    if dropout_p > 0.0:
        dp = dp * _drop_mask(seed, bh_idx, q_off, k_off, (bq, bk),
                             dropout_p)
    ds = (p * (dp - delta[:, None])).astype(k.dtype)
    acc_ref[...] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(k_off == last_k_off)
    def _done():
        _st(dq_ref, (acc_ref[...] * scale).astype(dq_ref.dtype))


def _bwd_dkv_kernel(kpos_ref, qpos_ref, bhpos_ref, seed_ref, q_ref,
                    k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                    dv_ref, dk_acc, dv_acc, *, scale, causal, q_len,
                    last_q_off, causal_off, dropout_p):
    # 3-D grid (bh, k block, q block), q innermost/sequential
    bk = k_ref.shape[1]
    bq = q_ref.shape[1]
    k = _ld(k_ref)
    v = _ld(v_ref)
    k_off = kpos_ref[0, 0, 0]
    q_off = qpos_ref[0, 0, 0]
    bh_idx = bhpos_ref[0, 0, 0]
    seed = seed_ref[0, 0, 0]

    @pl.when(q_off == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q = _ld(q_ref)
    do = _ld(do_ref)
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]
    s = _mm(q, k, 1, 1) * scale
    q_idx = q_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_idx = k_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    # padded q rows have lse=0 from the padded forward => exp(s) can
    # explode; mask on q_len as well as causal structure.
    mask = q_idx < q_len
    if causal:
        mask = mask & (q_idx + causal_off >= k_idx)
    p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
    if dropout_p > 0.0:
        # same (q_off, k_off) tile coordinates as the forward
        dmask = _drop_mask(seed, bh_idx, q_off, k_off, (bq, bk),
                           dropout_p)
        pd = p * dmask
    else:
        dmask = None
        pd = p
    dv_acc[...] += _mm(pd.astype(do.dtype), do, 0, 0)
    dp = _mm(do, v, 1, 1)
    if dmask is not None:
        dp = dp * dmask
    ds = (p * (dp - delta[:, None])).astype(q.dtype)
    dk_acc[...] += _mm(ds, q, 0, 0)

    @pl.when(q_off == last_q_off)
    def _done():
        _st(dk_ref, (dk_acc[...] * scale).astype(dk_ref.dtype))
        _st(dv_ref, dv_acc[...].astype(dv_ref.dtype))


def _flash_bwd_pallas(q, k, v, o, lse, do, seed, scale, causal,
                      dropout_p):
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq, bk = _block_q(sq), _block_k(sk)
    nq = _cdiv(sq, bq)
    nk = _cdiv(sk, bk)
    sq_pad, sk_pad = nq * bq, nk * bk
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    qp = jnp.pad(q, ((0, 0), (0, sq_pad - sq), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, sk_pad - sk), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, sk_pad - sk), (0, 0)))
    dop = jnp.pad(do, ((0, 0), (0, sq_pad - sq), (0, 0)))
    lsep = jnp.broadcast_to(
        jnp.pad(lse, ((0, 0), (0, sq_pad - sq)))[..., None],
        (bh, sq_pad, 8))
    deltap = jnp.broadcast_to(
        jnp.pad(delta, ((0, 0), (0, sq_pad - sq)))[..., None],
        (bh, sq_pad, 8))
    bspec = lambda shape, imap: pl.BlockSpec(  # noqa: E731
        shape, imap, memory_space=pltpu.VMEM)
    qpos, bhpos, _, _, _ = _pos_inputs(bh, nq, bq)
    kpos, _, _, _, _ = _pos_inputs(bh, nk, bk)
    seed_arr = _seed_input(seed)
    pos128 = lambda imap: bspec((1, 8, 128), imap)  # noqa: E731
    # these call sites are only reachable with pltpu present
    # (_use_pallas gates on _HAS_PLTPU even when forced)

    # dq: grid (bh, q block, k block) — k sequential into f32 scratch
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          kv_len=sk, last_k_off=(nk - 1) * bk,
                          causal_off=sk - sq, dropout_p=dropout_p),
        grid=(bh, nq, nk),
        in_specs=[
            pos128(lambda i, j, t: (j, 0, 0)),
            pos128(lambda i, j, t: (t, 0, 0)),
            pos128(lambda i, j, t: (i, 0, 0)),
            pos128(lambda i, j, t: (0, 0, 0)),
            bspec((1, bq, d), lambda i, j, t: (i, j, 0)),
            bspec((1, bk, d), lambda i, j, t: (i, t, 0)),
            bspec((1, bk, d), lambda i, j, t: (i, t, 0)),
            bspec((1, bq, d), lambda i, j, t: (i, j, 0)),
            bspec((1, bq, 8), lambda i, j, t: (i, j, 0)),
            bspec((1, bq, 8), lambda i, j, t: (i, j, 0)),
        ],
        out_specs=bspec((1, bq, d), lambda i, j, t: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq_pad, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_compiler_params(
            ("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(qpos, kpos, bhpos, seed_arr, qp, kp, vp, dop, lsep, deltap)

    # dk/dv: grid (bh, k block, q block) — q sequential into scratch
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          q_len=sq, last_q_off=(nq - 1) * bq,
                          causal_off=sk - sq, dropout_p=dropout_p),
        grid=(bh, nk, nq),
        in_specs=[
            pos128(lambda i, j, t: (j, 0, 0)),
            pos128(lambda i, j, t: (t, 0, 0)),
            pos128(lambda i, j, t: (i, 0, 0)),
            pos128(lambda i, j, t: (0, 0, 0)),
            bspec((1, bq, d), lambda i, j, t: (i, t, 0)),
            bspec((1, bk, d), lambda i, j, t: (i, j, 0)),
            bspec((1, bk, d), lambda i, j, t: (i, j, 0)),
            bspec((1, bq, d), lambda i, j, t: (i, t, 0)),
            bspec((1, bq, 8), lambda i, j, t: (i, t, 0)),
            bspec((1, bq, 8), lambda i, j, t: (i, t, 0)),
        ],
        out_specs=[
            bspec((1, bk, d), lambda i, j, t: (i, j, 0)),
            bspec((1, bk, d), lambda i, j, t: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk_pad, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk_pad, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=_compiler_params(
            ("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(kpos, qpos, bhpos, seed_arr, qp, kp, vp, dop, lsep, deltap)
    return dq[:, :sq], dk[:, :sk], dv[:, :sk]


# ---------------------------------------------------------------------------
# jnp reference path (identical lse formulation; runs anywhere)
# ---------------------------------------------------------------------------


def _jnp_keep_mask(seed, shape, dropout_p):
    """bool keep mask (u16 threshold compare, see _common.keep_mask_u16):
    random-bit traffic dominates attention-dropout cost on this path —
    one s x s bits array per layer per pass."""
    from ._common import keep_mask_u16

    key = jax.random.PRNGKey(seed.astype(jnp.uint32))
    return keep_mask_u16(key, shape, dropout_p)


def _causal_mask_f32(s, sq, sk):
    q_idx = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    k_idx = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    return jnp.where(q_idx + (sk - sq) >= k_idx, s, _NEG_INF)


def _flash_fwd_jnp(q, k, v, seed, scale, causal, dropout_p):
    # bf16 matmuls with f32 accumulation (MXU native — f32 inputs would
    # halve matmul throughput); softmax math stays f32
    s = jnp.einsum("bqd,bkd->bqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask_f32(s, s.shape[-2], s.shape[-1])
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    inv = 1.0 / l
    if dropout_p > 0.0:
        inv = inv / (1.0 - dropout_p)
    probs = (p * inv[..., None]).astype(q.dtype)
    if dropout_p > 0.0:
        # mask applied on the bf16 probs (half the s x s traffic of an
        # f32 where) — numerically identical to masking p first
        keep = _jnp_keep_mask(seed, probs.shape, dropout_p)
        probs = jnp.where(keep, probs, jnp.zeros((), probs.dtype))
    o = jnp.einsum("bqk,bkd->bqd", probs, v,
                   preferred_element_type=jnp.float32)
    return o.astype(q.dtype), m + jnp.log(l)


def _flash_bwd_jnp(q, k, v, o, lse, do, seed, scale, causal, dropout_p):
    s = jnp.einsum("bqd,bkd->bqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask_f32(s, s.shape[-2], s.shape[-1])
    p = jnp.exp(s - lse[..., None])  # normalised probs, f32
    delta = jnp.einsum("bqd,bqd->bq", do, o,
                       preferred_element_type=jnp.float32)
    if dropout_p > 0.0:
        keep = _jnp_keep_mask(seed, p.shape, dropout_p)
        inv_keep = 1.0 / (1.0 - dropout_p)
        # masks on the bf16 operands feeding the matmuls (half the
        # traffic of f32 wheres); ds keeps its one f32 where fused into
        # the (dp - delta) elementwise chain
        pd16 = jnp.where(keep, (p * inv_keep).astype(q.dtype),
                         jnp.zeros((), q.dtype))
    else:
        keep = None
        pd16 = p.astype(q.dtype)
    dv = jnp.einsum("bqk,bqd->bkd", pd16, do,
                    preferred_element_type=jnp.float32)
    dp = jnp.einsum("bqd,bkd->bqk", do, v,
                    preferred_element_type=jnp.float32)
    if keep is not None:
        dp = jnp.where(keep, dp * inv_keep, 0.0)
    ds = (p * (dp - delta[..., None])).astype(q.dtype)
    dq = jnp.einsum("bqk,bkd->bqd", ds, k,
                    preferred_element_type=jnp.float32) * scale
    dk = jnp.einsum("bqk,bqd->bkd", ds, q,
                    preferred_element_type=jnp.float32) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------


def _fwd_impl4(q, k, v, seed, causal, scale, dropout_p):
    """Per-device forward on 4-D [b, h, s, d]: pallas-or-jnp dispatch."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    q3 = q.reshape(b * h, sq, d)
    k3 = k.reshape(b * h, sk, d)
    v3 = v.reshape(b * h, sk, d)
    if _use_pallas(sq):
        o3, lse3 = _flash_fwd_pallas(q3, k3, v3, seed, scale, causal,
                                     dropout_p)
    else:
        o3, lse3 = _flash_fwd_jnp(q3, k3, v3, seed, scale, causal,
                                  dropout_p)
    return o3.reshape(b, h, sq, d), lse3.reshape(b, h, sq)


def _bwd_impl4(q, k, v, o, lse, do, seed, causal, scale, dropout_p):
    """Per-device backward on 4-D [b, h, s, d]."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    args = (q.reshape(b * h, sq, d), k.reshape(b * h, sk, d),
            v.reshape(b * h, sk, d), o.reshape(b * h, sq, d),
            lse.reshape(b * h, sq), do.reshape(b * h, sq, d))
    if _use_pallas(sq):
        dq, dk, dv = _flash_bwd_pallas(*args, seed, scale, causal,
                                       dropout_p)
    else:
        dq, dk, dv = _flash_bwd_jnp(*args, seed, scale, causal, dropout_p)
    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


# ---------------------------------------------------------------------------
# GSPMD partitioning (VERDICT r4 item 1): batch/heads shardable, seq and
# head_dim replicated — meshed programs keep the Mosaic kernel instead of
# falling back to jnp.  The reference's fused CUDA kernels run unmodified
# under every parallelism because NCCL parallelism is per-process
# (operators/fused/multihead_matmul_op.cu); custom_partitioning is the
# GSPMD-native equivalent: the partition rule runs the SAME per-device
# kernel on each shard.
# ---------------------------------------------------------------------------

from jax.experimental.custom_partitioning import (  # noqa: E402
    custom_partitioning,
)
from jax.sharding import (  # noqa: E402
    NamedSharding, PartitionSpec as _P,
)


def _spec_axes(entry):
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(a for a in entry if a is not None)
    return (entry,)


def _bh_mesh_spec(mesh, q_shape):
    """(mesh, (b_entry, h_entry)) from q's chosen sharding; seq and
    head_dim are always forced replicated (ring/Ulysses seq sharding has
    its own path in fleet.meta_parallel.context_parallel)."""
    sh = getattr(q_shape, "sharding", None)
    if isinstance(sh, NamedSharding):
        mesh = sh.mesh
        sp = tuple(sh.spec) + (None,) * (4 - len(tuple(sh.spec)))
        return mesh, (sp[0], sp[1])
    return mesh, (None, None)


def _shard_seed(seed, axes, mesh):
    """Decorrelate the dropout stream across b/h shards: fold the shard
    id into the seed (the kernels then mix in the LOCAL bh index)."""
    if not axes:
        return seed
    sid = jnp.int32(0)
    for name in axes:
        sid = sid * jnp.int32(mesh.shape[name]) + lax.axis_index(name)
    return seed + sid * jnp.int32(7919)


def _fwd_infer(causal, scale, dropout_p, mesh, arg_shapes, result_shape):
    mesh, (b, h) = _bh_mesh_spec(mesh, arg_shapes[0])
    return (NamedSharding(mesh, _P(b, h, None, None)),
            NamedSharding(mesh, _P(b, h, None)))


def _fwd_partition(causal, scale, dropout_p, mesh, arg_shapes,
                   result_shape):
    mesh, (b, h) = _bh_mesh_spec(mesh, arg_shapes[0])
    bh_axes = _spec_axes(b) + _spec_axes(h)
    qs = NamedSharding(mesh, _P(b, h, None, None))
    repl = NamedSharding(mesh, _P())

    def lower_fn(q, k, v, seed):
        return _fwd_impl4(q, k, v, _shard_seed(seed, bh_axes, mesh),
                          causal, scale, dropout_p)

    return (mesh, lower_fn,
            (qs, NamedSharding(mesh, _P(b, h, None))),
            (qs, qs, qs, repl))


def _bwd_infer(causal, scale, dropout_p, mesh, arg_shapes, result_shape):
    mesh, (b, h) = _bh_mesh_spec(mesh, arg_shapes[0])
    qs = NamedSharding(mesh, _P(b, h, None, None))
    return (qs, qs, qs)


def _bwd_partition(causal, scale, dropout_p, mesh, arg_shapes,
                   result_shape):
    mesh, (b, h) = _bh_mesh_spec(mesh, arg_shapes[0])
    bh_axes = _spec_axes(b) + _spec_axes(h)
    qs = NamedSharding(mesh, _P(b, h, None, None))
    ls = NamedSharding(mesh, _P(b, h, None))
    repl = NamedSharding(mesh, _P())

    def lower_fn(q, k, v, o, lse, do, seed):
        return _bwd_impl4(q, k, v, o, lse, do,
                          _shard_seed(seed, bh_axes, mesh),
                          causal, scale, dropout_p)

    return (mesh, lower_fn, (qs, qs, qs),
            (qs, qs, qs, qs, ls, qs, repl))


def _def_partition(cp, **kwargs):
    """def_partition across jax versions: older releases don't take the
    shardy kwargs (sharding_rule/need_replication_factors) — drop them
    there; the GSPMD infer/partition callbacks carry the same info."""
    try:
        cp.def_partition(**kwargs)
    except TypeError:
        kwargs.pop("sharding_rule", None)
        kwargs.pop("need_replication_factors", None)
        cp.def_partition(**kwargs)


_flash_fwd_cp = custom_partitioning(_fwd_impl4, static_argnums=(4, 5, 6))
_def_partition(
    _flash_fwd_cp,
    partition=_fwd_partition,
    infer_sharding_from_operands=_fwd_infer,
    sharding_rule="b h q d, b h k d, b h k d, -> b h q d, b h q",
    need_replication_factors=("q", "d", "k"))

_flash_bwd_cp = custom_partitioning(_bwd_impl4, static_argnums=(7, 8, 9))
_def_partition(
    _flash_bwd_cp,
    partition=_bwd_partition,
    infer_sharding_from_operands=_bwd_infer,
    sharding_rule=("b h q d, b h k d, b h k d, b h q d, b h q, "
                   "b h q d, -> b h q d, b h k d, b h k d"),
    need_replication_factors=("q", "d", "k"))


def _route_cp() -> bool:
    """Trace-time routing under gspmd_tracing: True -> go through the
    custom_partitioning wrappers; False -> inline the per-device impl.

    Inside a shard_map region whose non-manual mesh axes are all
    trivial (size 1) the partitioner canonicalizes operand shardings to
    fully MANUAL, which custom_partitioning rejects — and there is
    nothing left to partition anyway (operands are already per-shard),
    so the plain impl is both legal and exact there.  Partial-manual
    regions with real auto axes (e.g. pipeline shard_map over 'pp'
    composing with dp/sharding) keep the cp route, which handles the
    subgroup shardings."""
    if not _gspmd_tracing:
        return False
    m = jax.sharding.get_abstract_mesh()
    manual = tuple(getattr(m, "manual_axes", ()) or ())
    if not manual:
        return True
    live = tuple(getattr(m, "auto_axes", ()) or ()) + tuple(
        getattr(m, "explicit_axes", ()) or ())
    return any(m.shape[a] > 1 for a in live)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_attention(q, k, v, seed, causal, scale, dropout_p):
    o, _ = _flash_fwd(q, k, v, seed, causal, scale, dropout_p)
    return o


def _flash_fwd(q, k, v, seed, causal, scale, dropout_p):
    if _route_cp():
        return _flash_fwd_cp(q, k, v, seed, causal, scale, dropout_p)
    return _fwd_impl4(q, k, v, seed, causal, scale, dropout_p)


def _flash_fwd_rule(q, k, v, seed, causal, scale, dropout_p):
    o, lse = _flash_fwd(q, k, v, seed, causal, scale, dropout_p)
    return o, (q, k, v, seed, o, lse)


def _flash_bwd_rule(causal, scale, dropout_p, res, g):
    q, k, v, seed, o, lse = res
    if _route_cp():
        dq, dk, dv = _flash_bwd_cp(q, k, v, o, lse, g, seed, causal,
                                   scale, dropout_p)
    else:
        dq, dk, dv = _bwd_impl4(q, k, v, o, lse, g, seed, causal,
                                scale, dropout_p)
    return dq, dk, dv, jnp.zeros_like(seed)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@register_op("flash_attention")
def flash_attention(q, k, v, seed=None, *, is_causal=False, scale=None,
                    dropout_p=0.0):
    """Flash attention. q,k,v: [batch, heads, seq, head_dim].

    Ref parity: paddle/fluid/operators/fused/multihead_matmul_op.cu and
    fused attention dropout — here a Pallas online-softmax kernel with
    custom-VJP backward; attention-probability dropout runs IN-kernel
    (pltpu PRNG seeded by global tile coordinates, so the backward
    regenerates the identical mask instead of storing an s*s buffer).
    `seed`: int32 scalar array driving the dropout PRNG (ignored when
    dropout_p == 0).
    """
    q, k, v = jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if dropout_p > 0.0 and (q.shape[2] >= 65536 or k.shape[2] >= 65536):
        # the dropout PRNG packs (q_off, k_off) into one 32-bit word
        # (_drop_mask); beyond 2^16 tiles would reuse streams silently
        raise ValueError(
            "flash_attention dropout supports seq < 65536; disable "
            "dropout_p or use ring attention for longer sequences")
    if seed is None:
        seed = jnp.zeros((), jnp.int32)
    else:
        seed = jnp.asarray(seed).astype(jnp.int32).reshape(())
    return _flash_attention(q, k, v, seed, bool(is_causal), float(s),
                            float(dropout_p))


# The fused-epilogue convolution kernels (conv + BN normalize + act
# [+ residual] in one Mosaic kernel, with the transposed-conv custom
# backward) live in fused_conv.py — same gating/interpret/testing idiom
# as the attention kernels above; re-exported here for discoverability.
from .fused_conv import fused_conv2d_bn_act  # noqa: E402,F401
