"""Compatibility registrations: reference op names whose kernels already
exist here under unified names, plus composed "fusion_*" ops.

Ref parity: paddle registers many historical twins — reshape2/transpose2/
squeeze2 (the "v2" program-desc forms of reshape/transpose/squeeze),
five interpolation modes as ten separate ops (linear_interp[,_v2], ...),
and a family of CPU fusion ops (fusion_gru, fusion_squared_mat_sub, ...)
whose bodies are compositions of primitives. On TPU one kernel serves
each family — XLA does the fusing — but the NAMES must still resolve so
reference programs run unmodified. Each shim here adapts attr/signature
differences; none duplicates kernel code.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.op_registry import _REGISTRY, OpDef, register_op


def _alias(alias: str, target: str):
    """Register `alias` to the SAME OpDef as `target` (identical
    semantics — e.g. reshape2's extra XShape output has no meaning in a
    functional program)."""
    d = _REGISTRY[target]
    if alias in _REGISTRY:
        raise KeyError(f"alias '{alias}' already registered")
    _REGISTRY[alias] = OpDef(alias, d.fn, has_aux=d.has_aux,
                             multi_out=d.multi_out, no_grad=d.no_grad)


# -- program-desc v2 twins ---------------------------------------------------
_alias("reshape2", "reshape")
_alias("transpose2", "transpose")
_alias("squeeze2", "squeeze")
_alias("unsqueeze2", "unsqueeze")
_alias("expand_as_v2", "broadcast_to")
_alias("expand_as", "broadcast_to")
_alias("top_k", "top_k_v2")
_alias("slice", "slice_op")
_alias("trace", "trace_op")
_alias("cudnn_lstm", "rnn")


@register_op("flatten2")
def flatten2(x, *, axis=1):
    """ref flatten_op.cc (flatten2): fold to 2-D at `axis`."""
    lead = 1
    for s in x.shape[:axis]:
        lead *= s
    return x.reshape(lead, -1)


@register_op("expand")
def expand(x, *, expand_times):
    """ref expand_op.cc (v1): tile by repeat counts."""
    return jnp.tile(x, tuple(int(t) for t in expand_times))


@register_op("lookup_table")
def lookup_table(ids, w, *, padding_idx=-1):
    """ref lookup_table_op.cc (v1): ids carry a trailing [,1] dim."""
    from .nn_ops import lookup_table_v2

    return lookup_table_v2(jnp.squeeze(jnp.asarray(ids), -1), w,
                           padding_idx=padding_idx)


# -- interpolation twins -----------------------------------------------------

def _make_interp(mode):
    def interp(x, *, out_h=None, out_w=None, out_d=None, scale=None,
               size=None, align_corners=True, align_mode=1,
               data_format="NCHW"):
        from .nn_ops import interpolate

        if size is None:
            size = [s for s in (out_d, out_h, out_w) if s is not None] \
                or None
        return interpolate(x, size=size, scale_factor=scale, mode=mode,
                           align_corners=align_corners,
                           data_format=data_format)
    interp.__name__ = f"{mode}_interp"
    interp.__doc__ = f"ref interpolate_op.cc ({mode}); one unified kernel."
    return interp


for _m in ("linear", "bilinear", "nearest", "trilinear", "bicubic"):
    _f = _make_interp(_m)
    register_op(f"{_m}_interp")(_f)
    register_op(f"{_m}_interp_v2")(_f)


# -- selected-rows helpers ---------------------------------------------------


@register_op("merge_selected_rows", has_aux=True)
def merge_selected_rows(rows, values, *, height=None):
    """ref merge_selected_rows_op.cc: sum duplicate row ids. Static-shape
    form: returns (unique_rows_padded, merged_values); aux is the count
    of unique rows."""
    rows = jnp.asarray(rows)
    uniq, inv = jnp.unique(rows, return_inverse=True,
                           size=rows.shape[0], fill_value=-1)
    merged = jax.ops.segment_sum(values, inv,
                                 num_segments=rows.shape[0])
    return merged, (uniq, (uniq >= 0).sum())


@register_op("get_tensor_from_selected_rows")
def get_tensor_from_selected_rows(rows, values, *, height):
    """ref get_tensor_from_selected_rows_op.cc: densify to [height, D]."""
    out = jnp.zeros((height,) + values.shape[1:], values.dtype)
    return out.at[rows].add(values)


@register_op("coalesce_tensor", multi_out=True)
def coalesce_tensor(*xs, use_align=True, align_size=256):
    """ref coalesce_tensor_op.cc: fuse N tensors into one flat buffer and
    return views. Functional form: returns (fused, *reshaped_views) —
    PJRT owns real allocation, so the op's value is the contiguous
    layout, which XLA already gives fused buffers."""
    flat = jnp.concatenate([x.reshape(-1) for x in xs])
    outs = []
    off = 0
    for x in xs:
        n = 1
        for s in x.shape:
            n *= s
        outs.append(flat[off:off + n].reshape(x.shape))
        off += n
    return (flat,) + tuple(outs)


# -- debug / callback --------------------------------------------------------


@register_op("print", no_grad=True)
def print_op(x, *, message="", first_n=-1, summarize=20):
    """ref print_op.cc: debug print inside compiled programs."""
    # the user message is opaque text, not a format string
    safe = message.replace("{", "{{").replace("}", "}}")
    jax.debug.print(safe + "{x}", x=x)
    return x


@register_op("py_func")
def py_func(*xs, func, out_shape=None, out_dtype=None):
    """ref py_func_op.cc: host-Python callback inside the graph via
    pure_callback (the reference suspends execution and calls back into
    the interpreter; pure_callback is the XLA-native equivalent)."""
    import numpy as np

    if out_shape is None:
        out_shape = xs[0].shape
        out_dtype = out_dtype or xs[0].dtype
    sds = jax.ShapeDtypeStruct(tuple(out_shape),
                               np.dtype(out_dtype or "float32"))
    return jax.pure_callback(func, sds, *xs)


# -- quantization ------------------------------------------------------------


@register_op("quantize", no_grad=True)
def quantize(x, *, scale=1.0, shift=0.0, bfloat16=False):
    """ref mkldnn quantize_op.cc: affine int8 quantization."""
    if bfloat16:
        return x.astype(jnp.bfloat16)
    return jnp.clip(jnp.round(x * scale + shift), -128,
                    127).astype(jnp.int8)


@register_op("dequantize", no_grad=True)
def dequantize(x, *, scale=1.0, shift=0.0):
    """ref dequantize_op.cc."""
    return (x.astype(jnp.float32) - shift) / scale


@register_op("requantize", no_grad=True)
def requantize(x, *, scale_in=1.0, scale_out=1.0, shift_in=0.0,
               shift_out=0.0):
    """ref requantize_op.cc: rescale int8 without a float detour in the
    reference; numerically identical here."""
    y = (x.astype(jnp.float32) - shift_in) * (scale_out / scale_in) \
        + shift_out
    return jnp.clip(jnp.round(y), -128, 127).astype(jnp.int8)


# -- rnn units ---------------------------------------------------------------


@register_op("lstm_unit", multi_out=True)
def lstm_unit(x, c_prev, *, forget_bias=0.0):
    """ref lstm_unit_op.cc: one LSTM step on pre-projected x [B, 4H]."""
    h = c_prev.shape[-1]
    i, f, o, j = (x[:, :h], x[:, h:2 * h], x[:, 2 * h:3 * h],
                  x[:, 3 * h:])
    c = (c_prev * jax.nn.sigmoid(f + forget_bias)
         + jax.nn.sigmoid(i) * jnp.tanh(j))
    return c, jnp.tanh(c) * jax.nn.sigmoid(o)


@register_op("gru_unit", multi_out=True)
def gru_unit(x, h_prev, weight, bias=None, *,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    """ref gru_unit_op.cc: one GRU step. x: [B, 3H] pre-projected input,
    weight: [H, 3H] (update/reset gates then candidate)."""
    hsz = h_prev.shape[-1]
    act = dict(tanh=jnp.tanh, relu=jax.nn.relu,
               sigmoid=jax.nn.sigmoid, identity=lambda v: v)
    g = x[:, :2 * hsz] + h_prev @ weight[:, :2 * hsz]
    if bias is not None:
        g = g + bias[:2 * hsz]
    u = act[gate_activation](g[:, :hsz])
    r = act[gate_activation](g[:, hsz:])
    cand = x[:, 2 * hsz:] + (r * h_prev) @ weight[:, 2 * hsz:]
    if bias is not None:
        cand = cand + bias[2 * hsz:]
    c = act[activation](cand)
    gate = jnp.concatenate([u, r, c], axis=1)  # ref Gate: [B, 3H] activated
    if origin_mode:
        h = u * h_prev + (1.0 - u) * c
    else:
        h = (1.0 - u) * h_prev + u * c
    return gate, r * h_prev, h


@register_op("gru", multi_out=True)
def gru(x, h0, weight, bias=None, *, activation="tanh",
        gate_activation="sigmoid", is_reverse=False, origin_mode=False):
    """ref gru_op.cc: full-sequence GRU over pre-projected input
    [B, T, 3H] via lax.scan."""
    fn = _REGISTRY["gru_unit"].fn  # returns (gate, reset_h, h)

    def step(h, xt):
        _, _, hn = fn(xt, h, weight, bias, activation=activation,
                      gate_activation=gate_activation,
                      origin_mode=origin_mode)
        return hn, hn

    xs = jnp.swapaxes(x, 0, 1)
    hT, ys = lax.scan(step, h0, xs, reverse=is_reverse)
    return jnp.swapaxes(ys, 0, 1), hT


@register_op("lstm", multi_out=True)
def lstm(x, h0, c0, w_ih, w_hh, b_ih=None, b_hh=None, *,
         is_reverse=False):
    """ref lstm_op.cc: full-sequence LSTM [B, T, in] via the shared
    scan cell."""
    from .rnn_ops import _scan_direction

    xs = jnp.swapaxes(x, 0, 1)
    ys, hT, cT = _scan_direction("LSTM", xs, h0, c0, w_ih, w_hh, b_ih,
                                 b_hh, reverse=is_reverse)
    return jnp.swapaxes(ys, 0, 1), hT, cT


@register_op("lstmp", multi_out=True)
def lstmp(x, h0, c0, w_ih, w_hh, w_proj, b_ih=None, b_hh=None, *,
          is_reverse=False):
    """ref lstmp_op.cc: LSTM with a recurrent projection layer —
    h_t = proj(cell_h_t); the projected state feeds the recurrence."""
    def step(carry, xt):
        h, c = carry
        gates = xt @ w_ih.T + h @ w_hh.T
        if b_ih is not None:
            gates = gates + b_ih
        if b_hh is not None:
            gates = gates + b_hh
        hs = c.shape[-1]
        i, f, g, o = (gates[:, :hs], gates[:, hs:2 * hs],
                      gates[:, 2 * hs:3 * hs], gates[:, 3 * hs:])
        c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h_new = (jax.nn.sigmoid(o) * jnp.tanh(c_new)) @ w_proj
        return (h_new, c_new), h_new

    xs = jnp.swapaxes(x, 0, 1)
    (hT, cT), ys = lax.scan(step, (h0, c0), xs, reverse=is_reverse)
    return jnp.swapaxes(ys, 0, 1), hT, cT


# -- fusion ops (compositions; XLA re-fuses them) ----------------------------


@register_op("fusion_repeated_fc_relu")
def fusion_repeated_fc_relu(x, *ws_and_bs):
    """ref fusion_repeated_fc_relu_op.cc: (fc+relu)*N."""
    n = len(ws_and_bs) // 2
    out = x
    for i in range(n):
        out = jax.nn.relu(out @ ws_and_bs[2 * i] + ws_and_bs[2 * i + 1])
    return out


@register_op("fusion_squared_mat_sub")
def fusion_squared_mat_sub(x, y, *, scalar=1.0):
    """ref fusion_squared_mat_sub_op.cc: ((x@y)^2 - (x^2)@(y^2)) * s."""
    return ((x @ y) ** 2 - (x * x) @ (y * y)) * scalar


@register_op("fusion_gru", multi_out=True)
def fusion_gru(x, h0, wx, wh, bias=None, *, activation="tanh",
               gate_activation="sigmoid", is_reverse=False,
               origin_mode=False):
    """ref fusion_gru_op.cc: input projection + GRU in one op."""
    proj = x @ wx
    fn = _REGISTRY["gru"].fn
    return fn(proj, h0, wh, bias, activation=activation,
              gate_activation=gate_activation, is_reverse=is_reverse,
              origin_mode=origin_mode)


def _preproj_lstm_scan(proj, h0, c0, wh, is_reverse):
    """LSTM over pre-projected gates [B, T, 4H] — the input matmul is
    already done, so the scan body only pays the recurrent matmul."""
    hs = c0.shape[-1]

    def step(carry, gt):
        h, c = carry
        gates = gt + h @ wh
        i, f, g, o = (gates[:, :hs], gates[:, hs:2 * hs],
                      gates[:, 2 * hs:3 * hs], gates[:, 3 * hs:])
        c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        return (h_new, c_new), h_new

    (hT, cT), ys = lax.scan(step, (h0, c0), jnp.swapaxes(proj, 0, 1),
                            reverse=is_reverse)
    return jnp.swapaxes(ys, 0, 1), hT, cT


@register_op("fusion_lstm", multi_out=True)
def fusion_lstm(x, h0, c0, wx, wh, bias=None, *, is_reverse=False):
    """ref fusion_lstm_op.cc: input projection + LSTM in one op.
    wx: [in, 4H], wh: [H, 4H]."""
    proj = x @ wx
    if bias is not None:
        proj = proj + bias
    return _preproj_lstm_scan(proj, h0, c0, wh, is_reverse)


@register_op("multi_gru", multi_out=True)
def multi_gru(x, h0, *wxs_whs, layers=2, is_reverse=False):
    """ref mkldnn multi_gru_op.cc: stacked fusion_gru layers."""
    fn = _REGISTRY["fusion_gru"].fn
    out = x
    hT = None
    for i in range(layers):
        wx, wh = wxs_whs[2 * i], wxs_whs[2 * i + 1]
        out, hT = fn(out, h0[i], wx, wh, None, is_reverse=is_reverse)
    return out, hT


@register_op("fused_embedding_fc_lstm", multi_out=True)
def fused_embedding_fc_lstm(ids, emb, h0, c0, wx, wh, bias=None, *,
                            is_reverse=False):
    """ref fused_embedding_fc_lstm_op.cc: embedding lookup + fc + lstm."""
    x = jnp.take(emb, jnp.asarray(ids).astype(jnp.int32), axis=0)
    fn = _REGISTRY["fusion_lstm"].fn
    return fn(x, h0, c0, wx, wh, bias, is_reverse=is_reverse)


@register_op("attention_lstm", multi_out=True)
def attention_lstm(x, h0, c0, attn_w, lstm_wx, lstm_wh, *,
                   is_reverse=False):
    """ref attention_lstm_op.cc: scalar attention over the input
    sequence gates what feeds the LSTM. TPU divergence (documented): the
    reference recomputes attention per decode step against the previous
    hidden state (a data-dependent T^2 loop); here one content-based
    attention pass weights the sequence before a single LSTM scan."""
    scores = jnp.squeeze(x @ attn_w, -1)             # [B, T]
    alpha = jax.nn.softmax(scores, axis=-1)
    seq = x * (alpha[..., None] * x.shape[1])        # weighted sequence
    fn = _REGISTRY["fusion_lstm"].fn
    return fn(seq, h0, c0, lstm_wx, lstm_wh, None, is_reverse=is_reverse)


@register_op("fusion_seqconv_eltadd_relu")
def fusion_seqconv_eltadd_relu(x, w, b, *, context_length,
                               context_start=0):
    """ref fusion_seqconv_eltadd_relu_op.cc: sequence_conv + bias +
    relu."""
    from .sequence_ops import sequence_conv

    return jax.nn.relu(
        sequence_conv(x, w, context_length=context_length,
                      context_start=context_start) + b)


@register_op("fusion_seqpool_concat")
def fusion_seqpool_concat(*xs, pooltype="SUM"):
    """ref fusion_seqpool_concat_op.cc: pool each [B, T, D] over T then
    concat features."""
    red = dict(SUM=jnp.sum, AVERAGE=jnp.mean, SQRT=jnp.sum,
               MAX=jnp.max, LAST=None, FIRST=None)[pooltype.upper()]
    outs = []
    for x in xs:
        if pooltype.upper() == "LAST":
            outs.append(x[:, -1])
        elif pooltype.upper() == "FIRST":
            outs.append(x[:, 0])
        else:
            o = red(x, axis=1)
            if pooltype.upper() == "SQRT":
                o = o / jnp.sqrt(jnp.asarray(x.shape[1], x.dtype))
            outs.append(o)
    return jnp.concatenate(outs, axis=-1)


@register_op("fusion_seqexpand_concat_fc")
def fusion_seqexpand_concat_fc(ref_seq, *rest):
    """ref fusion_seqexpand_concat_fc_op.cc: expand row-level inputs to
    the reference sequence length, concat, then fc (+relu in ref's
    default act)."""
    *row_inputs, w, b = rest
    t = ref_seq.shape[1]
    expanded = [jnp.broadcast_to(r[:, None, :],
                                 (r.shape[0], t, r.shape[-1]))
                for r in row_inputs]
    cat = jnp.concatenate([ref_seq] + expanded, axis=-1)
    return jax.nn.relu(cat @ w + b)


@register_op("sync_batch_norm", has_aux=True)
def sync_batch_norm(x, scale, bias, mean, variance, *, momentum=0.9,
                    epsilon=1e-5, is_test=False, data_format="NCHW",
                    use_global_stats=False, axis_name="dp"):
    """ref sync_batch_norm_op.cu: BN statistics reduced across the data
    axis. Under pjit, GSPMD's global batch reduction already IS sync-BN;
    inside shard_map (per-rank shards) the count/sum/sumsq are psum'd
    over `axis_name` by hand, exactly like the reference's NCCL
    allreduce of the partial moments."""
    if is_test or use_global_stats or not _axis_bound(axis_name):
        from .nn_ops import batch_norm

        return batch_norm(x, scale, bias, mean, variance,
                          momentum=momentum, epsilon=epsilon,
                          is_test=is_test, data_format=data_format,
                          use_global_stats=use_global_stats)
    from .nn_ops import batch_norm_apply

    c_axis = 1 if data_format == "NCHW" else x.ndim - 1
    reduce_axes = tuple(a for a in range(x.ndim) if a != c_axis)
    x32 = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16,
                                               jnp.float16) else x
    n_local = 1
    for a in reduce_axes:
        n_local *= x.shape[a]
    # ONE fused allreduce of both partial moments (the reference's
    # single NCCL allreduce of the stacked sums)
    s1, s2 = lax.psum((jnp.sum(x32, axis=reduce_axes),
                       jnp.sum(x32 * x32, axis=reduce_axes)), axis_name)
    n = n_local * lax.axis_size(axis_name)
    use_mean = s1 / n
    # E[x^2]-E[x]^2 can round negative in fp32 at large means; clamp
    # before rsqrt and the running-stat update
    use_var = jnp.maximum(s2 / n - use_mean * use_mean, 0.0)
    return batch_norm_apply(x, scale, bias, mean, variance, use_mean,
                            use_var, momentum=momentum, epsilon=epsilon,
                            c_axis=c_axis)


# -- compiled collectives (c_* family) --------------------------------------
# The reference's c_* ops wrap NCCL calls bound to a communicator ring.
# Here they are the in-graph XLA collectives of distributed/collective.py:
# inside pjit/shard_map they lower to psum/all_gather/ppermute on the
# mesh axis; outside a mapped context (single process) they are the
# mathematical identity on the full array, which is exactly the 1-rank
# communicator behavior. c_comm_init*/c_gen_*_id/c_wait_* are
# design-deleted: PJRT + jax.distributed own communicator setup and
# stream ordering (documented in distributed/collective.py).


def _axis_bound(axis_name):
    try:
        lax.axis_index(axis_name)
        return True
    except NameError:
        return False


@register_op("c_allreduce_sum")
def c_allreduce_sum(x, *, ring_id=0, axis_name="dp"):
    """ref collective/c_allreduce_op.h."""
    if _axis_bound(axis_name):
        return lax.psum(x, axis_name)
    return x


@register_op("c_allgather")
def c_allgather(x, *, nranks=1, ring_id=0, axis_name="dp"):
    """ref collective/c_allgather_op.cc."""
    if _axis_bound(axis_name):
        return lax.all_gather(x, axis_name, tiled=True)
    return x


@register_op("c_reducescatter")
def c_reducescatter(x, *, nranks=1, ring_id=0, axis_name="dp"):
    """ref collective/c_reducescatter_op.cc."""
    if _axis_bound(axis_name):
        return lax.psum_scatter(x, axis_name, tiled=True)
    return x


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _ident_fwd_psum_bwd(x, axis_name):
    return x


def _ifpb_fwd(x, axis_name):
    return x, None


def _ifpb_bwd(axis_name, _res, g):
    return (lax.psum(g, axis_name),)


_ident_fwd_psum_bwd.defvjp(_ifpb_fwd, _ifpb_bwd)


@register_op("c_identity")
def c_identity(x, *, ring_id=0, axis_name="mp"):
    """ref collective/c_identity_op.cc: identity fwd, allreduce bwd —
    the TP input boundary (under pjit GSPMD inserts this implicitly;
    the explicit op serves shard_map programs)."""
    if _axis_bound(axis_name):
        return _ident_fwd_psum_bwd(x, axis_name)
    return x


@register_op("c_concat")
def c_concat(x, *, nranks=1, ring_id=0, axis_name="mp"):
    """ref collective/c_concat_op.cc: gather shards along the last dim."""
    if _axis_bound(axis_name):
        return lax.all_gather(x, axis_name, axis=x.ndim - 1, tiled=True)
    return x


@register_op("c_split")
def c_split(x, *, nranks=1, rank=0, ring_id=0, axis_name="mp"):
    """ref collective/c_split_op.cc: keep this rank's shard of the last
    dim."""
    if _axis_bound(axis_name):
        r = lax.axis_index(axis_name)
        n = lax.axis_size(axis_name)
        sz = x.shape[-1] // n
        return lax.dynamic_slice_in_dim(x, r * sz, sz, axis=x.ndim - 1)
    if nranks > 1:
        sz = x.shape[-1] // nranks
        return lax.dynamic_slice_in_dim(x, rank * sz, sz, axis=x.ndim - 1)
    return x


@register_op("alltoall")
def alltoall_op(x, *, ring_id=0, axis_name="mp"):
    """ref collective/alltoall_op.cc: split dim0, exchange, concat."""
    if _axis_bound(axis_name):
        n = lax.axis_size(axis_name)
        return lax.all_to_all(x.reshape((n, x.shape[0] // n)
                                        + x.shape[1:]),
                              axis_name, split_axis=0, concat_axis=0,
                              tiled=False).reshape(x.shape)
    return x


@register_op("c_embedding")
def c_embedding(ids, w, *, start_index=0):
    """ref collective/c_embedding_op.cc: vocab-sharded lookup — ids
    outside this shard's [start, start+rows) contribute zeros (summed
    across mp by the caller's allreduce)."""
    ids = jnp.asarray(ids).astype(jnp.int32)
    local = ids - start_index
    inside = (local >= 0) & (local < w.shape[0])
    out = jnp.take(w, jnp.clip(local, 0, w.shape[0] - 1), axis=0)
    return out * inside[..., None].astype(out.dtype)


# -- tensor-array / control-flow plumbing ------------------------------------
# The reference's LoDTensorArray ops mutate a scope-held vector<Tensor>;
# the functional equivalents operate on a stacked [L, ...] array, which
# is exactly how lax.scan carries per-step stacks.


@register_op("write_to_array")
def write_to_array(arr, i, x):
    """ref lod_array_ops: arr[i] = x on a stacked tensor-array."""
    return lax.dynamic_update_index_in_dim(arr, x.astype(arr.dtype),
                                           jnp.asarray(i, jnp.int32), 0)


@register_op("read_from_array")
def read_from_array(arr, i):
    """ref lod_array_ops: arr[i]."""
    return lax.dynamic_index_in_dim(arr, jnp.asarray(i, jnp.int32), 0,
                                    keepdims=False)


@register_op("lod_tensor_to_array", multi_out=True)
def lod_tensor_to_array(x, lengths, *, max_len=None):
    """ref lod_tensor_to_array_op.cc: split instances into a stacked
    array ordered by step (the RNN memory layout); padded form keeps the
    [B] axis and returns the per-step validity mask."""
    ln = jnp.asarray(lengths, jnp.int32)
    t = x.shape[1] if max_len is None else max_len
    steps = jnp.swapaxes(x[:, :t], 0, 1)            # [T, B, D]
    mask = (jnp.arange(t)[:, None] < ln[None, :])
    return steps, mask


@register_op("array_to_lod_tensor")
def array_to_lod_tensor(steps, mask):
    """ref array_to_lod_tensor_op.cc: inverse of the above."""
    x = jnp.swapaxes(steps, 0, 1)
    return x * jnp.swapaxes(mask, 0, 1)[..., None].astype(x.dtype)


@register_op("shrink_rnn_memory")
def shrink_rnn_memory(x, lengths, *, step):
    """ref shrink_rnn_memory_op.cc: zero the memory rows of sequences
    already finished at `step` (static-shape form of the reference's
    row shrink)."""
    alive = (jnp.asarray(lengths, jnp.int32) > step)
    return x * alive[:, None].astype(x.dtype)


@register_op("merge_lod_tensor")
def merge_lod_tensor(mask, in_true, in_false):
    """ref merge_lod_tensor_op.cc: row-wise select — the merge half of
    the reference's IfElse lowering (the split half is a where on the
    caller side; lax.cond covers the control flow itself)."""
    m = jnp.asarray(mask).reshape(-1)
    shape = (m.shape[0],) + (1,) * (in_true.ndim - 1)
    return jnp.where(m.reshape(shape) != 0, in_true, in_false)


@register_op("select_input")
def select_input(mask, *xs):
    """ref select_input_op.cc: pick input branch by scalar mask."""
    return lax.switch(jnp.asarray(mask, jnp.int32).reshape(()),
                      [lambda x=x: x for x in xs])


@register_op("select_output", multi_out=True)
def select_output(x, mask, *, n_branches=2):
    """ref select_output_op.cc: route x to branch `mask`; other branches
    receive zeros (functional form — downstream cond picks the live
    one)."""
    m = jnp.asarray(mask, jnp.int32).reshape(())
    return tuple(jnp.where(m == i, x, jnp.zeros_like(x))
                 for i in range(n_branches))


@register_op("beam_search", has_aux=True)
def beam_search(pre_ids, pre_scores, ids, scores, *, beam_size,
                end_id=0):
    """ref beam_search_op.cc: one decode step. Rows are grouped
    [n_seqs * beam_size]; each sequence keeps the top beam_size of its
    beam_size*K candidates. Returns (selected_scores,
    (selected_ids, parent_idx))."""
    bw, k = ids.shape
    n_seqs = bw // beam_size
    finished = (pre_ids[:, -1:] == end_id) & (pre_ids[:, -1:] >= 0)
    # finished beams propagate a single candidate (their own score)
    total = jnp.where(finished, jnp.where(
        jnp.arange(k)[None, :] == 0, pre_scores[:, None], -jnp.inf),
        pre_scores[:, None] + scores)
    cand_ids = jnp.where(finished, jnp.full_like(ids, end_id), ids)
    flat = total.reshape(n_seqs, beam_size * k)
    top, pos = lax.top_k(flat, beam_size)            # [n_seqs, beam]
    parent = pos // k + (jnp.arange(n_seqs) * beam_size)[:, None]
    chosen = jnp.take_along_axis(
        cand_ids.reshape(n_seqs, beam_size * k), pos, axis=1)
    return (top.reshape(bw), (chosen.reshape(bw).astype(ids.dtype),
                              parent.reshape(bw).astype(jnp.int32)))


# -- parameter-server eager ops ---------------------------------------------


def _ps_runtime():
    from ..distributed.ps import runtime as rt

    if getattr(rt, "_runtime", None) is None:
        raise RuntimeError(
            "pull/push_sparse require an initialised PS runtime "
            "(fleet.init with a PSRoleMaker)")
    return rt._runtime


@register_op("pull_sparse", no_grad=True)
def pull_sparse(ids, *, table_name="embedding", dim=None):
    """ref pslib pull_sparse_op.cc: eager embedding pull from the PS
    tables (host round-trip; the compiled path pre-pulls via
    DistributedEmbedding)."""
    import numpy as np

    rt = _ps_runtime()
    rows = rt._client.pull_sparse(table_name, np.asarray(ids).reshape(-1))
    return jnp.asarray(rows).reshape(tuple(np.asarray(ids).shape)
                                     + (rows.shape[-1],))


@register_op("push_sparse", no_grad=True)
def push_sparse(ids, grads, *, table_name="embedding"):
    """ref pslib push_sparse_op.cc: eager gradient push."""
    import numpy as np

    rt = _ps_runtime()
    rt._communicator.push_sparse(table_name,
                                 np.asarray(ids).reshape(-1),
                                 np.asarray(grads).reshape(
                                     -1, np.asarray(grads).shape[-1]))
    return jnp.zeros((), jnp.float32)


_alias("pull_sparse_v2", "pull_sparse")
_alias("push_sparse_v2", "push_sparse")
