"""Math ops: elementwise binary/unary, activations, matmul family.

Ref parity: paddle/fluid/operators/elementwise/, activation_op.cc,
matmul_v2_op.cc, scale_op.cc, clip_op.cc. Pure jnp — XLA fuses the
elementwise chains into surrounding matmuls (what the reference needed
fused CUDA kernels and IR passes for).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.op_registry import register_op
from ._common import align_for_axis_broadcast

# -- elementwise binary -----------------------------------------------------


def _binary(name, fn):
    def op(x, y, *, axis=-1):
        x, y = align_for_axis_broadcast(x, y, axis)
        return fn(x, y)

    op.__name__ = name
    register_op(name)(op)
    return op


_binary("elementwise_add", jnp.add)
_binary("elementwise_sub", jnp.subtract)
_binary("elementwise_mul", jnp.multiply)
_binary("elementwise_div", jnp.divide)
_binary("elementwise_min", jnp.minimum)
_binary("elementwise_max", jnp.maximum)
_binary("elementwise_pow", jnp.power)
_binary("elementwise_mod", jnp.mod)
_binary("elementwise_floordiv", jnp.floor_divide)
_binary("elementwise_heaviside", jnp.heaviside)
_binary("fmax", jnp.fmax)
_binary("fmin", jnp.fmin)
_binary("atan2", jnp.arctan2)
_binary("nextafter", jnp.nextafter)
_binary("logaddexp", jnp.logaddexp)


@register_op("remainder")
def remainder(x, y):
    return jnp.remainder(x, y)


# -- comparison / logical (no grad) ----------------------------------------

for _name, _fn in [
    ("equal", jnp.equal), ("not_equal", jnp.not_equal),
    ("less_than", jnp.less), ("less_equal", jnp.less_equal),
    ("greater_than", jnp.greater), ("greater_equal", jnp.greater_equal),
    ("logical_and", jnp.logical_and), ("logical_or", jnp.logical_or),
    ("logical_xor", jnp.logical_xor),
]:
    register_op(_name, no_grad=True)(
        (lambda f: lambda x, y: f(x, y))(_fn))

register_op("logical_not", no_grad=True)(lambda x: jnp.logical_not(x))
# bitwise family (paddle maps the &,|,^,~ operators here; for bool inputs
# bitwise == logical)
register_op("bitwise_and", no_grad=True)(
    lambda x, y: jnp.bitwise_and(x, y))
register_op("bitwise_or", no_grad=True)(lambda x, y: jnp.bitwise_or(x, y))
register_op("bitwise_xor", no_grad=True)(
    lambda x, y: jnp.bitwise_xor(x, y))
register_op("bitwise_not", no_grad=True)(lambda x: jnp.bitwise_not(x))
register_op("isnan", no_grad=True)(lambda x: jnp.isnan(x))
register_op("isinf", no_grad=True)(lambda x: jnp.isinf(x))
register_op("isfinite", no_grad=True)(lambda x: jnp.isfinite(x))
register_op("isclose", no_grad=True)(
    lambda x, y, *, rtol=1e-5, atol=1e-8, equal_nan=False:
    jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan))
register_op("sign", no_grad=True)(lambda x: jnp.sign(x))


# -- unary ------------------------------------------------------------------

def _unary(name, fn):
    op = (lambda f: lambda x: f(x))(fn)
    op.__name__ = name
    register_op(name)(op)


for _name, _fn in [
    ("exp", jnp.exp), ("expm1", jnp.expm1), ("log", jnp.log),
    ("log2", jnp.log2), ("log10", jnp.log10), ("log1p", jnp.log1p),
    ("sqrt", jnp.sqrt), ("square", jnp.square),
    ("sin", jnp.sin), ("cos", jnp.cos), ("tan", jnp.tan),
    ("asin", jnp.arcsin), ("acos", jnp.arccos), ("atan", jnp.arctan),
    ("sinh", jnp.sinh), ("cosh", jnp.cosh), ("tanh", jnp.tanh),
    ("asinh", jnp.arcsinh), ("acosh", jnp.arccosh), ("atanh", jnp.arctanh),
    ("abs", jnp.abs), ("ceil", jnp.ceil), ("floor", jnp.floor),
    ("round", jnp.round), ("trunc", jnp.trunc), ("frac", lambda x: x - jnp.trunc(x)),
    ("reciprocal", jnp.reciprocal), ("neg", jnp.negative),
    ("erf", jax.scipy.special.erf), ("erfinv", jax.scipy.special.erfinv),
    ("digamma", jax.scipy.special.digamma),
    ("lgamma", jax.scipy.special.gammaln),
    ("i0", lambda x: jax.scipy.special.i0(x)),
    ("rsqrt", jax.lax.rsqrt),
    ("sigmoid", jax.nn.sigmoid), ("logsigmoid", jax.nn.log_sigmoid),
    ("relu", jax.nn.relu), ("relu6", jax.nn.relu6),
    ("softplus_default", jax.nn.softplus),
    ("silu", jax.nn.silu), ("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x))),
    ("tanh_shrink", lambda x: x - jnp.tanh(x)),
]:
    _unary(_name, _fn)


@register_op("selu")
def selu_op(x, *, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@register_op("scale")
def scale(x, *, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


@register_op("pow")
def pow_(x, *, factor=1.0):
    return jnp.power(x, factor)


@register_op("clip")
def clip(x, *, min=None, max=None):
    return jnp.clip(x, min, max)


@register_op("gelu")
def gelu(x, *, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


@register_op("leaky_relu")
def leaky_relu(x, *, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope)


@register_op("elu")
def elu(x, *, alpha=1.0):
    return jax.nn.elu(x, alpha)


@register_op("celu")
def celu(x, *, alpha=1.0):
    return jax.nn.celu(x, alpha)




@register_op("hardtanh")
def hardtanh(x, *, min=-1.0, max=1.0):
    return jnp.clip(x, min, max)


@register_op("hardsigmoid")
def hardsigmoid(x, *, slope=1.0 / 6.0, offset=0.5):
    return jnp.clip(x * slope + offset, 0.0, 1.0)


@register_op("hardswish")
def hardswish(x):
    return x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


@register_op("hardshrink")
def hardshrink(x, *, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@register_op("softshrink")
def softshrink(x, *, threshold=0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


@register_op("softplus")
def softplus(x, *, beta=1.0, threshold=20.0):
    scaled = x * beta
    return jnp.where(scaled > threshold, x, jax.nn.softplus(scaled) / beta)


@register_op("softsign")
def softsign(x):
    return jax.nn.soft_sign(x)


@register_op("swish")
def swish(x):
    return jax.nn.silu(x)


@register_op("prelu")
def prelu(x, alpha):
    return jnp.where(x >= 0, x, alpha * x)


@register_op("maximum")
def maximum(x, y):
    return jnp.maximum(x, y)


@register_op("minimum")
def minimum(x, y):
    return jnp.minimum(x, y)


@register_op("stanh")
def stanh(x, *, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


# -- matmul family (MXU ops — keep large and let XLA tile) ------------------


@register_op("matmul_v2")
def matmul_v2(x, y, *, trans_x=False, trans_y=False):
    if trans_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if trans_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


@register_op("matmul")
def matmul_v1(x, y, *, transpose_X=False, transpose_Y=False, alpha=1.0):
    xx = jnp.swapaxes(x, -1, -2) if transpose_X and x.ndim > 1 else x
    yy = jnp.swapaxes(y, -1, -2) if transpose_Y and y.ndim > 1 else y
    out = jnp.matmul(xx, yy)
    return out * alpha if alpha != 1.0 else out


@register_op("mul")
def mul(x, y, *, x_num_col_dims=1, y_num_col_dims=1):
    xm = x.reshape((int(jnp.prod(jnp.array(x.shape[:x_num_col_dims]))), -1)) \
        if x.ndim > 2 else x
    ym = y.reshape((y.shape[0], -1)) if y.ndim > 2 else y
    return jnp.matmul(xm, ym)


@register_op("bmm")
def bmm(x, y):
    return jnp.matmul(x, y)


@register_op("addmm")
def addmm(input, x, y, *, alpha=1.0, beta=1.0):
    return beta * input + alpha * jnp.matmul(x, y)


@register_op("dot")
def dot(x, y):
    return jnp.sum(x * y, axis=-1)


@register_op("outer")
def outer(x, y):
    return jnp.outer(x, y)


@register_op("cross")
def cross(x, y, *, axis=None):
    if axis is None:
        axis = -1
    return jnp.cross(x, y, axis=axis)


@register_op("einsum")
def einsum(*operands, equation):
    return jnp.einsum(equation, *operands)


@register_op("kron")
def kron(x, y):
    return jnp.kron(x, y)


# -- cumulative -------------------------------------------------------------


@register_op("cumsum")
def cumsum(x, *, axis=None, reverse=False, exclusive=False):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if exclusive:
        out = out - x
    if reverse:
        out = jnp.flip(out, axis)
    return out


@register_op("cumprod")
def cumprod(x, *, dim=None):
    if dim is None:
        x = x.reshape(-1)
        dim = 0
    return jnp.cumprod(x, axis=dim)


@register_op("logcumsumexp")
def logcumsumexp(x, *, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jax.lax.cumlogsumexp(x, axis=axis)


@register_op("lerp")
def lerp(x, y, weight):
    return x + weight * (y - x)


@register_op("angle")
def angle(x):
    return jnp.angle(x)


@register_op("conj")
def conj(x):
    return jnp.conj(x)


@register_op("real")
def real(x):
    return jnp.real(x)


@register_op("imag")
def imag(x):
    return jnp.imag(x)


@register_op("trace_op")
def trace_op(x, *, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@register_op("diag")
def diag(x, *, offset=0, padding_value=0.0):
    if x.ndim == 1:
        out = jnp.diag(x, k=offset)
        if padding_value != 0.0:
            mask = jnp.diag(jnp.ones_like(x, dtype=bool), k=offset)
            out = jnp.where(mask, out, padding_value)
        return out
    return jnp.diagonal(x, offset=offset)


@register_op("diagonal")
def diagonal(x, *, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)
