"""Fake-quantization ops.

Ref parity: paddle/fluid/operators/fake_quantize_op.cc kernels behind
the slim quantization passes (python/paddle/fluid/contrib/slim/
quantization/quantization_pass.py op set).  None of the code mirrors the
reference kernels — each op is a pure jnp composition.

TPU-native design: quant-dequant is SIMULATED in float arithmetic with a
straight-through estimator spelled as `x + stop_gradient(qdq(x) - x)`,
so one registered op serves QAT training, PTQ calibration, and frozen
inference under jit with no custom gradient plumbing (the reference
pairs each fake_quantize op with a pass-through grad op).  True int8
storage happens at freeze time in paddle_tpu.quantization, where weights
are kept as int8 arrays and dequantized on the fly — on TPU the win is
HBM bytes, not int8 ALUs, so dequant-to-bf16 before the MXU matmul is
the native lowering.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..core.op_registry import register_op


def _qmax(bit_length):
    return float(2 ** (bit_length - 1) - 1)


def quant_dequant(x, scale, qmax):
    """Symmetric uniform quantize-dequantize: round(x/scale*qmax) bucket
    values, clipped to [-qmax, qmax], mapped back to float."""
    s = jnp.maximum(jnp.asarray(scale, x.dtype), 1e-9)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def _ste(x, y):
    """Straight-through estimator: forward y, gradient of identity."""
    return x + lax.stop_gradient(y - x)


@register_op("fake_quantize_dequantize_abs_max", has_aux=True)
def fake_quantize_dequantize_abs_max(x, *, bit_length=8):
    """ref fake_quantize_op.cc FakeQuantizeDequantizeAbsMax: per-tensor
    dynamic scale = max|x|; returns (out, scale)."""
    qmax = _qmax(bit_length)
    scale = jnp.max(jnp.abs(x)).astype(jnp.float32)
    y = _ste(x, quant_dequant(x, scale, qmax))
    return y, lax.stop_gradient(scale)


@register_op("fake_channel_wise_quantize_dequantize_abs_max", has_aux=True)
def fake_channel_wise_quantize_dequantize_abs_max(x, *, bit_length=8,
                                                  quant_axis=0):
    """ref fake_quantize_op.cc channel-wise variant: one scale per slice
    along quant_axis (conv OIHW -> axis 0; linear [in,out] -> axis 1)."""
    qmax = _qmax(bit_length)
    axes = tuple(a for a in range(x.ndim) if a != quant_axis)
    scale = jnp.max(jnp.abs(x), axis=axes).astype(jnp.float32)
    sshape = [1] * x.ndim
    sshape[quant_axis] = x.shape[quant_axis]
    y = _ste(x, quant_dequant(x, scale.reshape(sshape), qmax))
    return y, lax.stop_gradient(scale)


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             has_aux=True)
def fake_quantize_dequantize_moving_average_abs_max(
        x, in_scale, *, bit_length=8, moving_rate=0.9, is_test=False):
    """ref fake_quantize_op.cc moving-average variant: activations keep
    an EMA of per-batch abs-max; inference freezes it.  Returns
    (out, new_scale) — the caller threads new_scale back into its
    buffer, exactly the running-stat pattern batch_norm uses."""
    qmax = _qmax(bit_length)
    in_scale = jnp.asarray(in_scale, jnp.float32).reshape(())
    if is_test:
        scale = in_scale
    else:
        cur = jnp.max(jnp.abs(x)).astype(jnp.float32)
        # first batch (scale==0) adopts the batch stat outright so the
        # EMA never anchors on the zero init
        ema = moving_rate * in_scale + (1.0 - moving_rate) * cur
        scale = jnp.where(in_scale > 0, ema, cur)
    # an uncalibrated scale (eval/export before any training batch) must
    # pass the activation through, not clamp it to ~0
    y = jnp.where(scale > 0, _ste(x, quant_dequant(x, scale, qmax)), x)
    return y, lax.stop_gradient(scale)


# ---------------------------------------------------------------------------
# frozen-int8 decode path: in-trace dequant + dequant-matmul epilogue
# ---------------------------------------------------------------------------
#
# The serving engine freezes weights to int8 at build time
# (quantization.quantize_state_int8) and dequantizes inside the one
# compiled decode trace.  Two primitives live here:
#
#   dequant_int8(q, scale)      the ONE dequant formula everywhere:
#                               q_f32 * (scale / 127.0).  Engine body,
#                               rollout golden digests, and the freeze
#                               helpers all share it so the canary gate
#                               stays bitwise.
#   dequant_matmul(x, q, scale) x @ dequant(q).T with the dequant as a
#                               matmul EPILOGUE: contract against the
#                               raw int8 rows (f32 accumulate) and scale
#                               the [*, N] output tile — exact for
#                               per-tensor / per-row scales because
#                               column scaling commutes with the
#                               contraction, and the int8 operand is
#                               what rides HBM.
#
# Execution paths gated exactly like fused_conv / fused_loss:
#   * Pallas TPU kernel when FLAGS_use_pallas and backend==tpu (first
#     use probes a tiny call, permanent lax fallback on Mosaic reject).
#   * The same kernel in interpreter mode when
#     PADDLE_TPU_QUANT_FORCE=pallas off-TPU, so CPU tier-1 certifies
#     the exact kernel math.
#   * A pure-lax fallback everywhere else — identical formula.

# row/column tiles: int8 min tile on TPU is (32, 128), f32 is (8, 128);
# K is carried whole per tile (LM-head K = hidden size, a few hundred)
_DQ_BLOCK_M = 256
_DQ_BLOCK_N = 512

# incremented whenever the pallas dequant-matmul is traced (not the lax
# fallback) — tests assert the forced path really hits the kernel
_TRACE_COUNT = 0

_warned_no_pltpu = False
_probe_result = None  # None=untried, True=kernel lowers, False=disabled


def _mm(a, b, ca: int, cb: int):
    """Matmul contracting a's dim `ca` with b's dim `cb`, f32 accumulate
    (see fused_ops._mm — the MXU reads either operand orientation
    natively; an explicit .T would materialise a relayout)."""
    return lax.dot_general(a, b, (((ca,), (cb,)), ((), ())),
                           preferred_element_type=jnp.float32)


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _round_up(a: int, b: int) -> int:
    return _cdiv(a, b) * b


def _compiler_params(semantics):
    if not _HAS_PLTPU:
        return None
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams", None)
    return cls(dimension_semantics=tuple(semantics)) if cls else None


def _use_pallas_quant() -> bool:
    force = os.environ.get("PADDLE_TPU_QUANT_FORCE", "")
    if force == "pallas":
        if not _HAS_PLTPU:
            global _warned_no_pltpu
            if not _warned_no_pltpu:
                _warned_no_pltpu = True
                import warnings

                warnings.warn("pallas TPU backend unavailable; "
                              "dequant_matmul uses the lax path")
            return False
        return True
    if force == "lax":
        return False
    from ..framework.flags import flag

    if not flag("FLAGS_use_pallas"):
        return False
    if not (_HAS_PLTPU and jax.default_backend() == "tpu"):
        return False
    return _probe()


def _interpret() -> bool:
    return (os.environ.get("PADDLE_TPU_QUANT_FORCE", "") == "pallas"
            and jax.default_backend() != "tpu")


def _probe() -> bool:
    """One tiny dequant-matmul through the kernel on first on-TPU use; a
    Mosaic lowering failure disables the pallas path for the session
    instead of wedging every decode step (mirrors fused_conv._probe)."""
    global _probe_result
    if _probe_result is None:
        try:
            x = jnp.zeros((8, 128), jnp.float32)
            q = jnp.zeros((32, 128), jnp.int8)
            s = jnp.ones((32,), jnp.float32)
            jax.block_until_ready(_dq_mm_pallas(x, q, s))
            _probe_result = True
        except Exception as e:  # pragma: no cover - TPU only
            import warnings

            warnings.warn(f"pallas dequant_matmul disabled (probe "
                          f"failed: {e}); using the lax path")
            _probe_result = False
    return _probe_result


def dequant_int8(q, scale):
    """Canonical int8 dequant: q_f32 * (scale / 127.0).

    Every consumer of a frozen weight set (decode-trace body, rollout
    golden digests, test references) must use this exact expression —
    epilogue dequant in `dequant_matmul` is algebraically equal but not
    bitwise, so the bitwise contracts pin which formula runs where."""
    return q.astype(jnp.float32) * (jnp.asarray(scale, jnp.float32)
                                    / 127.0)


def _dq_kernel(x_ref, q_ref, s_ref, o_ref):
    # x (bm, K) · q (bn, K) int8 -> o (bm, bn) f32, scale epilogue on
    # the output tile; s rides as (bn, 8) broadcast rows (scalar-per-row
    # VMEM idiom, see fused_loss._row8)
    acc = _mm(x_ref[...], q_ref[...].astype(jnp.float32), 1, 1)
    o_ref[...] = acc * (s_ref[:, 0][None, :] / 127.0)


def _dq_mm_pallas(x2, q, scale):
    global _TRACE_COUNT
    _TRACE_COUNT += 1
    m, k = x2.shape
    n = q.shape[0]
    bm = min(_DQ_BLOCK_M, _round_up(m, 8))
    bn = min(_DQ_BLOCK_N, _round_up(n, 32))
    kp = _round_up(k, 128)
    mp, np_ = _round_up(m, bm), _round_up(n, bn)
    xp = jnp.zeros((mp, kp), x2.dtype).at[:m, :k].set(x2)
    qp = jnp.zeros((np_, kp), q.dtype).at[:n, :k].set(q)
    sp = jnp.zeros((np_, 8), jnp.float32).at[:n, :].set(
        jnp.broadcast_to(scale[:, None], (n, 8)))
    vmem = pltpu.VMEM  # call sites gate on _HAS_PLTPU
    bspec = lambda shape, imap: pl.BlockSpec(  # noqa: E731
        shape, imap, memory_space=vmem)
    out = pl.pallas_call(
        _dq_kernel,
        grid=(mp // bm, np_ // bn),
        in_specs=[bspec((bm, kp), lambda i, j: (i, 0)),
                  bspec((bn, kp), lambda i, j: (j, 0)),
                  bspec((bn, 8), lambda i, j: (j, 0))],
        out_specs=bspec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        compiler_params=_compiler_params(("parallel", "parallel")),
        interpret=_interpret(),
    )(xp, qp, sp)
    return out[:m, :n]


@register_op("dequant_matmul", no_grad=True)
def dequant_matmul(x, qweight, scale):
    """out = x @ dequant_int8(qweight, scale).T without materialising
    the dequantized weight: contract f32 activations against the raw
    int8 rows and apply `scale/127` as an output epilogue.

    x: (..., K) activations; qweight: (N, K) int8 (LM head = the tied
    embedding table); scale: scalar or (N,) per-row f32.  Returns
    (..., N) float32 logits.  Exact (in real arithmetic) vs operand
    dequant since the per-output-column scale commutes with the K
    contraction; bitwise it is a DIFFERENT formula, which is why the
    serving engine and the rollout golden digests both route the head
    through this op."""
    x = jnp.asarray(x)
    lead, k = x.shape[:-1], x.shape[-1]
    n = qweight.shape[0]
    x2 = x.reshape(-1, k)
    sc = jnp.asarray(scale, jnp.float32).reshape(-1)
    if sc.size == 1:
        sc = jnp.broadcast_to(sc, (n,))
    if _use_pallas_quant():
        out = _dq_mm_pallas(x2, qweight, sc)
    else:
        out = _mm(x2, qweight.astype(jnp.float32), 1, 1) \
            * (sc[None, :] / 127.0)
    return out.reshape(*lead, n)
