"""Fake-quantization ops.

Ref parity: paddle/fluid/operators/fake_quantize_op.cc kernels behind
the slim quantization passes (python/paddle/fluid/contrib/slim/
quantization/quantization_pass.py op set).  None of the code mirrors the
reference kernels — each op is a pure jnp composition.

TPU-native design: quant-dequant is SIMULATED in float arithmetic with a
straight-through estimator spelled as `x + stop_gradient(qdq(x) - x)`,
so one registered op serves QAT training, PTQ calibration, and frozen
inference under jit with no custom gradient plumbing (the reference
pairs each fake_quantize op with a pass-through grad op).  True int8
storage happens at freeze time in paddle_tpu.quantization, where weights
are kept as int8 arrays and dequantized on the fly — on TPU the win is
HBM bytes, not int8 ALUs, so dequant-to-bf16 before the MXU matmul is
the native lowering.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..core.op_registry import register_op


def _qmax(bit_length):
    return float(2 ** (bit_length - 1) - 1)


def quant_dequant(x, scale, qmax):
    """Symmetric uniform quantize-dequantize: round(x/scale*qmax) bucket
    values, clipped to [-qmax, qmax], mapped back to float."""
    s = jnp.maximum(jnp.asarray(scale, x.dtype), 1e-9)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def _ste(x, y):
    """Straight-through estimator: forward y, gradient of identity."""
    return x + lax.stop_gradient(y - x)


@register_op("fake_quantize_dequantize_abs_max", has_aux=True)
def fake_quantize_dequantize_abs_max(x, *, bit_length=8):
    """ref fake_quantize_op.cc FakeQuantizeDequantizeAbsMax: per-tensor
    dynamic scale = max|x|; returns (out, scale)."""
    qmax = _qmax(bit_length)
    scale = jnp.max(jnp.abs(x)).astype(jnp.float32)
    y = _ste(x, quant_dequant(x, scale, qmax))
    return y, lax.stop_gradient(scale)


@register_op("fake_channel_wise_quantize_dequantize_abs_max", has_aux=True)
def fake_channel_wise_quantize_dequantize_abs_max(x, *, bit_length=8,
                                                  quant_axis=0):
    """ref fake_quantize_op.cc channel-wise variant: one scale per slice
    along quant_axis (conv OIHW -> axis 0; linear [in,out] -> axis 1)."""
    qmax = _qmax(bit_length)
    axes = tuple(a for a in range(x.ndim) if a != quant_axis)
    scale = jnp.max(jnp.abs(x), axis=axes).astype(jnp.float32)
    sshape = [1] * x.ndim
    sshape[quant_axis] = x.shape[quant_axis]
    y = _ste(x, quant_dequant(x, scale.reshape(sshape), qmax))
    return y, lax.stop_gradient(scale)


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             has_aux=True)
def fake_quantize_dequantize_moving_average_abs_max(
        x, in_scale, *, bit_length=8, moving_rate=0.9, is_test=False):
    """ref fake_quantize_op.cc moving-average variant: activations keep
    an EMA of per-batch abs-max; inference freezes it.  Returns
    (out, new_scale) — the caller threads new_scale back into its
    buffer, exactly the running-stat pattern batch_norm uses."""
    qmax = _qmax(bit_length)
    in_scale = jnp.asarray(in_scale, jnp.float32).reshape(())
    if is_test:
        scale = in_scale
    else:
        cur = jnp.max(jnp.abs(x)).astype(jnp.float32)
        # first batch (scale==0) adopts the batch stat outright so the
        # EMA never anchors on the zero init
        ema = moving_rate * in_scale + (1.0 - moving_rate) * cur
        scale = jnp.where(in_scale > 0, ema, cur)
    # an uncalibrated scale (eval/export before any training batch) must
    # pass the activation through, not clamp it to ~0
    y = jnp.where(scale > 0, _ste(x, quant_dequant(x, scale, qmax)), x)
    return y, lax.stop_gradient(scale)
