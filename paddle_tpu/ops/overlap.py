"""Latency-hiding collective-matmul: ring-decomposed mp collectives.

The GSPMD path emits the tensor-parallel collectives as monolithic
all-gather / all-reduce ops around the sharded matmuls; on a ring
interconnect the collective time is exposed whenever the compiler's
async overlap pass can't split it. This module decomposes each
mp-sharded matmul + collective pair into `mp` ring steps — one
`lax.ppermute` hop interleaved with one per-shard partial matmul — so
every hop's transfer hides behind the next partial product (the
fluid-era "parallelism by program rewriting" lesson, SURVEY.md; same
ring schedule as the pallas guide's ring collectives, expressed at the
`lax` level so it runs on CPU meshes and composes with autodiff).

Three primitives cover the Megatron block:

- ``matmul_allreduce``      row-parallel, dense activations
                            (x·W followed by all-reduce over mp)
- ``allgather_matmul``      column-parallel, sequence-parallel input
                            (all-gather of the seq axis before x·W)
- ``matmul_reducescatter``  row-parallel, sequence-parallel output
                            (x·W followed by reduce-scatter of seq)

All three run SPMD-manual inside `jax.shard_map` (the compat shim in
paddle_tpu/__init__.py covers old jax) and are exact up to partial-sum
reassociation: the ring accumulates the mp partial products in ring
order rather than the single fused reduction's order, so parity vs the
GSPMD path is bitwise for the gather phase and ~1 ulp for the reduce
phases (tests use rtol 1e-6 on fp32).

Routing: engines enter `region(mesh, sequence_parallel=...)` around the
model call when `FLAGS_mp_overlap` is on (PADDLE_TPU_MP_OVERLAP_FORCE
overrides) and the mesh qualifies (`supported`); Column/RowParallelLinear
consult `current()` and fall back to the GSPMD collectives whenever a
guard fails — shapes that don't divide the ring, tape-based autograd,
eager execution, or an enclosing manual region.
"""

from __future__ import annotations

import contextlib
import os
import threading

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor

# canonical mesh axis names (== distributed.topology.DP_AXIS/MP_AXIS;
# spelled out so `paddle_tpu.ops` stays importable before the
# distributed package finishes loading during package init)
DP_AXIS = "dp"
MP_AXIS = "mp"

__all__ = [
    "enabled", "supported", "region", "current",
    "matmul_allreduce", "allgather_matmul", "matmul_reducescatter",
    "maybe_column_parallel", "maybe_row_parallel",
    "model_sequence_parallel",
]


def model_sequence_parallel(layer):
    """True when any sublayer runs megatron sequence parallelism (the
    decoder blocks carry a `sequence_parallel` attr)."""
    try:
        subs = layer.sublayers(include_self=True)
    except (AttributeError, TypeError):
        subs = [layer]
    return any(bool(getattr(l, "sequence_parallel", False))
               for l in subs)


def _force():
    """PADDLE_TPU_MP_OVERLAP_FORCE=on|off wins over the flag; else None."""
    v = os.environ.get("PADDLE_TPU_MP_OVERLAP_FORCE", "").strip().lower()
    if v in ("1", "on", "true", "yes"):
        return True
    if v in ("0", "off", "false", "no"):
        return False
    return None


def enabled():
    forced = _force()
    if forced is not None:
        return forced
    from ..framework.flags import flag
    return bool(flag("FLAGS_mp_overlap"))


def _axis_sizes(mesh):
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def supported(mesh):
    """Ring decomposition applies on pure dp x mp meshes with mp > 1.

    Any other nontrivial axis (pp, sharding, sep) means the step is
    already inside — or about to enter — another manual region the ring
    shard_map can't nest under old jax, so the GSPMD path stays.
    """
    if mesh is None:
        return False
    sizes = _axis_sizes(mesh)
    if sizes.get(MP_AXIS, 1) <= 1:
        return False
    return all(size == 1 for name, size in sizes.items()
               if name not in (DP_AXIS, MP_AXIS))


# -- trace region ------------------------------------------------------------

_tls = threading.local()


class _Region:
    __slots__ = ("mesh", "sequence_parallel")

    def __init__(self, mesh, sequence_parallel):
        self.mesh = mesh
        self.sequence_parallel = bool(sequence_parallel)


@contextlib.contextmanager
def region(mesh, sequence_parallel=False):
    """Mark a trace region whose mp matmuls may use the ring kernels.

    No-op (plain GSPMD trace) unless overlap is enabled AND the mesh
    qualifies; entering costs nothing per step — it only runs at trace
    time inside jit.
    """
    if not (enabled() and supported(mesh)):
        yield
        return
    prev = getattr(_tls, "region", None)
    _tls.region = _Region(mesh, sequence_parallel)
    try:
        yield
    finally:
        _tls.region = prev


def current():
    """The active overlap region, or None."""
    return getattr(_tls, "region", None)


def _inside_manual_region():
    """True when tracing already runs under a shard_map's named axes —
    the ring shard_map must not nest there (old-jax compat is
    fully-manual only)."""
    try:
        from jax._src import core as _core
        return bool(_core.get_axis_env().axis_sizes)
    except (AttributeError, ImportError):
        return False


# -- ring primitives ---------------------------------------------------------
#
# Shapes below are GLOBAL; n = mp degree. All primitives return None when
# a divisibility guard fails so the caller keeps the GSPMD path.


def _ring(n):
    # forward ring: device i sends to i+1 (mod n)
    return [(i, (i + 1) % n) for i in range(n)]


def _dp_part(mesh, x):
    """Shard the leading batch axis over dp when it divides; else
    replicate over dp (exact, just redundant)."""
    dp = _axis_sizes(mesh).get(DP_AXIS, 1)
    if dp > 1 and x.ndim >= 3 and x.shape[0] % dp == 0:
        return DP_AXIS
    return None


def _spec(ndim, dp, seq=None, last=None):
    """PartitionSpec of exactly `ndim` entries: optional dp on dim 0,
    `seq` on dim -2, `last` on dim -1."""
    parts = [None] * ndim
    if dp is not None and ndim >= 3:
        parts[0] = dp
    if seq is not None:
        parts[-2] = seq
    if last is not None:
        parts[-1] = last
    return P(*parts)


def _smap(mesh, fn, in_specs, out_specs):
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs,
                         axis_names={DP_AXIS, MP_AXIS}, check_vma=False)


def _pmm(a, b):
    """One ring-hop partial matmul. Under FLAGS_lowp_matmul the
    per-shard partials quantize through the scaled-matmul family
    (dynamic per-hop abs-max scales — this runs inside a shard_map
    body, where the train step's delayed-scaling region must not leak)
    and accumulate across hops at the operands' precision."""
    from . import lowp as _lowp

    m = _lowp.mode()
    if m == "off":
        return a @ b
    return _lowp.scaled_matmul(a, b, qdtype=m,
                               out_dtype=jnp.result_type(a, b))


def matmul_allreduce(x, w, mesh):
    """Row-parallel matmul with the all-reduce decomposed into a
    reduce-scatter ring + all-gather ring, both hidden behind per-chunk
    partial matmuls.

    x [..., s, h] (last dim mp-sharded), w [h, M] (dim 0 mp-sharded)
    -> [..., s, M] replicated over mp. Requires h % n == 0, M % n == 0.
    """
    n = _axis_sizes(mesh)[MP_AXIS]
    if x.ndim < 2 or x.shape[-1] != w.shape[0]:
        return None
    if x.shape[-1] % n or w.shape[1] % n:
        return None
    dp = _dp_part(mesh, x)
    fwd = _ring(n)

    def local(xl, wl):
        # xl [..., s, h/n], wl [h/n, M]
        idx = lax.axis_index(MP_AXIS)
        csz = wl.shape[1] // n

        def wchunk(c):
            return lax.dynamic_slice_in_dim(wl, c * csz, csz, axis=1)

        # reduce-scatter phase: after n-1 hops device idx holds output
        # chunk idx fully summed over all mp shards of the contraction
        acc = _pmm(xl, wchunk((idx - 1) % n))
        for t in range(1, n):
            acc = lax.ppermute(acc, MP_AXIS, fwd) \
                + _pmm(xl, wchunk((idx - t - 1) % n))
        # all-gather phase: circulate the finished chunks
        parts = [acc]
        cur = acc
        for _ in range(n - 1):
            cur = lax.ppermute(cur, MP_AXIS, fwd)
            parts.append(cur)
        stacked = jnp.stack(parts)           # [n, ..., s, csz]
        # parts[k] on device idx is chunk (idx - k) mod n; reorder to 0..n-1
        order = (idx - jnp.arange(n)) % n
        y = jnp.take(stacked, jnp.argsort(order), axis=0)
        y = jnp.moveaxis(y, 0, -2)           # [..., s, n, csz]
        return y.reshape(y.shape[:-2] + (n * csz,))

    out = _smap(mesh, local,
                (_spec(x.ndim, dp, last=MP_AXIS), P(MP_AXIS, None)),
                _spec(x.ndim, dp))
    return out(x, w)


def allgather_matmul(x, w, mesh):
    """Column-parallel matmul over a sequence-parallel input with the
    seq all-gather decomposed into ring hops hidden behind per-chunk
    matmuls.

    x [..., s, h] (dim -2 mp-sharded), w [h, M] (dim 1 mp-sharded)
    -> [..., s, M] with last dim mp-sharded. Requires s % n == 0,
    M % n == 0.
    """
    n = _axis_sizes(mesh)[MP_AXIS]
    if x.ndim < 2 or x.shape[-1] != w.shape[0]:
        return None
    if x.shape[-2] % n or w.shape[1] % n:
        return None
    dp = _dp_part(mesh, x)
    fwd = _ring(n)

    def local(xl, wl):
        # xl [..., s/n, h], wl [h, M/n]
        idx = lax.axis_index(MP_AXIS)
        sl = xl.shape[-2]
        cur = xl
        y = None
        for t in range(n):
            part = _pmm(cur, wl)             # [..., s/n, M/n]
            if y is None:
                y = jnp.zeros(part.shape[:-2] + (n * sl, part.shape[-1]),
                              part.dtype)
            c = (idx - t) % n                # which seq chunk `cur` is
            y = lax.dynamic_update_slice_in_dim(y, part, c * sl, axis=-2)
            if t < n - 1:
                cur = lax.ppermute(cur, MP_AXIS, fwd)
        return y

    out = _smap(mesh, local,
                (_spec(x.ndim, dp, seq=MP_AXIS), P(None, MP_AXIS)),
                _spec(x.ndim, dp, last=MP_AXIS))
    return out(x, w)


def matmul_reducescatter(x, w, mesh):
    """Row-parallel matmul whose output reduce-scatters the seq axis,
    decomposed into ring hops hidden behind per-chunk partial matmuls.

    x [..., s, h] (last dim mp-sharded), w [h, M] (dim 0 mp-sharded)
    -> [..., s, M] with dim -2 mp-sharded. Requires h % n == 0,
    s % n == 0.
    """
    n = _axis_sizes(mesh)[MP_AXIS]
    if x.ndim < 2 or x.shape[-1] != w.shape[0]:
        return None
    if x.shape[-1] % n or x.shape[-2] % n:
        return None
    dp = _dp_part(mesh, x)
    fwd = _ring(n)

    def local(xl, wl):
        # xl [..., s, h/n], wl [h/n, M]
        idx = lax.axis_index(MP_AXIS)
        sl = xl.shape[-2] // n

        def pchunk(c):
            return _pmm(lax.dynamic_slice_in_dim(xl, c * sl, sl, axis=-2),
                        wl)

        # after n-1 hops device idx holds seq chunk idx fully summed
        acc = pchunk((idx - 1) % n)
        for t in range(1, n):
            acc = lax.ppermute(acc, MP_AXIS, fwd) \
                + pchunk((idx - t - 1) % n)
        return acc

    out = _smap(mesh, local,
                (_spec(x.ndim, dp, last=MP_AXIS), P(MP_AXIS, None)),
                _spec(x.ndim, dp, seq=MP_AXIS))
    return out(x, w)


# -- Tensor-level routing (consulted by mp_layers) ---------------------------


def _routable(*tensors):
    """All guards a route must pass before leaving the GSPMD path."""
    ctx = current()
    if ctx is None:
        return None
    if _inside_manual_region():
        return None
    for t in tensors:
        if not isinstance(t, Tensor):
            return None
        if not isinstance(t._value, jax.core.Tracer):
            return None
        if getattr(t, "_tape", None) is not None:
            return None
    return ctx


def maybe_column_parallel(x, weight):
    """Ring path for ColumnParallelLinear (gather_output=False under
    sequence parallelism — the only column case with a forward
    collective to hide). Returns the output Tensor (bias NOT applied)
    or None to keep the GSPMD path."""
    ctx = _routable(x, weight)
    if ctx is None or not ctx.sequence_parallel:
        return None
    if x._value.ndim < 2:
        return None
    out = allgather_matmul(x._value, weight._value, ctx.mesh)
    return None if out is None else Tensor(out)


def maybe_row_parallel(x, weight):
    """Ring path for RowParallelLinear: reduce-scatter variant under
    sequence parallelism, decomposed all-reduce otherwise. Returns the
    output Tensor (bias NOT applied) or None to keep the GSPMD path."""
    ctx = _routable(x, weight)
    if ctx is None:
        return None
    if x._value.ndim < 2:
        return None
    if ctx.sequence_parallel:
        out = matmul_reducescatter(x._value, weight._value, ctx.mesh)
    else:
        out = matmul_allreduce(x._value, weight._value, ctx.mesh)
    return None if out is None else Tensor(out)
