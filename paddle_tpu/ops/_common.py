"""Shared helpers for op implementations."""

from __future__ import annotations

import jax.numpy as jnp


def align_for_axis_broadcast(x, y, axis=-1):
    """Paddle legacy elementwise `axis` attr: broadcast y starting at `axis`
    of x (ref: paddle/fluid/operators/elementwise/elementwise_op.h)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if axis == -1 or y.ndim == 0 or x.ndim == y.ndim:
        return x, y
    if y.ndim > x.ndim:
        return x, y
    shape = [1] * axis + list(y.shape)
    shape += [1] * (x.ndim - len(shape))
    return x, y.reshape(shape)


def normalize_axis(axis, ndim):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(normalize_axis(a, ndim) for a in axis)
    axis = int(axis)
    return axis + ndim if axis < 0 else axis


def keep_mask_u16(key_or_bits_key, shape, dropout_p):
    """bool dropout keep-mask from a u16 threshold compare.

    16 random bits per element: half the traffic of a u32 stream and no
    int->float conversion (vs bernoulli's f32 uniform); the keep rate
    quantises to 1/65536 (error <= 1.5e-5 of the requested p — far below
    training noise). Shared by ops/nn_ops.dropout and the attention
    paths in ops/fused_ops.
    """
    import jax

    bits = jax.random.bits(key_or_bits_key, shape, jnp.uint16)
    thresh = jnp.uint16(min(int(round((1.0 - dropout_p) * 2.0 ** 16)),
                            2 ** 16 - 1))
    return bits < thresh
