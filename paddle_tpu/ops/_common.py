"""Shared helpers for op implementations."""

from __future__ import annotations

import jax.numpy as jnp


def align_for_axis_broadcast(x, y, axis=-1):
    """Paddle legacy elementwise `axis` attr: broadcast y starting at `axis`
    of x (ref: paddle/fluid/operators/elementwise/elementwise_op.h)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if axis == -1 or y.ndim == 0 or x.ndim == y.ndim:
        return x, y
    if y.ndim > x.ndim:
        return x, y
    shape = [1] * axis + list(y.shape)
    shape += [1] * (x.ndim - len(shape))
    return x, y.reshape(shape)


def normalize_axis(axis, ndim):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(normalize_axis(a, ndim) for a in axis)
    axis = int(axis)
    return axis + ndim if axis < 0 else axis
