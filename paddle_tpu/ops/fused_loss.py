"""Fused LM-head loss: chunked-vocab linear + cross-entropy that never
materializes the `[N, V]` logits.

Ref parity: the reference computes the tied-decoder projection
(matmul_v2 against the embedding table) and then
softmax_with_cross_entropy as two ops, paying `[N, V]` of HBM in forward
and again in backward.  Here both collapse into one streaming op
(flash-attention / Liger-Kernel lineage — the same online-logsumexp
trick fused_ops.py uses over keys, applied over vocab chunks):

  forward   streams `[cv, H]` chunks of the weight through VMEM, keeps a
            per-row online (max, sumexp, picked-logit) triple in f32, and
            emits only per-row `nll = lse - s[label]` and `lse`.
  backward  re-streams the same chunks, rebuilds each score tile from
            (x, w, lse), forms `dlogits = softmax - onehot` in-register
            and contracts it immediately into dx / dw f32 accumulators —
            the logits gradient also never touches HBM.

Numerics match `cross_entropy(matmul(x, w.T))` exactly at fp32 (same
lse formulation) and to bf16 tolerance under AMP: operands stay bf16
into the MXU with f32 accumulation (`_mm`), loss/lse are f32.

Three execution paths, gated exactly like fused_conv:
  * Pallas TPU kernels when `FLAGS_use_pallas` and the backend is TPU
    (first use probes a tiny call and permanently falls back if Mosaic
    rejects the lowering).
  * The same kernels in interpreter mode when
    PADDLE_TPU_LMLOSS_FORCE=pallas off-TPU, so CPU tier-1 certifies the
    exact kernel math + backward.
  * A pure-lax `lax.scan` chunked fallback everywhere else — same
    no-materialization memory profile (XLA sees only `[N, cv]` tiles).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..core.op_registry import register_op

_NEG_INF = -1e30

# Row block / vocab chunk: VMEM at (256, 1024, H=768) — x tile 384KB
# bf16, w chunk 1.5MB bf16, score tile 1MB f32, dw accumulator 3MB f32 —
# comfortably under the 16MB/core budget while keeping the MXU matmuls
# large enough that grid overhead doesn't dominate (same sizing logic as
# fused_ops._BLOCK_Q/_BLOCK_K).
_BLOCK_N = 256
_CHUNK_V = 1024

# incremented whenever a pallas lm-loss is traced (not the lax
# fallback) — tests assert the forced path really goes through the
# kernels rather than silently falling back
_TRACE_COUNT = 0

_warned_no_pltpu = False
_probe_result = None  # None=untried, True=kernel lowers, False=disabled


def _mm(a, b, ca: int, cb: int):
    """Matmul contracting a's dim `ca` with b's dim `cb`, f32 accumulate
    (see fused_ops._mm: the MXU reads either operand orientation
    natively; an explicit .T would materialise a relayout)."""
    return lax.dot_general(a, b, (((ca,), (cb,)), ((), ())),
                           preferred_element_type=jnp.float32)


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _round_up(a: int, b: int) -> int:
    return _cdiv(a, b) * b


def _compiler_params(semantics):
    if not _HAS_PLTPU:
        return None
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams", None)
    return cls(dimension_semantics=tuple(semantics)) if cls else None


def _use_pallas_lm() -> bool:
    force = os.environ.get("PADDLE_TPU_LMLOSS_FORCE", "")
    if force == "pallas":
        if not _HAS_PLTPU:
            global _warned_no_pltpu
            if not _warned_no_pltpu:
                _warned_no_pltpu = True
                import warnings

                warnings.warn("pallas TPU backend unavailable; fused "
                              "lm loss uses the lax path")
            return False
        return True
    if force == "lax":
        return False
    from ..framework.flags import flag

    if not flag("FLAGS_use_pallas"):
        return False
    if not (_HAS_PLTPU and jax.default_backend() == "tpu"):
        return False
    return _probe()


def _interpret() -> bool:
    return (os.environ.get("PADDLE_TPU_LMLOSS_FORCE", "") == "pallas"
            and jax.default_backend() != "tpu")


def _probe() -> bool:
    """One tiny fused loss through the kernels on first on-TPU use; a
    Mosaic lowering failure disables the pallas path for the session
    instead of wedging every step (mirrors fused_conv._probe — the
    real-TPU lowering is the one part CPU tier-1 cannot certify)."""
    global _probe_result
    if _probe_result is None:
        try:
            x = jnp.zeros((8, 128), jnp.float32)
            w = jnp.zeros((256, 128), jnp.float32)
            lbl = jnp.zeros((8,), jnp.int32)
            nll, lse = _fwd_pallas(x, w, lbl, 128)
            jax.block_until_ready(
                _bwd_pallas(x, w, lbl, lse, jnp.ones_like(nll), 128))
            _probe_result = True
        except Exception as e:  # pragma: no cover - TPU only
            _probe_result = False
            import warnings

            warnings.warn(
                "pallas fused lm loss failed to lower; using the lax "
                f"chunked path for this session ({type(e).__name__}: {e})")
    return _probe_result


# ---------------------------------------------------------------------------
# pallas kernels
# ---------------------------------------------------------------------------
#
# Layout notes (idioms from fused_ops.py):
#   * per-row scalars (labels, lse, loss, upstream g) travel as (N, 8)
#     broadcasts — Mosaic pads lanes to 128 in VMEM but HBM only moves 8.
#   * block offsets arrive as (n, 8, 128) int32 data inputs instead of
#     pl.program_id, which fails to re-trace under nested AD here.
#   * the sequential grid dim accumulates into VMEM f32 scratch with
#     @pl.when init on the first slot and write-out on the last.


def _off_inputs(n, step):
    """(n, 8, 128) int32 block-offset input: [i*step] broadcast."""
    return jnp.broadcast_to(
        (jnp.arange(n, dtype=jnp.int32) * step)[:, None, None],
        (n, 8, 128))


def _row8(v, n_pad):
    """Pad a per-row (N,) vector to (n_pad, 8) f32/i32 broadcast."""
    v = jnp.pad(v, (0, n_pad - v.shape[0]),
                constant_values=jnp.zeros((), v.dtype))
    return jnp.broadcast_to(v[:, None], (n_pad, 8))


def _fwd_kernel(voff_ref, x_ref, w_ref, lbl_ref, loss_ref, lse_ref,
                m_sc, l_sc, p_sc, *, vocab, last_voff):
    # x_ref: (bn, H), w_ref: (cv, H), lbl_ref: (bn, 8) int32,
    # loss/lse_ref: (bn, 8) f32; scratch m/l/p: (bn, 8) f32 carrying the
    # online (running max, sumexp, picked logit) across vocab chunks.
    bn = x_ref.shape[0]
    cv = w_ref.shape[0]
    v_off = voff_ref[0, 0, 0]

    @pl.when(v_off == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)
        p_sc[...] = jnp.zeros_like(p_sc)

    x = x_ref[...]
    w = w_ref[...]
    s = _mm(x, w, 1, 1)  # (bn, cv) f32 scores for this vocab chunk
    col = v_off + lax.broadcasted_iota(jnp.int32, (bn, cv), 1)
    valid = col < vocab
    s = jnp.where(valid, s, _NEG_INF)
    lbl = lbl_ref[:, :1]
    m_i = m_sc[:, :1]
    l_i = l_sc[:, :1]
    m_new = jnp.maximum(m_i, jnp.max(s, axis=-1, keepdims=True))
    l_new = l_i * jnp.exp(m_i - m_new) + \
        jnp.sum(jnp.exp(s - m_new), axis=-1, keepdims=True)
    hit = valid & (col == lbl)
    p_new = p_sc[:, :1] + jnp.sum(jnp.where(hit, s, 0.0), axis=-1,
                                  keepdims=True)
    m_sc[...] = jnp.broadcast_to(m_new, m_sc.shape)
    l_sc[...] = jnp.broadcast_to(l_new, l_sc.shape)
    p_sc[...] = jnp.broadcast_to(p_new, p_sc.shape)

    @pl.when(v_off == last_voff)
    def _done():
        # every row sees >= 1 valid column, so l >= exp(0) after the
        # running max: no zero guard needed (unlike flash's masked rows)
        lse = m_sc[:, :1] + jnp.log(l_sc[:, :1])
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)
        loss_ref[...] = jnp.broadcast_to(lse - p_sc[:, :1],
                                         loss_ref.shape)


def _dlogits(x, w, v_off, vocab, lbl, lse, g):
    """(softmax - onehot) * g for one score tile, rebuilt from lse —
    shared by the dx and dw kernels so both see identical tiles."""
    bn = x.shape[0]
    cv = w.shape[0]
    s = _mm(x, w, 1, 1)
    col = v_off + lax.broadcasted_iota(jnp.int32, (bn, cv), 1)
    valid = col < vocab
    p = jnp.where(valid, jnp.exp(s - lse), 0.0)
    hit = valid & (col == lbl)
    return (p - hit.astype(jnp.float32)) * g


def _bwd_dx_kernel(voff_ref, x_ref, w_ref, lbl_ref, lse_ref, g_ref,
                   dx_ref, acc_sc, *, vocab, last_voff):
    v_off = voff_ref[0, 0, 0]

    @pl.when(v_off == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)

    x = x_ref[...]
    w = w_ref[...]
    d = _dlogits(x, w, v_off, vocab, lbl_ref[:, :1], lse_ref[:, :1],
                 g_ref[:, :1])
    # dx += d @ w: contract the chunk dim; d drops to the operand dtype
    # so the MXU stays at bf16 throughput (accumulator is f32 scratch)
    acc_sc[...] += _mm(d.astype(x.dtype), w, 1, 0)

    @pl.when(v_off == last_voff)
    def _done():
        dx_ref[...] = acc_sc[...].astype(dx_ref.dtype)


def _bwd_dw_kernel(voff_ref, roff_ref, x_ref, w_ref, lbl_ref, lse_ref,
                   g_ref, dw_ref, acc_sc, *, vocab, last_roff):
    v_off = voff_ref[0, 0, 0]
    r_off = roff_ref[0, 0, 0]

    @pl.when(r_off == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)

    x = x_ref[...]
    w = w_ref[...]
    d = _dlogits(x, w, v_off, vocab, lbl_ref[:, :1], lse_ref[:, :1],
                 g_ref[:, :1])
    # dw += d.T @ x: contract the row dim
    acc_sc[...] += _mm(d.astype(x.dtype), x, 0, 0)

    @pl.when(r_off == last_roff)
    def _done():
        dw_ref[...] = acc_sc[...].astype(dw_ref.dtype)


def _block_n(n: int) -> int:
    return min(_BLOCK_N, _round_up(n, 8))


def _pad_operands(x, w, labels, cv):
    n, h = x.shape
    v = w.shape[0]
    bn = _block_n(n)
    nr = _cdiv(n, bn)
    n_pad = nr * bn
    cv = min(_round_up(cv, 128), _round_up(v, 128))
    nv = _cdiv(v, cv)
    v_pad = nv * cv
    xp = jnp.pad(x, ((0, n_pad - n), (0, 0)))
    wp = jnp.pad(w, ((0, v_pad - v), (0, 0)))
    # padded rows get label -1: it never matches a column, so their
    # picked logit is 0 and their (finite) nll is discarded by the
    # caller's slice; their g is 0-padded in backward.
    lblp = _row8(jnp.pad(labels.astype(jnp.int32), (0, n_pad - n),
                         constant_values=-1), n_pad)
    return xp, wp, lblp, bn, nr, n_pad, cv, nv


def _fwd_pallas(x, w, labels, cv):
    global _TRACE_COUNT
    _TRACE_COUNT += 1
    n, h = x.shape
    v = w.shape[0]
    xp, wp, lblp, bn, nr, n_pad, cv, nv = _pad_operands(x, w, labels, cv)
    vmem = pltpu.VMEM  # call sites gate on _HAS_PLTPU
    bspec = lambda shape, imap: pl.BlockSpec(  # noqa: E731
        shape, imap, memory_space=vmem)
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, vocab=v, last_voff=(nv - 1) * cv),
        grid=(nr, nv),
        in_specs=[
            bspec((1, 8, 128), lambda i, j: (j, 0, 0)),
            bspec((bn, h), lambda i, j: (i, 0)),
            bspec((cv, h), lambda i, j: (j, 0)),
            bspec((bn, 8), lambda i, j: (i, 0)),
        ],
        out_specs=[
            bspec((bn, 8), lambda i, j: (i, 0)),
            bspec((bn, 8), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, 8), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 8), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bn, 8), jnp.float32),
                        pltpu.VMEM((bn, 8), jnp.float32),
                        pltpu.VMEM((bn, 8), jnp.float32)],
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        interpret=_interpret(),
    )(_off_inputs(nv, cv), xp, wp, lblp)
    return loss[:n, 0], lse[:n, 0]


def _bwd_pallas(x, w, labels, lse, g, cv):
    n, h = x.shape
    v = w.shape[0]
    xp, wp, lblp, bn, nr, n_pad, cv, nv = _pad_operands(x, w, labels, cv)
    lsep = _row8(lse, n_pad)
    gp = _row8(g, n_pad)
    vmem = pltpu.VMEM
    bspec = lambda shape, imap: pl.BlockSpec(  # noqa: E731
        shape, imap, memory_space=vmem)

    # dx: grid (row block, vocab chunk) — chunks sequential into scratch
    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, vocab=v,
                          last_voff=(nv - 1) * cv),
        grid=(nr, nv),
        in_specs=[
            bspec((1, 8, 128), lambda i, j: (j, 0, 0)),
            bspec((bn, h), lambda i, j: (i, 0)),
            bspec((cv, h), lambda i, j: (j, 0)),
            bspec((bn, 8), lambda i, j: (i, 0)),
            bspec((bn, 8), lambda i, j: (i, 0)),
            bspec((bn, 8), lambda i, j: (i, 0)),
        ],
        out_specs=bspec((bn, h), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, h), x.dtype),
        scratch_shapes=[pltpu.VMEM((bn, h), jnp.float32)],
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        interpret=_interpret(),
    )(_off_inputs(nv, cv), xp, wp, lblp, lsep, gp)

    # dw: grid (vocab chunk, row block) — rows sequential into scratch
    dw = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, vocab=v,
                          last_roff=(nr - 1) * bn),
        grid=(nv, nr),
        in_specs=[
            bspec((1, 8, 128), lambda a, b: (a, 0, 0)),
            bspec((1, 8, 128), lambda a, b: (b, 0, 0)),
            bspec((bn, h), lambda a, b: (b, 0)),
            bspec((cv, h), lambda a, b: (a, 0)),
            bspec((bn, 8), lambda a, b: (b, 0)),
            bspec((bn, 8), lambda a, b: (b, 0)),
            bspec((bn, 8), lambda a, b: (b, 0)),
        ],
        out_specs=bspec((cv, h), lambda a, b: (a, 0)),
        out_shape=jax.ShapeDtypeStruct((nv * cv, h), w.dtype),
        scratch_shapes=[pltpu.VMEM((cv, h), jnp.float32)],
        compiler_params=_compiler_params(("parallel", "arbitrary")),
        interpret=_interpret(),
    )(_off_inputs(nv, cv), _off_inputs(nr, bn), xp, wp, lblp, lsep, gp)
    return dx[:n], dw[:v]


# ---------------------------------------------------------------------------
# lax.scan fallback (identical math; runs anywhere; XLA only ever sees
# [N, cv] score tiles so the no-materialization profile is preserved)
# ---------------------------------------------------------------------------


def _chunked_w(w, cv):
    v, h = w.shape
    nv = _cdiv(v, cv)
    wp = jnp.pad(w, ((0, nv * cv - v), (0, 0)))
    return wp.reshape(nv, cv, h), nv


def _fwd_lax(x, w, labels, cv):
    n, _ = x.shape
    v = w.shape[0]
    wc, nv = _chunked_w(w, cv)
    lbl = labels.astype(jnp.int32)

    def step(carry, inp):
        m_i, l_i, p_i = carry
        off, wk = inp
        s = _mm(x, wk, 1, 1)  # (n, cv) f32
        col = off + jnp.arange(cv, dtype=jnp.int32)
        s = jnp.where(col[None, :] < v, s, _NEG_INF)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=-1))
        l_new = l_i * jnp.exp(m_i - m_new) + \
            jnp.sum(jnp.exp(s - m_new[:, None]), axis=-1)
        hit = (col[None, :] < v) & (col[None, :] == lbl[:, None])
        p_new = p_i + jnp.sum(jnp.where(hit, s, 0.0), axis=-1)
        return (m_new, l_new, p_new), None

    offs = jnp.arange(nv, dtype=jnp.int32) * cv
    init = (jnp.full((n,), _NEG_INF, jnp.float32),
            jnp.zeros((n,), jnp.float32), jnp.zeros((n,), jnp.float32))
    (m, l, picked), _ = lax.scan(step, init, (offs, wc))
    lse = m + jnp.log(l)
    return lse - picked, lse


def _bwd_lax(x, w, labels, lse, g, cv):
    n, h = x.shape
    v = w.shape[0]
    wc, nv = _chunked_w(w, cv)
    lbl = labels.astype(jnp.int32)

    def step(dx_acc, inp):
        off, wk = inp
        s = _mm(x, wk, 1, 1)
        col = off + jnp.arange(cv, dtype=jnp.int32)
        valid = col[None, :] < v
        p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)
        hit = valid & (col[None, :] == lbl[:, None])
        d = ((p - hit.astype(jnp.float32)) * g[:, None]).astype(x.dtype)
        dx_acc = dx_acc + _mm(d, wk, 1, 0)
        dwk = _mm(d, x, 0, 0)
        return dx_acc, dwk

    offs = jnp.arange(nv, dtype=jnp.int32) * cv
    dx, dwc = lax.scan(step, jnp.zeros((n, h), jnp.float32), (offs, wc))
    dw = dwc.reshape(nv * cv, h)[:v]
    return dx.astype(x.dtype), dw.astype(w.dtype)


# ---------------------------------------------------------------------------
# custom_vjp + public op
# ---------------------------------------------------------------------------


def _fwd_lax_lowp(x, w, labels, cv, qdtype):
    """_fwd_lax with the per-chunk score matmuls quantized (the lowp
    route for the fused LM-head loss). Scales are dynamic per-tensor
    abs-max — this runs inside the _lce custom_vjp forward rule, a
    sub-trace where the train step's delayed-scaling region must not
    record. x quantizes once; each weight chunk quantizes in-scan.
    The backward recomputes scores at full precision against the lowp
    lse (standard lowp-fwd/high-precision-bwd recipe; the mismatch is
    covered by the bench.py --lowp rtol gate)."""
    from . import lowp as _lowp

    monitor_name = f"lowp.matmuls_{qdtype}"
    from ..framework import monitor as _monitor

    _monitor.stat_add(monitor_name)
    n, _ = x.shape
    v = w.shape[0]
    wc, nv = _chunked_w(w, cv)
    lbl = labels.astype(jnp.int32)
    sx = _lowp.amax_of(x)
    if qdtype == "int8":
        qx = _lowp._quant_int8(x, sx)
    else:
        qx = _lowp._quant_f8(x, sx).astype(jnp.float32)

    def scores(wk):
        sw = _lowp.amax_of(wk)
        if qdtype == "int8":
            qw = _lowp._quant_int8(wk, sw)
            acc = lax.dot_general(qx, qw, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.int32)
            return acc.astype(jnp.float32) * (sx * sw / (127.0 * 127.0))
        qw = _lowp._quant_f8(wk, sw).astype(jnp.float32)
        acc = lax.dot_general(qx, qw, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
        return acc * (sx * sw / (448.0 * 448.0))

    def step(carry, inp):
        m_i, l_i, p_i = carry
        off, wk = inp
        s = scores(wk)  # (n, cv) f32
        col = off + jnp.arange(cv, dtype=jnp.int32)
        s = jnp.where(col[None, :] < v, s, _NEG_INF)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=-1))
        l_new = l_i * jnp.exp(m_i - m_new) + \
            jnp.sum(jnp.exp(s - m_new[:, None]), axis=-1)
        hit = (col[None, :] < v) & (col[None, :] == lbl[:, None])
        p_new = p_i + jnp.sum(jnp.where(hit, s, 0.0), axis=-1)
        return (m_new, l_new, p_new), None

    offs = jnp.arange(nv, dtype=jnp.int32) * cv
    init = (jnp.full((n,), _NEG_INF, jnp.float32),
            jnp.zeros((n,), jnp.float32), jnp.zeros((n,), jnp.float32))
    (m, l, picked), _ = lax.scan(step, init, (offs, wc))
    lse = m + jnp.log(l)
    return lse - picked, lse


def _lowp_mode():
    from . import lowp as _lowp

    return _lowp.mode()


def _fwd_dispatch(x, w, labels, cv):
    m = _lowp_mode()
    if m != "off":
        # lowp forces the lax scan (the pallas LM-loss kernels stay
        # full-precision; the quantized scores use the same online-lse
        # math)
        return _fwd_lax_lowp(x, w, labels, cv, m)
    if _use_pallas_lm():
        return _fwd_pallas(x, w, labels, cv)
    return _fwd_lax(x, w, labels, cv)


def _bwd_dispatch(x, w, labels, lse, g, cv):
    if _use_pallas_lm():
        return _bwd_pallas(x, w, labels, lse, g, cv)
    return _bwd_lax(x, w, labels, lse, g, cv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _lce(x, w, labels, cv):
    """Per-row raw nll = lse - s[label], f32 (N,). ignore_index masking
    happens OUTSIDE (a jnp.where whose vjp zeroes g on ignored rows), so
    the kernel never needs to know about it."""
    nll, _ = _fwd_dispatch(x, w, labels, cv)
    return nll


def _lce_fwd_rule(x, w, labels, cv):
    nll, lse = _fwd_dispatch(x, w, labels, cv)
    return nll, (x, w, labels, lse)


def _lce_bwd_rule(cv, res, g):
    x, w, labels, lse = res
    dx, dw = _bwd_dispatch(x, w, labels, lse,
                           g.astype(jnp.float32), cv)
    return dx, dw, jnp.zeros_like(labels)


_lce.defvjp(_lce_fwd_rule, _lce_bwd_rule)


@register_op("fused_linear_cross_entropy")
def fused_linear_cross_entropy(x, weight, label, *, ignore_index=-100,
                               reduction="mean", chunk_v=0):
    """cross_entropy(x @ weight.T, label) without the `[N, V]` logits.

    x: (..., H) hidden states, weight: (V, H) tied decoder table,
    label: (...,) int.  Output is f32 (the reference cross_entropy
    upcasts before log_softmax); `mean` divides by the non-ignored row
    count clamped to 1, matching nn_ops.cross_entropy.
    """
    lead = x.shape[:-1]
    h = x.shape[-1]
    v = weight.shape[0]
    x2 = x.reshape(-1, h)
    lbl = jnp.asarray(label).reshape(-1)
    w = weight
    if w.dtype != x2.dtype:
        # AMP may cast only the float inputs it recognises; align on the
        # activation dtype (astype is differentiable — its vjp casts dw
        # back to the parameter dtype)
        w = w.astype(x2.dtype)
    cv = int(chunk_v) if chunk_v else min(_CHUNK_V, _round_up(v, 128))
    nll = _lce(x2, w, lbl, cv)
    valid = lbl.astype(jnp.int32) != ignore_index
    loss = jnp.where(valid, nll, 0.0)
    if reduction == "none":
        return loss.reshape(lead)
    if reduction == "sum":
        return jnp.sum(loss)
    return jnp.sum(loss) / jnp.maximum(
        jnp.sum(valid.astype(jnp.float32)), 1.0)


# ---------------------------------------------------------------------------
# deferred LM head: the routing handle ErniePretrainingHeads returns in
# place of materialized logits when the fused path is active
# ---------------------------------------------------------------------------


class DeferredLMHead:
    """(hidden, tied weight) pair standing in for `hidden @ weight.T`.

    ErniePretrainingHeads returns this instead of `[B, S, V]` logits when
    the plainness predicate holds; ErniePretrainingCriterion consumes it
    via F.fused_linear_cross_entropy.  Registered as a pytree node so the
    engine's output-tree wrapping (`jax.tree.map(Tensor, out)`) descends
    into the two arrays instead of boxing the handle itself.  Callers
    that need real logits (inference, external heads) call
    `materialize()` — the unfused tied matmul."""

    def __init__(self, hidden, weight):
        self.hidden = hidden
        self.weight = weight

    def materialize(self):
        from ..core.dispatch import apply

        return apply("matmul_v2", self.hidden, self.weight, trans_y=True)


jax.tree_util.register_pytree_node(
    DeferredLMHead,
    lambda d: ((d.hidden, d.weight), None),
    lambda _, c: DeferredLMHead(*c))
