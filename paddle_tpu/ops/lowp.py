"""Low-precision scaled-matmul family: int8 / fp8-sim compute behind
``FLAGS_lowp_matmul``.

Ref parity: the fluid-era Paddle reached low-precision compute with
slim/QAT program passes that rewrote matmuls against calibrated scales.
Here the jax-native answer is ONE kernel family shared by the training
step and the serving decode trace:

  scaled_matmul(a, b, a_scale, b_scale)   custom_vjp — the standard
      recipe: low-precision forward (int8 with int32 accumulation, or
      bit-faithful e4m3 emulation with f32 accumulation), bf16
      backward against the saved full-precision operands.
  w8a8_matmul(x, qweight, scale, act_scale)   the serving epilogue:
      activations quantize in-trace against a frozen per-tensor scale
      and contract directly with an int8-frozen table (the
      quant_ops.dequant_matmul extension from weights-only to w8a8).

Scale semantics (shared with quantization/): a scale is the
REPRESENTABLE ABS-MAX of its tensor — ``q = clip(round(x/s * qmax))``
for int8 (qmax 127, matching quantize_weight_int8) and
``q = e4m3(x/s * 448)`` for fp8 — so the int8 epilogue factor
``s_a*s_b/127**2`` composes with the weights-only tables unchanged.

Scales come from three places, in priority order: explicit arguments
(serving's frozen scales), the active delayed-scaling region
(quantization/scaling.py ScaleState threaded through the train step as
donated carry — never a host sync or retrace), or dynamic current-step
abs-max (everywhere else: the hybrid block scan, the overlap-ring
per-shard partials, eager calls).

Three execution paths, gated exactly like quant_ops/fused_loss:
  * Pallas TPU kernels when FLAGS_use_pallas and the backend is TPU
    (first use probes a tiny call, permanent fallback on failure).
  * The same kernels in interpreter mode when
    PADDLE_TPU_LOWP_FORCE=pallas off-TPU, so CPU tier-1 certifies the
    exact kernel math (int8 parity with the lax path is bitwise:
    identical quantize, int32 accumulation, f32 epilogue).
  * A pure-lax fallback everywhere else.
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..framework import monitor

__all__ = [
    "mode", "scaled_matmul", "w8a8_matmul", "maybe_linear",
    "scale_region", "current", "operand_scales", "QMAX",
]

#: representable-abs-max -> code-point factor per quantized dtype
QMAX = {"int8": 127.0, "fp8": 448.0}

_Q_BLOCK_M = 256
_Q_BLOCK_N = 256
_EPS = 1e-9

# incremented whenever a pallas lowp matmul is traced (not the lax
# fallback) — tests assert the forced path really goes through the
# kernels rather than silently falling back
_TRACE_COUNT = 0

_warned_no_pltpu = False
_warned_slots = False
_probe_result = None  # None=untried, True=kernels lower, False=disabled


def mode() -> str:
    """'off' | 'int8' | 'fp8' from FLAGS_lowp_matmul."""
    from ..framework.flags import flag

    m = str(flag("FLAGS_lowp_matmul")).strip().lower()
    if m in ("", "0", "false", "no", "none", "off"):
        return "off"
    if m not in QMAX:
        raise ValueError(
            f"FLAGS_lowp_matmul must be off|int8|fp8, got {m!r}")
    return m


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _round_up(a: int, b: int) -> int:
    return _cdiv(a, b) * b


def _compiler_params(semantics):
    if not _HAS_PLTPU:
        return None
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams", None)
    return cls(dimension_semantics=tuple(semantics)) if cls else None


def _use_pallas_lowp() -> bool:
    force = os.environ.get("PADDLE_TPU_LOWP_FORCE", "")
    if force == "pallas":
        if not _HAS_PLTPU:
            global _warned_no_pltpu
            if not _warned_no_pltpu:
                _warned_no_pltpu = True
                import warnings

                warnings.warn("pallas TPU backend unavailable; lowp "
                              "matmuls use the lax path")
            return False
        return True
    if force == "lax":
        return False
    from ..framework.flags import flag

    if not flag("FLAGS_use_pallas"):
        return False
    if not (_HAS_PLTPU and jax.default_backend() == "tpu"):
        return False
    return _probe()


def _interpret() -> bool:
    return (os.environ.get("PADDLE_TPU_LOWP_FORCE", "") == "pallas"
            and jax.default_backend() != "tpu")


def _probe() -> bool:
    """One tiny scaled matmul per qdtype through the kernels on first
    on-TPU use; a Mosaic lowering failure disables the pallas path for
    the session (mirrors quant_ops._probe)."""
    global _probe_result
    if _probe_result is None:
        try:
            a = jnp.zeros((8, 128), jnp.float32)
            b = jnp.zeros((128, 128), jnp.float32)
            s = jnp.ones((), jnp.float32)
            jax.block_until_ready(_smm_pallas(a, b, s, s, "int8"))
            jax.block_until_ready(_smm_pallas(a, b, s, s, "fp8"))
            q = jnp.zeros((128, 128), jnp.int8)
            jax.block_until_ready(_w8a8_pallas(a, q, s, s))
            _probe_result = True
        except Exception as e:  # pragma: no cover - TPU only
            _probe_result = False
            import warnings

            warnings.warn(
                "pallas lowp matmul failed to lower; using the lax "
                f"path for this session ({type(e).__name__}: {e})")
    return _probe_result


# ---------------------------------------------------------------------------
# quantize helpers (per-tensor; scale = representable abs-max)
# ---------------------------------------------------------------------------


def amax_of(x):
    """The QAT observers' abs-max statistic (quantization/: the EMA
    observer and quantize_weight_int8 reduce the same way), clamped
    away from zero and gradient-stopped — the scale input."""
    return jnp.maximum(
        jnp.max(jnp.abs(lax.stop_gradient(x.astype(jnp.float32)))), _EPS)


def _quant_int8(x, s):
    q = jnp.round(x.astype(jnp.float32) * (127.0 / s))
    return jnp.clip(q, -127.0, 127.0).astype(jnp.int8)


def _quant_f8(x, s):
    """Bit-faithful e4m3 emulation: scale to the fp8 dynamic range,
    saturate (the e4m3fn cast maps overflow to NaN, so clip first) and
    round-trip through the hardware dtype."""
    y = jnp.clip(x.astype(jnp.float32) * (448.0 / s), -448.0, 448.0)
    return y.astype(jnp.float8_e4m3fn)


# ---------------------------------------------------------------------------
# lax path (identical math to the kernels: int8 accumulates int32 so
# pallas-vs-lax int8 parity is bitwise; fp8 accumulates f32)
# ---------------------------------------------------------------------------


def _mm_dims(ca, cb):
    return (((ca,), (cb,)), ((), ()))


def _smm_lax(a, b, sa, sb, qdtype):
    if qdtype == "int8":
        acc = lax.dot_general(_quant_int8(a, sa), _quant_int8(b, sb),
                              _mm_dims(1, 0),
                              preferred_element_type=jnp.int32)
        return acc.astype(jnp.float32) * (sa * sb / (127.0 * 127.0))
    qa = _quant_f8(a, sa).astype(jnp.float32)
    qb = _quant_f8(b, sb).astype(jnp.float32)
    acc = lax.dot_general(qa, qb, _mm_dims(1, 0),
                          preferred_element_type=jnp.float32)
    return acc * (sa * sb / (448.0 * 448.0))


def _w8a8_lax(a, qb, sb, sa):
    acc = lax.dot_general(_quant_int8(a, sa), qb, _mm_dims(1, 0),
                          preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * (sa * sb / (127.0 * 127.0))


# ---------------------------------------------------------------------------
# pallas kernels: grid (M/bm, N/bn), full K per tile, scales in SMEM
# ---------------------------------------------------------------------------


def _qmm_kernel(sa_ref, sb_ref, a_ref, b_ref, o_ref, *, qdtype):
    sa = sa_ref[0, 0]
    sb = sb_ref[0, 0]
    if qdtype == "int8":
        qa = _quant_int8(a_ref[...], sa)
        qb = _quant_int8(b_ref[...], sb)
        acc = lax.dot_general(qa, qb, _mm_dims(1, 0),
                              preferred_element_type=jnp.int32)
        o_ref[...] = acc.astype(jnp.float32) * (sa * sb / (127.0 * 127.0))
    else:
        qa = _quant_f8(a_ref[...], sa).astype(jnp.float32)
        qb = _quant_f8(b_ref[...], sb).astype(jnp.float32)
        acc = lax.dot_general(qa, qb, _mm_dims(1, 0),
                              preferred_element_type=jnp.float32)
        o_ref[...] = acc * (sa * sb / (448.0 * 448.0))


def _w8a8_kernel(sa_ref, sb_ref, a_ref, qb_ref, o_ref):
    sa = sa_ref[0, 0]
    sb = sb_ref[0, 0]
    qa = _quant_int8(a_ref[...], sa)
    acc = lax.dot_general(qa, qb_ref[...], _mm_dims(1, 0),
                          preferred_element_type=jnp.int32)
    o_ref[...] = acc.astype(jnp.float32) * (sa * sb / (127.0 * 127.0))


def _smem11(s):
    return jnp.broadcast_to(jnp.asarray(s, jnp.float32), (1, 1))


def _pallas_mm(kernel, a, b, sa, sb):
    """Shared pad/grid/specs for the quantizing matmul kernels: a
    [m, k] float, b [k, n] float or int8, scalars in SMEM; zero padding
    quantizes to zero so the padded contraction is exact."""
    global _TRACE_COUNT
    _TRACE_COUNT += 1
    m, k = a.shape
    n = b.shape[1]
    bm = min(_Q_BLOCK_M, _round_up(m, 8))
    bn = min(_Q_BLOCK_N, _round_up(n, 128))
    mp, np_, kp = _round_up(m, bm), _round_up(n, bn), _round_up(k, 128)
    ap = jnp.pad(a, ((0, mp - m), (0, kp - k)))
    bp = jnp.pad(b, ((0, kp - k), (0, np_ - n)))
    smem = pl.BlockSpec((1, 1), lambda i, j: (0, 0),
                        memory_space=pltpu.SMEM)
    vmem = pltpu.VMEM
    out = pl.pallas_call(
        kernel,
        grid=(mp // bm, np_ // bn),
        in_specs=[
            smem, smem,
            pl.BlockSpec((bm, kp), lambda i, j: (i, 0),
                         memory_space=vmem),
            pl.BlockSpec((kp, bn), lambda i, j: (0, j),
                         memory_space=vmem),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j),
                               memory_space=vmem),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        compiler_params=_compiler_params(("parallel", "parallel")),
        interpret=_interpret(),
    )(_smem11(sa), _smem11(sb), ap, bp)
    return out[:m, :n]


def _smm_pallas(a, b, sa, sb, qdtype):
    return _pallas_mm(functools.partial(_qmm_kernel, qdtype=qdtype),
                      a, b, sa, sb)


def _w8a8_pallas(a, qb, sb, sa):
    return _pallas_mm(_w8a8_kernel, a, qb, sa, sb)


# ---------------------------------------------------------------------------
# custom_vjp: lowp forward, bf16 backward (standard recipe)
# ---------------------------------------------------------------------------


def _fwd_dispatch(a, b, sa, sb, qdtype):
    # trace-time: one quantized-matmul instance per compiled program
    monitor.stat_add(f"lowp.matmuls_{qdtype}")
    if _use_pallas_lowp():
        return _smm_pallas(a, b, sa, sb, qdtype)
    return _smm_lax(a, b, sa, sb, qdtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _smm(a, b, sa, sb, qdtype):
    return _fwd_dispatch(a, b, sa, sb, qdtype)


def _smm_fwd_rule(a, b, sa, sb, qdtype):
    return _fwd_dispatch(a, b, sa, sb, qdtype), (a, b, sa, sb)


def _smm_bwd_rule(qdtype, res, g):
    a, b, sa, sb = res
    # high-precision backward: bf16 operands into the MXU with f32
    # accumulation against the SAVED full-precision inputs — gradients
    # never see the quantization error (straight-through)
    g16 = g.astype(jnp.bfloat16)
    da = lax.dot_general(g16, b.astype(jnp.bfloat16), _mm_dims(1, 1),
                         preferred_element_type=jnp.float32)
    db = lax.dot_general(a.astype(jnp.bfloat16), g16, _mm_dims(0, 0),
                         preferred_element_type=jnp.float32)
    return (da.astype(a.dtype), db.astype(b.dtype),
            jnp.zeros_like(sa), jnp.zeros_like(sb))


_smm.defvjp(_smm_fwd_rule, _smm_bwd_rule)


def scaled_matmul(a, b, a_scale=None, b_scale=None, out_dtype=None,
                  qdtype=None):
    """``a @ b`` computed in low precision with f32/int32 accumulation.

    a: (..., K) float, b: (K, N) float. Scales are per-tensor
    representable-abs-max scalars; None computes the current-step
    abs-max (dynamic scaling — exact range, zero clipping). qdtype
    None follows FLAGS_lowp_matmul ('off' there still computes int8 —
    callers gate routing, this op always quantizes). The custom_vjp
    backward runs bf16 against the full-precision operands.
    """
    if qdtype is None:
        m = mode()
        qdtype = m if m != "off" else "int8"
    if qdtype not in QMAX:
        raise ValueError(f"qdtype must be int8|fp8, got {qdtype!r}")
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    if a.ndim < 1 or b.ndim != 2:
        raise ValueError(
            f"scaled_matmul expects a (..., K) and b (K, N); got "
            f"{a.shape} x {b.shape}")
    lead = a.shape[:-1]
    a2 = a.reshape(-1, a.shape[-1])
    sa = amax_of(a2) if a_scale is None \
        else jnp.maximum(jnp.asarray(a_scale, jnp.float32), _EPS)
    sb = amax_of(b) if b_scale is None \
        else jnp.maximum(jnp.asarray(b_scale, jnp.float32), _EPS)
    out = _smm(a2, b, sa, sb, qdtype)
    out = out.reshape(lead + (b.shape[1],))
    return out if out_dtype is None else out.astype(out_dtype)


def w8a8_matmul(x, qweight, scale, act_scale):
    """w8a8 decode epilogue: quantize activation rows to int8 against
    the frozen per-tensor `act_scale` and contract with an int8-frozen
    table (`qweight` [K, N] or its [N, K] quantize_state_int8 layout is
    the CALLER's concern — pass it contraction-ready). No grad: the
    serving trace never differentiates."""
    monitor.stat_add("lowp.matmuls_int8")
    x = jnp.asarray(x)
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    sb = jnp.maximum(jnp.asarray(scale, jnp.float32), _EPS)
    sa = jnp.maximum(jnp.asarray(act_scale, jnp.float32), _EPS)
    if _use_pallas_lowp():
        out = _w8a8_pallas(x2, qweight, sb, sa)
    else:
        out = _w8a8_lax(x2, qweight, sb, sa)
    return lax.stop_gradient(out.reshape(lead + (qweight.shape[1],)))


# ---------------------------------------------------------------------------
# delayed-scaling region (the train-step ScaleState carry) + routing
# ---------------------------------------------------------------------------

_tls = threading.local()


class _ScaleRegion:
    """Trace-time recorder binding ScaleState slots to matmul call
    sites in (deterministic) trace order. All recorded values are
    tracers of the enclosing loss trace; `updated()` must be consumed
    before that trace returns (the engine folds it into the new
    buffers)."""

    def __init__(self, state):
        self.state = state
        self.capacity = int(state.scale.shape[0])
        self.n = 0
        self._amax = {}          # slot -> recorded abs-max scalar
        self._clipped = jnp.zeros((), jnp.float32)
        self._total = jnp.zeros((), jnp.float32)

    def slot(self):
        i = self.n
        self.n += 1
        if i >= self.capacity:
            global _warned_slots
            if not _warned_slots:
                _warned_slots = True
                import warnings

                warnings.warn(
                    f"lowp: more quantized matmul operands than the "
                    f"ScaleState capacity {self.capacity} "
                    "(FLAGS_lowp_slots); extras use dynamic scaling")
            monitor.stat_add("lowp.slot_overflow")
            return None
        return i

    def scale_for(self, i, x):
        """Delayed scale for slot i; the very first step has an empty
        history, so it falls back to the current-step abs-max."""
        return jnp.where(self.state.step > 0,
                         jnp.maximum(self.state.scale[i], _EPS),
                         amax_of(x))

    def record(self, i, x, s):
        xf = lax.stop_gradient(x.astype(jnp.float32))
        self._amax[i] = amax_of(x)
        self._clipped = self._clipped + jnp.sum(
            (jnp.abs(xf) > s).astype(jnp.float32))
        self._total = self._total + jnp.asarray(float(x.size),
                                                jnp.float32)

    def updated(self):
        """The next ScaleState: ring-write this step's amaxes and run
        the delayed-scale update schedule (in-graph, no host sync)."""
        from ..quantization.scaling import update_scale_state

        cap = self.capacity
        amax = jnp.zeros((cap,), jnp.float32)
        mask = jnp.zeros((cap,), jnp.bool_)
        for i, v in self._amax.items():
            amax = amax.at[i].set(v)
            mask = mask.at[i].set(True)
        return update_scale_state(self.state, amax, mask,
                                  self._clipped, self._total)


@contextlib.contextmanager
def scale_region(state):
    """Bind a ScaleState to the matmuls of the enclosed trace. None
    (or lowp off) is a no-op yielding None; routing then uses dynamic
    scales."""
    if state is None or mode() == "off":
        yield None
        return
    prev = getattr(_tls, "region", None)
    _tls.region = _ScaleRegion(state)
    try:
        yield _tls.region
    finally:
        _tls.region = prev


def current():
    """The active delayed-scaling region, or None."""
    return getattr(_tls, "region", None)


@contextlib.contextmanager
def suppress_region():
    """Hide the active region from the enclosed code: sub-traces
    (jax.checkpoint segments, scan bodies, shard_map bodies) must not
    record their tracers into the outer trace's region — their matmuls
    quantize with dynamic scales instead."""
    prev = getattr(_tls, "region", None)
    _tls.region = None
    try:
        yield
    finally:
        _tls.region = prev


def operand_scales(a, b):
    """(a_scale, b_scale) for one matmul: delayed-scaling slots when a
    region is active, dynamic abs-max otherwise. Also records this
    step's amaxes + clip counts into the region."""
    ctx = current()
    if ctx is None:
        return amax_of(a), amax_of(b)
    ia, ib = ctx.slot(), ctx.slot()
    sa = amax_of(a) if ia is None else ctx.scale_for(ia, a)
    sb = amax_of(b) if ib is None else ctx.scale_for(ib, b)
    if ia is not None:
        ctx.record(ia, a, sa)
    if ib is not None:
        ctx.record(ib, b, sb)
    return sa, sb


def maybe_linear(x, weight):
    """Lowp route for F.linear (bias NOT applied): returns the output
    Tensor, or None to keep the matmul_v2 path — flag off, tape-based
    autograd in flight, or non-float/low-rank operands. The bitwise
    contract: 'off' returns None before touching anything."""
    if mode() == "off":
        return None
    from ..core.tensor import Tensor

    if not isinstance(x, Tensor) or not isinstance(weight, Tensor):
        return None
    if getattr(x, "_tape", None) is not None or \
            getattr(weight, "_tape", None) is not None:
        return None
    xv, wv = x._value, weight._value
    if xv.ndim < 2 or wv.ndim != 2:
        return None
    if not (jnp.issubdtype(xv.dtype, jnp.floating)
            and jnp.issubdtype(wv.dtype, jnp.floating)):
        return None
    m = mode()
    sa, sb = operand_scales(xv, wv)
    out = scaled_matmul(xv, wv, sa, sb, qdtype=m,
                        out_dtype=jnp.result_type(xv.dtype, wv.dtype))
    return Tensor(out)
