"""Fused multi-layer RNN op (SimpleRNN / LSTM / GRU).

Ref parity: paddle/fluid/operators/rnn_op.h (the cudnn-style fused RNN the
reference dispatches nn.LSTM/GRU/SimpleRNN to) and the cell equations of
python/paddle/nn/layer/rnn.py:258,390,543. TPU-native design: the whole
stacked, optionally bidirectional recurrence is ONE op whose time loop is a
`lax.scan` — XLA compiles it to a fused while-loop keeping the [B, 4H]
gate matmuls on the MXU, and `jax.vjp` of the scan gives the backward pass
(the reference needed a hand-written rnn_grad kernel).

Weight layout per (layer, direction): weight_ih [G*H, in], weight_hh
[G*H, H], bias_ih [G*H], bias_hh [G*H] with G = 1 (simple), 4 (lstm,
gates i,f,g,o), 3 (gru, gates r,z,c).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.op_registry import register_op

_GATE_MULT = {"RNN_TANH": 1, "RNN_RELU": 1, "LSTM": 4, "GRU": 3}


def _cell_step(mode, xt, h, c, w_ih, w_hh, b_ih, b_hh):
    """One time step. xt: [B, in], h/c: [B, H]. Returns (h', c')."""
    if mode == "GRU":
        # paddle applies bias_hh inside the candidate's reset product, so
        # the hidden contribution stays separate for the c gate
        hidden = h @ w_hh.T + (b_hh if b_hh is not None else 0.0)
        x_part = xt @ w_ih.T + (b_ih if b_ih is not None else 0.0)
        xr, xz, xc = jnp.split(x_part, 3, axis=-1)
        hr, hz, hc = jnp.split(hidden, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        cand = jnp.tanh(xc + r * hc)
        h_new = z * h + (1.0 - z) * cand
        return h_new, c
    gates = xt @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        gates = gates + b_ih
    if b_hh is not None:
        gates = gates + b_hh
    if mode == "LSTM":
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c_new = f * c + i * jnp.tanh(g)
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new
    act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu
    return act(gates), c


def _scan_direction(mode, xs, h0, c0, w_ih, w_hh, b_ih, b_hh, reverse):
    """xs: [T, B, in] time-major. Returns (ys [T, B, H], hT, cT)."""

    def step(carry, xt):
        h, c = carry
        h, c = _cell_step(mode, xt, h, c, w_ih, w_hh, b_ih, b_hh)
        return (h, c), h

    (hT, cT), ys = lax.scan(step, (h0, c0), xs, reverse=reverse)
    return ys, hT, cT


@register_op("rnn", multi_out=True)
def rnn(x, init_h, init_c, key, *weights, mode, num_layers=1,
        hidden_size=None, is_bidirec=False, time_major=False, dropout=0.0,
        has_bias=True):
    """Stacked RNN. x: [B, T, in] (or [T, B, in] when time_major).
    init_h/init_c: [num_layers*num_dirs, B, H] (init_c ignored unless LSTM).
    `key` (PRNG key) drives inter-layer dropout; pass dropout=0.0 to
    disable. Returns (outputs, final_h, final_c)."""
    num_dirs = 2 if is_bidirec else 1
    per = 4 if has_bias else 2
    assert len(weights) == num_layers * num_dirs * per, \
        f"expected {num_layers * num_dirs * per} weights, got {len(weights)}"

    xs = x if time_major else jnp.swapaxes(x, 0, 1)  # [T, B, in]
    final_h, final_c = [], []
    for layer in range(num_layers):
        outs = []
        for d in range(num_dirs):
            li = layer * num_dirs + d
            ws = weights[li * per:(li + 1) * per]
            w_ih, w_hh = ws[0], ws[1]
            b_ih = ws[2] if has_bias else None
            b_hh = ws[3] if has_bias else None
            h0 = init_h[li]
            c0 = init_c[li]
            ys, hT, cT = _scan_direction(
                mode, xs, h0, c0, w_ih, w_hh, b_ih, b_hh, reverse=(d == 1))
            outs.append(ys)
            final_h.append(hT)
            final_c.append(cT)
        xs = outs[0] if num_dirs == 1 else jnp.concatenate(outs, axis=-1)
        if dropout > 0.0 and layer < num_layers - 1:
            lkey = jax.random.fold_in(jnp.asarray(key), layer)
            keep = jax.random.bernoulli(lkey, 1.0 - dropout, xs.shape)
            xs = xs * keep.astype(xs.dtype) / (1.0 - dropout)

    outputs = xs if time_major else jnp.swapaxes(xs, 0, 1)
    return outputs, jnp.stack(final_h), jnp.stack(final_c)
