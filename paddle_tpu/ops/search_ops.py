"""Search / sort / sampling-index ops.

Ref parity: paddle/fluid/operators/ arg_max_op, top_k_v2_op, argsort_op,
where_index_op, unique_op, masked_select_op. Ops with data-dependent output
shapes (nonzero, masked_select, unique) are eager-only: they cannot appear
inside a jit region (XLA static shapes) — same constraint the reference
solves with LoD, we solve with padding/masks at the API layer.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.op_registry import register_op


@register_op("arg_max", no_grad=True)
def arg_max(x, *, axis=None, keepdim=False, dtype="int64"):
    from ..core.dtype import to_jax_dtype

    out = jnp.argmax(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(to_jax_dtype(dtype))


@register_op("arg_min", no_grad=True)
def arg_min(x, *, axis=None, keepdim=False, dtype="int64"):
    from ..core.dtype import to_jax_dtype

    out = jnp.argmin(x, axis=axis, keepdims=keepdim if axis is not None else False)
    return out.astype(to_jax_dtype(dtype))


@register_op("top_k_v2", has_aux=True)
def top_k_v2(x, *, k, axis=-1, largest=True, sorted=True):
    import jax

    axis = axis if axis >= 0 else x.ndim + axis
    xs = jnp.moveaxis(x, axis, -1)
    if largest:
        vals, idx = jax.lax.top_k(xs, k)
    else:
        vals, idx = jax.lax.top_k(-xs, k)
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    return vals, idx.astype(jnp.int64)


@register_op("argsort", no_grad=True)
def argsort(x, *, axis=-1, descending=False):
    idx = jnp.argsort(x, axis=axis, descending=descending)
    return idx.astype(jnp.int64)


@register_op("sort_op", has_aux=True)
def sort_op(x, *, axis=-1, descending=False):
    idx = jnp.argsort(x, axis=axis, descending=descending)
    vals = jnp.take_along_axis(x, idx, axis=axis)
    return vals, idx.astype(jnp.int64)


@register_op("searchsorted", no_grad=True)
def searchsorted(sorted_sequence, values, *, out_int32=False, right=False):
    side = "right" if right else "left"
    out = jnp.searchsorted(sorted_sequence, values, side=side)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@register_op("bucketize", no_grad=True)
def bucketize(x, sorted_sequence, *, out_int32=False, right=False):
    side = "right" if right else "left"
    out = jnp.searchsorted(sorted_sequence, x, side=side)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@register_op("nonzero", no_grad=True)
def nonzero(x):
    # eager-only: data-dependent shape
    import numpy as np

    arr = np.asarray(x)
    return jnp.asarray(np.stack(np.nonzero(arr), axis=-1).astype(np.int64))


@register_op("masked_select", no_grad=True)
def masked_select(x, mask):
    import numpy as np

    arr, m = np.asarray(x), np.asarray(mask)
    return jnp.asarray(arr[np.broadcast_to(m, arr.shape)])


@register_op("unique", no_grad=True)
def unique(x, *, return_index=False, return_inverse=False,
           return_counts=False, axis=None):
    import numpy as np

    res = np.unique(np.asarray(x), return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        return tuple(jnp.asarray(r) for r in res)
    return jnp.asarray(res)


@register_op("masked_fill")
def masked_fill(x, mask, *, value):
    return jnp.where(jnp.asarray(mask), jnp.asarray(value, x.dtype), x)


@register_op("index_put")
def index_put(x, indices, value):
    import jax

    idx = tuple(jnp.asarray(i) for i in indices) \
        if isinstance(indices, (list, tuple)) else (jnp.asarray(indices),)
    return x.at[idx].set(jnp.asarray(value))


@register_op("kthvalue", has_aux=True)
def kthvalue(x, *, k, axis=-1, keepdim=False):
    vals = jnp.sort(x, axis=axis)
    idxs = jnp.argsort(x, axis=axis)
    taken = jnp.take(vals, k - 1, axis=axis)
    tidx = jnp.take(idxs, k - 1, axis=axis)
    if keepdim:
        taken = jnp.expand_dims(taken, axis)
        tidx = jnp.expand_dims(tidx, axis)
    return taken, tidx.astype(jnp.int64)


@register_op("mode_op", has_aux=True)
def mode_op(x, *, axis=-1, keepdim=False):
    """Mode along `axis`: most frequent value (ties -> smallest value),
    index of its last occurrence. O(n^2) equality-matrix counting keeps it
    jit-able with static shapes (lanes are short in practice)."""
    orig_dtype = x.dtype
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.int32)
    xm = jnp.moveaxis(x, axis, -1)
    eq = xm[..., :, None] == xm[..., None, :]
    counts = eq.sum(-1)
    is_max = counts == counts.max(-1, keepdims=True)
    big = jnp.asarray(jnp.inf, xm.dtype) if jnp.issubdtype(
        xm.dtype, jnp.floating) else jnp.iinfo(xm.dtype).max
    mode_val = jnp.where(is_max, xm, big).min(-1)
    match = xm == mode_val[..., None]
    n = xm.shape[-1]
    idx = (n - 1) - jnp.argmax(jnp.flip(match, -1), -1)
    if keepdim:
        mode_val = jnp.expand_dims(mode_val, axis)
        idx = jnp.expand_dims(idx, axis)
    return mode_val.astype(orig_dtype), idx.astype(jnp.int64)
