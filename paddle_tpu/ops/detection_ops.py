"""Detection ops: boxes, anchors, NMS, RoI pooling.

Ref parity: paddle/fluid/operators/detection/ (iou_similarity_op.cc,
box_coder_op.cc, prior_box_op.cc, yolo_box_op.cu, roi_align_op.cu,
multiclass_nms_op.cc). TPU-native: everything up to NMS is pure
jax/XLA-traceable with static shapes (boxes stay fixed-size, scores
carry the ranking); NMS itself emits a fixed `keep_top_k` result with a
validity mask instead of the reference's dynamic-length LoD output —
host-side postprocessing slices by the returned count.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.op_registry import register_op


def _box_area(b, off):
    return (jnp.maximum(b[..., 2] - b[..., 0] + off, 0)
            * jnp.maximum(b[..., 3] - b[..., 1] + off, 0))


def _iou(a, b, off=0.0):
    """Pairwise IoU: a [..., N, 4], b [..., M, 4] -> [..., N, M]."""
    ix1 = jnp.maximum(a[..., :, None, 0], b[..., None, :, 0])
    iy1 = jnp.maximum(a[..., :, None, 1], b[..., None, :, 1])
    ix2 = jnp.minimum(a[..., :, None, 2], b[..., None, :, 2])
    iy2 = jnp.minimum(a[..., :, None, 3], b[..., None, :, 3])
    inter = (jnp.maximum(ix2 - ix1 + off, 0)
             * jnp.maximum(iy2 - iy1 + off, 0))
    union = (_box_area(a, off)[..., :, None]
             + _box_area(b, off)[..., None, :] - inter)
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


@register_op("iou_similarity", no_grad=True)
def iou_similarity(x, y, *, box_normalized=True):
    """ref detection/iou_similarity_op.cc: pairwise IoU [N,4]x[M,4]."""
    off = 0.0 if box_normalized else 1.0
    return _iou(jnp.asarray(x), jnp.asarray(y), off)


@register_op("box_coder", no_grad=True)
def box_coder(prior_box, prior_box_var, target_box, *,
              code_type="encode_center_size", box_normalized=True,
              axis=0):
    """ref detection/box_coder_op.cc: encode corner boxes against priors
    into (dx, dy, dw, dh) offsets, or decode offsets back to corners."""
    pb = jnp.asarray(prior_box, jnp.float32)
    tb = jnp.asarray(target_box, jnp.float32)
    var = None if prior_box_var is None else jnp.asarray(
        prior_box_var, jnp.float32)
    norm = 0.0 if box_normalized else 1.0

    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5

    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        # every target against every prior: [T, P, 4]
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(jnp.maximum(tw[:, None] / pw[None, :], 1e-10))
        dh = jnp.log(jnp.maximum(th[:, None] / ph[None, :], 1e-10))
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        if var is not None:
            out = out / var[None, :, :]
        return out

    if code_type == "decode_center_size":
        # tb: [N, P, 4] offsets (or broadcastable); axis selects which dim
        # aligns with the priors
        if tb.ndim == 2:
            tb = tb[:, None, :]
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = (pw[None, :], ph[None, :],
                                    pcx[None, :], pcy[None, :])
            v = var[None, :, :] if var is not None else None
        else:
            pw_, ph_, pcx_, pcy_ = (pw[:, None], ph[:, None],
                                    pcx[:, None], pcy[:, None])
            v = var[:, None, :] if var is not None else None
        t = tb * v if v is not None else tb
        cx = t[..., 0] * pw_ + pcx_
        cy = t[..., 1] * ph_ + pcy_
        w = jnp.exp(t[..., 2]) * pw_
        h = jnp.exp(t[..., 3]) * ph_
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - norm, cy + h * 0.5 - norm],
                         axis=-1)
    raise ValueError(f"unknown code_type {code_type!r}")


@register_op("prior_box", no_grad=True)
def prior_box(input, image, *, min_sizes, max_sizes=None,
              aspect_ratios=(1.0,), variances=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, step=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False):
    """ref detection/prior_box_op.cc (SSD anchors): one prior per
    (cell, size/ratio combination) over the feature map grid.

    input: [N, C, H, W] feature map; image: [N, C, IH, IW].
    Returns (boxes [H, W, P, 4], variances [H, W, P, 4])."""
    h, w = input.shape[2], input.shape[3]
    ih, iw = image.shape[2], image.shape[3]

    # ExpandAspectRatios order (ref prior_box_op.h): 1.0 first, then each
    # user ratio followed immediately by its flip — anchor order defines
    # the SSD head channel layout, so it must match the reference exactly
    ars = [1.0]
    for r in aspect_ratios:
        if any(abs(r - e) < 1e-6 for e in ars):
            continue
        ars.append(r)
        if flip:
            ars.append(1.0 / r)

    step_w = step[0] or iw / w
    step_h = step[1] or ih / h

    whs = []
    for i, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order and max_sizes:
            # ref prior_box_op.h min_max_aspect_ratios_order=True: the
            # max-size prior comes right after the ratio-1 min prior
            whs.append((ms, ms))
            mx = max_sizes[i]
            s = (ms * mx) ** 0.5
            whs.append((s, s))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * (ar ** 0.5), ms / (ar ** 0.5)))
        else:
            for ar in ars:
                whs.append((ms * (ar ** 0.5), ms / (ar ** 0.5)))
            if max_sizes:
                mx = max_sizes[i]
                s = (ms * mx) ** 0.5
                whs.append((s, s))
    whs = jnp.asarray(whs, jnp.float32)  # [P, 2]
    p = whs.shape[0]

    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)  # [H, W]
    cxg = cxg[..., None]
    cyg = cyg[..., None]
    bw = whs[None, None, :, 0] * 0.5
    bh = whs[None, None, :, 1] * 0.5
    boxes = jnp.stack([(cxg - bw) / iw, (cyg - bh) / ih,
                       (cxg + bw) / iw, (cyg + bh) / ih], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (h, w, p, 4))
    return boxes, var


@register_op("yolo_box", no_grad=True)
def yolo_box(x, img_size, *, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
    """ref detection/yolo_box_op.cu: decode one YOLOv3 head.

    x: [N, A*(5+C), H, W]; img_size: [N, 2] (h, w).
    Returns (boxes [N, A*H*W, 4], scores [N, A*H*W, C]); boxes whose
    objectness < conf_thresh are zeroed like the reference."""
    n, _, h, w = x.shape
    a = len(anchors) // 2
    c = class_num
    anc = jnp.asarray(anchors, jnp.float32).reshape(a, 2)
    x = x.reshape(n, a, 5 + c, h, w)

    gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    input_h = jnp.asarray(downsample_ratio * h, jnp.float32)
    input_w = jnp.asarray(downsample_ratio * w, jnp.float32)

    sig = jax.nn.sigmoid
    bias = -0.5 * (scale_x_y - 1.0)
    bx = (sig(x[:, :, 0]) * scale_x_y + bias + gx) / w
    by = (sig(x[:, :, 1]) * scale_x_y + bias + gy) / h
    bw = jnp.exp(x[:, :, 2]) * anc[None, :, 0, None, None] / input_w
    bh = jnp.exp(x[:, :, 3]) * anc[None, :, 1, None, None] / input_h
    obj = sig(x[:, :, 4])
    cls = sig(x[:, :, 5:])  # [N, A, C, H, W]

    img_h = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    img_w = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (bx - bw * 0.5) * img_w
    y1 = (by - bh * 0.5) * img_h
    x2 = (bx + bw * 0.5) * img_w
    y2 = (by + bh * 0.5) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
        x2 = jnp.clip(x2, 0, img_w - 1)
        y2 = jnp.clip(y2, 0, img_h - 1)
    keep = (obj > conf_thresh).astype(x1.dtype)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * keep[..., None]
    scores = cls * (obj[:, :, None] * (obj > conf_thresh)[:, :, None])
    boxes = boxes.reshape(n, a * h * w, 4)
    scores = jnp.moveaxis(scores, 2, -1).reshape(n, a * h * w, c)
    return boxes, scores


@register_op("roi_align")
def roi_align(x, boxes, boxes_num, *, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """ref roi_align_op.cu: bilinear average pooling inside each RoI.

    x: [N, C, H, W]; boxes: [R, 4] (x1, y1, x2, y2 in image coords);
    boxes_num: [N] rois per image. Differentiable w.r.t. x.

    TPU divergence: with sampling_ratio=-1 the reference adaptively
    samples ceil(roi_size/pooled_size) points per bin PER RoI — a
    data-dependent shape XLA cannot compile. Here -1 means a fixed 2
    samples per bin axis; pass an explicit sampling_ratio for more
    resolution when porting models sensitive to large-RoI pooling."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    n, c, h, w = x.shape
    r = boxes.shape[0]
    boxes = jnp.asarray(boxes, jnp.float32)
    bn = jnp.asarray(boxes_num, jnp.int32)
    # image index per roi from boxes_num (cumulative)
    img_of_roi = jnp.searchsorted(jnp.cumsum(bn), jnp.arange(r),
                                  side="right").astype(jnp.int32)

    off = 0.5 if aligned else 0.0
    x1 = boxes[:, 0] * spatial_scale - off
    y1 = boxes[:, 1] * spatial_scale - off
    x2 = boxes[:, 2] * spatial_scale - off
    y2 = boxes[:, 3] * spatial_scale - off
    rw = x2 - x1
    rh = y2 - y1
    if not aligned:
        rw = jnp.maximum(rw, 1.0)
        rh = jnp.maximum(rh, 1.0)
    bin_w = rw / pw
    bin_h = rh / ph
    s = sampling_ratio if sampling_ratio > 0 else 2
    # sample grid: [R, ph*s] x [R, pw*s]
    iy = (jnp.arange(ph * s, dtype=jnp.float32) + 0.5) / s
    ix = (jnp.arange(pw * s, dtype=jnp.float32) + 0.5) / s
    sy = y1[:, None] + iy[None, :] * bin_h[:, None]  # [R, ph*s]
    sx = x1[:, None] + ix[None, :] * bin_w[:, None]  # [R, pw*s]

    def bilinear(img, yy, xx):
        """img [C,H,W], yy [ph*s], xx [pw*s] -> [C, ph*s, pw*s]"""
        y0 = jnp.floor(yy)
        x0 = jnp.floor(xx)
        wy1 = yy - y0
        wx1 = xx - x0
        y0i = jnp.clip(y0.astype(jnp.int32), 0, h - 1)
        y1i = jnp.clip(y0i + 1, 0, h - 1)
        x0i = jnp.clip(x0.astype(jnp.int32), 0, w - 1)
        x1i = jnp.clip(x0i + 1, 0, w - 1)
        inside_y = ((yy >= -1.0) & (yy <= h)).astype(img.dtype)
        inside_x = ((xx >= -1.0) & (xx <= w)).astype(img.dtype)
        g = lambda yi, xi: img[:, yi][:, :, xi]  # noqa: E731
        v = (g(y0i, x0i) * ((1 - wy1)[:, None] * (1 - wx1)[None, :])
             + g(y0i, x1i) * ((1 - wy1)[:, None] * wx1[None, :])
             + g(y1i, x0i) * (wy1[:, None] * (1 - wx1)[None, :])
             + g(y1i, x1i) * (wy1[:, None] * wx1[None, :]))
        return v * inside_y[None, :, None] * inside_x[None, None, :]

    def per_roi(roi_i):
        img = x[img_of_roi[roi_i]]
        v = bilinear(img, sy[roi_i], sx[roi_i])  # [C, ph*s, pw*s]
        v = v.reshape(c, ph, s, pw, s)
        return v.mean(axis=(2, 4))

    return jax.vmap(per_roi)(jnp.arange(r))


@register_op("multiclass_nms3", no_grad=True, has_aux=False)
def multiclass_nms3(bboxes, scores, *, score_threshold=0.05, nms_top_k=400,
                    keep_top_k=100, nms_threshold=0.3, normalized=True,
                    nms_eta=1.0, background_label=-1):
    """ref detection/multiclass_nms_op.cc (v3). TPU-native: fixed-size
    output — greedy per-class NMS over the top nms_top_k candidates,
    returning exactly keep_top_k rows [label, score, x1, y1, x2, y2]
    (invalid rows have label -1) plus the valid count. Static shapes =
    jit/batch friendly; the reference's LoD output is the host-side
    slice out[:count]."""
    bboxes = jnp.asarray(bboxes)  # [M, 4] single image
    scores = jnp.asarray(scores)  # [C, M]
    c, m = scores.shape
    off = 0.0 if normalized else 1.0
    iou = _iou(bboxes, bboxes, off)  # [M, M]

    top_k = min(nms_top_k, m)

    def one_class(cls_scores):
        s, idx = jax.lax.top_k(cls_scores, top_k)
        valid = s > score_threshold
        sub_iou = iou[idx][:, idx]

        def body(i, state):
            keep, thr = state
            # suppressed if it overlaps any higher-scoring kept box
            sup = jnp.any(jnp.where(jnp.arange(top_k) < i,
                                    (sub_iou[i] > thr) & keep,
                                    False))
            kept = valid[i] & ~sup
            # adaptive threshold decay (ref NMSFast: after each kept box,
            # while the current threshold still exceeds 0.5)
            thr = jnp.where(kept & (nms_eta < 1.0) & (thr > 0.5),
                            thr * nms_eta, thr)
            return keep.at[i].set(kept), thr

        keep, _ = jax.lax.fori_loop(
            0, top_k, body,
            (jnp.zeros(top_k, bool), jnp.float32(nms_threshold)))
        return s, idx, keep

    s_all, idx_all, keep_all = jax.vmap(one_class)(scores)
    labels = jnp.broadcast_to(jnp.arange(c)[:, None], (c, top_k))
    if background_label >= 0:
        keep_all = keep_all & (labels != background_label)

    flat_scores = jnp.where(keep_all, s_all, -jnp.inf).reshape(-1)
    k = min(keep_top_k, flat_scores.shape[0])
    best, flat_pos = jax.lax.top_k(flat_scores, k)
    flat_labels = labels.reshape(-1)[flat_pos]
    flat_box_idx = idx_all.reshape(-1)[flat_pos]
    valid_out = jnp.isfinite(best)
    out = jnp.concatenate([
        jnp.where(valid_out, flat_labels, -1)[:, None].astype(jnp.float32),
        jnp.where(valid_out, best, 0.0)[:, None],
        bboxes[flat_box_idx] * valid_out[:, None].astype(bboxes.dtype),
    ], axis=1)
    count = valid_out.sum().astype(jnp.int32)
    return out, count
