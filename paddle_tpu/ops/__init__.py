"""Registered op implementations (pure jax functions).

Ref parity: paddle/fluid/operators/ (~520 registered ops). On TPU each op is
an XLA-traceable function; XLA performs the fusion/layout/kernel-selection
work the reference does with hand-written CUDA kernels and IR passes.
Importing this package registers all ops into the registry.
"""

from . import math_ops  # noqa: F401
from . import reduce_ops  # noqa: F401
from . import manipulation_ops  # noqa: F401
from . import nn_ops  # noqa: F401
from . import search_ops  # noqa: F401
from . import random_ops  # noqa: F401
from . import linalg_ops  # noqa: F401
from . import fused_ops  # noqa: F401
from . import fused_conv  # noqa: F401
from . import fused_loss  # noqa: F401
from . import rnn_ops  # noqa: F401
from . import sequence_ops  # noqa: F401
from . import detection_ops  # noqa: F401
from . import extra_ops  # noqa: F401
from . import long_tail_ops  # noqa: F401
from . import compat_ops  # noqa: F401
from . import quant_ops  # noqa: F401
from . import overlap  # noqa: F401
