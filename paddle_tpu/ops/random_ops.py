"""Random ops. Keys come in as explicit primal inputs (threaded PRNG —
the TPU-native replacement for the reference's stateful Philox Generator,
paddle/fluid/framework/generator.h)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.op_registry import register_op
from ..core.dtype import to_jax_dtype


@register_op("gaussian_random", no_grad=True)
def gaussian_random(key, *, shape, mean=0.0, std=1.0, dtype="float32"):
    dt = to_jax_dtype(dtype)
    return mean + std * jax.random.normal(jnp.asarray(key), tuple(shape), dt)


@register_op("uniform_random", no_grad=True)
def uniform_random(key, *, shape, min=-1.0, max=1.0, dtype="float32"):
    dt = to_jax_dtype(dtype)
    return jax.random.uniform(jnp.asarray(key), tuple(shape), dt, min, max)


@register_op("randint", no_grad=True)
def randint(key, *, low, high, shape, dtype="int64"):
    dt = to_jax_dtype(dtype)
    return jax.random.randint(jnp.asarray(key), tuple(shape), low, high, dt)


@register_op("randperm", no_grad=True)
def randperm(key, *, n, dtype="int64"):
    return jax.random.permutation(jnp.asarray(key), n).astype(
        to_jax_dtype(dtype))


@register_op("bernoulli", no_grad=True)
def bernoulli(x, key):
    return jax.random.bernoulli(jnp.asarray(key), x).astype(x.dtype)


@register_op("multinomial", no_grad=True)
def multinomial(x, key, *, num_samples=1, replacement=False):
    key = jnp.asarray(key)
    logits = jnp.log(jnp.maximum(x, 1e-30))
    if replacement:
        return jax.random.categorical(
            key, logits, axis=-1,
            shape=(num_samples,) + x.shape[:-1]).T.astype(jnp.int64) \
            if x.ndim > 1 else jax.random.categorical(
                key, logits, shape=(num_samples,)).astype(jnp.int64)
    # without replacement: Gumbel top-k trick
    g = jax.random.gumbel(key, x.shape)
    _, idx = jax.lax.top_k(logits + g, num_samples)
    return idx.astype(jnp.int64)


@register_op("poisson", no_grad=True)
def poisson(x, key):
    return jax.random.poisson(jnp.asarray(key), x).astype(x.dtype)


@register_op("exponential", no_grad=True)
def exponential(x, key, *, lam=1.0):
    return jax.random.exponential(jnp.asarray(key), x.shape).astype(
        x.dtype) / lam


@register_op("normal_like", no_grad=True)
def normal_like(x, key, *, mean=0.0, std=1.0):
    return mean + std * jax.random.normal(jnp.asarray(key), x.shape, x.dtype)


@register_op("truncated_gaussian_random", no_grad=True)
def truncated_gaussian_random(key, *, shape, mean=0.0, std=1.0,
                              dtype="float32"):
    dt = to_jax_dtype(dtype)
    out = jax.random.truncated_normal(
        jnp.asarray(key), -2.0, 2.0, tuple(shape), dt)
    return mean + std * out
