"""Neural-network ops: conv/pool/norm/softmax/losses/embedding/attention.

Ref parity: paddle/fluid/operators/ conv_op.cc, pool_op.cc,
batch_norm_op.cc, layer_norm_op.cc, softmax_with_cross_entropy_op.cc,
dropout_op.cc, lookup_table_v2_op.cc, interpolate_v2. Convs/matmuls are the
MXU ops — implemented with lax.conv_general_dilated / jnp.matmul so XLA
tiles them onto the systolic array; elementwise epilogues fuse in.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.op_registry import register_op


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _conv_padding(padding, spatial, strides, dilations, ksizes,
                  channel_last=False):
    """Normalise paddle padding spec to lax's [(lo, hi), ...] or string."""
    if isinstance(padding, str):
        return padding.upper()  # 'SAME' / 'VALID'
    if isinstance(padding, int):
        return [(padding, padding)] * spatial
    if len(padding) > 0 and all(isinstance(p, (list, tuple)) for p in padding):
        pairs = [(int(lo), int(hi)) for lo, hi in padding]
        if len(pairs) == spatial:
            return pairs
        if len(pairs) == spatial + 2:
            # paddle's full-rank form includes batch/channel pairs: NCHW
            # keeps them in front, NHWC wraps the spatial dims
            return pairs[1:-1] if channel_last else pairs[2:]
        raise ValueError(f"bad padding {padding!r}")
    pads = [int(p) for p in padding]
    if len(pads) == spatial:
        return [(p, p) for p in pads]
    if len(pads) == 2 * spatial:
        return [(pads[2 * i], pads[2 * i + 1]) for i in range(spatial)]
    raise ValueError(f"bad padding {padding!r}")


@register_op("conv2d")
def conv2d(x, weight, *, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    strides = _pair(stride)
    dilations = _pair(dilation)
    kh, kw = weight.shape[-2], weight.shape[-1]
    pad = _conv_padding(padding, 2, strides, dilations, (kh, kw),
                        channel_last=(data_format != "NCHW"))
    if data_format == "NCHW" and groups == 1:
        # pallas stride-1 kernel + transposed-conv custom VJP; gated on
        # FLAGS_use_pallas_conv / PADDLE_TPU_CONV_FORCE and plan
        # eligibility — None keeps the XLA path below (lazy import:
        # fused_conv imports this module for _conv_padding/_bn_act_core)
        from . import fused_conv

        z = fused_conv.conv2d_maybe_pallas(x, weight, strides, pad,
                                           dilations, groups, data_format)
        if z is not None:
            return z
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW"
        else ("NHWC", "OIHW", "NHWC"))
    return lax.conv_general_dilated(
        x, weight, window_strides=strides, padding=pad,
        rhs_dilation=dilations, dimension_numbers=dn,
        feature_group_count=groups)


@register_op("depthwise_conv2d")
def depthwise_conv2d(x, weight, *, stride=1, padding=0, dilation=1, groups=1,
                     data_format="NCHW"):
    return conv2d(x, weight, stride=stride, padding=padding,
                  dilation=dilation, groups=groups, data_format=data_format)


def _conv_transpose(x, weight, spatial, stride, padding, output_padding,
                    dilation, groups, data_format):
    """Shared N-D transposed conv. Paddle weight layout is
    (C_in, C_out/groups, *k); lax wants OIHW' with feature groups, so the
    weight is regrouped (g, Ci/g, Co/g, *k) -> (Co, Ci/g, *k) + flipped."""
    strides = _pair(stride, spatial)
    dilations = _pair(dilation, spatial)
    opad = _pair(output_padding, spatial)
    ks = weight.shape[2:]
    channel_last = data_format in ("NHWC", "NDHWC")
    pad = _conv_padding(padding, spatial, strides, dilations, ks,
                        channel_last=channel_last)
    if isinstance(pad, str):
        lax_pad = pad
    else:
        # transpose conv: effective padding = k - 1 - p (+ output_padding hi)
        lax_pad = [
            (dilations[i] * (k - 1) - pad[i][0],
             dilations[i] * (k - 1) - pad[i][1] + opad[i])
            for i, k in enumerate(ks)
        ]
    ci, cog = weight.shape[0], weight.shape[1]
    w = weight.reshape((groups, ci // groups, cog) + ks)
    w = jnp.swapaxes(w, 1, 2).reshape((groups * cog, ci // groups) + ks)
    w = jnp.flip(w, axis=tuple(range(2, 2 + spatial)))
    sp = "DHW"[3 - spatial:]
    fmt = ("NC" + sp) if not channel_last else ("N" + sp + "C")
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    (fmt, "OI" + sp, fmt))
    return lax.conv_general_dilated(
        x, w, window_strides=(1,) * spatial, padding=lax_pad,
        lhs_dilation=strides, rhs_dilation=dilations,
        dimension_numbers=dn, feature_group_count=groups)


@register_op("conv2d_transpose")
def conv2d_transpose(x, weight, *, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, data_format="NCHW"):
    return _conv_transpose(x, weight, 2, stride, padding, output_padding,
                           dilation, groups, data_format)


@register_op("depthwise_conv2d_transpose")
def depthwise_conv2d_transpose(x, weight, *, stride=1, padding=0,
                               output_padding=0, dilation=1, groups=None,
                               data_format="NCHW"):
    """ref conv_transpose_op.cc depthwise registration: groups == C_in."""
    g = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    return _conv_transpose(x, weight, 2, stride, padding, output_padding,
                           dilation, groups if groups else g, data_format)


@register_op("conv3d_transpose")
def conv3d_transpose(x, weight, *, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, data_format="NCDHW"):
    """ref conv_transpose_op.cc:528 (conv3d_transpose)."""
    return _conv_transpose(x, weight, 3, stride, padding, output_padding,
                           dilation, groups, data_format)


@register_op("conv1d")
def conv1d(x, weight, *, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    x4 = x[:, :, None, :]
    w4 = weight[:, :, None, :]
    s = stride if isinstance(stride, int) else stride[0]
    d = dilation if isinstance(dilation, int) else dilation[0]
    if isinstance(padding, str):
        p = padding
    else:
        pv = padding if isinstance(padding, int) else padding[0]
        p = [(0, 0), (pv, pv)]
    out = conv2d(x4, w4, stride=(1, s), padding=p, dilation=(1, d),
                 groups=groups)
    return out[:, :, 0, :]


@register_op("conv3d")
def conv3d(x, weight, *, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    strides = _pair(stride, 3)
    dilations = _pair(dilation, 3)
    ks = weight.shape[2:]
    pad = _conv_padding(padding, 3, strides, dilations, ks)
    dn = lax.conv_dimension_numbers(x.shape, weight.shape,
                                    ("NCDHW", "OIDHW", "NCDHW"))
    return lax.conv_general_dilated(
        x, weight, window_strides=strides, padding=pad,
        rhs_dilation=dilations, dimension_numbers=dn,
        feature_group_count=groups)


# -- pooling ---------------------------------------------------------------


def _pool2d(x, ksize, stride, padding, ceil_mode, mode, exclusive,
            data_format):
    if data_format != "NCHW":
        x = jnp.transpose(x, (0, 3, 1, 2))
    ks = _pair(ksize)
    st = _pair(stride) if stride is not None else ks
    if isinstance(padding, str):
        if padding.upper() == "SAME":
            pads = "SAME"
        else:
            pads = [(0, 0), (0, 0), (0, 0), (0, 0)]
    else:
        p = _conv_padding(padding, 2, st, (1, 1), ks)
        pads = [(0, 0), (0, 0)] + list(p)
    if ceil_mode and not isinstance(pads, str):
        # add extra hi padding so ceil-division windows are produced
        h, w = x.shape[2], x.shape[3]
        extra = []
        for dim, k, s, (lo, hi) in zip((h, w), ks, st, pads[2:]):
            full = dim + lo + hi - k
            rem = full % s
            extra.append((lo, hi + (s - rem) % s if rem else hi))
        pads = [(0, 0), (0, 0)] + extra
    window = (1, 1) + ks
    strides = (1, 1) + st
    if mode == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        out = lax.reduce_window(x, init, lax.max, window, strides, pads)
    else:
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
        if exclusive and (isinstance(pads, str) or any(
                p != (0, 0) for p in pads)):
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(ones, 0.0, lax.add, window, strides,
                                       pads)
            out = summed / counts
        else:
            out = summed / (ks[0] * ks[1])
    if data_format != "NCHW":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


@register_op("pool2d")
def pool2d(x, *, ksize, stride=None, padding=0, ceil_mode=False,
           pooling_type="max", exclusive=True, global_pooling=False,
           adaptive=False, data_format="NCHW"):
    if global_pooling:
        axes = (2, 3) if data_format == "NCHW" else (1, 2)
        if pooling_type == "max":
            return jnp.max(x, axis=axes, keepdims=True)
        return jnp.mean(x, axis=axes, keepdims=True)
    if adaptive:
        return _adaptive_pool2d(x, ksize, pooling_type, data_format)
    return _pool2d(x, ksize, stride, padding, ceil_mode, pooling_type,
                   exclusive, data_format)


def adaptive_bounds(i, size, bins):
    """Paddle adaptive-pool bin i over `size` elements in `bins` cells:
    [floor(i*size/bins), ceil((i+1)*size/bins)) — shared by every
    adaptive pool so values and masks can never disagree."""
    return (i * size) // bins, -(-((i + 1) * size) // bins)


def _adaptive_pool2d(x, output_size, mode, data_format):
    os = _pair(output_size)
    axes = (2, 3) if data_format == "NCHW" else (1, 2)
    h, w = x.shape[axes[0]], x.shape[axes[1]]
    if h % os[0] == 0 and w % os[1] == 0:
        ks = (h // os[0], w // os[1])
        return _pool2d(x, ks, ks, 0, False, mode, True, data_format)
    # non-divisible: paddle bins overlap (start floor, end ceil), so
    # reduce each bin from a static slice — shapes are compile-time
    # constants, so this unrolls into os[0]*os[1] fused reductions
    if data_format != "NCHW":
        x = jnp.moveaxis(x, -1, 1)
    red = jnp.max if mode == "max" else jnp.mean
    rows = []
    for i in range(os[0]):
        s0, e0 = adaptive_bounds(i, h, os[0])
        cols = []
        for j in range(os[1]):
            s1, e1 = adaptive_bounds(j, w, os[1])
            cols.append(red(x[:, :, s0:e0, s1:e1], axis=(2, 3)))
        rows.append(jnp.stack(cols, axis=-1))
    out = jnp.stack(rows, axis=-2)
    if data_format != "NCHW":
        out = jnp.moveaxis(out, 1, -1)
    return out


def max_pool_with_index_nd(x, ks, st, pd):
    """Shared N-D (N=2,3) max-pool with argmax indices flat into the
    input spatial map (ref pool_with_index_op.cc).  Values are gathered
    from the INPUT by the computed index — exact by construction
    (x.flat[idx] == out), immune to patch-extraction roundoff."""
    import numpy as _np

    n, c, *sp = x.shape
    nd = len(sp)
    fmt = {2: ("NCHW", "OIHW", "NCHW"),
           3: ("NCDHW", "OIDHW", "NCDHW")}[nd]
    # HIGHEST precision: the one-hot extraction conv must not quantize
    # values on the MXU, or near-equal competitors flip the argmax
    patches = lax.conv_general_dilated_patches(
        x, tuple(ks), tuple(st), [(p, p) for p in pd],
        dimension_numbers=lax.conv_dimension_numbers(
            x.shape, (1, c, *ks), fmt),
        precision=lax.Precision.HIGHEST)
    osp = patches.shape[2:]
    ktot = int(_np.prod(ks))
    patches = patches.reshape(n, c, ktot, *osp)

    def coords_of(rel, lead_axes):
        """Per-dim absolute input coordinate for window-relative flat
        index `rel`; output-position bases broadcast over lead_axes."""
        out, rem = [None] * nd, rel
        for d in reversed(range(nd)):
            shape = [1] * (lead_axes + nd)
            shape[lead_axes + d] = osp[d]
            base = jnp.arange(osp[d]).reshape(shape)
            out[d] = base * st[d] - pd[d] + rem % ks[d]
            rem = rem // ks[d]
        return out

    # patch extraction zero-fills padding; mask positions outside the
    # input to -inf so a pad zero can never win the argmax (the
    # reference clamps window bounds to the valid region instead)
    rel_idx = jnp.arange(ktot).reshape((ktot,) + (1,) * nd)
    wc = coords_of(rel_idx, 1)
    valid = (wc[0] >= 0) & (wc[0] < sp[0])
    for d in range(1, nd):
        valid = valid & (wc[d] >= 0) & (wc[d] < sp[d])
    patches = jnp.where(valid[None, None], patches,
                        jnp.asarray(-jnp.inf, patches.dtype))
    rel = jnp.argmax(patches, axis=2)
    ac = coords_of(rel, 2)
    idx, mult = 0, 1
    for d in reversed(range(nd)):
        idx = idx + ac[d] * mult
        mult *= sp[d]
    idx = idx.astype(jnp.int32)
    out = jnp.take_along_axis(
        x.reshape(n, c, -1), idx.reshape(n, c, -1),
        axis=2).reshape(n, c, *osp)
    return out, idx


def adaptive_max_pool_with_index_nd(x, os):
    """Shared N-D adaptive max pool with indices: per-cell windows
    [floor(i*S/oS), ceil((i+1)*S/oS)) from adaptive_bounds, indices
    flat into the input spatial map.

    Divisible extents (every dim a multiple of its output size) take the
    uniform-window pool — identical bins, first-max argmax, same flat
    indices — in O(1) ops instead of O(cells).  The non-divisible
    fallback unrolls one slice+argmax per output cell, so its graph is
    capped at PADDLE_TPU_ADAPTIVE_POOL_MAX_CELLS (default 4096) cells —
    past that XLA compile time blows up (ADVICE r5 #4)."""
    import itertools
    import os as _os

    n, c, *sp = x.shape
    nd = len(sp)
    if all(sp[d] % os[d] == 0 for d in range(nd)):
        ks = tuple(sp[d] // os[d] for d in range(nd))
        return max_pool_with_index_nd(x, ks, ks, (0,) * nd)
    cells = int(np.prod(os))
    max_cells = int(_os.environ.get(
        "PADDLE_TPU_ADAPTIVE_POOL_MAX_CELLS", "4096"))
    if cells > max_cells:
        raise ValueError(
            f"adaptive max pool with indices: output {tuple(os)} needs "
            f"{cells} per-cell reductions (non-divisible input "
            f"{tuple(sp)} unrolls one slice per cell); cap is "
            f"{max_cells}.  Pick a divisor output size or raise "
            "PADDLE_TPU_ADAPTIVE_POOL_MAX_CELLS")
    vals, idxs = [], []
    for cell in itertools.product(*[range(o) for o in os]):
        bounds = [adaptive_bounds(cell[d], sp[d], os[d])
                  for d in range(nd)]
        win = x[(slice(None), slice(None))
                + tuple(slice(s, e) for s, e in bounds)]
        wshape = [e - s for s, e in bounds]
        flat = win.reshape(n, c, -1)
        rel = jnp.argmax(flat, axis=2)
        vals.append(jnp.max(flat, axis=2))
        pos, rem, mult = 0, rel, 1
        for d in reversed(range(nd)):
            pos = pos + (bounds[d][0] + rem % wshape[d]) * mult
            rem = rem // wshape[d]
            mult *= sp[d]
        idxs.append(pos.astype(jnp.int32))
    # itertools.product iterates row-major, so a straight reshape
    # restores the output grid
    return (jnp.stack(vals, axis=-1).reshape(n, c, *os),
            jnp.stack(idxs, axis=-1).reshape(n, c, *os))


@register_op("max_pool2d_with_index", has_aux=True)
def max_pool2d_with_index(x, *, ksize, stride=None, padding=0,
                          adaptive=False):
    if adaptive:
        return adaptive_max_pool_with_index_nd(x, _pair(ksize))
    kh, kw = _pair(ksize)
    st = _pair(stride) if stride is not None else (kh, kw)
    return max_pool_with_index_nd(x, (kh, kw), st, _pair(padding))


# -- normalisation ----------------------------------------------------------


@register_op("layer_norm")
def layer_norm(x, scale=None, bias=None, *, epsilon=1e-5, begin_norm_axis=1):
    axes = tuple(range(begin_norm_axis, x.ndim))
    x32 = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) else x
    # NOTE: deliberately NOT the one-pass E[x^2]-E[x]^2 form used by
    # batch_norm — LN reduces over the (small) trailing axis where XLA
    # already fuses the two passes, and the one-pass form measured
    # SLOWER on the ERNIE ladder (42.9% vs 44.6% MFU, r4 on v5e)
    mean = jnp.mean(x32, axis=axes, keepdims=True)
    var = jnp.var(x32, axis=axes, keepdims=True)
    y = (x32 - mean) * lax.rsqrt(var + epsilon)
    y = y.astype(x.dtype)
    if scale is not None:
        norm_shape = x.shape[begin_norm_axis:]
        y = y * scale.reshape(norm_shape)
    if bias is not None:
        norm_shape = x.shape[begin_norm_axis:]
        y = y + bias.reshape(norm_shape)
    return y


def batch_norm_apply(x, scale, bias, mean, variance, use_mean, use_var,
                     *, momentum, epsilon, c_axis):
    """Shared BN tail (normalise + running-stat update) used by both
    batch_norm and the cross-rank sync_batch_norm."""
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]
    new_mean = momentum * mean + (1 - momentum) * use_mean
    new_var = momentum * variance + (1 - momentum) * use_var
    inv = lax.rsqrt(use_var + epsilon)
    y = (x - use_mean.reshape(bshape).astype(x.dtype)) * \
        inv.reshape(bshape).astype(x.dtype)
    y = y * scale.reshape(bshape) + bias.reshape(bshape)
    return y, (lax.stop_gradient(new_mean), lax.stop_gradient(new_var))


@register_op("batch_norm", has_aux=True)
def batch_norm(x, scale, bias, mean, variance, *, momentum=0.9, epsilon=1e-5,
               is_test=False, data_format="NCHW", use_global_stats=False):
    c_axis = 1 if data_format == "NCHW" else x.ndim - 1
    reduce_axes = tuple(a for a in range(x.ndim) if a != c_axis)
    if is_test or use_global_stats:
        bshape = [1] * x.ndim
        bshape[c_axis] = x.shape[c_axis]
        inv = lax.rsqrt(variance + epsilon)
        y = (x - mean.reshape(bshape).astype(x.dtype)) * \
            inv.reshape(bshape).astype(x.dtype)
        y = y * scale.reshape(bshape) + bias.reshape(bshape)
        return y, (mean, variance)
    x32 = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) else x
    # one fused pass: E[x] and E[x^2] reduce together, var = E[x^2]-E[x]^2
    # (jnp.var would re-reduce for the mean — a third pass over the
    # activation, measurable on conv nets where BN is bandwidth-bound)
    use_mean = jnp.mean(x32, axis=reduce_axes)
    use_var = jnp.maximum(
        jnp.mean(x32 * x32, axis=reduce_axes) - use_mean * use_mean, 0.0)
    return batch_norm_apply(x, scale, bias, mean, variance, use_mean,
                            use_var, momentum=momentum, epsilon=epsilon,
                            c_axis=c_axis)


# -- fused BN + activation (+ residual) -------------------------------------
#
# Ref: paddle/fluid/operators/fused/fused_bn_activation_op.cu +
# framework/ir/fuse_bn_act_pass.cc.  The reference fuses BN-apply and the
# activation into one CUDA kernel; here the fusion lever is the custom
# VJP: forward saves ONLY (x, mean, inv) — never y, z, or an act mask —
# and backward recomputes the normalized activation in one fused pass,
# so the ~1.2 GB of ResNet activations is not re-read through saved
# intermediates (the measured BN/ReLU HBM ceiling, BENCH r4 analysis).


def _bn_act_math(act, c_axis, x, scale, bias, m, inv, residual):
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]
    xhat = (x.astype(jnp.float32) - m.reshape(bshape)) * inv.reshape(bshape)
    z = xhat * scale.reshape(bshape).astype(jnp.float32) \
        + bias.reshape(bshape).astype(jnp.float32)
    if residual is not None:
        z = z + residual.astype(jnp.float32)
    y = jnp.maximum(z, 0.0) if act == "relu" else z
    return y.astype(x.dtype), xhat, z


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _bn_act_core(act, c_axis, x, scale, bias, m, inv, residual):
    return _bn_act_math(act, c_axis, x, scale, bias, m, inv, residual)[0]


def _bn_act_core_fwd(act, c_axis, x, scale, bias, m, inv, residual):
    y, _, _ = _bn_act_math(act, c_axis, x, scale, bias, m, inv, residual)
    return y, (x, scale, bias, m, inv, residual)


def _bn_act_core_bwd(act, c_axis, saved, dy):
    x, scale, bias, m, inv, residual = saved
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]
    axes = tuple(a for a in range(x.ndim) if a != c_axis)
    n = float(np.prod([x.shape[a] for a in axes]))
    _, xhat, z = _bn_act_math(act, c_axis, x, scale, bias, m, inv,
                              residual)
    dy32 = dy.astype(jnp.float32)
    dz = jnp.where(z > 0.0, dy32, 0.0) if act == "relu" else dy32
    dbias = jnp.sum(dz, axis=axes)
    dscale = jnp.sum(dz * xhat, axis=axes)
    # training-mode dx: batch mean/var are functions of x
    dx = (scale.astype(jnp.float32) * inv).reshape(bshape) * (
        dz - dbias.reshape(bshape) / n
        - xhat * dscale.reshape(bshape) / n)
    dres = None if residual is None else dz.astype(residual.dtype)
    return (dx.astype(x.dtype), dscale.astype(scale.dtype),
            dbias.astype(bias.dtype), jnp.zeros_like(m),
            jnp.zeros_like(inv), dres)


_bn_act_core.defvjp(_bn_act_core_fwd, _bn_act_core_bwd)


@register_op("fused_bn_act", has_aux=True)
def fused_bn_act(x, scale, bias, mean, variance, residual=None, *,
                 momentum=0.9, epsilon=1e-5, act="relu", is_test=False,
                 data_format="NCHW", use_global_stats=False):
    """y = act(batch_norm(x) [+ residual]); aux = updated running stats.

    Training mode goes through the minimal-residual custom VJP above;
    eval normalizes with running stats (plain AD — nothing to save)."""
    c_axis = 1 if data_format == "NCHW" else x.ndim - 1
    if is_test or use_global_stats:
        inv = lax.rsqrt(variance + epsilon)
        y, _, _ = _bn_act_math(act, c_axis, x, scale, bias, mean, inv,
                               residual)
        return y, (mean, variance)
    reduce_axes = tuple(a for a in range(x.ndim) if a != c_axis)
    x32 = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16,
                                               jnp.float16) else x
    use_mean = lax.stop_gradient(jnp.mean(x32, axis=reduce_axes))
    use_var = lax.stop_gradient(jnp.maximum(
        jnp.mean(x32 * x32, axis=reduce_axes) - use_mean * use_mean,
        0.0))
    # the custom VJP owns the FULL training-mode dx (incl. the stats'
    # dependence on x), so the stats feed it stop-gradiented
    inv = lax.rsqrt(use_var + epsilon)
    y = _bn_act_core(act, c_axis, x, scale, bias, use_mean, inv,
                     residual)
    new_mean = momentum * mean + (1 - momentum) * use_mean
    new_var = momentum * variance + (1 - momentum) * use_var
    return y, (lax.stop_gradient(new_mean), lax.stop_gradient(new_var))


@register_op("instance_norm")
def instance_norm(x, scale=None, bias=None, *, epsilon=1e-5):
    axes = tuple(range(2, x.ndim))
    # f32 stats: the one-pass E[x^2]-mean^2 form cancels catastrophically
    # in bf16 (mean^2 and E[x^2] collide at 8 mantissa bits)
    x32 = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16,
                                               jnp.float16) else x
    mean = jnp.mean(x32, axis=axes, keepdims=True)
    var = jnp.maximum(jnp.mean(x32 * x32, axis=axes, keepdims=True)
                      - mean * mean, 0.0)
    y = ((x32 - mean) * lax.rsqrt(var + epsilon)).astype(x.dtype)
    bshape = [1, x.shape[1]] + [1] * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(bshape)
    if bias is not None:
        y = y + bias.reshape(bshape)
    return y


@register_op("group_norm")
def group_norm(x, scale=None, bias=None, *, epsilon=1e-5, groups=1,
               data_format="NCHW"):
    n, c = x.shape[0], x.shape[1]
    g = groups
    xg = x.reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    xg32 = xg.astype(jnp.float32) if xg.dtype in (jnp.bfloat16,
                                                  jnp.float16) else xg
    mean = jnp.mean(xg32, axis=axes, keepdims=True)
    var = jnp.maximum(jnp.mean(xg32 * xg32, axis=axes, keepdims=True)
                      - mean * mean, 0.0)
    y = ((xg32 - mean) * lax.rsqrt(var + epsilon)).reshape(
        x.shape).astype(x.dtype)
    bshape = [1, c] + [1] * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(bshape)
    if bias is not None:
        y = y + bias.reshape(bshape)
    return y


@register_op("rms_norm")
def rms_norm(x, scale=None, *, epsilon=1e-6):
    x32 = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) else x
    ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = (x32 * lax.rsqrt(ms + epsilon)).astype(x.dtype)
    if scale is not None:
        y = y * scale
    return y


@register_op("local_response_norm")
def local_response_norm(x, *, size, alpha=1e-4, beta=0.75, k=1.0):
    sq = x * x
    half = size // 2
    pads = [(0, 0), (half, size - half - 1)] + [(0, 0)] * (x.ndim - 2)
    padded = jnp.pad(sq, pads)
    acc = sum(padded[:, i:i + x.shape[1]] for i in range(size))
    return x / (k + alpha * acc) ** beta


@register_op("l2_normalize")
def l2_normalize(x, *, axis=-1, epsilon=1e-12):
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    return x / jnp.maximum(norm, epsilon)


# -- softmax & losses -------------------------------------------------------


@register_op("softmax")
def softmax(x, *, axis=-1):
    return jax.nn.softmax(x, axis=axis)


@register_op("log_softmax")
def log_softmax(x, *, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


@register_op("softmax_with_cross_entropy", has_aux=True)
def softmax_with_cross_entropy(logits, label, *, soft_label=False, axis=-1,
                               ignore_index=-100):
    logits32 = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits32, axis=axis)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lbl = jnp.asarray(label)
        squeeze = lbl.ndim == logits.ndim and lbl.shape[axis] == 1
        if squeeze:
            lbl = jnp.squeeze(lbl, axis=axis)
        lbl = lbl.astype(jnp.int32)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(jnp.maximum(lbl, 0), axis), axis=axis)
        loss = -picked
        mask = (lbl != ignore_index)
        loss = loss * jnp.expand_dims(mask, axis).astype(loss.dtype)
    return loss, lax.stop_gradient(jnp.exp(logp))


@register_op("cross_entropy")
def cross_entropy(input, label, *, soft_label=False, axis=-1,
                  ignore_index=-100, reduction="mean", use_softmax=True,
                  weight=None):
    logits32 = input.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits32, axis=axis) if use_softmax \
        else jnp.log(jnp.maximum(logits32, 1e-30))
    if soft_label:
        loss = -jnp.sum(jnp.asarray(label) * logp, axis=axis)
        valid = jnp.ones_like(loss, dtype=bool)
    else:
        lbl = jnp.asarray(label)
        if lbl.ndim == input.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis=axis)
        lbl = lbl.astype(jnp.int32)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(jnp.maximum(lbl, 0), axis), axis=axis)
        loss = -jnp.squeeze(picked, axis)
        valid = lbl != ignore_index
        loss = loss * valid.astype(loss.dtype)
        if weight is not None:
            w = jnp.take(jnp.asarray(weight), jnp.maximum(lbl, 0))
            loss = loss * w
    if reduction == "none":
        return loss
    if reduction == "sum":
        return jnp.sum(loss)
    # mean: weighted CE divides by the sum of gathered weights (paddle
    # semantics, ref python/paddle/nn/functional/loss.py cross_entropy)
    if not soft_label and weight is not None:
        denom = jnp.maximum(jnp.sum(w * valid.astype(loss.dtype)), 1e-12)
    else:
        denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
    return jnp.sum(loss) / denom


@register_op("sigmoid_cross_entropy_with_logits")
def sigmoid_ce_with_logits(x, label, *, ignore_index=-100, normalize=False):
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    mask = (label != ignore_index)
    loss = loss * mask.astype(loss.dtype)
    if normalize:
        loss = loss / jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
    return loss


@register_op("bce_loss")
def bce_loss(input, label):
    eps = 1e-12
    return -(label * jnp.log(jnp.maximum(input, eps)) +
             (1 - label) * jnp.log(jnp.maximum(1 - input, eps)))


@register_op("kldiv_loss")
def kldiv_loss(x, target, *, reduction="mean"):
    loss = target * (jnp.log(jnp.maximum(target, 1e-12)) - x)
    if reduction == "none":
        return loss
    if reduction == "sum":
        return jnp.sum(loss)
    if reduction == "batchmean":
        return jnp.sum(loss) / x.shape[0]
    return jnp.mean(loss)


@register_op("l1_loss")
def l1_loss(input, label, *, reduction="mean"):
    loss = jnp.abs(input - label)
    if reduction == "none":
        return loss
    return jnp.sum(loss) if reduction == "sum" else jnp.mean(loss)


@register_op("mse_loss")
def mse_loss(input, label, *, reduction="mean"):
    loss = jnp.square(input - label)
    if reduction == "none":
        return loss
    return jnp.sum(loss) if reduction == "sum" else jnp.mean(loss)


@register_op("smooth_l1_loss")
def smooth_l1_loss(input, label, *, delta=1.0, reduction="mean"):
    diff = jnp.abs(input - label)
    loss = jnp.where(diff < delta, 0.5 * diff * diff / delta,
                     diff - 0.5 * delta)
    if reduction == "none":
        return loss
    return jnp.sum(loss) if reduction == "sum" else jnp.mean(loss)


@register_op("nll_loss")
def nll_loss(input, label, weight=None, *, reduction="mean",
             ignore_index=-100):
    lbl = jnp.asarray(label).astype(jnp.int32)
    picked = jnp.take_along_axis(input, jnp.expand_dims(
        jnp.maximum(lbl, 0), 1), axis=1)
    loss = -jnp.squeeze(picked, 1)
    valid = lbl != ignore_index
    loss = loss * valid.astype(loss.dtype)
    if weight is not None:
        w = jnp.take(jnp.asarray(weight), jnp.maximum(lbl, 0))
        loss = loss * w
    if reduction == "none":
        return loss
    if reduction == "sum":
        return jnp.sum(loss)
    if weight is not None:
        denom = jnp.maximum(jnp.sum(w * valid.astype(loss.dtype)), 1e-12)
    else:
        denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
    return jnp.sum(loss) / denom


@register_op("hinge_loss")
def hinge_loss(logits, label):
    return jnp.maximum(0.0, 1.0 - logits * (2.0 * label - 1.0))


@register_op("margin_ranking_loss")
def margin_ranking_loss(input, other, label, *, margin=0.0, reduction="mean"):
    loss = jnp.maximum(0.0, -label * (input - other) + margin)
    if reduction == "none":
        return loss
    return jnp.sum(loss) if reduction == "sum" else jnp.mean(loss)


@register_op("cosine_similarity")
def cosine_similarity(x1, x2, *, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.sum(x1 * x1, axis=axis))
    n2 = jnp.sqrt(jnp.sum(x2 * x2, axis=axis))
    return dot / jnp.maximum(n1 * n2, eps)


# -- embedding --------------------------------------------------------------


@register_op("lookup_table_v2")
def lookup_table_v2(ids, w, *, padding_idx=-1):
    ids = jnp.asarray(ids).astype(jnp.int32)
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None].astype(out.dtype)
        out = out * mask
    return out


# -- dropout (key passed explicitly; see paddle_tpu.framework.random) -------


@register_op("dropout")
def dropout(x, key, *, p=0.5, training=True, mode="upscale_in_train"):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return x * (1.0 - p)
        return x
    keep = 1.0 - p
    from ._common import keep_mask_u16

    mask = keep_mask_u16(jnp.asarray(key), x.shape, p)
    scale = jnp.asarray(1.0 / keep if mode == "upscale_in_train" else 1.0,
                        x.dtype)
    return jnp.where(mask, x * scale, jnp.zeros((), x.dtype))


# -- attention (jnp fallback; pallas flash attention overrides on TPU) ------


@register_op("scaled_dot_product_attention")
def sdpa(q, k, v, mask=None, key=None, *, dropout_p=0.0, is_causal=False,
         scale=None):
    """q,k,v: [batch, heads, seq, head_dim] (already transposed).

    `key` (PRNG key) enables attention-probability dropout; without a key
    dropout_p is inert (inference / dropout disabled)."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * s
    logits = logits.astype(jnp.float32)
    if is_causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(causal, logits, -1e30)
    if mask is not None:
        mask = jnp.asarray(mask)
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if key is not None and dropout_p > 0.0:
        keep = jax.random.bernoulli(jnp.asarray(key), 1.0 - dropout_p,
                                    probs.shape).astype(probs.dtype)
        probs = probs * keep / (1.0 - dropout_p)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


# -- misc -------------------------------------------------------------------


@register_op("interpolate")
def interpolate(x, *, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format="NCHW"):
    nsp = x.ndim - 2
    channel_last = data_format in ("NWC", "NHWC", "NDHWC")
    spatial_axes = tuple(range(1, 1 + nsp)) if channel_last \
        else tuple(range(2, 2 + nsp))
    in_sizes = [x.shape[a] for a in spatial_axes]
    if size is None:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
            else [scale_factor] * nsp
        size = [int(s * f) for s, f in zip(in_sizes, sf)]
    out_shape = list(x.shape)
    for a, s in zip(spatial_axes, size):
        out_shape[a] = int(s)
    method = {"nearest": "nearest", "linear": "linear",
              "bilinear": "bilinear", "trilinear": "trilinear",
              "bicubic": "bicubic", "area": "linear"}[mode]
    return jax.image.resize(x, out_shape, method=method)


@register_op("pixel_shuffle")
def pixel_shuffle(x, *, upscale_factor, data_format="NCHW"):
    r = upscale_factor
    n, c, h, w = x.shape
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return x.reshape(n, c // (r * r), h * r, w * r)


@register_op("unfold")
def unfold(x, *, kernel_sizes, strides=1, paddings=0, dilations=1):
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)
    n, c = x.shape[0], x.shape[1]
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), [(ph, ph), (pw, pw)],
        rhs_dilation=(dh, dw),
        dimension_numbers=lax.conv_dimension_numbers(
            x.shape, (1, c, kh, kw), ("NCHW", "OIHW", "NCHW")))
    return patches.reshape(n, c * kh * kw, -1)


@register_op("temporal_shift")
def temporal_shift(x, *, seg_num, shift_ratio=0.25):
    nt, c, h, w = x.shape
    n = nt // seg_num
    x5 = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    left = jnp.concatenate([x5[:, 1:, :fold], jnp.zeros_like(x5[:, :1, :fold])], 1)
    right = jnp.concatenate([jnp.zeros_like(x5[:, :1, fold:2 * fold]),
                             x5[:, :-1, fold:2 * fold]], 1)
    rest = x5[:, :, 2 * fold:]
    return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)
