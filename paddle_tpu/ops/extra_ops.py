"""Long-tail op families: CTC/CRF sequence losses, spatial warps,
small losses/metrics, normalization variants, segment/pool extras.

Ref parity (paddle/fluid/operators/): warpctc_op.cc (here a native
lax.scan forward-backward — no warp-ctc library), linear_chain_crf_op.cc,
grid_sampler_op.cc, affine_grid_op.cc, affine_channel_op.cc,
huber_loss_op.cc, log_loss_op.cc, bpr_loss_op.cc, rank_loss_op.cc,
margin_rank_loss_op.cc, sigmoid_focal_loss (detection/), cos_sim_op.cc,
dist_op.cc, squared_l2_norm_op.cc, l1_norm_op.cc, lrn_op.cc,
data_norm_op.cc, roi_pool_op.cc, multiplex_op.cc, shuffle_channel_op.cc,
space_to_depth_op.cc, segment_pool_op.cc, gather_tree_op.cc,
pool3d (pool_op.cc), pad3d_op.cc. All pure-jax and XLA-traceable with
static shapes; CTC/CRF use lax.scan (compiled recurrences, no Python
loops under jit).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.op_registry import register_op

_NEG = -1e30


# ---------------------------------------------------------------------------
# sequence losses
# ---------------------------------------------------------------------------


@register_op("warpctc")
def warpctc(logits, labels, logit_lengths, label_lengths, *, blank=0,
            norm_by_times=False):
    """CTC loss (ref warpctc_op.cc; native implementation, no warp-ctc
    dependency): forward algorithm over the extended label sequence in
    log space, one lax.scan over time.

    logits: [B, T, C] (unnormalised); labels: [B, L] padded with any
    value beyond label_lengths; returns per-sample loss [B]."""
    logits = jnp.asarray(logits)
    labels = jnp.asarray(labels, jnp.int32)
    logit_lengths = jnp.asarray(logit_lengths, jnp.int32).reshape(-1)
    label_lengths = jnp.asarray(label_lengths, jnp.int32).reshape(-1)
    b, t, c = logits.shape
    l = labels.shape[1]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)

    # extended sequence: blank a1 blank a2 ... aL blank  (length 2L+1)
    ext = jnp.full((b, 2 * l + 1), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    ext_len = 2 * label_lengths + 1
    pos = jnp.arange(2 * l + 1)[None, :]
    valid = pos < ext_len[:, None]

    # allowed skip transition s-2 -> s: ext[s] != blank and ext[s]!=ext[s-2]
    ext_prev2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :-2]
    can_skip = (ext != blank) & (ext != ext_prev2)

    def emit(tt):
        return jnp.take_along_axis(logp[:, tt], ext, axis=1)  # [B, 2L+1]

    alpha0 = jnp.full((b, 2 * l + 1), _NEG, jnp.float32)
    alpha0 = alpha0.at[:, 0].set(emit(0)[:, 0])
    has1 = l > 0
    if has1:
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(ext_len > 1, emit(0)[:, 1], _NEG))

    def body(alpha, tt):
        prev1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                        constant_values=_NEG)[:, :-1]
        prev2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                        constant_values=_NEG)[:, :-2]
        acc = jnp.logaddexp(alpha, prev1)
        acc = jnp.where(can_skip, jnp.logaddexp(acc, prev2), acc)
        new = acc + emit(tt)
        new = jnp.where(valid, new, _NEG)
        # frozen past logit_lengths (loss reads the alpha at T_b - 1)
        new = jnp.where((tt < logit_lengths)[:, None], new, alpha)
        return new, None

    alpha, _ = lax.scan(body, alpha0, jnp.arange(1, t))
    last = ext_len - 1
    a_last = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(
        alpha, jnp.maximum(last - 1, 0)[:, None], axis=1)[:, 0]
    loss = -jnp.where(ext_len > 1, jnp.logaddexp(a_last, a_prev), a_last)
    if norm_by_times:
        loss = loss / jnp.maximum(logit_lengths, 1).astype(loss.dtype)
    return loss


@register_op("linear_chain_crf")
def linear_chain_crf(emission, transition, label, lengths):
    """Linear-chain CRF negative log-likelihood
    (ref linear_chain_crf_op.cc). emission: [B, T, C]; transition:
    [C+2, C] (row 0 = start scores, row 1 = stop scores, rows 2.. =
    transition matrix as in the reference's layout); label: [B, T];
    returns nll [B]."""
    emission = jnp.asarray(emission, jnp.float32)
    transition = jnp.asarray(transition, jnp.float32)
    label = jnp.asarray(label, jnp.int32)
    lengths = jnp.asarray(lengths, jnp.int32).reshape(-1)
    b, t, c = emission.shape
    start, stop, trans = transition[0], transition[1], transition[2:]

    # partition function: forward algorithm
    alpha0 = start[None, :] + emission[:, 0]

    def body(alpha, tt):
        # [B, C_prev, 1] + [C_prev, C] -> logsumexp over prev
        scores = alpha[:, :, None] + trans[None, :, :]
        new = jax.nn.logsumexp(scores, axis=1) + emission[:, tt]
        new = jnp.where((tt < lengths)[:, None], new, alpha)
        return new, None

    alpha, _ = lax.scan(body, alpha0, jnp.arange(1, t))
    logz = jax.nn.logsumexp(alpha + stop[None, :], axis=1)

    # gold path score
    pos = jnp.arange(t)[None, :]
    msk = (pos < lengths[:, None]).astype(jnp.float32)
    emit_scores = jnp.take_along_axis(
        emission, label[:, :, None], axis=2)[:, :, 0] * msk
    prev_l = label[:, :-1]
    next_l = label[:, 1:]
    trans_scores = trans[prev_l, next_l] * msk[:, 1:]
    first = start[label[:, 0]]
    last_idx = jnp.maximum(lengths - 1, 0)
    last_label = jnp.take_along_axis(label, last_idx[:, None], axis=1)[:, 0]
    gold = first + emit_scores.sum(1) + trans_scores.sum(1) \
        + stop[last_label]
    return logz - gold


# ---------------------------------------------------------------------------
# spatial warps
# ---------------------------------------------------------------------------


@register_op("affine_grid")
def affine_grid(theta, *, out_shape, align_corners=True):
    """ref affine_grid_op.cc: sampling grid [N, H, W, 2] from 2x3 theta."""
    theta = jnp.asarray(theta, jnp.float32)
    n, h, w = out_shape[0], out_shape[-2], out_shape[-1]

    def axis_coords(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    ys = axis_coords(h)
    xs = axis_coords(w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H, W, 3]
    return jnp.einsum("hwk,nck->nhwc", base, theta)  # [N, H, W, 2]


@register_op("grid_sampler")
def grid_sampler(x, grid, *, mode="bilinear", padding_mode="zeros",
                 align_corners=True):
    """ref grid_sampler_op.cc: sample x [N,C,H,W] at grid [N,Ho,Wo,2]
    (normalised [-1,1] xy coords). bilinear/nearest; zeros/border."""
    x = jnp.asarray(x)
    grid = jnp.asarray(grid, jnp.float32)
    n, c, h, w = x.shape

    def unnorm(g, size):
        if align_corners:
            return (g + 1.0) / 2.0 * (size - 1)
        return ((g + 1.0) * size - 1.0) / 2.0

    gx = unnorm(grid[..., 0], w)
    gy = unnorm(grid[..., 1], h)

    if padding_mode == "reflection":
        # reflect the FLOAT coordinate before any rounding (paddle
        # semantics): align_corners=True reflects about [0, size-1]
        # (period 2(size-1)); False about [-0.5, size-0.5] (period
        # 2*size, border pixels repeat once)
        def reflect_coord(c, size):
            if align_corners:
                period = jnp.maximum(2.0 * (size - 1), 1.0)
                r = jnp.abs(c) % period
                return jnp.where(r > size - 1, period - r, r)
            period = 2.0 * size
            r = jnp.abs(c + 0.5) % period
            r = jnp.minimum(r, period - r)
            return jnp.clip(r - 0.5, 0.0, size - 1)

        gx = reflect_coord(gx, w)
        gy = reflect_coord(gy, h)

    def sample_at(yi, xi):
        inside = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yc = jnp.clip(yi, 0, h - 1)
        xc = jnp.clip(xi, 0, w - 1)
        vals = jax.vmap(
            lambda img, yy, xx: img[:, yy, xx])(x, yc, xc)  # [N,C,Ho,Wo]
        if padding_mode == "zeros":
            vals = vals * inside[:, None].astype(vals.dtype)
        return vals

    if mode == "nearest":
        return sample_at(jnp.round(gy).astype(jnp.int32),
                         jnp.round(gx).astype(jnp.int32))
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = (gx - x0)[:, None]
    wy = (gy - y0)[:, None]
    x0i, y0i = x0.astype(jnp.int32), y0.astype(jnp.int32)
    v00 = sample_at(y0i, x0i)
    v01 = sample_at(y0i, x0i + 1)
    v10 = sample_at(y0i + 1, x0i)
    v11 = sample_at(y0i + 1, x0i + 1)
    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
            + v10 * wy * (1 - wx) + v11 * wy * wx)


@register_op("affine_channel")
def affine_channel(x, scale, bias, *, data_layout="NCHW"):
    """ref affine_channel_op.cc: x * scale + bias per channel."""
    if data_layout == "NCHW":
        shape = (1, -1) + (1,) * (x.ndim - 2)
    else:
        shape = (1,) * (x.ndim - 1) + (-1,)
    return x * scale.reshape(shape) + bias.reshape(shape)


# ---------------------------------------------------------------------------
# small losses / similarity
# ---------------------------------------------------------------------------


@register_op("huber_loss")
def huber_loss(x, y, *, delta=1.0):
    """ref huber_loss_op.cc (input, label) -> residual loss."""
    r = jnp.abs(y - x)
    return jnp.where(r <= delta, 0.5 * r * r,
                     delta * (r - 0.5 * delta))


@register_op("log_loss")
def log_loss(predicted, labels, *, epsilon=1e-4):
    """ref log_loss_op.cc: -l*log(p+eps) - (1-l)*log(1-p+eps)."""
    p = jnp.asarray(predicted)
    l = jnp.asarray(labels)
    return -l * jnp.log(p + epsilon) - (1.0 - l) * jnp.log(
        1.0 - p + epsilon)


@register_op("bpr_loss")
def bpr_loss(x, label):
    """ref bpr_loss_op.cc (Bayesian personalised ranking over logits
    [B, C] with positive-class label [B, 1])."""
    x = jnp.asarray(x)
    label = jnp.asarray(label, jnp.int32).reshape(-1)
    b, c = x.shape
    pos = jnp.take_along_axis(x, label[:, None], axis=1)  # [B, 1]
    diff = pos - x  # [B, C]
    lsm = jnp.log1p(jnp.exp(-diff))
    not_pos = jnp.arange(c)[None, :] != label[:, None]
    return (lsm * not_pos).sum(axis=1, keepdims=True) / jnp.maximum(
        c - 1, 1)


@register_op("rank_loss")
def rank_loss(label, left, right):
    """ref rank_loss_op.cc: RankNet pairwise loss."""
    d = left - right
    return jnp.log1p(jnp.exp(d)) - label * d


@register_op("margin_rank_loss")
def margin_rank_loss(label, left, right, *, margin=0.0):
    """ref margin_rank_loss_op.cc: max(0, -label*(left-right)+margin)."""
    return jnp.maximum(-label * (left - right) + margin, 0.0)


@register_op("sigmoid_focal_loss")
def sigmoid_focal_loss(x, label, *, normalizer=None, alpha=0.25,
                       gamma=2.0):
    """ref detection/sigmoid_focal_loss_op.cc (dense binary-label form:
    label [..., 1] in {0,1} per anchor-class entry, matching
    paddle.nn.functional.sigmoid_focal_loss)."""
    x = jnp.asarray(x, jnp.float32)
    label = jnp.asarray(label, jnp.float32)
    p = jax.nn.sigmoid(x)
    ce = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p_t = p * label + (1.0 - p) * (1.0 - label)
    a_t = alpha * label + (1.0 - alpha) * (1.0 - label)
    loss = a_t * ((1.0 - p_t) ** gamma) * ce
    if normalizer is not None:
        loss = loss / normalizer
    return loss


@register_op("cos_sim")
def cos_sim(x, y):
    """ref cos_sim_op.cc: row-wise cosine similarity [B, 1]."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
    return jnp.sum(x * y, axis=-1, keepdims=True) / jnp.maximum(
        xn * yn, 1e-12)


@register_op("dist")
def dist(x, y, *, p=2.0):
    """ref dist_op.cc: p-norm of (x - y), scalar."""
    d = jnp.abs(jnp.asarray(x) - jnp.asarray(y))
    if p == float("inf"):
        return jnp.max(d)
    if p == 0:
        return jnp.sum((d != 0).astype(d.dtype))
    return jnp.sum(d ** p) ** (1.0 / p)


@register_op("squared_l2_norm")
def squared_l2_norm(x):
    return jnp.sum(jnp.square(jnp.asarray(x)))


@register_op("l1_norm")
def l1_norm(x):
    return jnp.sum(jnp.abs(jnp.asarray(x)))


@register_op("npair_loss")
def npair_loss(anchor, positive, labels, *, l2_reg=0.002):
    """ref python/paddle/fluid/layers/loss.py npair_loss."""
    anchor = jnp.asarray(anchor)
    positive = jnp.asarray(positive)
    labels = jnp.asarray(labels).reshape(-1)
    same = (labels[:, None] == labels[None, :]).astype(anchor.dtype)
    tgt = same / jnp.sum(same, axis=1, keepdims=True)
    logits = anchor @ positive.T
    xent = -jnp.sum(tgt * jax.nn.log_softmax(logits, axis=1), axis=1)
    reg = jnp.mean(jnp.sum(anchor * anchor, 1)
                   + jnp.sum(positive * positive, 1)) * l2_reg * 0.25
    return jnp.mean(xent) + reg


# ---------------------------------------------------------------------------
# normalization variants
# ---------------------------------------------------------------------------


@register_op("lrn")
def lrn(x, *, n=5, k=1.0, alpha=1e-4, beta=0.75, data_format="NCHW"):
    """ref lrn_op.cc: local response normalisation across channels."""
    x = jnp.asarray(x)
    if data_format != "NCHW":
        x = jnp.moveaxis(x, -1, 1)
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, n - 1 - half), (0, 0), (0, 0)))
    # sliding-window channel sum via reduce_window
    win = lax.reduce_window(pad, 0.0, lax.add, (1, n, 1, 1), (1, 1, 1, 1),
                            "VALID")
    out = x / jnp.power(k + alpha * win, beta)
    if data_format != "NCHW":
        out = jnp.moveaxis(out, 1, -1)
    return out


@register_op("data_norm", has_aux=True)
def data_norm(x, batch_size, batch_sum, batch_square_sum, *,
              epsilon=1e-4):
    """ref data_norm_op.cc (CTR models): normalise with accumulated
    batch statistics; aux returns the updated accumulators."""
    x = jnp.asarray(x, jnp.float32)
    mean = batch_sum / batch_size
    scale = jnp.sqrt(batch_size / jnp.maximum(
        batch_square_sum - batch_size * mean * mean + epsilon, epsilon))
    out = (x - mean[None, :]) * scale[None, :]
    b = x.shape[0]
    new_size = batch_size + b
    new_sum = batch_sum + x.sum(0)
    new_sq = batch_square_sum + (x * x).sum(0)
    return out, (new_size, new_sum, new_sq)


@register_op("spectral_norm")
def spectral_norm(weight, u, v, *, dim=0, power_iters=1, eps=1e-12):
    """ref spectral_norm_op.cc: weight / sigma with power iteration."""
    w = jnp.asarray(weight, jnp.float32)
    w2 = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    uu, vv = jnp.asarray(u, jnp.float32), jnp.asarray(v, jnp.float32)
    for _ in range(max(power_iters, 1)):
        vv = w2.T @ uu
        vv = vv / jnp.maximum(jnp.linalg.norm(vv), eps)
        uu = w2 @ vv
        uu = uu / jnp.maximum(jnp.linalg.norm(uu), eps)
    sigma = uu @ w2 @ vv
    return (w / sigma).astype(weight.dtype)


# ---------------------------------------------------------------------------
# pooling / layout extras
# ---------------------------------------------------------------------------


@register_op("pool3d")
def pool3d(x, *, ksize, stride=None, padding=0, pooling_type="max",
           ceil_mode=False, exclusive=True, global_pooling=False,
           adaptive=False, data_format="NCDHW"):
    """ref pool_op.cc 3-D variant (NCDHW/NDHWC, ceil_mode extends hi
    padding so partial windows are produced, paddle semantics)."""
    x = jnp.asarray(x)
    channel_last = data_format == "NDHWC"
    if channel_last:
        x = jnp.moveaxis(x, -1, 1)
    if adaptive:
        out = _adaptive_pool3d(x, ksize, pooling_type)
        return jnp.moveaxis(out, 1, -1) if channel_last else out
    if global_pooling:
        axes = (2, 3, 4)
        out = (jnp.max(x, axes, keepdims=True) if pooling_type == "max"
               else jnp.mean(x, axes, keepdims=True))
        return jnp.moveaxis(out, 1, -1) if channel_last else out
    ks = tuple(ksize) if isinstance(ksize, (list, tuple)) else (ksize,) * 3
    st = tuple(stride) if isinstance(stride, (list, tuple)) else \
        ((stride,) * 3 if stride is not None else ks)
    pd = tuple(padding) if isinstance(padding, (list, tuple)) else \
        (padding,) * 3
    pairs = [(p, p) for p in pd]
    if ceil_mode:
        for i, (dim, k, s) in enumerate(zip(x.shape[2:], ks, st)):
            lo, hi = pairs[i]
            rem = (dim + lo + hi - k) % s
            if rem:
                pairs[i] = (lo, hi + (s - rem))
    pads = [(0, 0), (0, 0)] + pairs
    window = (1, 1) + ks
    strides = (1, 1) + st
    if pooling_type == "max":
        out = lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                                pads)
    else:
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
        if exclusive and (ceil_mode or any(p for p in pd)):
            counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add,
                                       window, strides, pads)
            out = summed / counts
        else:
            import numpy as _np

            out = summed / _np.prod(ks)
    return jnp.moveaxis(out, 1, -1) if channel_last else out


def _adaptive_pool3d(x, output_size, mode):
    """Adaptive 3-D pooling over NCDHW input (ref pool_op.cc adaptive
    attr).  Divisible dims collapse to a strided reduce_window; uneven
    dims use paddle's floor/ceil bin bounds, unrolled as static slices
    (output sizes are small compile-time constants)."""
    os3 = tuple(output_size) if isinstance(output_size, (list, tuple)) \
        else (output_size,) * 3
    d, h, w = x.shape[2:]
    if d % os3[0] == 0 and h % os3[1] == 0 and w % os3[2] == 0:
        ks = (d // os3[0], h // os3[1], w // os3[2])
        window, strides = (1, 1) + ks, (1, 1) + ks
        if mode == "max":
            return lax.reduce_window(x, -jnp.inf, lax.max, window,
                                     strides, [(0, 0)] * 5)
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides,
                                   [(0, 0)] * 5)
        import numpy as _np

        return summed / _np.prod(ks)
    red = jnp.max if mode == "max" else jnp.mean
    from .nn_ops import adaptive_bounds as bounds

    planes = []
    for i in range(os3[0]):
        s0, e0 = bounds(i, d, os3[0])
        rows = []
        for j in range(os3[1]):
            s1, e1 = bounds(j, h, os3[1])
            cols = [red(x[:, :, s0:e0, s1:e1,
                          bounds(k, w, os3[2])[0]:bounds(k, w, os3[2])[1]],
                        axis=(2, 3, 4))
                    for k in range(os3[2])]
            rows.append(jnp.stack(cols, axis=-1))
        planes.append(jnp.stack(rows, axis=-2))
    return jnp.stack(planes, axis=-3)


@register_op("maxout")
def maxout(x, *, groups, axis=1):
    """ref maxout_op.cc: split the channel axis into `groups`-sized
    chunks and take the elementwise max."""
    x = jnp.asarray(x)
    axis = axis % x.ndim
    c = x.shape[axis]
    if c % groups != 0:
        raise ValueError(f"channels {c} not divisible by groups {groups}")
    shape = x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1:]
    return jnp.max(x.reshape(shape), axis=axis + 1)


@register_op("thresholded_relu")
def thresholded_relu(x, *, threshold=1.0):
    """ref thresholded_relu_op.cc: x if x > threshold else 0."""
    x = jnp.asarray(x)
    return jnp.where(x > threshold, x, jnp.zeros_like(x))


@register_op("hierarchical_sigmoid")
def hierarchical_sigmoid(x, w, label, bias=None, path_table=None,
                         path_code=None, *, num_classes):
    """ref hierarchical_sigmoid_op.cc: hierarchical sigmoid loss.

    Default tree: classes are leaves of a heap-numbered complete binary
    tree (leaf id = label + num_classes, root = 1); the loss walks the
    root->leaf path, scoring internal node n with weight row n-1 and
    sign from the branch bit.  Custom trees pass path_table (node rows,
    -1 padded) and path_code (branch bits).  Returns [N, 1] losses."""
    x = jnp.asarray(x)
    lbl = jnp.asarray(label).reshape(-1).astype(jnp.int32)
    if path_table is not None:
        nodes = jnp.asarray(path_table).astype(jnp.int32)
        bits = jnp.asarray(path_code).astype(jnp.float32)
        valid = (nodes >= 0)
        nodes_safe = jnp.maximum(nodes, 0)
    else:
        import numpy as _np

        depth = max(int(_np.ceil(_np.log2(num_classes))), 1)
        leaf = lbl + num_classes              # heap leaf index
        # bits of `leaf` below its MSB, walked MSB-first; internal node
        # visited at step k is leaf >> (depth - k)
        ks = jnp.arange(depth, 0, -1)
        anc = leaf[:, None] >> ks[None, :]    # [N, depth] ancestors
        valid = anc >= 1
        nodes_safe = jnp.maximum(anc - 1, 0)  # weight row = node - 1
        bits = ((leaf[:, None] >> (ks[None, :] - 1)) & 1).astype(
            jnp.float32)
    wrows = jnp.take(jnp.asarray(w), nodes_safe, axis=0)  # [N, L, F]
    logit = jnp.einsum("nlf,nf->nl", wrows.astype(jnp.float32),
                       x.astype(jnp.float32))
    if bias is not None:
        logit = logit + jnp.take(jnp.asarray(bias).reshape(-1),
                                 nodes_safe)
    # bit==1 -> right branch -> sigmoid(+logit); paddle codes bits as
    # (1 - 2*code)*logit inside log(1+exp(.)) == softplus
    z = jnp.where(bits > 0.5, -logit, logit)
    losses = jnp.where(valid, jax.nn.softplus(z), 0.0)
    return jnp.sum(losses, axis=1, keepdims=True)


@register_op("pad3d")
def pad3d(x, *, paddings, mode="constant", value=0.0,
          data_format="NCDHW"):
    """ref pad3d_op.cc: paddings [left, right, top, bottom, front, back]
    — paddle attr order, W pairs first, then H, then D."""
    pl_, pr, pt, pb, pf, pk = [int(p) for p in paddings]
    if data_format == "NCDHW":
        cfg = [(0, 0), (0, 0), (pf, pk), (pt, pb), (pl_, pr)]
    else:
        cfg = [(0, 0), (pf, pk), (pt, pb), (pl_, pr), (0, 0)]
    if mode == "constant":
        return jnp.pad(x, cfg, constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    return jnp.pad(x, cfg, mode=jmode)


@register_op("roi_pool", no_grad=True)
def roi_pool(x, boxes, boxes_num, *, output_size, spatial_scale=1.0):
    """ref roi_pool_op.cc: max pooling inside each RoI bin (quantised
    boundaries, unlike roi_align's bilinear sampling)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    x = jnp.asarray(x)
    n, c, h, w = x.shape
    boxes = jnp.asarray(boxes, jnp.float32)
    bn = jnp.asarray(boxes_num, jnp.int32)
    r = boxes.shape[0]
    img_of_roi = jnp.searchsorted(jnp.cumsum(bn), jnp.arange(r),
                                  side="right").astype(jnp.int32)
    x1 = jnp.round(boxes[:, 0] * spatial_scale).astype(jnp.int32)
    y1 = jnp.round(boxes[:, 1] * spatial_scale).astype(jnp.int32)
    x2 = jnp.round(boxes[:, 2] * spatial_scale).astype(jnp.int32)
    y2 = jnp.round(boxes[:, 3] * spatial_scale).astype(jnp.int32)
    rw = jnp.maximum(x2 - x1 + 1, 1)
    rh = jnp.maximum(y2 - y1 + 1, 1)

    ys = jnp.arange(h)
    xs = jnp.arange(w)

    def per_roi(i):
        img = x[img_of_roi[i]]

        def per_bin(py, px):
            hs = y1[i] + (py * rh[i]) // ph
            he = y1[i] + ((py + 1) * rh[i] + ph - 1) // ph
            ws_ = x1[i] + (px * rw[i]) // pw
            we = x1[i] + ((px + 1) * rw[i] + pw - 1) // pw
            m = ((ys[:, None] >= hs) & (ys[:, None] < he)
                 & (xs[None, :] >= ws_) & (xs[None, :] < we))
            sel = jnp.where(m[None], img, -jnp.inf)
            v = jnp.max(sel, axis=(1, 2))
            return jnp.where(jnp.isfinite(v), v, 0.0)

        grid = jax.vmap(lambda py: jax.vmap(
            lambda px: per_bin(py, px))(jnp.arange(pw)))(jnp.arange(ph))
        return jnp.moveaxis(grid, -1, 0)  # [C, ph, pw]

    return jax.vmap(per_roi)(jnp.arange(r))


@register_op("space_to_depth")
def space_to_depth(x, *, blocksize):
    """ref space_to_depth_op.cc: [N,C,H,W] -> [N,C*b*b,H/b,W/b]."""
    n, c, h, w = x.shape
    b = blocksize
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return x.reshape(n, c * b * b, h // b, w // b)


@register_op("shuffle_channel")
def shuffle_channel(x, *, group):
    """ref shuffle_channel_op.cc (ShuffleNet)."""
    n, c, h, w = x.shape
    x = x.reshape(n, group, c // group, h, w)
    return jnp.swapaxes(x, 1, 2).reshape(n, c, h, w)


@register_op("multiplex", no_grad=False)
def multiplex(index, *inputs):
    """ref multiplex_op.cc: out[i] = inputs[index[i]][i]."""
    index = jnp.asarray(index, jnp.int32).reshape(-1)
    stacked = jnp.stack(inputs)  # [K, B, ...]
    return jnp.take_along_axis(
        stacked, index[None, :].reshape(
            (1, -1) + (1,) * (stacked.ndim - 2)), axis=0)[0]


@register_op("segment_pool")
def segment_pool(x, segment_ids, *, pool_type="sum", num_segments=None):
    """ref segment_pool_op.cc: pool rows by segment id (sorted ids;
    num_segments static under jit — defaults to x.shape[0])."""
    x = jnp.asarray(x)
    ids = jnp.asarray(segment_ids, jnp.int32).reshape(-1)
    ns = int(num_segments) if num_segments is not None else x.shape[0]
    pool = pool_type.lower()
    if pool == "sum":
        return jax.ops.segment_sum(x, ids, num_segments=ns)
    if pool == "mean":
        s = jax.ops.segment_sum(x, ids, num_segments=ns)
        cnt = jax.ops.segment_sum(jnp.ones((x.shape[0],), x.dtype), ids,
                                  num_segments=ns)
        return s / jnp.maximum(cnt, 1.0)[:, None] if x.ndim > 1 else \
            s / jnp.maximum(cnt, 1.0)
    if pool == "max":
        return jax.ops.segment_max(x, ids, num_segments=ns)
    if pool == "min":
        return jax.ops.segment_min(x, ids, num_segments=ns)
    raise ValueError(f"unknown pool_type {pool_type!r}")


@register_op("gather_tree", no_grad=True)
def gather_tree(ids, parents):
    """ref gather_tree_op.cc (beam search backtrace): ids/parents
    [T, B, W] -> full beams re-threaded through parent pointers."""
    ids = jnp.asarray(ids, jnp.int32)
    parents = jnp.asarray(parents, jnp.int32)
    t = ids.shape[0]

    def body(carry, tt):
        beam = carry  # [B, W] current beam index per slot
        step = t - 1 - tt
        out = jnp.take_along_axis(ids[step], beam, axis=1)
        beam = jnp.take_along_axis(parents[step], beam, axis=1)
        return beam, out

    w = ids.shape[2]
    init = jnp.broadcast_to(jnp.arange(w, dtype=jnp.int32)[None, :],
                            ids.shape[1:])
    _, outs = lax.scan(body, init, jnp.arange(t))
    return jnp.flip(outs, axis=0)
